"""Elastic world-size resharding: resume a sharded (ZeRO) checkpoint saved
at world N on a gang of world M.

The redistribution discipline is the one from "Memory-efficient array
redistribution through portable collective communication": never
materialize the full replicated state anywhere — each **destination** rank
fetches only the byte spans it will own.  The PR 6 shard layout was built
for exactly this consumption: every rank's checkpointed ZeRO state is, per
dtype group, ONE flat contiguous array that concatenates the member
leaves' owned ring chunks (``ring._bounds(leaf.size, world)[rank]``) in
leaf order.  Both the old and the new partition are therefore pure
functions of ``(leaf sizes, world)`` — the same bounds math the
bucketer/ring run — so the mapping from any new rank's owned spans to
``(old_rank, offset, length)`` source fragments is computable by every
rank independently, with no coordination beyond agreeing on ``(step, N)``.

Three layers:

- **Manifest** (:func:`manifest_from_arrays`, embedded by
  ``checkpoint.save(shard=...)`` into each shard checkpoint's
  ``tree.json``): leaf sizes + dtypes (the partition inputs), which saved
  arrays are sharded along the group axis vs replicated, and a sha256 per
  *fragment* (each member leaf's chunk inside the flat shard) — so an
  N→M restore is self-describing and digest-verified at the granularity
  actually read.
- **Plan** (:class:`ReshardPlan`): for every new rank, the exact
  ``(old_rank, old_offset, length)`` fragments covering its new spans —
  deterministic and identical on every rank, which is what lets the peer
  path run as a pre-agreed push/fetch with no request/response protocol.
- **Execution** (:func:`reshard_restore`): fragments whose old shard
  checkpoint is disk-visible are **range-read** straight out of the
  uncompressed ``arrays.npz`` (no full-file load); the rest are pushed by
  the lowest-ranked peer that can see them over the p2p data plane
  (``transport.py`` send/recv, sends issued as async Work handles on the
  ordered engine) and received under an explicit deadline that names the
  peer.  Peak memory is accounted and bounded by
  ``old_shard + new_shard + one fragment``.

``resilience.TrainState.resume`` drives this automatically (visibility
exchange + step/world agreement through the control-plane store); the
functions here are also directly usable for offline conversion of a
checkpoint tree between world sizes.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import zipfile
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

__all__ = ["ReshardError", "ReshardPlan", "ReshardStats",
           "manifest_from_arrays", "local_visibility", "resumable_steps",
           "reshard_restore", "plan_summary"]

_META_SEG = "['meta']"
_MANIFEST_META = ("rank", "world", "leaf_size", "leaf_dtype")


class ReshardError(RuntimeError):
    """Elastic resharding cannot proceed (missing source shard, absent
    manifest, template/manifest structure mismatch, or a dead peer named
    mid-fetch)."""


# ---------------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------------

def _groups(leaf_dtypes: Sequence[str]) -> List[Tuple[str, List[int]]]:
    """Dtype groups in first-occurrence leaf order — the exact grouping
    ``ZeroOptimizer._build_plan`` uses, reconstructed from the recorded
    per-leaf dtype strings so any world can recompute the layout."""
    groups: List[Tuple[str, List[int]]] = []
    by_key: Dict[str, List[int]] = {}
    for i, key in enumerate(leaf_dtypes):
        if key not in by_key:
            by_key[key] = []
            groups.append((key, by_key[key]))
        by_key[key].append(i)
    return groups


def _bounds(n_elems: int, n: int):
    # the unified rule plane's flat chunk contract (parallel/rules.py),
    # itself pinned to ring._bounds — ZeRO shards, the ring
    # reduce-scatter, and these manifests all cut the same spans
    from ..parallel.rules import chunk_bounds
    return chunk_bounds(int(n_elems), int(n))


def _span_len(size: int, world: int, rank: int) -> int:
    lo, hi = _bounds(size, world)[rank]
    return hi - lo


def manifest_from_arrays(arrays: Dict[str, np.ndarray]) -> Optional[dict]:
    """Build the reshard manifest for one shard checkpoint's flattened
    array dict, or None when the tree holds no ZeRO-style ``meta``
    (``rank``/``world``/``leaf_size``/``leaf_dtype``) — such a tree is
    world-size-opaque and stays restorable only at its own coordinates.

    One manifest *entry* per subtree that carries a meta block (the
    ``prefix`` is the flattened key path of that subtree, e.g.
    ``"['zero']"``); each entry records the partition inputs, the sharded
    vs replicated array paths, and per-fragment digests.
    """
    entries: Dict[str, dict] = {}
    for key in arrays:
        suffix = f"{_META_SEG}['leaf_size']"
        if not key.endswith(suffix):
            continue
        prefix = key[:-len(suffix)]
        meta_keys = {m: f"{prefix}{_META_SEG}['{m}']" for m in _MANIFEST_META}
        if not all(k in arrays for k in meta_keys.values()):
            continue  # pre-elastic meta (no leaf_dtype): not reshardable
        rank = int(np.asarray(arrays[meta_keys["rank"]]))
        world = int(np.asarray(arrays[meta_keys["world"]]))
        sizes = [int(s) for s in np.asarray(arrays[meta_keys["leaf_size"]])]
        dtypes = [str(d) for d in np.asarray(arrays[meta_keys["leaf_dtype"]])]
        groups = _groups(dtypes)
        shard_len = {g: sum(_span_len(sizes[i], world, rank) for i in idxs)
                     for g, idxs in groups}
        sharded: Dict[str, str] = {}
        replicated: Dict[str, dict] = {}
        frag_sha: Dict[str, List[str]] = {}
        repl_sha: Dict[str, str] = {}
        for path, a in arrays.items():
            if not path.startswith(prefix) \
                    or path.startswith(prefix + _META_SEG):
                continue
            a = np.asarray(a)
            gkey = a.dtype.str
            if a.ndim == 1 and gkey in shard_len \
                    and a.size == shard_len[gkey]:
                sharded[path] = gkey
                digests, pos = [], 0
                for i in dict(groups)[gkey]:
                    ln = _span_len(sizes[i], world, rank)
                    digests.append(hashlib.sha256(
                        np.ascontiguousarray(a[pos:pos + ln])
                        .tobytes()).hexdigest())
                    pos += ln
                frag_sha[path] = digests
            else:
                replicated[path] = {"shape": list(a.shape),
                                    "dtype": a.dtype.str}
                repl_sha[path] = hashlib.sha256(
                    np.ascontiguousarray(a).tobytes()).hexdigest()
        entries[prefix] = {
            "rank": rank, "world": world,
            "leaf_size": sizes, "leaf_dtype": dtypes,
            "sharded": sharded, "replicated": replicated,
            "frag_sha256": frag_sha, "repl_sha256": repl_sha,
        }
    if not entries:
        return None
    return {"version": 1, "entries": entries}


# ---------------------------------------------------------------------------
# plan
# ---------------------------------------------------------------------------

class _Frag:
    """One contiguous overlap between a new rank's owned span of a leaf
    and an old rank's: ``length`` elements read at ``old_off`` of the old
    rank's flat array at ``path``, landing at ``new_off`` of the new one.
    ``chunk_off``/``chunk_len`` locate the *whole* old fragment (the old
    rank's full chunk of this leaf — the digest unit) and ``leaf_pos``
    indexes its recorded sha256."""

    __slots__ = ("fid", "path", "dtype", "old_rank", "new_rank", "old_off",
                 "new_off", "length", "chunk_off", "chunk_len", "leaf_pos",
                 "leaf_ord")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw[k])

    def describe(self) -> str:
        return (f"{self.path}[leaf {self.leaf_ord}] old_rank "
                f"{self.old_rank} [{self.old_off}:{self.old_off + self.length}]"
                f" -> new_rank {self.new_rank}")


class _Repl:
    """A replicated (identical on every old rank) saved array — scalar
    step counters and the like: copied whole from one source old rank to
    every new rank."""

    __slots__ = ("rid", "path", "shape", "dtype", "sha256")

    def __init__(self, rid, path, shape, dtype, sha256):
        self.rid, self.path, self.shape = rid, path, shape
        self.dtype, self.sha256 = dtype, sha256

    def describe(self) -> str:
        return f"replicated array {self.path!r}"


class ReshardStats:
    """What one rank's reshard actually did — surfaced in the restart log
    and asserted by the memory-bound test."""

    def __init__(self):
        self.old_world = 0
        self.new_world = 0
        self.step = -1
        self.frags_total = 0        # fragments this rank assembled
        self.bytes_total = 0
        self.frags_disk = 0
        self.frags_peer = 0
        self.frags_pushed = 0       # fragments this rank served to peers
        self.peak_bytes = 0         # accounted live allocation high-water
        self.new_shard_bytes = 0
        self.frag_bytes_max = 0
        self._live = 0
        self._mu = threading.Lock()

    def _alloc(self, n: int) -> None:
        with self._mu:
            self._live += n
            self.peak_bytes = max(self.peak_bytes, self._live)

    def _free(self, n: int) -> None:
        with self._mu:
            self._live -= n

    def describe(self) -> str:
        return (f"world {self.old_world} -> {self.new_world} @ step "
                f"{self.step}: {self.frags_total} fragments / "
                f"{self.bytes_total} B ({self.frags_disk} disk, "
                f"{self.frags_peer} peer; {self.frags_pushed} pushed), "
                f"peak {self.peak_bytes} B")


class ReshardPlan:
    """The full N→M fragment map — every rank's fetches, not just this
    one's, because the peer path is a pre-agreed push: source ranks must
    know exactly what to send where without a request round-trip."""

    def __init__(self, manifest: dict, new_world: int):
        entries = manifest.get("entries") or {}
        if not entries:
            raise ReshardError(
                "manifest has no reshardable entries (the checkpoint was "
                "saved without ZeRO leaf_dtype meta — re-save it with this "
                "tpu_dist before resuming at a different world size)")
        worlds = {e["world"] for e in entries.values()}
        if len(worlds) != 1:
            raise ReshardError(f"manifest entries disagree on the saved "
                               f"world size: {sorted(worlds)}")
        self.old_world = worlds.pop()
        self.new_world = int(new_world)
        if self.new_world < 1:
            raise ReshardError(f"new world must be >= 1, got {new_world}")
        self.frags: List[_Frag] = []
        self.repl: List[_Repl] = []
        self.new_len: Dict[str, int] = {}
        self.new_dtype: Dict[str, np.dtype] = {}
        self._build(entries)

    def _build(self, entries: Dict[str, dict]) -> None:
        N, M = self.old_world, self.new_world
        fid = rid = 0
        for prefix in sorted(entries):
            e = entries[prefix]
            sizes = e["leaf_size"]
            groups = _groups(e["leaf_dtype"])
            # element offset of member leaf j's chunk inside each rank's
            # flat group array, old and new partition alike
            off_old = {g: self._frag_offsets(sizes, idxs, N)
                       for g, idxs in groups}
            off_new = {g: self._frag_offsets(sizes, idxs, M)
                       for g, idxs in groups}
            gidx = dict(groups)
            for path in sorted(e["sharded"]):
                gkey = e["sharded"][path]
                if gkey not in gidx:
                    raise ReshardError(
                        f"manifest path {path!r} names unknown dtype group "
                        f"{gkey!r}")
                idxs = gidx[gkey]
                self.new_len[path] = [
                    sum(_span_len(sizes[i], M, d) for i in idxs)
                    for d in range(M)]
                self.new_dtype[path] = np.dtype(gkey)
                for j, i in enumerate(idxs):
                    ob = _bounds(sizes[i], N)
                    nb = _bounds(sizes[i], M)
                    for d in range(M):
                        nlo, nhi = nb[d]
                        if nhi <= nlo:
                            continue
                        for o in range(N):
                            olo, ohi = ob[o]
                            lo, hi = max(nlo, olo), min(nhi, ohi)
                            if hi <= lo:
                                continue
                            self.frags.append(_Frag(
                                fid=fid, path=path,
                                dtype=np.dtype(gkey),
                                old_rank=o, new_rank=d,
                                old_off=off_old[gkey][o][j] + (lo - olo),
                                new_off=off_new[gkey][d][j] + (lo - nlo),
                                length=hi - lo,
                                chunk_off=off_old[gkey][o][j],
                                chunk_len=ohi - olo,
                                leaf_pos=j, leaf_ord=i))
                            fid += 1
            for path in sorted(e.get("replicated", {})):
                info = e["replicated"][path]
                self.repl.append(_Repl(
                    rid, path, tuple(info["shape"]),
                    np.dtype(info["dtype"]),
                    e.get("repl_sha256", {}).get(path)))
                rid += 1
        self.entries = entries

    @staticmethod
    def _frag_offsets(sizes, idxs, world) -> List[List[int]]:
        """``out[rank][j]`` = element offset of member leaf ``idxs[j]``'s
        chunk inside rank's flat group array."""
        out = []
        for r in range(world):
            offs, pos = [], 0
            for i in idxs:
                offs.append(pos)
                pos += _span_len(sizes[i], world, r)
            out.append(offs)
        return out

    # -- queries -------------------------------------------------------------

    def frags_for(self, new_rank: int) -> List[_Frag]:
        return [f for f in self.frags if f.new_rank == new_rank]

    def bytes_for(self, new_rank: int) -> int:
        return sum(f.length * f.dtype.itemsize for f in self.frags
                   if f.new_rank == new_rank)

    def summary_rows(self) -> List[Tuple[int, int, int]]:
        """``(new_rank, n_fragments, bytes)`` per destination rank."""
        return [(d, len(self.frags_for(d)), self.bytes_for(d))
                for d in range(self.new_world)]

    def resolve_sources(self, visibility: Dict[int, Set[int]]
                        ) -> Dict[int, int]:
        """``{fid: serving new rank}``: the destination itself when it can
        see the old shard on disk, else the lowest-ranked peer that can —
        deterministic, so every rank derives the same push schedule.
        ``visibility[r]`` is the set of old ranks whose shard checkpoints
        rank ``r`` reported disk-visible (at the agreed step)."""
        sees: Dict[int, List[int]] = {}
        for r in sorted(visibility):
            for o in visibility[r]:
                sees.setdefault(o, []).append(r)
        out: Dict[int, int] = {}
        needed_old = sorted({f.old_rank for f in self.frags})
        missing = [o for o in needed_old if o not in sees]
        if self.repl and not sees:
            missing = needed_old or [0]
        if missing:
            raise ReshardError(
                f"no rank can see old rank(s) {missing}'s shard "
                f"checkpoint(s); resharding from world {self.old_world} "
                f"needs every old shard disk-visible to at least one "
                f"surviving rank")
        for f in self.frags:
            out[f.fid] = (f.new_rank
                          if f.old_rank in visibility.get(f.new_rank, ())
                          else sees[f.old_rank][0])
        return out

    def repl_source_old_rank(self, visibility: Dict[int, Set[int]]) -> int:
        """The old rank whose copy serves every replicated array: the
        lowest old rank anyone can see (replicated arrays are identical
        across old ranks by construction)."""
        seen = sorted({o for v in visibility.values() for o in v})
        if not seen:
            raise ReshardError("no old shard checkpoint visible to any "
                               "rank; cannot restore replicated arrays")
        return seen[0]


# ---------------------------------------------------------------------------
# npz range reads
# ---------------------------------------------------------------------------

class _ShardReader:
    """Range-reads out of one old shard checkpoint's ``arrays.npz``
    without loading the file: ``np.savez`` writes an uncompressed
    (ZIP_STORED) archive, so each member is a raw ``.npy`` at a computable
    offset — seek to ``data_start + lo * itemsize`` and read exactly the
    fragment.  Falls back to a streamed member read for compressed or
    exotic archives (still never more than one member in memory)."""

    _LOCAL_HEADER = 30  # fixed part of a zip local file header

    def __init__(self, root: str, old_rank: int, step: int):
        from .. import checkpoint
        self._setup(os.path.join(checkpoint.shard_root(root, old_rank),
                                 f"step_{step:08d}"), old_rank)

    def _setup(self, step_dir: str, label) -> None:
        """The ONE init body (both constructors share it, so a field
        added here can never be missing from ``from_dir`` readers).
        ``label`` is the old rank — or a descriptive string for
        non-shard-root readers — used in diagnostics."""
        self.old_rank = label
        self._dir = os.fspath(step_dir)
        self.path = os.path.join(self._dir, "arrays.npz")
        self._zf: Optional[zipfile.ZipFile] = None
        self._raw = None
        self._offsets: Dict[str, Tuple[int, np.dtype, int]] = {}
        self._manifest: Optional[dict] = None
        # one reader may serve BOTH the main thread's fills and the ordered
        # engine's pushes; seeks and reads on the shared file handle must
        # not interleave (RLock: read_range nests _member_layout)
        self._mu = threading.RLock()

    @classmethod
    def from_dir(cls, step_dir: str, label: str = "checkpoint"
                 ) -> "_ShardReader":
        """A reader over an arbitrary checkpoint step directory (not a
        per-rank ZeRO shard root) — the fragment range-read machinery
        applied to FULL checkpoints, e.g. loading a whole-model save
        directly into tensor-parallel shard layouts
        (``tpu_dist.serve.sharded.ShardedParams.from_checkpoint``)."""
        self = cls.__new__(cls)
        self._setup(step_dir, label)
        return self

    def frag_digest(self, path: str, leaf_pos: int) -> Optional[str]:
        """The sha256 THIS old rank's checkpoint recorded for member leaf
        ``leaf_pos``'s chunk of ``path`` — digests are per shard file, so
        verification must consult the source rank's own manifest, not the
        one the plan happened to be built from."""
        with self._mu:
            if self._manifest is None:
                try:
                    with open(os.path.join(self._dir, "tree.json")) as f:
                        self._manifest = (json.load(f).get("metadata", {})
                                          .get("reshard") or {})
                except (OSError, json.JSONDecodeError):
                    self._manifest = {}
            for e in (self._manifest.get("entries") or {}).values():
                digests = (e.get("frag_sha256") or {}).get(path)
                if digests is not None and leaf_pos < len(digests):
                    return digests[leaf_pos]
            return None

    def _open(self):
        if self._zf is None:
            self._raw = open(self.path, "rb")
            self._zf = zipfile.ZipFile(self._raw)
        return self._zf

    def _member_layout(self, member: str) -> Tuple[int, np.dtype, int]:
        """``(data_start, dtype, n_elems)`` of an uncompressed member's
        raw array data, parsing the zip local header + npy header once."""
        cached = self._offsets.get(member)
        if cached is not None:
            return cached
        zf = self._open()
        zi = zf.getinfo(member)
        if zi.compress_type != zipfile.ZIP_STORED:
            raise ValueError("compressed member")  # caller falls back
        f = self._raw
        f.seek(zi.header_offset + 26)
        fnlen = int.from_bytes(f.read(2), "little")
        extralen = int.from_bytes(f.read(2), "little")
        npy_start = zi.header_offset + self._LOCAL_HEADER + fnlen + extralen
        f.seek(npy_start)
        version = np.lib.format.read_magic(f)
        if version == (1, 0):
            shape, fortran, dtype = np.lib.format.read_array_header_1_0(f)
        else:
            shape, fortran, dtype = np.lib.format.read_array_header_2_0(f)
        if fortran:
            raise ValueError("fortran-order member")
        layout = (f.tell(), dtype, int(np.prod(shape, dtype=np.int64)))
        self._offsets[member] = layout
        return layout

    def read_range(self, path: str, elem_lo: int, elem_hi: int,
                   dtype: np.dtype) -> np.ndarray:
        """``arrays[path][elem_lo:elem_hi]`` (flat), reading only those
        bytes when the archive allows it."""
        member = path + ".npy"
        with self._mu:
            try:
                data_start, mdtype, n = self._member_layout(member)
            except (ValueError, KeyError, OSError) as e:
                if isinstance(e, KeyError):
                    raise ReshardError(
                        f"old rank {self.old_rank}'s shard checkpoint at "
                        f"{self.path!r} has no array {path!r}") from e
                return self._read_full(member, dtype)[elem_lo:elem_hi].copy()
            if mdtype != dtype:
                raise ReshardError(
                    f"old rank {self.old_rank}'s {path!r} has dtype "
                    f"{mdtype}, plan expects {dtype}")
            if elem_hi > n:
                raise ReshardError(
                    f"fragment [{elem_lo}:{elem_hi}) overruns old rank "
                    f"{self.old_rank}'s {path!r} ({n} elements)")
            f = self._raw
            f.seek(data_start + elem_lo * dtype.itemsize)
            nbytes = (elem_hi - elem_lo) * dtype.itemsize
            buf = f.read(nbytes)
        if len(buf) != nbytes:
            raise ReshardError(
                f"truncated read of {path!r} from old rank "
                f"{self.old_rank} ({len(buf)}/{nbytes} bytes)")
        return np.frombuffer(buf, dtype=dtype).copy()

    def _read_full(self, member: str, dtype: np.dtype) -> np.ndarray:
        with self._open().open(member) as m:
            version = np.lib.format.read_magic(m)
            if version == (1, 0):
                shape, _, mdtype = np.lib.format.read_array_header_1_0(m)
            else:
                shape, _, mdtype = np.lib.format.read_array_header_2_0(m)
            data = m.read()
        return np.frombuffer(data, dtype=mdtype).reshape(-1)

    def close(self) -> None:
        with self._mu:
            if self._zf is not None:
                self._zf.close()
                self._raw.close()
                self._zf = self._raw = None


# ---------------------------------------------------------------------------
# visibility + step/world agreement inputs
# ---------------------------------------------------------------------------

# path → (mtime_ns, size, recorded shard_world) for tree.jsons already
# parsed by THIS process, validated by stat on every hit: a resumed
# worker re-executing steps left behind by the previous incarnation
# OVERWRITES step dirs it may have read during its own resume (atomic
# rename ⇒ new mtime), so a never-invalidate cache would serve a stale
# world.  Keeps keep-N pruning (which calls local_visibility on every
# cadence save) at one stat per step instead of a JSON parse.
_WORLD_CACHE: Dict[str, Tuple[int, int, int]] = {}


def local_visibility(root: str) -> dict:
    """What THIS host's disk can serve: replicated steps under ``root``
    plus, per old shard root present, ``{step: recorded shard_world}``.
    The per-step world comes from each shard checkpoint's own metadata, so
    a root holding checkpoints from several incarnations (pre- and
    post-shrink) reports each step at the world it was actually saved."""
    from .. import checkpoint
    vis = {"repl": [int(s) for s in checkpoint.all_steps(root)],
           "shards": {}}
    if not os.path.isdir(root):
        return vis
    for name in sorted(os.listdir(root)):
        if not name.startswith("shard_r"):
            continue
        try:
            old_rank = int(name[len("shard_r"):])
        except ValueError:
            continue
        sroot = os.path.join(root, name)
        steps = {}
        for s in checkpoint.all_steps(sroot):
            tj = os.path.join(sroot, f"step_{s:08d}", "tree.json")
            try:
                st = os.stat(tj)
            except OSError:
                continue
            cached = _WORLD_CACHE.get(tj)
            if cached is not None and cached[:2] == (st.st_mtime_ns,
                                                     st.st_size):
                w = cached[2]
            else:
                try:
                    with open(tj) as f:
                        md = json.load(f).get("metadata", {})
                    w = int(md.get("shard_world", 0))
                except (OSError, ValueError, json.JSONDecodeError):
                    continue
                _WORLD_CACHE[tj] = (st.st_mtime_ns, st.st_size, w)
            if w > 0:
                steps[int(s)] = w
        if steps:
            vis["shards"][old_rank] = steps
    return vis


def resumable_steps(vis_list: Sequence[dict]) -> Dict[int, int]:
    """``{step: old_world}`` of steps the union of the ranks' visibility
    can serve: the replicated checkpoint exists on EVERY rank (each rank
    restores it locally) and, at the world the step's shard 0 records,
    every old shard 0..N-1 is visible *somewhere* with the same recorded
    world.  A step whose shard set records mixed worlds — a kill landed
    between a world transition's overwrites — is not resumable; the
    agreement falls back to an older complete step."""
    if not vis_list:
        return {}
    repl = set(vis_list[0].get("repl", ()))
    for v in vis_list[1:]:
        repl &= set(v.get("repl", ()))
    union: Dict[Tuple[int, int], Optional[int]] = {}
    for v in vis_list:
        for o, steps in (v.get("shards") or {}).items():
            o = int(o)
            for s, w in steps.items():
                s, w = int(s), int(w)
                prev = union.get((o, s))
                union[(o, s)] = w if prev in (None, w) else -1  # conflict
    out: Dict[int, int] = {}
    for s in repl:
        w = union.get((0, s))
        if not w or w < 0:
            continue
        if all(union.get((o, s)) == w for o in range(1, w)):
            out[s] = w
    return out


# ---------------------------------------------------------------------------
# execution
# ---------------------------------------------------------------------------

def _frag_timeout() -> float:
    try:
        return float(os.environ.get("TPU_DIST_RESHARD_TIMEOUT", "120"))
    except ValueError:
        return 120.0


def _obs_fetch_span(src: int, path_kind: str):
    """A span per fragment/replicated-array fetch (disk or dataplane) —
    what makes a slow reshard diagnosable with ``obs diagnose``."""
    from ..obs import hooks as _hooks
    return _hooks.collective_span(
        "reshard_fetch", kind="p2p", peer=src, path=path_kind)


def _read_fragment(reader: _ShardReader, frag: _Frag,
                   verify: bool, stats: ReshardStats) -> np.ndarray:
    """One fragment off disk.  With ``verify``, the whole containing old
    chunk (the digest unit) is read and checked against the manifest's
    per-fragment sha256 before slicing — the load-time defense against a
    shard corrupted after commit, at fragment granularity so an N→M
    restore never has to hash a whole shard it mostly does not want."""
    if verify:
        recorded = reader.frag_digest(frag.path, frag.leaf_pos)
        chunk = reader.read_range(frag.path, frag.chunk_off,
                                  frag.chunk_off + frag.chunk_len,
                                  frag.dtype)
        stats._alloc(chunk.nbytes)
        try:
            if recorded is None:
                raise _digest_error(
                    f"shard checkpoint of old rank {frag.old_rank} records "
                    f"no fragment digest for {frag.path!r} (leaf "
                    f"{frag.leaf_ord}); re-save with this tpu_dist or pass "
                    f"verify=False")
            actual = hashlib.sha256(chunk.tobytes()).hexdigest()
            if actual != recorded:
                raise _digest_error(
                    f"fragment digest mismatch on {frag.describe()} "
                    f"(recorded sha256 {recorded[:12]}…, actual "
                    f"{actual[:12]}…) — corrupted shard fragment; refusing "
                    f"to resume divergent")
            lo = frag.old_off - frag.chunk_off
            return chunk[lo:lo + frag.length].copy()
        finally:
            stats._free(chunk.nbytes)
    a = reader.read_range(frag.path, frag.old_off,
                          frag.old_off + frag.length, frag.dtype)
    return a


def _digest_error(msg: str):
    from .. import checkpoint
    return checkpoint.DigestError(msg)


def execute_plan(plan: ReshardPlan, *, rank: int, root: str, step: int,
                 visibility: Dict[int, Set[int]], dp=None,
                 verify: bool = False, timeout: Optional[float] = None
                 ) -> Tuple[Dict[str, np.ndarray], ReshardStats]:
    """Run this rank's share of the redistribution; returns the assembled
    ``{path: flat array}`` for every sharded + replicated path, plus
    stats.  EVERY new rank must call this together whenever any fragment
    needs the peer path (sources push; there is no request protocol) —
    callers that know everything is disk-visible may run it alone.
    """
    import time as _time

    from ..collectives.work import engine_for, wait_all

    timeout = _frag_timeout() if timeout is None else float(timeout)
    deadline = _time.monotonic() + timeout
    stats = ReshardStats()
    stats.old_world, stats.new_world = plan.old_world, plan.new_world
    stats.step = step
    sources = plan.resolve_sources(visibility)
    my_old = visibility.get(rank, set())
    readers: Dict[int, _ShardReader] = {}

    def reader_for(o: int) -> _ShardReader:
        r = readers.get(o)
        if r is None:
            r = readers[o] = _ShardReader(root, o, step)
        return r

    out: Dict[str, np.ndarray] = {}
    for path, lens in plan.new_len.items():
        a = np.zeros(lens[rank], dtype=plan.new_dtype[path])
        stats._alloc(a.nbytes)
        out[path] = a
    stats.new_shard_bytes = sum(a.nbytes for a in out.values())

    # replicated arrays: one source old rank, served like a whole-array
    # fragment by the lowest rank that sees it
    repl_src_old = plan.repl_source_old_rank(visibility) if plan.repl \
        else None
    repl_server = None
    if plan.repl:
        repl_server = min(r for r in sorted(visibility)
                          if repl_src_old in visibility[r])

    push_handles = []
    if dp is not None:
        engine = engine_for(dp)
        # pushes: fragments (and replicated arrays) this rank serves.
        # Issued as async Work handles on the ordered engine so disk reads
        # for rank d+1 overlap the wire to rank d; errors surface at the
        # wait_all below.
        for f in plan.frags:
            if sources[f.fid] != rank or f.new_rank == rank:
                continue

            def push(f=f):
                a = _read_fragment(reader_for(f.old_rank), f,
                                   verify, stats)
                stats._alloc(a.nbytes)
                try:
                    dp.send_array(f.new_rank, _frag_tag(step, f.fid), a)
                finally:
                    stats._free(a.nbytes)
                stats.frags_pushed += 1

            push_handles.append(engine.submit(push,
                                              label=f"reshard_push/{f.fid}"))
        if repl_server == rank:
            for rp in plan.repl:
                for d in sorted(visibility):
                    if d == rank or repl_src_old in visibility.get(d, ()):
                        continue

                    def push_repl(rp=rp, d=d):
                        a = reader_for(repl_src_old).read_range(
                            rp.path, 0,
                            int(np.prod(rp.shape, dtype=np.int64)),
                            rp.dtype)
                        dp.send_array(d, _repl_tag(step, rp.rid), a)

                    push_handles.append(engine.submit(
                        push_repl, label=f"reshard_push_repl/{rp.rid}"))

    # fills: this rank's owned fragments, disk or peer
    for f in plan.frags_for(rank):
        src = sources[f.fid]
        if src == rank:
            with _obs_fetch_span(rank, "disk"):
                a = _read_fragment(reader_for(f.old_rank), f, verify, stats)
            stats.frags_disk += 1
        else:
            a = _recv_fragment(dp, src, _frag_tag(step, f.fid), f,
                               deadline)
            stats.frags_peer += 1
        stats._alloc(a.nbytes)
        stats.frag_bytes_max = max(stats.frag_bytes_max, a.nbytes)
        if a.size != f.length or a.dtype != f.dtype:
            raise ReshardError(
                f"fragment {f.describe()} arrived as {a.size} x {a.dtype}, "
                f"expected {f.length} x {f.dtype}")
        out[f.path][f.new_off:f.new_off + f.length] = a
        stats._free(a.nbytes)
        stats.frags_total += 1
        stats.bytes_total += a.nbytes

    for rp in plan.repl:
        n = int(np.prod(rp.shape, dtype=np.int64))
        if repl_src_old in my_old:
            with _obs_fetch_span(rank, "disk"):
                a = reader_for(repl_src_old).read_range(rp.path, 0, n,
                                                        rp.dtype)
            if verify and rp.sha256:
                actual = hashlib.sha256(
                    np.ascontiguousarray(a).tobytes()).hexdigest()
                if actual != rp.sha256:
                    raise _digest_error(
                        f"replicated array {rp.path!r} digest mismatch "
                        f"(recorded {rp.sha256[:12]}…, actual "
                        f"{actual[:12]}…)")
        else:
            a = _recv_repl(dp, repl_server, _repl_tag(step, rp.rid), rp,
                           deadline)
        out[rp.path] = np.asarray(a, dtype=rp.dtype).reshape(rp.shape)

    if push_handles:
        # tpudlint: disable=TD004  # wait_all's positional IS the deadline
        wait_all(push_handles, max(0.1, deadline - _time.monotonic()))
    for r in readers.values():
        r.close()
    return out, stats


def _frag_tag(step: int, fid: int) -> str:
    return f"rshd/s{step}/f{fid}"


def _repl_tag(step: int, rid: int) -> str:
    return f"rshd/s{step}/r{rid}"


def _recv_fragment(dp, src: int, tag: str, f: _Frag, deadline: float):
    import time as _time
    if dp is None:
        raise ReshardError(
            f"fragment {f.describe()} lives only on rank {src}'s disk and "
            f"no data plane is available for the peer fetch")
    left = max(0.1, deadline - _time.monotonic())
    try:
        with _obs_fetch_span(src, "dataplane"):
            return dp.recv_array(src, tag, timeout=left)
    except TimeoutError as e:
        raise ReshardError(
            f"peer rank {src} did not deliver fragment {f.describe()} "
            f"within {left:.0f}s — peer dead or its disk read stalled"
        ) from e
    except ConnectionError as e:  # PeerGoneError names the peer
        raise ReshardError(
            f"peer rank {src} died while serving fragment "
            f"{f.describe()}: {e}") from e


def _recv_repl(dp, src: int, tag: str, rp: _Repl, deadline: float):
    import time as _time
    if dp is None:
        raise ReshardError(
            f"replicated array {rp.path!r} lives only on rank {src}'s "
            f"disk and no data plane is available for the peer fetch")
    left = max(0.1, deadline - _time.monotonic())
    try:
        with _obs_fetch_span(src, "dataplane"):
            return dp.recv_array(src, tag, timeout=left)
    except TimeoutError as e:
        raise ReshardError(
            f"peer rank {src} did not deliver replicated array "
            f"{rp.path!r} within {left:.0f}s") from e
    except ConnectionError as e:
        raise ReshardError(
            f"peer rank {src} died while serving replicated array "
            f"{rp.path!r}: {e}") from e


# ---------------------------------------------------------------------------
# template-driven restore (the TrainState entry point)
# ---------------------------------------------------------------------------

def load_manifest(root: str, step: int, old_rank: int) -> Optional[dict]:
    """The reshard manifest recorded in old ``old_rank``'s shard
    checkpoint at ``step`` (None when absent/unreadable)."""
    from .. import checkpoint
    p = os.path.join(checkpoint.shard_root(root, old_rank),
                     f"step_{step:08d}", "tree.json")
    try:
        with open(p) as f:
            return json.load(f).get("metadata", {}).get("reshard")
    except (OSError, json.JSONDecodeError):
        return None


def reshard_restore(root: str, template: Any, step: int,
                    shard: Tuple[int, int], *, manifest: Optional[dict] = None,
                    visibility: Optional[Dict[int, Set[int]]] = None,
                    dp=None, verify: bool = False,
                    timeout: Optional[float] = None):
    """Restore ``template``'s structure at ``shard=(rank, new_world)``
    from shard checkpoints saved at a *different* world size, fetching
    only the fragments this rank will own.  Returns ``(tree, stats)``.

    ``template`` must be the new-world state (e.g. a fresh
    ``ZeroOptimizer.init`` at world M): its ``meta`` subtrees — the new
    layout pins — are kept verbatim; every other path is assembled from
    old-shard fragments (sharded paths) or copied from one old rank
    (replicated paths).  ``visibility`` maps each new rank to the old
    shard roots it can read (default: everything locally visible, i.e.
    the shared-filesystem case, executed standalone); when any fragment
    needs a peer, every rank of the new gang must call this together with
    the *same* exchanged visibility map and a live ``dp``.
    """
    import jax

    from .. import checkpoint
    rank, new_world = int(shard[0]), int(shard[1])
    if manifest is None:
        vis_here = local_visibility(root)
        for o in sorted(vis_here["shards"]):
            if step in vis_here["shards"][o]:
                manifest = load_manifest(root, step, o)
                if manifest is not None:
                    break
    if manifest is None:
        raise ReshardError(
            f"no reshard manifest for step {step} under {root!r}: the "
            f"shard checkpoints predate elastic resharding (or none are "
            f"visible here) — re-save with this tpu_dist, or resume at "
            f"the original world size")
    plan = ReshardPlan(manifest, new_world)
    if visibility is None:
        here = {o for o, steps in local_visibility(root)["shards"].items()
                if steps.get(step) == plan.old_world}
        visibility = {r: set(here) for r in range(new_world)}

    flat_t = checkpoint._flatten(template)
    known = set(plan.new_len) | {rp.path for rp in plan.repl}
    meta_paths = {p for p in flat_t
                  if any(p.startswith(prefix + _META_SEG)
                         for prefix in plan.entries)}
    missing = sorted(set(flat_t) - known - meta_paths)
    extra = sorted(known - set(flat_t))
    if missing or extra:
        raise ReshardError(
            f"template does not match the shard manifest: template-only="
            f"{missing[:4]}{'…' if len(missing) > 4 else ''} manifest-only="
            f"{extra[:4]}{'…' if len(extra) > 4 else ''} — the parameter "
            f"structure changed since the checkpoint was saved")
    for path, lens in plan.new_len.items():
        t = flat_t[path]
        tshape = tuple(np.shape(t))
        if tshape != (lens[rank],):
            raise ReshardError(
                f"template path {path!r} has shape {tshape}, the world-"
                f"{new_world} plan owns {lens[rank]} elements — template "
                f"built at the wrong world or from different parameters")

    from ..obs import hooks as _hooks
    with _hooks.collective_span("reshard", path="dataplane"
                                if dp is not None else "disk"):
        arrays, stats = execute_plan(plan, rank=rank, root=root, step=step,
                                     visibility=visibility, dp=dp,
                                     verify=verify, timeout=timeout)

    out_leaves = []
    for path, tleaf in flat_t.items():  # _flatten preserves leaf order
        if path in meta_paths:
            out_leaves.append(tleaf)
            continue
        a = arrays[path]
        tdtype = np.dtype(getattr(tleaf, "dtype", np.result_type(tleaf)))
        if a.dtype != tdtype:
            raise ReshardError(
                f"resharded {path!r} has dtype {a.dtype}, template wants "
                f"{tdtype}")
        out_leaves.append(a.reshape(np.shape(tleaf)))
    treedef = jax.tree_util.tree_structure(template)
    return jax.tree_util.tree_unflatten(treedef, out_leaves), stats


# ---------------------------------------------------------------------------
# supervisor-facing summary
# ---------------------------------------------------------------------------

def plan_summary(manifest: dict, new_world: int) -> str:
    """Multi-line human summary of an N→``new_world`` plan — the
    supervisor prints this next to the last-known-positions table when it
    re-forms an elastic world, so the operator sees the redistribution
    before the new gang starts fetching."""
    plan = ReshardPlan(manifest, new_world)
    lines = [f"reshard plan: world {plan.old_world} -> {plan.new_world} "
             f"({len(plan.frags)} fragments, "
             f"{sum(f.length * f.dtype.itemsize for f in plan.frags)} B "
             f"+ {len(plan.repl)} replicated arrays)"]
    for d, n, b in plan.summary_rows():
        lines.append(f"  new rank {d}: {n} fragments, {b} B "
                     f"(disk when the old shard roots are visible, "
                     f"else peer fetch)")
    return "\n".join(lines)
