"""Stateless NN ops lowered straight to XLA (lax) primitives.

These are the TPU-native equivalents of the cuDNN/ATen kernels the reference
exercises through torch layers (conv/pool/relu/linear/cross-entropy at
/root/reference/mpspawn_dist.py:11-43,63).  Convolutions use NHWC/HWIO — the
layout XLA tiles best onto the TPU MXU — rather than torch's NCHW/OIHW.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "conv2d", "max_pool2d", "avg_pool2d", "relu", "linear", "dropout",
    "log_softmax", "softmax", "cross_entropy", "one_hot", "flatten",
    "batch_norm",
]

_IntOr2 = Union[int, Tuple[int, int]]


def _pair(v: _IntOr2) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else tuple(v)


def conv2d(x, w, b=None, stride: _IntOr2 = 1, padding: _IntOr2 = 0,
           dilation: _IntOr2 = 1, groups: int = 1):
    """2-D convolution, NHWC input, HWIO kernel.

    ``padding`` is symmetric-integer (torch semantics); strings "SAME"/"VALID"
    are also accepted.
    """
    sh, sw = _pair(stride)
    dh, dw = _pair(dilation)
    if isinstance(padding, str):
        pad = padding
    else:
        ph, pw = _pair(padding)
        pad = [(ph, ph), (pw, pw)]
    return _bias_add(
        lax.conv_general_dilated(
            x, w,
            window_strides=(sh, sw),
            padding=pad,
            rhs_dilation=(dh, dw),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=groups,
        ),
        b,
    )


def _bias_add(y, b):
    return y if b is None else y + b


def max_pool2d(x, kernel_size: _IntOr2, stride: Optional[_IntOr2] = None,
               padding: _IntOr2 = 0):
    """Max pooling over NHWC, floor mode (torch default)."""
    kh, kw = _pair(kernel_size)
    sh, sw = _pair(stride if stride is not None else kernel_size)
    ph, pw = _pair(padding)
    return lax.reduce_window(
        x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
        lax.max,
        window_dimensions=(1, kh, kw, 1),
        window_strides=(1, sh, sw, 1),
        padding=[(0, 0), (ph, ph), (pw, pw), (0, 0)],
    )


def avg_pool2d(x, kernel_size: _IntOr2, stride: Optional[_IntOr2] = None,
               padding: _IntOr2 = 0, count_include_pad: bool = True):
    kh, kw = _pair(kernel_size)
    sh, sw = _pair(stride if stride is not None else kernel_size)
    ph, pw = _pair(padding)
    # NOTE: the init value must be a Python scalar (not an Array) so JAX
    # recognizes the add-monoid and uses the differentiable window-sum path.
    summed = lax.reduce_window(
        x, 0.0, lax.add,
        window_dimensions=(1, kh, kw, 1),
        window_strides=(1, sh, sw, 1),
        padding=[(0, 0), (ph, ph), (pw, pw), (0, 0)],
    )
    if count_include_pad or (ph == 0 and pw == 0):
        # torch default: padded zeros count toward the denominator
        return summed / (kh * kw)
    counts = lax.reduce_window(
        jnp.ones(x.shape[:3] + (1,), x.dtype), 0.0, lax.add,
        window_dimensions=(1, kh, kw, 1),
        window_strides=(1, sh, sw, 1),
        padding=[(0, 0), (ph, ph), (pw, pw), (0, 0)],
    )
    return summed / counts


def relu(x):
    return jnp.maximum(x, 0)


def linear(x, w, b=None):
    """``x @ w + b`` with ``w`` shaped (in_features, out_features)."""
    return _bias_add(jnp.dot(x, w), b)


def dropout(x, rate: float, key, training: bool = True):
    """Inverted dropout: scale by 1/(1-rate) at train time, identity at eval."""
    if not training or rate == 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


def one_hot(labels, num_classes: int, dtype=jnp.float32):
    return jax.nn.one_hot(labels, num_classes, dtype=dtype)


def log_softmax(x, axis: int = -1):
    return jax.nn.log_softmax(x, axis=axis)


def softmax(x, axis: int = -1):
    return jax.nn.softmax(x, axis=axis)


def cross_entropy(logits, labels, reduction: str = "mean",
                  label_smoothing: float = 0.0, ignore_index: int = -100,
                  weight=None):
    """Softmax cross-entropy with integer labels (torch CrossEntropyLoss).

    Matches ``nn.CrossEntropyLoss()`` as used at
    /root/reference/mpspawn_dist.py:63 and /root/reference/example_mp.py:83,
    including the optional torch semantics:

    - ``label_smoothing``: blend ``(1-eps)*nll + eps*mean_c(-logp_c)``;
    - ``ignore_index``: rows with this label contribute nothing (and are
      excluded from the mean's denominator), torch's padding convention;
    - ``weight``: per-class rescaling; the mean divides by the summed
      weights of the counted rows, exactly as torch does.
    """
    labels = labels.astype(jnp.int32)
    keep = labels != ignore_index
    safe = jnp.where(keep, labels, 0)  # ignored rows must not index OOB
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    wy = (jnp.asarray(weight)[safe] if weight is not None
          else jnp.ones_like(nll))
    loss = nll * wy
    if label_smoothing:
        # torch formula: the target term scales by w[y], the uniform term
        # weights each class's -logp by its own w_c (NOT by w[y])
        wc = jnp.asarray(weight) if weight is not None else 1.0
        smooth = -(logp * wc).sum(axis=-1) / logits.shape[-1]
        loss = (1.0 - label_smoothing) * loss + label_smoothing * smooth
    wy = jnp.where(keep, wy, 0.0)
    loss = jnp.where(keep, loss, 0.0)
    if reduction == "mean":
        return loss.sum() / jnp.maximum(wy.sum(), jnp.finfo(loss.dtype).tiny)
    if reduction == "sum":
        return loss.sum()
    if reduction == "none":
        return loss
    raise ValueError(f"Unknown reduction {reduction!r}")


def flatten(x, start_dim: int = 1):
    return x.reshape(x.shape[:start_dim] + (-1,))


def batch_norm(x, mean, var, weight=None, bias=None, eps: float = 1e-5):
    """Normalize NHWC (or (N, C)) activations with given statistics."""
    inv = lax.rsqrt(var + eps)
    y = (x - mean) * inv
    if weight is not None:
        y = y * weight
    if bias is not None:
        y = y + bias
    return y
