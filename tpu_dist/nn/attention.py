"""Attention layers — the sequence-model substrate.

The reference has no attention (workloads are 28²/32² image classifiers,
SURVEY.md §5 long-context row: absent).  tpu_dist treats long-context as
first-class: these layers run dense single-device attention by default and
switch to **sequence-parallel** execution (ring attention or Ulysses
all-to-all, tpu_dist.parallel.ring_attention) when given a mesh axis, so the
same model scales from one chip to a pod slice with a constructor argument.

Functional core: :func:`scaled_dot_product_attention` (flash-style math is
XLA's job on TPU — it fuses and tiles the softmax; the explicitly blocked
variants live in the parallel package where the blocking crosses devices).
"""

from __future__ import annotations

import contextlib
import math
from typing import Optional

import jax
import jax.numpy as jnp

from . import functional as F
from .module import Module
from . import init as I

__all__ = ["scaled_dot_product_attention", "MultiheadSelfAttention",
           "attention_impl", "rotary_embed"]

_IMPL_OVERRIDE: list = []

# auto-dispatch crossover: below this sequence length the XLA-fused dense
# path beats the Pallas kernel (tile padding to the 128-lane grid plus
# kernel launch overhead dominate when the score matrix is small).
# Measured at the model level on v5e bf16: ViT-B at T=197 trains 1.54x
# faster dense (921.7 vs 596.8 img/s); GPT-2-small at T=2048 trains with
# flash 1.63x faster fwd+bwd (BENCH_EXTENDED flash row).
_FLASH_MIN_SEQ = 1024


@contextlib.contextmanager
def attention_impl(impl: str):
    """Trace-scoped default for :func:`scaled_dot_product_attention`'s
    ``impl`` — overrides the auto choice for every attention call traced
    inside the block (explicit per-call ``impl=`` still wins).  Used by
    ``make_gspmd_train_step`` to force ``"dense"``: a Pallas custom call
    can't be cut by XLA's SPMD partitioner, so under GSPMD-sharded jit the
    flash kernel must not be auto-dispatched (inside ``shard_map`` — the
    DDP and ring-attention paths — per-device flash is fine and used)."""
    _IMPL_OVERRIDE.append(impl)
    try:
        yield
    finally:
        _IMPL_OVERRIDE.pop()


def scaled_dot_product_attention(q, k, v, causal: bool = False,
                                 mask: Optional[jax.Array] = None,
                                 impl: Optional[str] = None):
    """Attention.  ``q,k,v``: (..., T, H, D) → (..., T, H, D).

    ``mask``: broadcastable to (..., H, Tq, Tk), True = keep.

    ``impl``: ``"dense"`` materializes the (Tq, Tk) scores (supports
    arbitrary masks); ``"flash"`` runs the O(T)-memory Pallas kernel
    (tpu_dist.ops.flash_attention; causal/no-mask only).  Default (None /
    ``"auto"``): flash on TPU backends when no arbitrary mask is given
    AND the sequence is at least ``_FLASH_MIN_SEQ`` (short sequences are
    faster through XLA's fused dense path — see the crossover note at the
    constant); dense elsewhere (the kernel runs interpreted off-TPU —
    correct but slower than XLA's fused dense path).
    """
    if impl in (None, "auto"):
        if _IMPL_OVERRIDE:
            impl = _IMPL_OVERRIDE[-1]
        else:
            flash_ok = (mask is None and jax.default_backend() == "tpu"
                        and max(q.shape[-3], k.shape[-3]) >= _FLASH_MIN_SEQ
                        and q.shape[:-3] == k.shape[:-3] == v.shape[:-3]
                        and k.shape == v.shape)  # no broadcast-KV kernel path
            impl = "flash" if flash_ok else "dense"
    if impl == "flash":
        if mask is not None:
            raise ValueError("impl='flash' supports causal masking only; "
                             "pass impl='dense' for arbitrary masks")
        from ..ops.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal)
    if impl != "dense":
        raise ValueError(f"Unknown attention impl {impl!r}")
    d = q.shape[-1]
    # (..., H, Tq, Tk)
    scores = jnp.einsum("...qhd,...khd->...hqk", q, k) / math.sqrt(d)
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        qpos = jnp.arange(tq)[:, None]
        kpos = jnp.arange(tk)[None, :]
        scores = jnp.where(kpos <= qpos, scores, -jnp.inf)
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("...hqk,...khd->...qhd", w, v)


def rotary_embed(x, positions, theta: float = 10000.0):
    """Rotate ``x`` (..., T, H, D) by per-position angles — RoPE (Su et al.,
    arXiv:2104.09864), rotate-half convention.  ``positions``: (T,) int
    absolute positions; attention scores then depend only on relative
    distance, so no learned position table is needed and contexts
    extrapolate.  Angles computed in f32, result cast back to x.dtype."""
    d = x.shape[-1]
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) * 2.0 / d)
    # positions may be (T,) — shared across the batch — or carry leading
    # batch dims, e.g. (B, T) during per-slot continuous-batching decode
    # where every cache slot sits at its own position
    ang = positions.astype(jnp.float32)[..., None] * freqs  # (..., T, half)
    # (..., T, 1, half) broadcasts against (..., T, H, half) for ANY number
    # of leading batch dims (including none)
    cos = jnp.cos(ang)[..., None, :]
    sin = jnp.sin(ang)[..., None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(
        jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                          axis=-1)
    return out.astype(x.dtype)


class MultiheadSelfAttention(Module):
    """Multi-head self-attention with fused QKV projection.

    ``sequence_axis``: when set (e.g. ``'seq'``) and traced inside
    ``shard_map`` over that mesh axis, the layer computes sequence-parallel
    attention — ``mode='ring'`` rotates KV blocks around the ring
    (ring attention), ``mode='ulysses'`` redistributes heads via all-to-all.
    Results equal the dense computation (tested in tests/test_ring_attention.py).
    """

    def __init__(self, embed_dim: int, num_heads: int, bias: bool = True,
                 causal: bool = False, sequence_axis: Optional[str] = None,
                 mode: str = "ring", attn_impl: Optional[str] = None,
                 rope: bool = False, rope_theta: float = 10000.0):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError(f"embed_dim {embed_dim} not divisible by "
                             f"num_heads {num_heads}")
        if mode not in ("ring", "ulysses"):
            raise ValueError(f"Unknown sequence-parallel mode {mode!r}")
        if rope and (embed_dim // num_heads) % 2:
            raise ValueError(f"rotary embeddings need an even head_dim, "
                             f"got {embed_dim // num_heads}")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.bias = bias
        self.causal = causal
        self.sequence_axis = sequence_axis
        self.mode = mode
        self.attn_impl = attn_impl  # None=auto | "dense" | "flash"
        self.rope = rope
        self.rope_theta = rope_theta

    def create_params(self, key):
        k1, k2 = jax.random.split(key)
        p = {"qkv_weight": I.torch_default_uniform(
                 k1, (self.embed_dim, 3 * self.embed_dim), self.embed_dim),
             "out_weight": I.torch_default_uniform(
                 k2, (self.embed_dim, self.embed_dim), self.embed_dim)}
        if self.bias:
            p["qkv_bias"] = jnp.zeros((3 * self.embed_dim,))
            p["out_bias"] = jnp.zeros((self.embed_dim,))
        return p

    def _qkv_proj(self, p, x):
        """The fused qkv projection — overridden by the int8 inference
        subclass (nn.quant.QuantMultiheadSelfAttention), which hoists its
        per-channel scale to the (tiny) output instead of dequantizing the
        (huge) weight."""
        return F.linear(x, p["qkv_weight"], p.get("qkv_bias"))

    def _out_proj(self, p, out):
        return F.linear(out, p["out_weight"], p.get("out_bias"))

    def forward(self, x):
        from .module import _ctx
        ctx = _ctx()
        p = ctx.get_params(self._path)
        b, t, _ = x.shape
        qkv = self._qkv_proj(p, x)
        qkv = qkv.reshape(b, t, 3, self.num_heads, self.head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if self.rope:
            # absolute positions of THESE tokens: the cache write index
            # during decode, the shard offset under sequence parallelism,
            # 0 otherwise.  Keys are cached post-rotation, so the decode
            # path needs no re-rotation of the prefix.
            if ctx.state is not None and self._path in ctx.state:
                offset = ctx.get_state(self._path)["index"]
            elif self.sequence_axis is not None:
                from jax import lax
                offset = lax.axis_index(self.sequence_axis) * t
            else:
                offset = 0
            off = jnp.asarray(offset)
            # vector offset = per-slot decode positions: (B,) -> (B, t)
            pos = (off[..., None] + jnp.arange(t) if off.ndim
                   else offset + jnp.arange(t))
            q = rotary_embed(q, pos, self.rope_theta)
            k = rotary_embed(k, pos, self.rope_theta)
        if ctx.state is not None and self._path in ctx.state:
            # autoregressive decode: a KV cache was allocated for this layer
            # (TransformerLM.init_cache) — append this call's K/V at the
            # write index and attend over the cached prefix
            out = self._decode(ctx, q, k, v)
        elif self.sequence_axis is not None:
            from ..parallel.ring_attention import (ring_self_attention,
                                                   ulysses_self_attention)
            fn = (ring_self_attention if self.mode == "ring"
                  else ulysses_self_attention)
            out = fn(q, k, v, axis_name=self.sequence_axis,
                     causal=self.causal, impl=self.attn_impl)
        else:
            out = scaled_dot_product_attention(q, k, v, causal=self.causal,
                                               impl=self.attn_impl)
        out = out.reshape(b, t, self.embed_dim)
        return self._out_proj(p, out)

    @staticmethod
    def _quantize_kv(x):
        """Symmetric per-(token, head) int8: x (B, t, H, D) -> (q int8,
        scale (B, t, H) f32).  amax over the head dim only, so one outlier
        token/head cannot flatten every other's resolution."""
        xf = x.astype(jnp.float32)
        amax = jnp.max(jnp.abs(xf), axis=-1)
        scale = jnp.where(amax > 0, amax / 127.0, 1.0)
        q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127
                     ).astype(jnp.int8)
        return q, scale

    def _decode(self, ctx, q, k, v):
        """Cached attention step.  q/k/v: (B, t, H, D) with t the number of
        new positions (t>1 = prefill, t=1 = one decode step).  The cache is
        state ``{"k": (B, Tmax, H, D), "v": ..., "index": ()}``; new keys
        land at [index, index+t) and queries see cache positions <= their
        own global position (cache slots past the index are masked, so the
        zeros there never contribute).

        With an int8 cache (``init_cache(dtype=jnp.int8)``) K/V are stored
        quantized with per-(token, head) symmetric scales and the scales are
        HOISTED out of both matmuls — scores multiply by ``k_scale`` on the
        (t, Tmax) tile and probabilities by ``v_scale`` before the PV
        matmul — so the big cache tensors cross HBM as int8 and are
        converted in the MXU tile load, never materialized dequantized.
        Long-context decode reads the cache, not the weights; halving its
        bytes halves the bandwidth bill where it dominates."""
        st = ctx.get_state(self._path)
        index = jnp.asarray(st["index"])
        t = q.shape[1]
        int8_cache = st["k"].dtype == jnp.int8
        if int8_cache:
            kq, ks = self._quantize_kv(k)
            vq, vs = self._quantize_kv(v)
        if index.ndim:
            # per-slot write positions (continuous batching, serve/engine):
            # index is (B,) — every cache slot appends at its OWN position
            # and masks to its own prefix.  Rows whose slot is free write
            # garbage the next prefill fully overwrites (and mask away).
            b = q.shape[0]
            rows = jnp.arange(b)[:, None]                     # (B, 1)
            cols = index[:, None] + jnp.arange(t)[None, :]    # (B, t)
            if int8_cache:
                st = dict(st,
                          k=st["k"].at[rows, cols].set(kq),
                          v=st["v"].at[rows, cols].set(vq),
                          k_scale=st["k_scale"].at[rows, cols].set(ks),
                          v_scale=st["v_scale"].at[rows, cols].set(vs))
            else:
                st = dict(st,
                          k=st["k"].at[rows, cols].set(
                              k.astype(st["k"].dtype)),
                          v=st["v"].at[rows, cols].set(
                              v.astype(st["v"].dtype)))
            ctx.put_state(self._path, dict(st, index=index + t))
            tmax = st["k"].shape[1]
            kpos = jnp.arange(tmax)
            # (B, 1, t, Tmax): per-row causal+unwritten mask, broadcast
            # over heads
            mask = (kpos[None, None, :] <= cols[:, :, None])[:, None]
        else:
            if int8_cache:
                st = dict(
                    st,
                    k=jax.lax.dynamic_update_slice(st["k"], kq,
                                                   (0, index, 0, 0)),
                    v=jax.lax.dynamic_update_slice(st["v"], vq,
                                                   (0, index, 0, 0)),
                    k_scale=jax.lax.dynamic_update_slice(
                        st["k_scale"], ks, (0, index, 0)),
                    v_scale=jax.lax.dynamic_update_slice(
                        st["v_scale"], vs, (0, index, 0)))
            else:
                st = dict(
                    st,
                    k=jax.lax.dynamic_update_slice(
                        st["k"], k.astype(st["k"].dtype), (0, index, 0, 0)),
                    v=jax.lax.dynamic_update_slice(
                        st["v"], v.astype(st["v"].dtype), (0, index, 0, 0)))
            ctx.put_state(self._path, dict(st, index=index + t))
            tmax = st["k"].shape[1]
            qpos = index + jnp.arange(t)[:, None]           # (t, 1) global
            kpos = jnp.arange(tmax)[None, :]                # (1, Tmax)
            mask = kpos <= qpos                             # causal + unwritten
        if not int8_cache:
            return scaled_dot_product_attention(
                q, st["k"].astype(q.dtype), st["v"].astype(q.dtype),
                mask=mask, impl="dense")
        # hoisted-scale dense attention over the int8 cache
        sm = 1.0 / math.sqrt(self.head_dim)
        s = jnp.einsum("bthd,bshd->bhts", q, st["k"].astype(q.dtype),
                       preferred_element_type=jnp.float32)
        s = s * sm * jnp.transpose(st["k_scale"], (0, 2, 1))[:, :, None, :]
        s = jnp.where(mask if mask.ndim == 4 else mask[None, None],
                      s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        pv = (p * jnp.transpose(st["v_scale"], (0, 2, 1))[:, :, None, :]
              ).astype(q.dtype)
        return jnp.einsum("bhts,bshd->bthd", pv, st["v"].astype(q.dtype),
                          preferred_element_type=jnp.float32).astype(q.dtype)

    def init_cache(self, batch: int, max_len: int, dtype=jnp.float32):
        """Per-layer KV cache entry (used via TransformerLM.init_cache).
        ``dtype=jnp.int8`` allocates the quantized cache layout: int8 K/V
        plus float32 per-(token, head) scales (see :meth:`_decode`)."""
        cache = {"k": jnp.zeros((batch, max_len, self.num_heads,
                                 self.head_dim), dtype),
                 "v": jnp.zeros((batch, max_len, self.num_heads,
                                 self.head_dim), dtype),
                 "index": jnp.zeros((), jnp.int32)}
        if jnp.dtype(dtype) == jnp.int8:
            cache["k_scale"] = jnp.zeros((batch, max_len, self.num_heads),
                                         jnp.float32)
            cache["v_scale"] = jnp.zeros((batch, max_len, self.num_heads),
                                         jnp.float32)
        return cache

    def __repr__(self):
        sp = (f", sequence_axis={self.sequence_axis!r}, mode={self.mode!r}"
              if self.sequence_axis else "")
        return (f"MultiheadSelfAttention({self.embed_dim}, "
                f"heads={self.num_heads}, causal={self.causal}{sp})")
