"""Weight-only int8 quantization for inference — decode bandwidth relief.

Autoregressive decode is HBM-bandwidth-bound: every generated token reads
every parameter once, so the ceiling is bandwidth / bytes-per-token (the
decode bench records ~310 GB/s of bf16 weight reads on v5e).  Weight-only
int8 halves the bytes: :class:`QuantLinear` stores the weight as int8
with a float32 **per-output-channel symmetric scale** (``w ≈ q * scale``)
and dequantizes on the fly — XLA fuses the dequant into the matmul's
weight load, so only int8 ever crosses HBM.  Activations, bias, and the
matmul itself stay in the activation dtype (bf16 MXU), which is what
"weight-only" buys: no activation-quantization error, no calibration
data needed.

:func:`quantize_linear_weights` converts a built model + trained params
in one call (swaps every ``nn.Linear`` for a ``QuantLinear`` and rewrites
the params tree); the quantized model drives the same ``apply`` /
``generate`` code paths.  Training is out of scope — quantize AFTER
training, for serving (torch analogue:
``torch.ao.quantization.quantize_dynamic(model, {nn.Linear}, qint8)``).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from .module import Module, _ctx
from .layers import Embedding, Linear
from .attention import MultiheadSelfAttention
from . import functional as F

__all__ = ["QuantEmbedding", "QuantLinear", "QuantMultiheadSelfAttention",
           "quantize_linear_weights"]


class QuantLinear(Module):
    """Inference-only Linear with int8 weight + per-out-channel scale.

    Params: ``q_weight`` (in, out) int8, ``scale`` (out,) float32,
    optional ``bias``.  Built by :func:`quantize_linear_weights`;
    ``create_params`` exists only so ``init``/``eval_shape`` work on a
    converted topology (identity-scale zeros — meaningless to train).
    """

    def __init__(self, in_features: int, out_features: int,
                 bias: bool = True):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.use_bias = bias

    def create_params(self, key):
        p = {"q_weight": jnp.zeros((self.in_features, self.out_features),
                                   jnp.int8),
             "scale": jnp.ones((self.out_features,), jnp.float32)}
        if self.use_bias:
            p["bias"] = jnp.zeros((self.out_features,))
        return p

    def forward(self, x):
        p = _ctx().get_params(self._path)
        # Per-OUT-channel scale commutes with the contraction, so hoist it
        # past the matmul: the (in, out) weight crosses HBM as int8 and is
        # converted in the MXU tile load; the scale multiplies only the
        # (..., out) output (measured: the pre-multiplied form materialized
        # a dequantized bf16 weight and gave back ~40% of the byte win).
        y = F.linear(x, p["q_weight"].astype(x.dtype))
        y = y * p["scale"].astype(x.dtype)
        if "bias" in p:
            y = y + p["bias"].astype(x.dtype)
        return y

    def __repr__(self):
        return (f"QuantLinear(in={self.in_features}, "
                f"out={self.out_features}, int8)")


class QuantMultiheadSelfAttention(MultiheadSelfAttention):
    """Inference-only MHSA with int8 qkv/out projection weights.

    Same forward as :class:`~tpu_dist.nn.MultiheadSelfAttention` — only
    the projection-weight fetch differs (dequant fused into the matmul).
    Params: ``qkv_q``/``qkv_scale``, ``out_q``/``out_scale`` (+ biases).
    Built by :func:`quantize_linear_weights` with ``attention=True``.
    """

    def create_params(self, key):
        d = self.embed_dim
        p = {"qkv_q": jnp.zeros((d, 3 * d), jnp.int8),
             "qkv_scale": jnp.ones((3 * d,), jnp.float32),
             "out_q": jnp.zeros((d, d), jnp.int8),
             "out_scale": jnp.ones((d,), jnp.float32)}
        if self.bias:
            p["qkv_bias"] = jnp.zeros((3 * d,))
            p["out_bias"] = jnp.zeros((d,))
        return p

    def _qkv_proj(self, p, x):
        # hoisted per-out-channel scale, same reasoning as QuantLinear
        y = F.linear(x, p["qkv_q"].astype(x.dtype))
        y = y * p["qkv_scale"].astype(x.dtype)
        if "qkv_bias" in p:
            y = y + p["qkv_bias"].astype(x.dtype)
        return y

    def _out_proj(self, p, out):
        y = F.linear(out, p["out_q"].astype(out.dtype))
        y = y * p["out_scale"].astype(out.dtype)
        if "out_bias" in p:
            y = y + p["out_bias"].astype(out.dtype)
        return y

    def __repr__(self):
        return (f"QuantMultiheadSelfAttention({self.embed_dim}, "
                f"heads={self.num_heads}, int8)")


class QuantEmbedding(Module):
    """Inference-only embedding with int8 rows + per-row scale.

    Decode gathers ONE row per token, so this buys model-size (HBM
    capacity), not decode bandwidth — the 50 MB bf16 table of a
    GPT-2-small-shaped LM was ~31% of quantized-model bytes while
    contributing ~1.5 KB/token of actual read traffic.  Measured caveat
    (v5e, interleaved A/B): int8 table gathers lower POORLY inside the
    decode loop — batch-1 decode ran 1.38x slower with the int8 table
    (0.328 vs 0.238 ms/token), so use this for capacity-constrained
    serving, and keep bf16 tables when decode latency rules.  Params:
    ``q_weight`` (V, d) int8, ``scale`` (V,) float32 (symmetric per row).
    """

    def __init__(self, num_embeddings: int, embedding_dim: int):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim

    def create_params(self, key):
        return {"q_weight": jnp.zeros((self.num_embeddings,
                                       self.embedding_dim), jnp.int8),
                "scale": jnp.ones((self.num_embeddings,), jnp.float32)}

    def forward(self, idx):
        p = _ctx().get_params(self._path)
        rows = jnp.take(p["q_weight"], idx, axis=0)
        scale = jnp.take(p["scale"], idx, axis=0)
        # output dtype follows the scale leaf (f32 as quantized; a model
        # cast to bf16 for serving carries bf16 scales and emits bf16)
        return rows.astype(scale.dtype) * scale[..., None]

    def __repr__(self):
        return (f"QuantEmbedding({self.num_embeddings}, "
                f"{self.embedding_dim}, int8)")


def _quantize_weight(w) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric per-output-channel int8: w (in, out) ≈ q * scale[out]."""
    w = np.asarray(w, np.float32)
    amax = np.abs(w).max(axis=0)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return q, scale


def quantize_linear_weights(model: Module, params: dict,
                            skip: Optional[Sequence[str]] = None,
                            attention: bool = False,
                            embedding: bool = False,
                            ) -> Tuple[Module, dict]:
    """Swap every ``nn.Linear`` in ``model`` for :class:`QuantLinear` and
    quantize its weights in ``params``; with ``attention=True`` also swap
    every ``nn.MultiheadSelfAttention`` for
    :class:`QuantMultiheadSelfAttention` (int8 qkv/out projections), and
    with ``embedding=True`` every ``nn.Embedding`` for
    :class:`QuantEmbedding` (int8 rows — a model-size win; decode reads
    one row per token either way).

    Mutates ``model`` in place (topology objects hold no arrays — the
    same contract as ``convert_sync_batchnorm``) and returns ``(model,
    new_params)``.  ``skip``: param paths to leave in full precision
    (e.g. a numerically sensitive head).  Norms and convs are untouched.
    """
    skip = set(skip or ())
    model._assign_paths()
    # one quantized module per unique OBJECT: weight-tied modules (the
    # same module registered under several attributes) keep sharing one
    # module — and therefore one params path — after conversion.
    # "weight"/"qkv_weight" in params[path] is the idempotency check
    # (already-converted paths carry q_* leaves instead).  Path "" is the
    # root module itself — it has no parent to swap it on; wrap it.
    q_for: dict = {}
    new_params = dict(params)
    for path, mod in model.named_modules():
        if not path or path in skip or path not in params:
            continue
        if isinstance(mod, Linear) and "weight" in params[path]:
            q_for[id(mod)] = QuantLinear(mod.in_features, mod.out_features,
                                         bias=mod.use_bias)
            q, scale = _quantize_weight(params[path]["weight"])
            leaf = {"q_weight": jnp.asarray(q), "scale": jnp.asarray(scale)}
            if "bias" in params[path]:
                leaf["bias"] = params[path]["bias"]
            new_params[path] = leaf
        elif (attention and isinstance(mod, MultiheadSelfAttention)
              and "qkv_weight" in params[path]):
            q_mod = QuantMultiheadSelfAttention(
                mod.embed_dim, mod.num_heads, bias=mod.bias,
                causal=mod.causal, sequence_axis=mod.sequence_axis,
                mode=mod.mode, attn_impl=mod.attn_impl, rope=mod.rope,
                rope_theta=mod.rope_theta)
            q_for[id(mod)] = q_mod
            leaf = {}
            for src, dst in (("qkv_weight", "qkv"), ("out_weight", "out")):
                q, scale = _quantize_weight(params[path][src])
                leaf[f"{dst}_q"] = jnp.asarray(q)
                leaf[f"{dst}_scale"] = jnp.asarray(scale)
            for b in ("qkv_bias", "out_bias"):
                if b in params[path]:
                    leaf[b] = params[path][b]
            new_params[path] = leaf
        elif (embedding and isinstance(mod, Embedding)
              and "weight" in params[path]):
            q_for[id(mod)] = QuantEmbedding(mod.num_embeddings,
                                            mod.embedding_dim)
            # rows are the output channels here: transpose into the
            # (in, out) convention _quantize_weight scales over
            q, scale = _quantize_weight(np.asarray(params[path]["weight"]).T)
            new_params[path] = {"q_weight": jnp.asarray(q.T),
                                "scale": jnp.asarray(scale)}
    # swap EVERY registration of each converted object (ties included)
    for _, parent in model.named_modules():
        for name, child in list(parent._modules.items()):
            if id(child) in q_for:
                setattr(parent, name, q_for[id(child)])
    model._assign_paths()
    return model, new_params
