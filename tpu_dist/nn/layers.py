"""Parameterized layers (TPU-native equivalents of the torch layers the
reference uses: Conv2d/MaxPool2d/ReLU/Linear/Dropout at
/root/reference/mpspawn_dist.py:11-43, BatchNorm inside torchvision ResNet-18
at /root/reference/example_mp.py:50).

Layouts are TPU-first: activations NHWC, conv kernels HWIO, linear weights
(in, out).  Default initialization matches torch's defaults in distribution.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from . import functional as F
from . import init as init_lib
from .module import Module, _ctx

__all__ = [
    "Linear", "Conv2d", "MaxPool2d", "AvgPool2d", "AdaptiveAvgPool2d",
    "ReLU", "Flatten", "Dropout", "BatchNorm2d", "Identity",
    "Embedding", "LayerNorm", "GELU",
]

_IntOr2 = Union[int, Tuple[int, int]]


class Linear(Module):
    def __init__(self, in_features: int, out_features: int, bias: bool = True):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.use_bias = bias

    def create_params(self, key):
        kw, kb = jax.random.split(key)
        p = {"weight": init_lib.torch_default_uniform(
            kw, (self.in_features, self.out_features), self.in_features)}
        if self.use_bias:
            p["bias"] = init_lib.torch_default_uniform(
                kb, (self.out_features,), self.in_features)
        return p

    def forward(self, x):
        p = _ctx().get_params(self._path)
        return F.linear(x, p["weight"], p.get("bias"))

    def __repr__(self):
        return f"Linear(in={self.in_features}, out={self.out_features})"


class Conv2d(Module):
    def __init__(self, in_channels: int, out_channels: int,
                 kernel_size: _IntOr2, stride: _IntOr2 = 1,
                 padding: _IntOr2 = 0, dilation: _IntOr2 = 1,
                 groups: int = 1, bias: bool = True):
        super().__init__()
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.use_bias = bias

    def create_params(self, key):
        kh, kw_ = self.kernel_size
        shape = (kh, kw_, self.in_channels // self.groups, self.out_channels)
        fan_in = kh * kw_ * (self.in_channels // self.groups)
        k1, k2 = jax.random.split(key)
        p = {"weight": init_lib.torch_default_uniform(k1, shape, fan_in)}
        if self.use_bias:
            p["bias"] = init_lib.torch_default_uniform(k2, (self.out_channels,), fan_in)
        return p

    def forward(self, x):
        p = _ctx().get_params(self._path)
        return F.conv2d(x, p["weight"], p.get("bias"), stride=self.stride,
                        padding=self.padding, dilation=self.dilation,
                        groups=self.groups)

    def __repr__(self):
        return (f"Conv2d({self.in_channels}, {self.out_channels}, "
                f"kernel={self.kernel_size}, stride={self.stride}, "
                f"padding={self.padding})")


class MaxPool2d(Module):
    def __init__(self, kernel_size: _IntOr2, stride: Optional[_IntOr2] = None,
                 padding: _IntOr2 = 0):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding

    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding)

    def __repr__(self):
        return f"MaxPool2d(kernel={self.kernel_size}, stride={self.stride})"


class AvgPool2d(Module):
    def __init__(self, kernel_size: _IntOr2, stride: Optional[_IntOr2] = None,
                 padding: _IntOr2 = 0):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding

    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding)


class AdaptiveAvgPool2d(Module):
    """Average-pool NHWC to a fixed (h, w) output (torchvision ResNet head)."""

    def __init__(self, output_size: _IntOr2 = 1):
        super().__init__()
        self.output_size = (output_size, output_size) if isinstance(output_size, int) else tuple(output_size)

    def forward(self, x):
        oh, ow = self.output_size
        n, h, w, c = x.shape
        if h % oh == 0 and w % ow == 0:
            return F.avg_pool2d(x, (h // oh, w // ow))
        # general torch bin rule — output cell i averages input rows
        # [floor(i*H/out), ceil((i+1)*H/out)); covers non-divisible shapes
        # AND output > input (e.g. torchvision VGG pooling 1x1 -> 7x7 on
        # CIFAR inputs).  Static Python loop: oh + ow row/col reductions,
        # fixed at trace time, fused by XLA.
        rows = jnp.stack([
            x[:, (i * h) // oh: -((-(i + 1) * h) // oh)].mean(axis=1)
            for i in range(oh)], axis=1)                     # (n, oh, w, c)
        return jnp.stack([
            rows[:, :, (j * w) // ow: -((-(j + 1) * w) // ow)].mean(axis=2)
            for j in range(ow)], axis=2)                     # (n, oh, ow, c)


class ReLU(Module):
    def forward(self, x):
        return F.relu(x)

    def __repr__(self):
        return "ReLU()"


class Identity(Module):
    def forward(self, x):
        return x


class Flatten(Module):
    def __init__(self, start_dim: int = 1):
        super().__init__()
        self.start_dim = start_dim

    def forward(self, x):
        return F.flatten(x, self.start_dim)


class Dropout(Module):
    """Inverted dropout; active only in training mode (requires apply rng=).

    Note the reference ConvNet *defines* ``nn.Dropout(p=0.5)`` but never calls
    it in forward (/root/reference/mpspawn_dist.py:31 — dead layer); the ported
    ConvNet reproduces that faithfully.
    """

    def __init__(self, p: float = 0.5):
        super().__init__()
        self.p = p

    def forward(self, x):
        ctx = _ctx()
        if not ctx.training or self.p == 0.0:
            return x
        return F.dropout(x, self.p, ctx.next_rng(), training=True)

    def __repr__(self):
        return f"Dropout(p={self.p})"


class BatchNorm2d(Module):
    """Batch normalization over NHWC with torch semantics.

    - training: normalize with biased batch stats; update running stats with
      *unbiased* variance, ``running = (1-momentum)*running + momentum*batch``.
    - eval: normalize with running stats.
    - ``axis_name``: if set and traced inside ``shard_map``/``pmap`` with that
      mesh axis, batch statistics are ``pmean``-ed across replicas (SyncBN).
      Default ``None`` matches DDP's per-replica (non-synced) BatchNorm — the
      reference's ResNet-18 behavior under DDP (/root/reference/example_mp.py:53
      wraps without SyncBatchNorm conversion).
    """

    def __init__(self, num_features: int, eps: float = 1e-5,
                 momentum: float = 0.1, affine: bool = True,
                 track_running_stats: bool = True,
                 axis_name: Optional[str] = None):
        super().__init__()
        self.num_features = num_features
        self.eps = eps
        self.momentum = momentum
        self.affine = affine
        self.track_running_stats = track_running_stats
        self.axis_name = axis_name

    def create_params(self, key):
        if not self.affine:
            return None
        return {"weight": jnp.ones((self.num_features,)),
                "bias": jnp.zeros((self.num_features,))}

    def create_state(self):
        if not self.track_running_stats:
            return None
        return {"mean": jnp.zeros((self.num_features,)),
                "var": jnp.ones((self.num_features,))}

    def forward(self, x):
        ctx = _ctx()
        p = ctx.get_params(self._path) if self.affine else {}
        reduce_axes = tuple(range(x.ndim - 1))  # all but channel
        if ctx.training or not self.track_running_stats:
            mean = x.mean(reduce_axes)
            mean2 = (x * x).mean(reduce_axes)
            if self.axis_name is not None:
                mean = lax.pmean(mean, self.axis_name)
                mean2 = lax.pmean(mean2, self.axis_name)
            var = mean2 - mean * mean
            if self.track_running_stats:
                st = ctx.get_state(self._path)
                n = x.size // x.shape[-1]
                if self.axis_name is not None:
                    n = n * lax.psum(1, self.axis_name)
                unbiased = var * (n / max(n - 1, 1))
                m = self.momentum
                ctx.put_state(self._path, {
                    "mean": (1 - m) * st["mean"] + m * mean,
                    "var": (1 - m) * st["var"] + m * unbiased,
                })
        else:
            st = ctx.get_state(self._path)
            mean, var = st["mean"], st["var"]
        return F.batch_norm(x, mean, var, p.get("weight"), p.get("bias"),
                            self.eps)

    def __repr__(self):
        return f"BatchNorm2d({self.num_features})"


class Embedding(Module):
    """Token embedding lookup (torch ``nn.Embedding`` parity; N(0,1) init).

    Divergence from torch: out-of-range indices are CLAMPED to the last row
    (XLA gather semantics under jit — no device-side bounds trap exists on
    TPU), where torch raises IndexError.  Validate token ids host-side when
    the vocabulary mapping is untrusted.
    """

    def __init__(self, num_embeddings: int, embedding_dim: int):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim

    def create_params(self, key):
        return {"weight": init_lib.normal(
            key, (self.num_embeddings, self.embedding_dim), std=1.0)}

    def forward(self, idx):
        w = _ctx().get_params(self._path)["weight"]
        return jnp.take(w, idx, axis=0)

    def __repr__(self):
        return f"Embedding({self.num_embeddings}, {self.embedding_dim})"


class LayerNorm(Module):
    """Layer normalization over the trailing dimension(s)
    (torch ``nn.LayerNorm`` parity: biased variance, affine by default)."""

    def __init__(self, normalized_shape, eps: float = 1e-5,
                 elementwise_affine: bool = True):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.eps = eps
        self.elementwise_affine = elementwise_affine

    def create_params(self, key):
        if not self.elementwise_affine:
            return None
        return {"weight": jnp.ones(self.normalized_shape),
                "bias": jnp.zeros(self.normalized_shape)}

    def forward(self, x):
        axes = tuple(range(x.ndim - len(self.normalized_shape), x.ndim))
        mean = x.mean(axes, keepdims=True)
        var = ((x - mean) ** 2).mean(axes, keepdims=True)
        y = (x - mean) * lax.rsqrt(var + self.eps)
        if self.elementwise_affine:
            p = _ctx().get_params(self._path)
            y = y * p["weight"] + p["bias"]
        return y

    def __repr__(self):
        return f"LayerNorm({self.normalized_shape})"


class RMSNorm(Module):
    """Root-mean-square normalization (torch ``nn.RMSNorm`` parity;
    Zhang & Sennrich, arXiv:1910.07467) — no mean subtraction, no bias,
    the LLaMA-family default.  Statistics in f32, result in x.dtype."""

    def __init__(self, normalized_shape, eps: float = 1e-6,
                 elementwise_affine: bool = True):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.eps = eps
        self.elementwise_affine = elementwise_affine

    def create_params(self, key):
        if not self.elementwise_affine:
            return None
        return {"weight": jnp.ones(self.normalized_shape)}

    def forward(self, x):
        axes = tuple(range(x.ndim - len(self.normalized_shape), x.ndim))
        xf = x.astype(jnp.float32)
        y = xf * lax.rsqrt(jnp.mean(jnp.square(xf), axes, keepdims=True)
                           + self.eps)
        y = y.astype(x.dtype)
        if self.elementwise_affine:
            w = _ctx().get_params(self._path)["weight"]
            y = y * w.astype(x.dtype)  # keep the promised output dtype
        return y

    def __repr__(self):
        return f"RMSNorm({self.normalized_shape})"


class GELU(Module):
    """Gaussian error linear unit (exact erf form, torch default)."""

    def forward(self, x):
        return jax.nn.gelu(x, approximate=False)

    def __repr__(self):
        return "GELU()"
