"""tpu_dist.nn — functional module system + layers (L2 of the layer map,
SURVEY.md §1)."""

from . import functional, init
from .layers import (AdaptiveAvgPool2d, AvgPool2d, BatchNorm2d, Conv2d,
                     Dropout, Flatten, Identity, Linear, MaxPool2d, ReLU)
from .loss import CrossEntropyLoss
from .module import Module, Sequential

__all__ = [
    "Module", "Sequential", "functional", "init",
    "Linear", "Conv2d", "MaxPool2d", "AvgPool2d", "AdaptiveAvgPool2d",
    "ReLU", "Flatten", "Dropout", "BatchNorm2d", "Identity",
    "CrossEntropyLoss",
]
