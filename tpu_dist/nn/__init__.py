"""tpu_dist.nn — functional module system + layers (L2 of the layer map,
SURVEY.md §1)."""

from . import functional, init
from .attention import (MultiheadSelfAttention, attention_impl,
                        rotary_embed, scaled_dot_product_attention)
from .layers import (AdaptiveAvgPool2d, AvgPool2d, BatchNorm2d, Conv2d,
                     Dropout, Embedding, Flatten, GELU, Identity, LayerNorm,
                     Linear, MaxPool2d, ReLU, RMSNorm)
from .loss import CrossEntropyLoss
from .moe import MoELayer
from .module import Module, Remat, Sequential, run_capturing_state
from .quant import (QuantEmbedding, QuantLinear,
                    QuantMultiheadSelfAttention, quantize_linear_weights)

__all__ = [
    "Module", "Remat", "Sequential", "run_capturing_state",
    "functional", "init",
    "Linear", "Conv2d", "MaxPool2d", "AvgPool2d", "AdaptiveAvgPool2d",
    "ReLU", "Flatten", "Dropout", "BatchNorm2d", "Identity",
    "Embedding", "LayerNorm", "RMSNorm", "GELU",
    "MultiheadSelfAttention", "scaled_dot_product_attention",
    "attention_impl", "MoELayer", "rotary_embed",
    "CrossEntropyLoss",
    "QuantEmbedding", "QuantLinear", "QuantMultiheadSelfAttention",
    "quantize_linear_weights",
]
