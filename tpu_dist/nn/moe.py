"""Mixture-of-Experts layer — expert parallelism the GSPMD way.

No reference counterpart (SURVEY.md §2: the reference is data-parallel image
classifiers); this exists because tpu_dist treats the 'expert' mesh axis as
first-class alongside dp/tp/pp/sp, and the driver's multi-chip dry-run
exercises an ep sharding.

TPU-first design — routing as dense einsums, not gather/scatter:

- Expert FFN weights are **stacked** on a leading expert axis: ``w1 (E, d,
  h)``, ``w2 (E, h, d)``.  Under expert parallelism that axis is sharded
  ``P('expert')`` (see :data:`MOE_EP_RULES`) and every expert matmul is a
  batched einsum the MXU tiles directly.
- Token routing is the GShard/Switch capacity formulation: top-k gating
  probabilities become dense **dispatch/combine tensors** ``(N, E, C)``
  built from one-hots and a cumsum position assignment — static shapes, no
  data-dependent gather, so the whole layer jits and the XLA SPMD
  partitioner inserts the token all-to-alls purely from the shardings
  (einsum ``nec,nd->ecd`` with the output sharded over 'expert' IS the
  dispatch all-to-all).  Tokens beyond an expert's capacity ``C =
  ceil(k*N/E * capacity_factor)`` are dropped — their combine weights are
  zero, so they pass through the surrounding residual unchanged.
- The Switch **load-balancing auxiliary loss** ``E * sum_e f_e * p_e``
  (fraction of tokens routed to e times mean router probability of e) is
  published through the module-state mechanism (``state["aux_loss"]``):
  it is a traced value in ``new_state``, so a trainer that adds
  ``coeff * new_state[path]["aux_loss"]`` to its objective gets gradients
  through the router exactly as if the layer had returned it.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from .module import Module
from . import init as init_lib

__all__ = ["MoELayer"]


class MoELayer(Module):
    """Top-k routed mixture of expert FFNs (drop-in for a transformer MLP).

    Args:
        dim: model width.
        num_experts: E, the expert count (shard over 'expert' for ep).
        hidden: expert FFN hidden width (default ``4 * dim``).
        top_k: experts consulted per token (1 = Switch, 2 = GShard default).
        capacity_factor: slack multiplier on the perfectly-balanced
            per-expert token budget; tokens past capacity are dropped.
        normalize_gates: renormalize the k selected gate values to sum to 1
            (GShard semantics); off uses raw softmax probabilities (Switch).
    """

    def __init__(self, dim: int, num_experts: int, hidden: int = 0,
                 top_k: int = 2, capacity_factor: float = 1.25,
                 normalize_gates: bool = True):
        super().__init__()
        if num_experts < 2:
            raise ValueError(f"num_experts must be >= 2, got {num_experts}")
        if not 1 <= top_k <= num_experts:
            raise ValueError(f"top_k {top_k} not in [1, {num_experts}]")
        self.dim = dim
        self.num_experts = num_experts
        self.hidden = hidden or 4 * dim
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.normalize_gates = normalize_gates

    def create_params(self, key):
        kr, k1, k2 = jax.random.split(key, 3)
        e, d, h = self.num_experts, self.dim, self.hidden

        def expert_uniform(k, shape, fan_in):
            # kaiming_uniform per expert: stacked (E, in, out) weights get
            # the same bound a (in, out) Linear would (init.calculate_fan
            # only knows 2-D/4-D shapes)
            bound = math.sqrt(6.0 / fan_in)
            return init_lib.uniform(k, shape, -bound, bound)

        return {
            "router": init_lib.kaiming_uniform(kr, (d, e)),
            "w1": expert_uniform(k1, (e, d, h), d),
            "b1": jnp.zeros((e, h)),
            "w2": expert_uniform(k2, (e, h, d), h),
            "b2": jnp.zeros((e, d)),
        }

    def create_state(self):
        return {"aux_loss": jnp.zeros(())}

    def _capacity(self, n_tokens: int) -> int:
        c = math.ceil(self.top_k * n_tokens / self.num_experts
                      * self.capacity_factor)
        # an expert can receive each token at most once (top-k experts are
        # distinct), so capacity beyond n_tokens only pads the einsums
        return max(1, min(c, n_tokens))

    def forward(self, x):
        from .module import _ctx
        p = _ctx().get_params(self._path)
        e, k = self.num_experts, self.top_k
        lead, d = x.shape[:-1], x.shape[-1]
        xt = x.reshape(-1, d)
        n = xt.shape[0]
        c = self._capacity(n)

        probs = jax.nn.softmax(xt @ p["router"], axis=-1)        # (N, E)
        gate_vals, gate_idx = lax.top_k(probs, k)                # (N, k)
        if self.normalize_gates and k > 1:
            gate_vals = gate_vals / jnp.maximum(
                gate_vals.sum(-1, keepdims=True), 1e-9)

        # slot assignment: flatten the k choices in priority order (all
        # first choices, then all second choices, ...) and cumsum the
        # one-hots — each (choice, token) gets its arrival index at the
        # chosen expert; indices >= capacity are dropped
        oh = jax.nn.one_hot(gate_idx.T, e, dtype=xt.dtype)       # (k, N, E)
        flat = oh.reshape(k * n, e)
        pos = (jnp.cumsum(flat, axis=0) - flat)                  # (k*N, E)
        pos = (pos * flat).sum(-1).reshape(k, n)                 # (k, N)
        keep = (pos < c).astype(xt.dtype)                        # (k, N)

        slot_oh = jax.nn.one_hot(pos.astype(jnp.int32), c,
                                 dtype=xt.dtype)                 # (k, N, C)
        # (k, N, E, C) collapsed over k → dispatch/combine (N, E, C)
        dispatch = jnp.einsum("kne,knc,kn->nec", oh, slot_oh, keep)
        combine = jnp.einsum("kne,knc,kn->nec", oh, slot_oh,
                             keep * gate_vals.T)

        xs = jnp.einsum("nec,nd->ecd", dispatch, xt)             # per-expert
        hdn = jax.nn.gelu(jnp.einsum("ecd,edh->ech", xs, p["w1"])
                          + p["b1"][:, None, :])
        out = jnp.einsum("ech,ehd->ecd", hdn, p["w2"]) + p["b2"][:, None, :]
        # dropped tokens have all-zero combine rows → output 0; the
        # surrounding residual connection passes them through unchanged
        y = jnp.einsum("nec,ecd->nd", combine, out)

        # Switch load-balance loss on first-choice assignments
        frac = jnp.mean(jax.nn.one_hot(gate_idx[:, 0], e, dtype=xt.dtype),
                        axis=0)
        mean_prob = probs.mean(0)
        self._put_aux(e * jnp.sum(frac * mean_prob))
        return y.reshape(*lead, d)

    def _put_aux(self, aux) -> None:
        from .module import current_context
        ctx = current_context()
        if ctx is not None and ctx.state is not None:
            ctx.put_state(self._path, {"aux_loss": aux})

    def __repr__(self):
        return (f"MoELayer({self.dim}, num_experts={self.num_experts}, "
                f"hidden={self.hidden}, top_k={self.top_k})")
