"""Mixture-of-Experts layer — expert parallelism the GSPMD way.

No reference counterpart (SURVEY.md §2: the reference is data-parallel image
classifiers); this exists because tpu_dist treats the 'expert' mesh axis as
first-class alongside dp/tp/pp/sp, and the driver's multi-chip dry-run
exercises an ep sharding.

TPU-first design — routing as dense einsums, not gather/scatter:

- Expert FFN weights are **stacked** on a leading expert axis: ``w1 (E, d,
  h)``, ``w2 (E, h, d)``.  Under expert parallelism that axis is sharded
  ``P('expert')`` (see :data:`MOE_EP_RULES`) and every expert matmul is a
  batched einsum the MXU tiles directly.
- Token routing is the GShard/Switch capacity formulation: a cumsum
  position assignment gives every (choice, token) a slot at its chosen
  expert; tokens beyond an expert's capacity ``C = ceil(k*N/E *
  capacity_factor)`` are dropped — their combine weights are zero, so they
  pass through the surrounding residual unchanged.  Two interchangeable
  dispatch realizations (``dispatch=``), numerically identical outputs:

  * ``"einsum"`` — dense **dispatch/combine tensors** ``(N, E, C)`` built
    from one-hots: static shapes, no data-dependent indexing, and the XLA
    SPMD partitioner inserts the token all-to-alls purely from the
    shardings (einsum ``nec,nd->ecd`` with the output sharded over
    'expert' IS the dispatch all-to-all).  The GSPMD/expert-parallel
    default — but the ``(N, E, C)`` temps cost ``O(N*E*C*d)`` FLOPs and
    HBM, which at LM scale rivals the expert FFNs themselves.
  * ``"gather"`` — the routing is a partial permutation, so dispatch and
    combine are **row-gathers** by the slot maps; custom VJPs express both
    backward passes as gathers by the opposite map, so XLA never emits a
    data scatter.  ``O(k*N*d)`` — use for single-device and shard_map/DDP
    execution (layer internals are per-shard local there), where it is
    strictly cheaper; prefer ``"einsum"`` under a GSPMD 'expert' axis.
  * ``"dropless"`` — MegaBlocks-style: rows sorted by expert (the routing
    cumsum doubles as a counting sort — no argsort, which alone measures
    ~5 ms at 16k rows on v5e), each expert run over its exact contiguous
    segment by the grouped-matmul kernels (ops/gmm.py), segments padded
    only to the row-block size.  No capacity, no drops, and the output
    never depends on batch composition.  Measured honestly (quiet-chip
    interleaved A/B at GPT-2-small MoE shapes): ~0.6x the capacity path
    forward / 0.8x fwd+bwd — XLA's dense batched einsum over the padded
    (E, C, d) tensor runs at near-peak MXU rate and beats the
    finer-grained grouped kernels despite doing 1.25x the FLOPs, so
    ``dropless`` is the EXACTNESS option (serving, drop-sensitive
    training), not a throughput one, at these shapes.

- The Switch **load-balancing auxiliary loss** ``E * sum_e f_e * p_e``
  (fraction of tokens routed to e times mean router probability of e) is
  published through the module-state mechanism (``state["aux_loss"]``):
  it is a traced value in ``new_state``, so a trainer that adds
  ``coeff * new_state[path]["aux_loss"]`` to its objective gets gradients
  through the router exactly as if the layer had returned it.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from .module import Module
from . import init as init_lib

from ..ops._pallas import ceil_to as _ceil_to

__all__ = ["MoELayer"]


# -- gather dispatch: permutation as index maps, not one-hot einsums --------
#
# The GShard (N, E, C) dispatch/combine tensors cost O(N*E*C*d) FLOPs and
# HBM — at GPT-2-small MoE shapes that is comparable to the expert FFNs
# themselves and OOMs a 16G chip at per-chip batch 8.  But the routing is a
# (partial) permutation: each (choice, token) lands in at most one (expert,
# slot) cell.  So dispatch = one row-gather by the inverse map and combine =
# one row-gather by the forward map; both backward passes are *also* pure
# gathers (by the opposite map), which the custom VJPs below express so XLA
# never emits a data scatter.  The only scatter anywhere is the int32
# slot->choice inverse-map build (~0.1 ms at 32k tokens on v5e).  Integer
# index arguments take no gradient (None cotangents).

@jax.custom_vjp
def _dispatch_rows(xt, token_for_slot, slot):
    """xt (N, d) -> xs_flat (E*C, d): row token_for_slot[s], zeros if == N."""
    pad = jnp.concatenate([xt, jnp.zeros((1, xt.shape[1]), xt.dtype)])
    return pad[token_for_slot]


def _dispatch_rows_fwd(xt, token_for_slot, slot):
    return _dispatch_rows(xt, token_for_slot, slot), slot


def _dispatch_rows_bwd(slot, g):
    # grad_xt[i] = sum_j grad_xs[slot[j, i]]; dropped choices point at the
    # appended zero row (slot == E*C)
    g_pad = jnp.concatenate([g, jnp.zeros((1, g.shape[1]), g.dtype)])
    gx = g_pad[slot.reshape(-1)].reshape(*slot.shape, g.shape[1])
    return gx.sum(0), None, None


_dispatch_rows.defvjp(_dispatch_rows_fwd, _dispatch_rows_bwd)


@jax.custom_vjp
def _combine_rows(out_flat, w, choice_for_slot, slot):
    """y (N, d) = sum_j w[j, i] * out_flat[slot[j, i]] (pad row = zeros).

    ``choice_for_slot`` (E*C,) is the inverse of ``slot``: the flattened
    (choice-major) index occupying each slot, k*N if empty — only the
    backward pass needs it, to invert the gy and w lookups as gathers.
    """
    pad = jnp.concatenate([out_flat,
                           jnp.zeros((1, out_flat.shape[1]), out_flat.dtype)])
    g = pad[slot.reshape(-1)].reshape(*slot.shape, out_flat.shape[1])
    return (g * w[:, :, None].astype(g.dtype)).sum(0)


def _combine_rows_fwd(out_flat, w, choice_for_slot, slot):
    return (_combine_rows(out_flat, w, choice_for_slot, slot),
            (out_flat, w, choice_for_slot, slot))


def _combine_rows_bwd(res, gy):
    out_flat, w, choice_for_slot, slot = res
    k, n = slot.shape
    d = out_flat.shape[1]
    # grad_out[s] = w[choice(s)] * gy[token(s)]; empty slots hit the padded
    # zero rows of both lookups (choice_for_slot == k*n -> token == n)
    token_for_slot = jnp.where(choice_for_slot == k * n, n,
                               choice_for_slot % jnp.int32(n))
    gy_pad = jnp.concatenate([gy, jnp.zeros((1, d), gy.dtype)])
    w_flat = jnp.concatenate([w.reshape(-1), jnp.zeros((1,), w.dtype)])
    w_at_slot = w_flat[choice_for_slot]
    g_out = w_at_slot[:, None].astype(gy.dtype) * gy_pad[token_for_slot]
    # grad_w[j, i] = dot(gy[i], out_pad[slot[j, i]])
    out_pad = jnp.concatenate([out_flat, jnp.zeros((1, d), out_flat.dtype)])
    g_rows = out_pad[slot.reshape(-1)].reshape(k, n, d)
    g_w = (g_rows * gy[None, :, :].astype(g_rows.dtype)).sum(-1)
    return g_out, g_w.astype(w.dtype), None, None


_combine_rows.defvjp(_combine_rows_fwd, _combine_rows_bwd)


class MoELayer(Module):
    """Top-k routed mixture of expert FFNs (drop-in for a transformer MLP).

    Args:
        dim: model width.
        num_experts: E, the expert count (shard over 'expert' for ep).
        hidden: expert FFN hidden width (default ``4 * dim``).
        top_k: experts consulted per token (1 = Switch, 2 = GShard default).
        capacity_factor: slack multiplier on the perfectly-balanced
            per-expert token budget; tokens past capacity are dropped.
            NOTE dropping makes outputs depend on the BATCH COMPOSITION
            (slot competition is a cumsum over every token in the call),
            so e.g. KV-cache decode of a prefix will not bit-match the
            full-sequence forward while drops occur.  For serving, use
            ``capacity_factor >= num_experts / top_k`` — capacity then
            equals the token count, nothing drops, and cached decode
            equals the full forward exactly (tests/test_moe.py).
        normalize_gates: renormalize the k selected gate values to sum to 1
            (GShard semantics); off uses raw softmax probabilities (Switch).
        dispatch: ``"einsum"`` (GSPMD/ep-friendly dense dispatch tensors),
            ``"gather"`` (index-map permutation — cheaper for
            single-device / shard_map execution), or ``"dropless"``
            (sort-by-expert + grouped-matmul kernels, ops/gmm.py: no
            capacity, no drops, batch-composition-independent outputs —
            the EXACTNESS option; measured ~0.6-0.8x the capacity
            path's speed at GPT-2-small shapes, see module docstring;
            ``capacity_factor`` is ignored).
    """

    def __init__(self, dim: int, num_experts: int, hidden: int = 0,
                 top_k: int = 2, capacity_factor: float = 1.25,
                 normalize_gates: bool = True, dispatch: str = "einsum"):
        super().__init__()
        if num_experts < 2:
            raise ValueError(f"num_experts must be >= 2, got {num_experts}")
        if not 1 <= top_k <= num_experts:
            raise ValueError(f"top_k {top_k} not in [1, {num_experts}]")
        if dispatch not in ("einsum", "gather", "dropless"):
            raise ValueError(f"dispatch must be 'einsum', 'gather', or "
                             f"'dropless', got {dispatch!r}")
        self.dim = dim
        self.num_experts = num_experts
        self.hidden = hidden or 4 * dim
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.normalize_gates = normalize_gates
        self.dispatch = dispatch

    def create_params(self, key):
        kr, k1, k2 = jax.random.split(key, 3)
        e, d, h = self.num_experts, self.dim, self.hidden

        def expert_uniform(k, shape, fan_in):
            # kaiming_uniform per expert: stacked (E, in, out) weights get
            # the same bound a (in, out) Linear would (init.calculate_fan
            # only knows 2-D/4-D shapes)
            bound = math.sqrt(6.0 / fan_in)
            return init_lib.uniform(k, shape, -bound, bound)

        return {
            "router": init_lib.kaiming_uniform(kr, (d, e)),
            "w1": expert_uniform(k1, (e, d, h), d),
            "b1": jnp.zeros((e, h)),
            "w2": expert_uniform(k2, (e, h, d), h),
            "b2": jnp.zeros((e, d)),
        }

    def create_state(self):
        return {"aux_loss": jnp.zeros(())}

    def _capacity(self, n_tokens: int) -> int:
        c = math.ceil(self.top_k * n_tokens / self.num_experts
                      * self.capacity_factor)
        # an expert can receive each token at most once (top-k experts are
        # distinct), so capacity beyond n_tokens only pads the einsums
        return max(1, min(c, n_tokens))

    def forward(self, x):
        from .module import _ctx
        p = _ctx().get_params(self._path)
        e, k = self.num_experts, self.top_k
        lead, d = x.shape[:-1], x.shape[-1]
        xt = x.reshape(-1, d)
        n = xt.shape[0]
        c = self._capacity(n)

        probs = jax.nn.softmax(xt @ p["router"], axis=-1)        # (N, E)
        gate_vals, gate_idx = lax.top_k(probs, k)                # (N, k)
        if self.normalize_gates and k > 1:
            gate_vals = gate_vals / jnp.maximum(
                gate_vals.sum(-1, keepdims=True), 1e-9)


        # slot assignment: flatten the k choices in priority order (all
        # first choices, then all second choices, ...) and cumsum the
        # one-hots — each (choice, token) gets its arrival index at the
        # chosen expert; indices >= capacity are dropped.  Bookkeeping runs
        # in int32 no matter what xt's dtype is: a bf16 cumsum rounds
        # positions past 256 and mis-slots tokens.
        oh_i = jax.nn.one_hot(gate_idx.T, e, dtype=jnp.int32)    # (k, N, E)
        flat = oh_i.reshape(k * n, e)
        pos = (jnp.cumsum(flat, axis=0) - flat)                  # (k*N, E)
        pos = (pos * flat).sum(-1).reshape(k, n)                 # (k, N)

        if self.dispatch == "dropless":
            # pos IS each row's stable within-expert rank — the same
            # cumsum doubles as a counting sort, so no argsort is needed
            # (measured ~5 ms for a 16k-row argsort on v5e, dwarfing the
            # expert matmuls themselves)
            counts = oh_i.sum((0, 1))                            # (E,)
            y = self._forward_dropless(p, xt, gate_vals, gate_idx, pos,
                                       counts)
            self._put_switch_aux(xt, probs, gate_idx)
            return y.reshape(*lead, d)

        keep = (pos < c).astype(xt.dtype)                        # (k, N)

        if self.dispatch == "gather":
            # forward map: (choice, token) -> flat slot e*C + pos (trash
            # slot E*C for dropped); inverse map via one int32 scatter
            slot = jnp.where(keep > 0,
                             gate_idx.T.astype(jnp.int32) * c + pos,
                             e * c)                              # (k, N)
            choice_for_slot = (
                jnp.full((e * c + 1,), k * n, jnp.int32)
                .at[slot.reshape(-1)]
                .set(jnp.arange(k * n, dtype=jnp.int32), mode="drop")[:-1])
            token_for_slot = jnp.where(choice_for_slot == k * n, n,
                                       choice_for_slot % jnp.int32(n))
            xs = _dispatch_rows(xt, token_for_slot, slot).reshape(e, c, d)
            combine_t = None
        else:
            slot_oh = jax.nn.one_hot(pos, c, dtype=xt.dtype)     # (k, N, C)
            oh = oh_i.astype(xt.dtype)
            # (k, N, E, C) collapsed over k → dispatch/combine (N, E, C)
            dispatch_t = jnp.einsum("kne,knc,kn->nec", oh, slot_oh, keep)
            combine_t = jnp.einsum("kne,knc,kn->nec", oh, slot_oh,
                                   keep * gate_vals.T)
            xs = jnp.einsum("nec,nd->ecd", dispatch_t, xt)
        hdn = jax.nn.gelu(jnp.einsum("ecd,edh->ech", xs, p["w1"])
                          + p["b1"][:, None, :])
        out = jnp.einsum("ech,ehd->ecd", hdn, p["w2"]) + p["b2"][:, None, :]
        # dropped tokens have all-zero combine rows → output 0; the
        # surrounding residual connection passes them through unchanged
        if self.dispatch == "gather":
            y = _combine_rows(out.reshape(e * c, d), keep * gate_vals.T,
                              choice_for_slot, slot)
        else:
            y = jnp.einsum("nec,ecd->nd", combine_t, out)

        self._put_switch_aux(xt, probs, gate_idx)
        return y.reshape(*lead, d)

    def _put_switch_aux(self, xt, probs, gate_idx):
        # Switch load-balance loss on first-choice assignments
        e = self.num_experts
        frac = jnp.mean(jax.nn.one_hot(gate_idx[:, 0], e, dtype=xt.dtype),
                        axis=0)
        self._put_aux(e * jnp.sum(frac * probs.mean(0)))

    def _forward_dropless(self, p, xt, gate_vals, gate_idx, rank, counts):
        """Dropless expert compute: sort the (choice, token) rows by
        expert and run each expert over its exact segment with the
        grouped-matmul kernels (ops/gmm.py) — MegaBlocks-style.

        No capacity, no drops: every routed row is processed, and the only
        padding is each segment's round-up to the row-block size (average
        E*B/2 rows ≈ a few percent at LM shapes, vs the capacity path's
        ``capacity_factor - 1`` ≈ 25% structural pad — the r4 verdict's
        remaining MoE cost).  Batch-composition independence comes free:
        unlike capacity slot competition, a token's output never depends
        on the other tokens in the call.

        The dispatch/combine row movements reuse the gather-path custom
        VJPs (_dispatch_rows/_combine_rows: both directions of both
        passes are gathers, never a data scatter); the per-expert FFN
        matmuls and all three of their backward passes are grouped
        matmuls over the same block→expert map (ops.gmm.grouped_linear).
        """
        from ..ops.gmm import grouped_linear

        e, k = self.num_experts, self.top_k
        n, d = xt.shape
        kn = k * n
        # row-block size: 512 rows amortizes grid/DMA overhead at LM
        # shapes; tiny calls (tests, dryrun) shrink to keep M small
        b = min(512, _ceil_to(max(kn // e, 1), 8))
        m_rows = (-(-kn // b) + e) * b                 # static upper bound
        nb = m_rows // b

        # destination row per (choice, token): its expert's block-aligned
        # segment start + its arrival rank there (``rank`` is the routing
        # cumsum from forward() — a stable counting sort, no argsort)
        padded = ((counts + b - 1) // b) * b
        pad_start = jnp.cumsum(padded) - padded                 # (E,)
        slot = (pad_start[gate_idx.T] + rank).astype(jnp.int32)  # (k, N)
        pos = slot.reshape(-1)                                   # (k*N,)

        # the two inverse maps the gather VJPs need; pad rows point at
        # the sentinels (token n = zero row, choice k*n = dropped)
        flat_choice = jnp.arange(kn, dtype=jnp.int32)
        token_for_row = (jnp.full((m_rows,), n, jnp.int32)
                         .at[pos].set(flat_choice % n))
        choice_for_row = (jnp.full((m_rows,), kn, jnp.int32)
                          .at[pos].set(flat_choice))

        cum_padded = jnp.cumsum(padded)
        n_live = (cum_padded[-1] // b).astype(jnp.int32)
        # block -> expert map; overallocation-tail blocks get clamped to
        # E-1 (tgmm needs them to extend the final segment with zero rows)
        bg = jnp.searchsorted(cum_padded,
                              jnp.arange(nb, dtype=jnp.int32) * b,
                              side="right")
        bg = jnp.minimum(bg, e - 1).astype(jnp.int32)
        present = counts > 0

        xs = _dispatch_rows(xt, token_for_row, slot)            # (M, d)
        hdn_lin = grouped_linear(xs, p["w1"], p["b1"], bg, n_live, present,
                                 b, 512)
        hdn = jax.nn.gelu(hdn_lin)
        out = grouped_linear(hdn, p["w2"], p["b2"], bg, n_live, present,
                             b, 512)
        return _combine_rows(out, gate_vals.T, choice_for_row, slot)

    def _put_aux(self, aux) -> None:
        from .module import current_context
        ctx = current_context()
        if ctx is not None and ctx.state is not None:
            ctx.put_state(self._path, {"aux_loss": aux})

    def __repr__(self):
        return (f"MoELayer({self.dim}, num_experts={self.num_experts}, "
                f"hidden={self.hidden}, top_k={self.top_k})")
