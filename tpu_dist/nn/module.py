"""Functional module system for TPU-native networks.

Design: a :class:`Module` is a *static* Python object describing the network
topology; parameters and mutable state (e.g. BatchNorm running statistics) live
in plain pytrees (nested dicts of ``jax.Array``) threaded explicitly through
``init`` / ``apply``.  Nothing on the module itself ever holds an array, so the
whole forward + backward + optimizer update compiles into a single XLA graph,
can be freely ``jax.jit`` / ``jax.grad`` / ``shard_map``-transformed, and
replicates across a device mesh without any of the object-graph machinery a
stateful module system (torch ``nn.Module``) needs.

This plays the role torch's ``nn.Module`` plays for the reference scripts
(``/root/reference/mpspawn_dist.py:11-43`` defines ``ConvNet(nn.Module)``;
``/root/reference/example_mp.py:50`` instantiates ``torchvision`` ResNet-18),
but TPU-first: ``apply`` is a pure function of ``(params, state, inputs, rng)``.

Usage::

    model = ConvNet()
    params = model.init(jax.random.key(0))
    logits = model.apply(params, images)                        # stateless nets
    logits, new_state = model.apply(params, images, state=state,
                                    training=True, rng=key)     # BN / dropout
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterator, Optional, Tuple

import jax

__all__ = ["Module", "Remat", "Sequential", "current_context",
           "run_capturing_state"]


class _Context:
    """Per-``apply`` tracing context (parameters, state, rng, mode)."""

    __slots__ = ("params", "state", "training", "rng", "new_state", "rng_counter")

    def __init__(self, params, state, training, rng):
        self.params = params or {}
        self.state = state
        self.training = training
        self.rng = rng
        self.new_state = {} if state is not None else None
        self.rng_counter = 0

    def get_params(self, path: str) -> Dict[str, Any]:
        try:
            return self.params[path]
        except KeyError:
            raise KeyError(
                f"No parameters found for module at path {path!r}. "
                f"Available: {list(self.params)}. Did you pass the pytree "
                f"returned by Module.init()?"
            ) from None

    def get_state(self, path: str) -> Dict[str, Any]:
        if self.state is None:
            raise ValueError(
                f"Module at path {path!r} carries mutable state (e.g. BatchNorm "
                f"running stats) but apply() was called without state=. Pass "
                f"the pytree returned by Module.init_state()."
            )
        return self.state[path]

    def put_state(self, path: str, value: Dict[str, Any]) -> None:
        if self.new_state is not None:
            self.new_state[path] = value

    def next_rng(self):
        if self.rng is None:
            raise ValueError(
                "A module requested randomness (dropout/augmentation) in "
                "training mode but apply() was called without rng=."
            )
        key = jax.random.fold_in(self.rng, self.rng_counter)
        self.rng_counter += 1
        return key


_TLS = threading.local()


def _stack():
    if not hasattr(_TLS, "stack"):
        _TLS.stack = []
    return _TLS.stack


def current_context() -> Optional[_Context]:
    stack = _stack()
    return stack[-1] if stack else None


def _ctx() -> _Context:
    ctx = current_context()
    if ctx is None:
        raise RuntimeError(
            "Modules can only be called inside Module.apply() (or init()). "
            "Call model.apply(params, x) rather than model(x) at top level."
        )
    return ctx


class Module:
    """Base class for all network modules.

    Subclasses create submodules in ``__init__`` (attribute assignment
    registers them) and define ``forward(*args)``.  Leaf modules holding
    parameters override :meth:`create_params` (and :meth:`create_state` for
    mutable buffers).
    """

    def __init__(self):
        object.__setattr__(self, "_modules", {})
        object.__setattr__(self, "_path", None)

    # -- submodule registration ------------------------------------------------
    def __setattr__(self, name: str, value: Any) -> None:
        mods = self.__dict__.get("_modules")
        if mods is None:
            raise RuntimeError(
                f"Call super().__init__() in {type(self).__name__}.__init__ "
                "before assigning attributes."
            )
        if isinstance(value, Module):
            mods[name] = value
        elif name in mods:
            del mods[name]
        object.__setattr__(self, name, value)

    # -- tree walking ----------------------------------------------------------
    def named_modules(self, prefix: str = "", _seen=None) -> Iterator[Tuple[str, "Module"]]:
        """Depth-first (pre-order) walk over ``(dotted_path, module)``.

        A module instance registered under several names (weight tying) is
        yielded once, at its first path — so tied modules share one parameter
        set rather than initializing divergent dead copies.
        """
        if _seen is None:
            _seen = set()
        if id(self) in _seen:
            return
        _seen.add(id(self))
        yield prefix, self
        for name, mod in self._modules.items():
            sub = f"{prefix}.{name}" if prefix else name
            yield from mod.named_modules(sub, _seen)

    def _assign_paths(self) -> None:
        for path, mod in self.named_modules():
            object.__setattr__(mod, "_path", path)

    # -- leaf hooks ------------------------------------------------------------
    def create_params(self, key) -> Optional[Dict[str, Any]]:
        """Leaf modules return their parameter dict; composites return None."""
        return None

    def create_state(self) -> Optional[Dict[str, Any]]:
        """Leaf modules with mutable buffers return their initial state."""
        return None

    # -- public API ------------------------------------------------------------
    def init(self, key) -> Dict[str, Dict[str, Any]]:
        """Create the parameter pytree: ``{dotted_path: {name: array}}``.

        Keys are derived per-module by folding the traversal index into
        ``key``, so initialization is deterministic given the module tree —
        the TPU analogue of the reference's ``torch.manual_seed(0)`` giving
        identical parameters on every rank (/root/reference/mpspawn_dist.py:56).
        """
        self._assign_paths()
        params: Dict[str, Dict[str, Any]] = {}
        for i, (path, mod) in enumerate(self.named_modules()):
            sub = jax.random.fold_in(key, i)
            p = mod.create_params(sub)
            if p:
                params[path] = p
        return params

    def init_state(self) -> Dict[str, Dict[str, Any]]:
        """Create the mutable-state pytree (empty dict if the net has none)."""
        self._assign_paths()
        state: Dict[str, Dict[str, Any]] = {}
        for path, mod in self.named_modules():
            s = mod.create_state()
            if s:
                state[path] = s
        return state

    def has_state(self) -> bool:
        return any(m.create_state() for _, m in self.named_modules())

    def apply(self, params, *args, state=None, training: bool = False,
              rng=None, **kwargs):
        """Run the network as a pure function.

        Returns ``forward(*args)`` — or ``(output, new_state)`` when ``state``
        is passed (mutable-state nets must thread it).
        """
        self._assign_paths()
        ctx = _Context(params, state, training, rng)
        _stack().append(ctx)
        try:
            out = self.forward(*args, **kwargs)
        finally:
            _stack().pop()
        if state is not None:
            # Carry through entries the trace did not update (e.g. eval mode).
            new_state = dict(state)
            new_state.update(ctx.new_state)
            return out, new_state
        return out

    # -- forward ---------------------------------------------------------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError(
            f"{type(self).__name__} does not define forward()."
        )

    def __call__(self, *args, **kwargs):
        _ctx()  # modules may only be invoked during apply()
        return self.forward(*args, **kwargs)

    # -- conveniences ----------------------------------------------------------
    def param_count(self, params) -> int:
        return sum(x.size for x in jax.tree.leaves(params))

    def __repr__(self) -> str:
        lines = [type(self).__name__ + "("]
        for name, mod in self._modules.items():
            body = repr(mod).replace("\n", "\n  ")
            lines.append(f"  ({name}): {body}")
        lines.append(")")
        return "\n".join(lines) if len(lines) > 2 else type(self).__name__ + "()"


class Sequential(Module):
    """Chain of modules applied in order (torch ``nn.Sequential`` analogue)."""

    def __init__(self, *modules: Module):
        super().__init__()
        for i, mod in enumerate(modules):
            setattr(self, str(i), mod)
        self._length = len(modules)

    def __len__(self) -> int:
        return self._length

    def __getitem__(self, i: int) -> Module:
        idx = i if i >= 0 else self._length + i
        if not 0 <= idx < self._length:
            raise IndexError(f"Sequential index {i} out of range "
                             f"(length {self._length})")
        return getattr(self, str(idx))

    def forward(self, x):
        for i in range(self._length):
            x = getattr(self, str(i))(x)
        return x


def run_capturing_state(module: Module, args: tuple, kwargs: dict = None):
    """Run ``module(*args, **kwargs)`` with the apply-context's state-update
    sink swapped for a fresh dict, returning ``(output, captured_updates)``.

    This turns a submodule's state writes (BN running stats, MoE aux
    losses) into explicit return values — required when the call runs
    inside a ``jax.checkpoint`` sub-trace, where writing to the outer
    context would leak tracers.  The caller re-publishes the updates via
    ``ctx.put_state`` outside the checkpointed region."""
    ctx = current_context()
    out_kwargs = kwargs or {}
    if ctx is None or ctx.new_state is None:
        return module(*args, **out_kwargs), {}
    saved = ctx.new_state
    ctx.new_state = {}
    try:
        out = module(*args, **out_kwargs)
        updates = ctx.new_state
    finally:
        ctx.new_state = saved
    return out, updates


class Remat(Module):
    """Activation checkpointing (``torch.utils.checkpoint.checkpoint``
    parity, as a wrapper module): the wrapped module's forward activations
    are NOT kept for backward — they are recomputed during the backward
    pass (``jax.checkpoint``), trading FLOPs for HBM.

    Usage::

        block = nn.Remat(TransformerBlock(...))
        y = block(x)

    NOTE: wrapping inserts one level into parameter paths — the wrapped
    module's params live under the ``inner`` attribute (``"<name>.X"``
    becomes ``"<name>.inner.X"``), so checkpoints trained without the
    wrapper need their keys remapped (or wrap before the first init).

    ``policy`` forwards to ``jax.checkpoint`` (e.g.
    ``jax.checkpoint_policies.dots_with_no_batch_dims_saveable`` keeps
    matmul outputs and recomputes the rest).  Keyword arguments and the
    module's parameters reach the inner module as closed-over values —
    ``jax.checkpoint`` differentiates through closures, so no explicit
    plumbing is needed; state updates are captured and re-published
    outside the sub-trace (see :func:`run_capturing_state`)."""

    def __init__(self, module: Module, policy=None):
        super().__init__()
        self.inner = module
        self.policy = policy

    def forward(self, *args, **kwargs):
        def inner_fn(*a):
            return run_capturing_state(self.inner, a, kwargs)

        out, updates = jax.checkpoint(inner_fn, policy=self.policy)(*args)
        ctx = current_context()
        if ctx is not None and updates:
            for path, val in updates.items():
                ctx.put_state(path, val)
        return out
