"""Loss modules (torch ``nn.CrossEntropyLoss`` parity —
/root/reference/mpspawn_dist.py:63, /root/reference/example_mp.py:83)."""

from __future__ import annotations

from . import functional as F
from .module import Module

__all__ = ["CrossEntropyLoss"]


class CrossEntropyLoss(Module):
    """Softmax cross-entropy over integer class labels.

    ``fused=True`` computes via the Pallas kernel
    (:func:`tpu_dist.ops.fused_cross_entropy`) — one VMEM-resident pass per
    row block instead of a materialized log-softmax; worth it for large
    vocabularies (LM heads)."""

    def __init__(self, reduction: str = "mean", fused: bool = False):
        super().__init__()
        self.reduction = reduction
        self.fused = fused

    def forward(self, logits, labels):
        if self.fused:
            from ..ops import fused_cross_entropy
            return fused_cross_entropy(logits, labels, self.reduction)
        return F.cross_entropy(logits, labels, self.reduction)

    # Losses carry no parameters, so allow calling outside apply() too.
    def __call__(self, logits, labels):
        return self.forward(logits, labels)
