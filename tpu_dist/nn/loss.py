"""Loss modules (torch ``nn.CrossEntropyLoss`` parity —
/root/reference/mpspawn_dist.py:63, /root/reference/example_mp.py:83)."""

from __future__ import annotations

import jax.numpy as jnp

from . import functional as F
from .module import Module

__all__ = ["CrossEntropyLoss"]


class CrossEntropyLoss(Module):
    """Softmax cross-entropy over integer class labels.

    ``fused=True`` computes via the Pallas kernel
    (:func:`tpu_dist.ops.fused_cross_entropy`) — one VMEM-resident pass per
    row block instead of a materialized log-softmax; worth it for large
    vocabularies (LM heads)."""

    def __init__(self, reduction: str = "mean", fused: bool = False,
                 label_smoothing: float = 0.0, ignore_index: int = -100,
                 weight=None):
        super().__init__()
        self.reduction = reduction
        self.fused = fused
        self.label_smoothing = label_smoothing
        self.ignore_index = ignore_index
        self.weight = weight
        if fused and (label_smoothing or weight is not None):
            raise ValueError(
                "the fused Pallas kernel computes plain softmax CE; use "
                "fused=False with label_smoothing/weight (ignore_index IS "
                "supported on the fused path)")

    def forward(self, logits, labels):
        if self.fused:
            from ..ops import fused_cross_entropy
            labels = labels.astype(jnp.int32)
            keep = labels != self.ignore_index
            # the kernel matches labels by column id, so an out-of-range
            # sentinel (-100) would silently yield nll = lse; mask outside
            safe = jnp.where(keep, labels, 0)
            nll = fused_cross_entropy(logits, safe, "none")
            nll = jnp.where(keep, nll, 0.0)
            if self.reduction == "mean":
                n = keep.sum().astype(nll.dtype)
                return nll.sum() / jnp.maximum(n,
                                               jnp.finfo(nll.dtype).tiny)
            if self.reduction == "sum":
                return nll.sum()
            if self.reduction == "none":
                return nll
            raise ValueError(f"Unknown reduction {self.reduction!r}")
        return F.cross_entropy(logits, labels, self.reduction,
                               label_smoothing=self.label_smoothing,
                               ignore_index=self.ignore_index,
                               weight=self.weight)

    # Losses carry no parameters, so allow calling outside apply() too.
    def __call__(self, logits, labels):
        return self.forward(logits, labels)
