"""Loss modules (torch ``nn.CrossEntropyLoss`` parity —
/root/reference/mpspawn_dist.py:63, /root/reference/example_mp.py:83)."""

from __future__ import annotations

from . import functional as F
from .module import Module

__all__ = ["CrossEntropyLoss"]


class CrossEntropyLoss(Module):
    """Softmax cross-entropy over integer class labels."""

    def __init__(self, reduction: str = "mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, logits, labels):
        return F.cross_entropy(logits, labels, self.reduction)

    # Losses carry no parameters, so allow calling outside apply() too.
    def __call__(self, logits, labels):
        return self.forward(logits, labels)
