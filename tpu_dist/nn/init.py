"""Parameter initializers with torch-compatible semantics.

The reference relies on torch's default layer initialization (ConvNet at
/root/reference/mpspawn_dist.py:11-43 never overrides init; torchvision
ResNet-18 at /root/reference/example_mp.py:50 uses kaiming_normal fan_out for
convs).  Matching the *distributions* (not the RNG streams) keeps training
dynamics comparable for loss-parity testing.

Weight layouts are TPU-first: conv kernels are HWIO, linear weights are
``(in_features, out_features)``.
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "calculate_fan", "uniform", "normal", "zeros", "ones",
    "kaiming_uniform", "kaiming_normal", "torch_default_uniform",
    "xavier_uniform", "trunc_normal",
]


def calculate_fan(shape: Sequence[int]) -> Tuple[int, int]:
    """Return ``(fan_in, fan_out)`` for HWIO conv or (in, out) linear shapes."""
    if len(shape) == 2:  # linear: (in, out)
        return shape[0], shape[1]
    if len(shape) == 4:  # conv HWIO: (kh, kw, in, out)
        receptive = shape[0] * shape[1]
        return receptive * shape[2], receptive * shape[3]
    raise ValueError(f"Unsupported weight shape {shape}")


def uniform(key, shape, minval, maxval, dtype=jnp.float32):
    return jax.random.uniform(key, shape, dtype, minval, maxval)


def normal(key, shape, std, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype) * std


def zeros(shape, dtype=jnp.float32):
    return jnp.zeros(shape, dtype)


def ones(shape, dtype=jnp.float32):
    return jnp.ones(shape, dtype)


def _gain(nonlinearity: str, a: float) -> float:
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        return math.sqrt(2.0 / (1 + a * a))
    if nonlinearity == "linear":
        return 1.0
    raise ValueError(f"Unsupported nonlinearity {nonlinearity!r}")


def kaiming_uniform(key, shape, a: float = 0.0, mode: str = "fan_in",
                    nonlinearity: str = "leaky_relu", dtype=jnp.float32):
    fan_in, fan_out = calculate_fan(shape)
    fan = fan_in if mode == "fan_in" else fan_out
    bound = _gain(nonlinearity, a) * math.sqrt(3.0 / fan)
    return uniform(key, shape, -bound, bound, dtype)


def kaiming_normal(key, shape, a: float = 0.0, mode: str = "fan_in",
                   nonlinearity: str = "leaky_relu", dtype=jnp.float32):
    fan_in, fan_out = calculate_fan(shape)
    fan = fan_in if mode == "fan_in" else fan_out
    std = _gain(nonlinearity, a) / math.sqrt(fan)
    return normal(key, shape, std, dtype)


def torch_default_uniform(key, shape, fan_in: int, dtype=jnp.float32):
    """torch's default Conv/Linear weight+bias init: U(-1/sqrt(fan_in), +)."""
    bound = 1.0 / math.sqrt(fan_in) if fan_in > 0 else 0.0
    return uniform(key, shape, -bound, bound, dtype)


def xavier_uniform(key, shape, gain: float = 1.0, dtype=jnp.float32):
    """torch ``nn.init.xavier_uniform_``: U(±gain*sqrt(6/(fan_in+fan_out)))."""
    fan_in, fan_out = calculate_fan(shape)
    limit = gain * math.sqrt(6.0 / (fan_in + fan_out))
    return uniform(key, shape, -limit, limit, dtype)


def trunc_normal(key, shape, std: float = 1.0, mean: float = 0.0,
                 a: float = -2.0, b: float = 2.0, dtype=jnp.float32):
    """torch ``nn.init.trunc_normal_``: N(mean, std) truncated to [a, b].

    NOTE torch's ``a``/``b`` are in VALUE units, not standard deviations —
    the defaults ±2 are effectively untruncated for the small stds
    torchvision passes (e.g. sqrt(1/768)); we reproduce that exactly by
    rescaling the bounds into standard units for jax's sampler.
    """
    lo = (a - mean) / std
    hi = (b - mean) / std
    return mean + std * jax.random.truncated_normal(key, lo, hi, shape, dtype)
