"""Flash attention — Pallas TPU kernel with custom VJP.

The dense path in tpu_dist.nn.attention.scaled_dot_product_attention
materializes the (Tq, Tk) score matrix in HBM; fine for the reference's
image workloads, quadratic-memory death for long sequences.  This kernel is
the single-device half of the long-context story (the cross-device half is
tpu_dist.parallel.ring_attention, which rotates KV blocks over ICI with the
same online-softmax recurrence): Q/K/V tiles stream HBM -> VMEM, scores for
one (block_q, block_k) tile live only in VMEM/registers, and the softmax is
accumulated online (flash recurrence), so memory is O(T) instead of O(T^2).

Layout (kernel-internal): (BH, T, D) with a (BH, nq, nk) grid; the KV index
is innermost so the f32 accumulators (m, l, acc) persist in VMEM scratch
across a Q row's KV sweep and the output tile is written back to HBM once.
Forward saves per-row logsumexp; backward recomputes score tiles from
(q, k, lse) flash-style — two kernels, one accumulating dQ over the KV
sweep, one accumulating dK/dV over the Q sweep (grid transposed so the
accumulators stay resident).  Residuals are just (q, k, v, o, lse): no
(Tq, Tk) tensor is ever materialized, forward or backward.

Causal masking is applied per-tile from global positions; tiles entirely
above the diagonal are predicated off with ``pl.when`` (no MXU work, the
grid still sweeps them).  Runs on TPU via Mosaic; everywhere else (CPU
tests) through ``interpret=True`` — same kernel, same numerics (tests
compare forward and grads against the dense composition).

The reference has no attention at all (SURVEY.md §5 long-context row:
absent — its workloads are 28^2/32^2 image classifiers); this kernel plus
ring attention is the beyond-parity long-context substrate.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from ._pallas import (ceil_to as _ceil_to, out_struct as _out_struct,
                      use_interpret as _use_interpret)

__all__ = ["flash_attention", "flash_attention_with_lse"]

_LANE = 128
_D_ALIGN = 64  # head_dim alignment: 64 halves K/V DMA for d=64 vs padding to 128
_NEG_INF = -1e30  # finite: keeps max/correction arithmetic NaN-free when a
                  # whole tile is masked (same sentinel as ring_attention)


def _clamp_blocks(dtype, tq, tk, block_q, block_k):
    """Tile sizes that fit VMEM: the 1024 defaults are tuned for bf16; with
    f32 inputs the tile intermediates double and the dK/dV kernel's
    (block_q, block_k) f32 score/prob/ds tiles blow the ~16 MB VMEM budget
    at 1024² (observed: 16.17M > 16M on v5e) — halve for 4-byte dtypes."""
    if jnp.dtype(dtype).itemsize >= 4:
        block_q = min(block_q, 512)
        block_k = min(block_k, 512)
    return (min(block_q, _ceil_to(tq, _LANE)),
            min(block_k, _ceil_to(tk, _LANE)))


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _masked_scores(q, k, sm_scale, tk, causal, q_lo, k_lo):
    """(block_q, block_k) score tile on the MXU (f32 accumulation), with
    out-of-range and above-diagonal entries set to _NEG_INF.  The single
    source of the score/mask convention shared by the forward and both
    backward kernels.

    ``causal`` is three-valued: ``True`` masks above the diagonal,
    ``False`` doesn't, and ``"offdiag"`` also doesn't — its tiles sit
    strictly below the diagonal band by the grid predicate, so per-element
    causal mask math (two iotas + compare + select per tile) is skipped
    entirely; only the K padding range check remains."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    s = s * sm_scale
    kpos = k_lo + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    mask = kpos < tk
    if causal is True:
        qpos = q_lo + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        mask = mask & (kpos <= qpos)
    return jnp.where(mask, s, _NEG_INF), mask


def _tile_live(causal, q_lo, k_lo, block_q, block_k):
    """Grid predicate: does tile (q_lo, k_lo) contribute any unmasked
    entries?  ``True`` = tiles intersecting or below the diagonal;
    ``"offdiag"`` = tiles STRICTLY below the diagonal band (the masked
    diagonal tiles are handled by a separate finer-tiled causal call —
    see _split_lse); ``False`` = all tiles."""
    if causal is True:
        return k_lo <= q_lo + block_q - 1
    if causal == "offdiag":
        return k_lo + block_k <= q_lo
    return k_lo >= 0  # trivially true (kernel body must sit under pl.when)


def _tile_probs(q_ref, k_ref, lse_ref, sm_scale, tk, causal, q_lo, k_lo):
    """Recompute the softmax probabilities of one tile from (q, k, lse) —
    the flash-backward recurrence shared by the dQ and dK/dV kernels."""
    s, mask = _masked_scores(q_ref[0], k_ref[0], sm_scale, tk, causal,
                             q_lo, k_lo)
    p = jnp.exp(s - lse_ref[0])                             # (bq, bk) f32
    return jnp.where(mask, p, 0.0)


def _make_fwd_kernel(sm_scale, tk, block_q, block_k, causal):
    from jax.experimental import pallas as pl

    def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr):
        qi = pl.program_id(1)
        ki = pl.program_id(2)
        nk = pl.num_programs(2)

        @pl.when(ki == 0)
        def _init():
            m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
            l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
            acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

        q_lo = qi * block_q
        k_lo = ki * block_k

        def body():
            s, mask = _masked_scores(q_ref[0], k_ref[0], sm_scale, tk,
                                     causal, q_lo, k_lo)
            m_prev = m_scr[:, 0:1]
            l_prev = l_scr[:, 0:1]
            m_cur = jnp.max(s, axis=1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)
            # fully-masked rows: s == m_new == _NEG_INF gives exp(0) = 1;
            # zero them so they contribute nothing
            p = jnp.where(mask, p, 0.0)
            l_scr[:] = jnp.broadcast_to(
                alpha * l_prev + jnp.sum(p, axis=1, keepdims=True),
                l_scr.shape)
            v = v_ref[0]
            pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                     (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            acc_scr[:] = acc_scr[:] * alpha + pv
            m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

        # tiles contributing nothing (above the diagonal / diagonal band)
        # are predicated off; non-causal uses a trivially-true predicate
        # (see _use_interpret for why the body must be under pl.when
        # either way)
        @pl.when(_tile_live(causal, q_lo, k_lo, block_q, block_k))
        def _():
            body()

        @pl.when(ki == nk - 1)
        def _fin():
            m = m_scr[:, 0:1]
            l = l_scr[:, 0:1]
            l_safe = jnp.where(l == 0.0, 1.0, l)
            o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
            lse_ref[0] = m + jnp.log(l_safe)

    return kernel


def _fwd_call(q, k, v, causal, sm_scale, block_q, block_k):
    """q: (BH, Tq, D); k, v: (BH, Tk, D) -> (o, lse) with lse (BH, Tq, 1)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, tq, d = q.shape
    tk = k.shape[1]
    block_q, block_k = _clamp_blocks(q.dtype, tq, tk, block_q, block_k)
    tqp, tkp, dp = _ceil_to(tq, block_q), _ceil_to(tk, block_k), _ceil_to(d, _D_ALIGN)
    qp = jnp.pad(q, ((0, 0), (0, tqp - tq), (0, dp - d)))
    kp = jnp.pad(k, ((0, 0), (0, tkp - tk), (0, dp - d)))
    vp = jnp.pad(v, ((0, 0), (0, tkp - tk), (0, dp - d)))
    grid = (bh, tqp // block_q, tkp // block_k)
    o, lse = pl.pallas_call(
        _make_fwd_kernel(sm_scale, tk, block_q, block_k, causal),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, dp), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, dp), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, dp), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, dp), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _out_struct((bh, tqp, dp), q.dtype, qp, kp, vp),
            _out_struct((bh, tqp, 1), jnp.float32, qp, kp, vp),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANE), jnp.float32),   # running max m
            pltpu.VMEM((block_q, _LANE), jnp.float32),   # running sum l
            pltpu.VMEM((block_q, dp), jnp.float32),      # output accumulator
        ],
        interpret=_use_interpret(),
    )(qp, kp, vp)
    return o[:, :tq, :d], lse[:, :tq]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _make_dq_kernel(sm_scale, tk, block_q, block_k, causal):
    from jax.experimental import pallas as pl

    def kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_scr):
        qi = pl.program_id(1)
        ki = pl.program_id(2)
        nk = pl.num_programs(2)

        @pl.when(ki == 0)
        def _init():
            acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

        q_lo = qi * block_q
        k_lo = ki * block_k

        def body():
            # q/k/v/do stay in their input dtype: bf16 inputs run bf16 MXU
            # passes with f32 accumulation (preferred_element_type)
            k = k_ref[0]
            v = v_ref[0]
            do = do_ref[0]
            p = _tile_probs(q_ref, k_ref, lse_ref, sm_scale, tk, causal,
                            q_lo, k_lo)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = (p * (dp - delta_ref[0])).astype(k.dtype)  # (bq, bk)
            acc_scr[:] = acc_scr[:] + sm_scale * jax.lax.dot_general(
                ds, k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(_tile_live(causal, q_lo, k_lo, block_q, block_k))
        def _():
            body()

        @pl.when(ki == nk - 1)
        def _fin():
            dq_ref[0] = acc_scr[:].astype(dq_ref.dtype)

    return kernel


def _make_dkv_kernel(sm_scale, tk, block_q, block_k, causal):
    from jax.experimental import pallas as pl

    def kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dk_ref, dv_ref, dk_scr, dv_scr):
        ki = pl.program_id(1)
        qi = pl.program_id(2)
        nq = pl.num_programs(2)

        @pl.when(qi == 0)
        def _init():
            dk_scr[:] = jnp.zeros(dk_scr.shape, jnp.float32)
            dv_scr[:] = jnp.zeros(dv_scr.shape, jnp.float32)

        q_lo = qi * block_q
        k_lo = ki * block_k

        def body():
            q = q_ref[0]
            v = v_ref[0]
            do = do_ref[0]
            p = _tile_probs(q_ref, k_ref, lse_ref, sm_scale, tk, causal,
                            q_lo, k_lo)
            # padded q rows contribute nothing: their do and delta are zero
            dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = (p * (dp - delta_ref[0])).astype(q.dtype)
            dk_scr[:] = dk_scr[:] + sm_scale * jax.lax.dot_general(
                ds, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(_tile_live(causal, q_lo, k_lo, block_q, block_k))
        def _():
            body()

        @pl.when(qi == nq - 1)
        def _fin():
            dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
            dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)

    return kernel


def _bwd_call(q, k, v, o, lse, do, causal, sm_scale, block_q, block_k,
              dlse=None, delta=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, tq, d = q.shape
    tk = k.shape[1]
    block_q, block_k = _clamp_blocks(q.dtype, tq, tk, block_q, block_k)
    tqp, tkp, dp = _ceil_to(tq, block_q), _ceil_to(tk, block_k), _ceil_to(d, _D_ALIGN)

    # delta_i = rowsum(dO_i * O_i) — the softmax-jacobian correction term;
    # cheap elementwise jnp, fused by XLA around the kernels.  When the
    # caller differentiates through lse too (ring-attention merge), its
    # cotangent enters the same place with opposite sign:
    # dL/ds_ij = p_ij * (dp_ij - delta_i + dlse_i), so fold it into delta.
    # The split-causal backward passes a precomputed ``delta`` so its two
    # region calls share one rowsum pass.
    if delta is None:
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1, keepdims=True)              # (BH, Tq, 1)
        if dlse is not None:
            delta = delta - dlse.astype(jnp.float32)

    qp = jnp.pad(q, ((0, 0), (0, tqp - tq), (0, dp - d)))
    kp = jnp.pad(k, ((0, 0), (0, tkp - tk), (0, dp - d)))
    vp = jnp.pad(v, ((0, 0), (0, tkp - tk), (0, dp - d)))
    dop = jnp.pad(do, ((0, 0), (0, tqp - tq), (0, dp - d)))
    lsep = jnp.pad(lse, ((0, 0), (0, tqp - tq), (0, 0)))
    deltap = jnp.pad(delta, ((0, 0), (0, tqp - tq), (0, 0)))

    q_spec = pl.BlockSpec((1, block_q, dp), lambda b, i, j: (b, i, 0),
                          memory_space=pltpu.VMEM)
    kv_spec_dq = pl.BlockSpec((1, block_k, dp), lambda b, i, j: (b, j, 0),
                              memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0),
                            memory_space=pltpu.VMEM)

    dq = pl.pallas_call(
        _make_dq_kernel(sm_scale, tk, block_q, block_k, causal),
        grid=(bh, tqp // block_q, tkp // block_k),
        in_specs=[q_spec, kv_spec_dq, kv_spec_dq, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=_out_struct((bh, tqp, dp), q.dtype, qp, kp, vp, dop),
        scratch_shapes=[pltpu.VMEM((block_q, dp), jnp.float32)],
        interpret=_use_interpret(),
    )(qp, kp, vp, dop, lsep, deltap)

    # grid transposed: KV tile outer, Q sweep inner, so dk/dv accumulate
    q_spec_t = pl.BlockSpec((1, block_q, dp), lambda b, j, i: (b, i, 0),
                            memory_space=pltpu.VMEM)
    kv_spec_t = pl.BlockSpec((1, block_k, dp), lambda b, j, i: (b, j, 0),
                             memory_space=pltpu.VMEM)
    row_spec_t = pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0),
                              memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        _make_dkv_kernel(sm_scale, tk, block_q, block_k, causal),
        grid=(bh, tkp // block_k, tqp // block_q),
        in_specs=[q_spec_t, kv_spec_t, kv_spec_t, q_spec_t, row_spec_t,
                  row_spec_t],
        out_specs=[kv_spec_t, kv_spec_t],
        out_shape=[_out_struct((bh, tkp, dp), k.dtype, qp, kp, vp, dop),
                   _out_struct((bh, tkp, dp), v.dtype, qp, kp, vp, dop)],
        scratch_shapes=[pltpu.VMEM((block_k, dp), jnp.float32),
                        pltpu.VMEM((block_k, dp), jnp.float32)],
        interpret=_use_interpret(),
    )(qp, kp, vp, dop, lsep, deltap)
    return dq[:, :tq, :d], dk[:, :tk, :d], dv[:, :tk, :d]


# ---------------------------------------------------------------------------
# custom VJP + public wrapper
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_lse(q, k, v, causal, sm_scale, block_q, block_k):
    """Like _flash but also returns the per-row logsumexp — the merge
    currency of blockwise/ring attention.  Differentiable in BOTH outputs."""
    return _fwd_call(q, k, v, causal, sm_scale, block_q, block_k)


def _flash_lse_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    o, lse = _fwd_call(q, k, v, causal, sm_scale, block_q, block_k)
    return (o, lse), (q, k, v, o, lse)


def _flash_lse_bwd(causal, sm_scale, block_q, block_k, res, cts):
    q, k, v, o, lse = res
    do, dlse = cts
    return _bwd_call(q, k, v, o, lse, do, causal, sm_scale, block_q, block_k,
                     dlse=dlse)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def _merge_lse(o_a, lse_a, o_b, lse_b):
    """Exact blockwise-attention merge of two partial results over disjoint
    KV sets (the identity from flash_attention_with_lse's docstring), in
    f32.  Plain jnp: autodiff routes the cotangents into both partials'
    custom VJPs (including dlse), exactly like ring_attention's merge."""
    m = jnp.maximum(lse_a, lse_b)
    w_a = jnp.exp(lse_a - m)
    w_b = jnp.exp(lse_b - m)
    den = w_a + w_b
    o = (o_a.astype(jnp.float32) * w_a + o_b.astype(jnp.float32) * w_b) / den
    return o.astype(o_a.dtype), m + jnp.log(den)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _split_lse(q, k, v, sm_scale, block_q, block_k):
    """Causal flash attention as two kernel calls per pass whose executed
    tile area ≈ the useful (unmasked) score area.

    A single causal call sweeps every tile touching the diagonal with
    full-size blocks, so at seq = 2·block the three executed 1024² tiles
    are only 2/3 useful (the two diagonal tiles are half masked) — the
    measured TFLOPs deficit at 2048 vs 8k (BENCH_EXTENDED
    curve_shape_note).  Split instead:

    - **off-diagonal**: tiles STRICTLY below the diagonal band (mode
      ``"offdiag"``) — full blocks, zero masked area, and no per-element
      causal mask math at all;
    - **diagonal band**: each q block attends causally within its own
      band, which is exactly a BATCHED causal attention over
      (BH·n_bands, block_q) sequences — the same kernel at half-size
      blocks, so the masked waste per band shrinks from block²/2 to
      block²/4 (minus the skipped above-diagonal sub-tile);

    merged with the exact blockwise-lse identity.  Executed-area ratio vs
    the single call: (n² + n/2) / (n² + n) per n = T/block — a 1/6 area
    cut at n=2, vanishing as n grows (the 8k curve point was already
    ~90% useful).  Measured on the v5e the area cut does NOT convert to
    time on a quiet chip: at 2048 the single call is bound by grid-step
    overhead (~1.9 us/step), and the split triples the step count, so it
    only wins under heavy chip contention (1.7-2.5x there, 0.3-0.5x
    quiet) — hence opt-in, see flash_attention_with_lse.

    The custom VJP is at THIS level, not composed from two _flash_lse
    VJPs: the backward recomputes p = exp(s - lse) from the MERGED lse in
    both regions (the standard flash recurrence is oblivious to how the
    forward was tiled), so the residuals are exactly the single-call ones
    (q, k, v, o, lse) — composing custom-VJP calls through the merge
    instead saves two extra partial (o, lse) pairs and differentiates the
    elementwise merge, which measured as a complete wash at 2048.

    Inputs are the kernel-internal (BH, T, D) layout; requires tq == tk
    and block_q | tq (the dispatch condition in
    flash_attention_with_lse)."""
    return _split_fwd_impl(q, k, v, sm_scale, block_q, block_k)


def _to_bands(x, n_bands, band):
    bh = x.shape[0]
    return x.reshape(bh * n_bands, band, x.shape[-1])


def _split_fwd_impl(q, k, v, sm_scale, block_q, block_k):
    bh, tq, d = q.shape
    n_bands = tq // block_q
    o_diag, lse_diag = _fwd_call(
        _to_bands(q, n_bands, block_q), _to_bands(k, n_bands, block_q),
        _to_bands(v, n_bands, block_q), True, sm_scale,
        block_q // 2, block_q // 2)
    o_off, lse_off = _fwd_call(q, k, v, "offdiag", sm_scale,
                               block_q, block_k)
    return _merge_lse(o_off, lse_off, o_diag.reshape(bh, tq, d),
                      lse_diag.reshape(bh, tq, 1))


def _split_fwd(q, k, v, sm_scale, block_q, block_k):
    o, lse = _split_fwd_impl(q, k, v, sm_scale, block_q, block_k)
    return (o, lse), (q, k, v, o, lse)


def _split_bwd(sm_scale, block_q, block_k, res, cts):
    q, k, v, o, lse = res
    do, dlse = cts
    bh, tq, d = q.shape
    n_bands = tq // block_q
    # one shared softmax-jacobian correction (see _bwd_call): both region
    # calls recompute p from the same merged lse, so they share delta too
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    dq_off, dk_off, dv_off = _bwd_call(
        q, k, v, o, lse, do, "offdiag", sm_scale, block_q, block_k,
        delta=delta)

    def bands(x):
        return _to_bands(x, n_bands, block_q)

    dq_d, dk_d, dv_d = _bwd_call(
        bands(q), bands(k), bands(v), bands(o), bands(lse), bands(do),
        True, sm_scale, block_q // 2, block_q // 2, delta=bands(delta))
    return (dq_off + dq_d.reshape(bh, tq, d),
            dk_off + dk_d.reshape(bh, tq, d),
            dv_off + dv_d.reshape(bh, tq, d))


_split_lse.defvjp(_split_fwd, _split_bwd)


def flash_attention_with_lse(q, k, v, causal: bool = False, sm_scale=None,
                             block_q: int = 1024, block_k: int = 1024,
                             split_diag=None):
    """Flash attention returning ``(out, lse)``.

    ``out``: (..., Tq, H, D) like :func:`flash_attention`; ``lse``:
    (..., Tq, H) float32 per-row logsumexp of the scaled scores.  Partial
    results ``(out_a, lse_a), (out_b, lse_b)`` over disjoint KV blocks merge
    exactly (the blockwise-attention identity used by
    tpu_dist.parallel.ring_attention)::

        m = max(lse_a, lse_b); w = exp(lse_? - m)
        out = (out_a*w_a + out_b*w_b) / (w_a + w_b); lse = m + log(w_a + w_b)

    Differentiable in both outputs (the lse cotangent folds into the
    softmax-jacobian correction).  Rows with no visible keys get lse ≈ -1e30
    and out 0 — the merge weight exp(lse - m) then vanishes exactly.
    """
    if q.ndim < 3:
        raise ValueError(f"expected (..., T, H, D), got {q.shape}")
    *lead, tq, h, d = q.shape
    tk = k.shape[-3]
    if not (q.shape[:-3] == k.shape[:-3] == v.shape[:-3]
            and k.shape[-2:] == v.shape[-2:] == (h, d)
            and v.shape[-3] == tk):
        # no numpy-broadcast batch semantics here: the (B*H, T, D) flatten
        # would silently misalign batches — use impl='dense' for shared KV
        raise ValueError(
            f"flash_attention needs identical batch/head dims for q, k, v; "
            f"got q={q.shape}, k={k.shape}, v={v.shape}")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if not isinstance(causal, str):
        # normalize truthy values (np.bool_, 1) to the literal bool the
        # kernels' three-valued dispatch (`causal is True`) relies on
        causal = bool(causal)

    def to3(x, t):
        x = x.reshape(-1, t, h, d)
        return jnp.swapaxes(x, 1, 2).reshape(-1, t, d)

    # ``split_diag`` is OPT-IN (default off).  The two-call split
    # (_split_lse) makes executed tile area ≈ useful area, and interleaved
    # A/B under heavy chip contention measured it 1.7-2.5x faster at seq
    # 2048 — but on a QUIET chip the same A/B inverts (0.3-0.5x): at 2048
    # the single call is grid-overhead-bound, not area-bound (128 grid
    # steps at ~1.9 us vs the split's ~384 across its finer-tiled calls),
    # and 1024^2 single-call already runs at the same per-executed-area
    # rate as 8k there (142 TF fwd reported / (4/3) accounting inflation
    # ~= 107 effective ~= the 8k row).  Quiet windows are what the
    # best-ever ratchet keeps, so the split stays a documented variant
    # (exact numerics, tests/test_flash_attention.py), not the default.
    bq_eff, bk_eff = _clamp_blocks(q.dtype, tq, tk, block_q, block_k)
    if split_diag is None:
        split_diag = False
    elif split_diag:
        # explicit opt-in: the split hardcodes causal self-attention
        # semantics, so reject configurations it would silently get wrong
        if causal is not True or tq != tk or tq % bq_eff:
            raise ValueError(
                "split_diag=True requires causal=True self-attention "
                f"(tq == tk) with block_q dividing tq; got causal={causal}, "
                f"tq={tq}, tk={tk}, effective block_q={bq_eff}")
        # the off-diagonal predicate (k_lo + block_k <= q_lo) skips key
        # columns outright if k tiles are coarser than the q banding —
        # square tiles are the only layout the split supports
        bk_eff = bq_eff
    if split_diag:
        o3, lse3 = _split_lse(to3(q, tq), to3(k, tk), to3(v, tk),
                              float(sm_scale), bq_eff, bk_eff)
    else:
        o3, lse3 = _flash_lse(to3(q, tq), to3(k, tk), to3(v, tk), causal,
                              float(sm_scale), int(block_q), int(block_k))
    o = jnp.swapaxes(o3.reshape(-1, h, tq, d), 1, 2).reshape(*lead, tq, h, d)
    lse = jnp.swapaxes(lse3.reshape(-1, h, tq), 1, 2)       # (B, Tq, H)
    return o, lse.reshape(*lead, tq, h)


def flash_attention(q, k, v, causal: bool = False, sm_scale=None,
                    block_q: int = 1024, block_k: int = 1024,
                    split_diag=None):
    """Flash attention.  ``q``: (..., Tq, H, D); ``k, v``: (..., Tk, H, D).

    Drop-in for :func:`tpu_dist.nn.attention.scaled_dot_product_attention`
    (mask=None); differentiable; O(T) memory.  ``block_q``/``block_k`` are
    VMEM tile sizes (auto-clamped for short sequences).  The 1024 defaults
    are from an on-chip sweep at (4, 8192, 8, 64) bf16 causal: large tiles
    amortize grid/DMA overhead and win ~2.5x over 128 tiles for training
    (fwd+bwd); measured vs jax.experimental.pallas.ops.tpu.flash_attention
    at the same shape this kernel is ~2x (fwd) / ~4x (fwd+bwd) faster.

    Same computation as :func:`flash_attention_with_lse` with the lse
    discarded (its cotangent is then zero, so the backward is identical).
    """
    return flash_attention_with_lse(q, k, v, causal=causal,
                                    sm_scale=sm_scale, block_q=block_q,
                                    block_k=block_k,
                                    split_diag=split_diag)[0]
