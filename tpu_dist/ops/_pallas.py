"""Shared plumbing for the Pallas kernels in this package."""

from __future__ import annotations

import jax

__all__ = ["use_interpret", "out_struct", "ceil_to"]


def ceil_to(x: int, m: int) -> int:
    """Round ``x`` up to a multiple of ``m`` (tile/lane alignment)."""
    return (x + m - 1) // m * m


def use_interpret() -> bool:
    """Compiled Mosaic on TPU; the HLO interpreter everywhere else.

    NOTE every kernel body in this package is wrapped in ``pl.when`` (a
    causal tile-skip predicate, or a trivially-true one).  That is not only
    an optimization: the HLO interpreter's discharge of a *bare* kernel
    body trips shard_map's varying-manual-axes check (ops mixing
    device-varying block data with invariant constants), while the
    ``pl.when``-discharged form composes — and the DDP wrapper and
    ring-attention flash path trace these kernels inside shard_map.
    """
    return jax.default_backend() != "tpu"


def out_struct(shape, dtype, *operands):
    """ShapeDtypeStruct carrying the union of the operands' varying-mesh-
    axes sets — required for pallas_call outputs traced inside shard_map
    (e.g. under the DDP wrapper), harmless outside it.  The vma probe is
    version-sensitive JAX-internals territory; this is the single copy."""
    try:
        vma = frozenset().union(*(jax.typeof(x).vma for x in operands))
    except (AttributeError, TypeError):
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
