"""Grouped matrix multiply — the dropless-MoE Pallas kernel pair.

MegaBlocks-style block-diagonal expert compute (no reference counterpart:
the reference is a 486-line data-parallel image tutorial; this backs the
beyond-parity MoE substrate, nn/moe.py ``dispatch="dropless"``).

The GShard capacity formulation pads every expert to ``C = ceil(k*N/E *
capacity_factor)`` slots, burning ``capacity_factor - 1`` of the expert-FFN
FLOPs on padding (and dropping tokens when an expert overflows).  Dropless
routing instead SORTS the (choice, token) rows by expert and runs each
expert over its exact contiguous segment, padded only to the row-block
size:

    x (M, D) sorted by expert, block-aligned segments
    w (E, D, H) stacked expert weights
    out[rows of expert e] = x[rows of e] @ w[e]

``gmm`` computes that with a (row_blocks, h_tiles) grid: each row block
carries a single expert id, delivered to the weight BlockSpec's index_map
through Pallas TPU **scalar prefetch** (the map is data-dependent — exactly
what PrefetchScalarGridSpec exists for).  Row blocks past the live count
(the block-alignment overallocation tail) skip the MXU entirely and write
zeros.  ``tgmm`` is the transposed pass (dw[e] = x_e^T @ dy_e) with the
row-block sweep INNERMOST so each expert's f32 accumulator tile stays in
VMEM scratch across its segment — group boundaries, also from the
prefetched map, zero and flush it.

Only forward primitives live here; nn/moe.py composes them into the
dropless dispatch and wires the custom VJP (dx via gmm against w^T, dw/db
via tgmm — all three backward passes are themselves grouped matmuls over
the same block map, no scatters anywhere).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ._pallas import (ceil_to as _ceil_to, out_struct as _out_struct,
                      use_interpret as _use_interpret)

__all__ = ["gmm", "tgmm", "grouped_linear"]

_LANE = 128
_VMEM_BUDGET = 12 * 1024 * 1024  # leave headroom under the ~16MB scoped vmem


def _fit_blocks(block_rows: int, block_h: int, dp: int, itemsize: int,
                scratch_rows: int = 0) -> tuple[int, int]:
    """Shrink tile sizes until the double-buffered working set fits VMEM.

    The default 512x512 tiles with a wide contraction dim (e.g. the dx
    pass against a 3072-wide hidden) blow the ~16MB scoped-vmem stack;
    estimate ≈ 2x(x_tile + w_tile + out_tile) + f32 accumulator(s) and
    halve the larger tile dim until it fits (floor 128)."""
    def need(br, bh):
        tiles = (br * dp + dp * bh + br * bh) * itemsize * 2
        acc = (br * bh + scratch_rows * bh) * 4
        return tiles + acc

    while need(block_rows, block_h) > _VMEM_BUDGET and (
            block_rows > 128 or block_h > 128):
        if block_rows >= block_h and block_rows > 128:
            block_rows //= 2
        elif block_h > 128:
            block_h //= 2
        else:
            break
    return block_rows, block_h


def gmm(x, w, block_groups, n_live_blocks, *, bias=None, block_rows: int = 512,
        block_h: int = 512, out_dtype=None, activation=None):
    """Block-diagonal grouped matmul: ``out[i*B:(i+1)*B] = x[i*B:(i+1)*B]
    @ w[block_groups[i]] (+ bias[block_groups[i]])``.

    Args:
        x: (M, D) rows sorted by group, M a multiple of ``block_rows``.
        w: (E, D, H) stacked per-group weights.
        block_groups: (M // block_rows,) int32 group id per row block —
            every row in a block must belong to that group (nn/moe.py's
            sort pads each group's segment to a block multiple).
        n_live_blocks: scalar int32; blocks at index >= this are the
            overallocation tail — skipped on the MXU, written as zeros.
        bias: optional (E, H) per-group bias, added in-kernel.
        block_rows / block_h: VMEM tile sizes (D is kept whole).
        activation: optional elementwise fn applied in-kernel on the f32
            accumulator (e.g. ``jax.nn.gelu``) — saves a full (M, H) HBM
            round-trip vs applying it outside.
    Returns:
        (M, H) in ``out_dtype`` (default ``x.dtype``).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, d = x.shape
    e, dw_, h = w.shape
    if dw_ != d:
        raise ValueError(f"w contraction dim {dw_} != x dim {d}")
    if m % block_rows:
        raise ValueError(f"M={m} not a multiple of block_rows={block_rows}")
    out_dtype = out_dtype or x.dtype
    dp = _ceil_to(d, _LANE)
    block_h = min(block_h, _ceil_to(h, _LANE))
    br = block_rows
    block_rows, block_h = _fit_blocks(block_rows, block_h, dp,
                                      jnp.dtype(x.dtype).itemsize)
    if block_rows != br:
        # each caller row-block split into equal sub-blocks: expand the
        # block->group map and live count to the finer granularity
        f = br // block_rows
        block_groups = jnp.repeat(block_groups, f)
        n_live_blocks = n_live_blocks * f
    nb = m // block_rows
    hp = _ceil_to(h, block_h)
    xp = jnp.pad(x, ((0, 0), (0, dp - d)))
    wp = jnp.pad(w, ((0, 0), (0, dp - d), (0, hp - h)))
    has_bias = bias is not None
    # (E, 1, Hp): the singleton middle axis keeps the block's last-two
    # dims legal for Mosaic ((1, block_h) blocks of a 2-D (E, H) array
    # are rejected — second-to-last dim must be 8-divisible or whole)
    bp = (jnp.pad(bias, ((0, 0), (0, hp - h)))[:, None, :]
          if has_bias else jnp.zeros((e, 1, block_h), w.dtype))
    scalars = jnp.concatenate(
        [block_groups.astype(jnp.int32),
         jnp.full((1,), n_live_blocks, jnp.int32)])

    def kernel(scalar_ref, x_ref, w_ref, b_ref, o_ref):
        i = pl.program_id(1)  # row-block index (INNER — see grid note)
        live = i < scalar_ref[nb]

        @pl.when(live)
        def _():
            acc = jax.lax.dot_general(
                x_ref[...], w_ref[0], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            if has_bias:
                acc = acc + b_ref[0, 0].astype(jnp.float32)
            if activation is not None:
                acc = activation(acc)
            o_ref[...] = acc.astype(o_ref.dtype)

        @pl.when(jnp.logical_not(live))
        def _():
            o_ref[...] = jnp.zeros(o_ref.shape, o_ref.dtype)

    # Grid order matters for HBM traffic: the row sweep must be INNER so
    # the weight BlockSpec index (s[i], j) stays constant across each
    # group's contiguous row blocks and Pallas keeps the tile resident —
    # w is then DMA'd once per h-tile sweep (= once total).  Rows outer
    # re-fetched the ENTIRE weight tensor per row block (~nb x |w|, the
    # measured ~2.4 ms floor at GPT-2-small MoE shapes); x re-reads per
    # h-tile are the cheaper side of that trade (|x| << nb x |w|).
    # The no-bias placeholder is (E, 1, block_h) — a single h-block — so its
    # index_map must pin j to 0 rather than lean on Pallas' out-of-bounds
    # block-index clamping (never read, but fragile against bounds-checking
    # changes).
    bias_index = ((lambda j, i, s: (s[i], 0, j)) if has_bias
                  else (lambda j, i, s: (s[i], 0, 0)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(hp // block_h, nb),
        in_specs=[
            pl.BlockSpec((block_rows, dp), lambda j, i, s: (i, 0)),
            pl.BlockSpec((1, dp, block_h), lambda j, i, s: (s[i], 0, j)),
            pl.BlockSpec((1, 1, block_h), bias_index),
        ],
        out_specs=pl.BlockSpec((block_rows, block_h),
                               lambda j, i, s: (i, j)),
    )
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=_out_struct((m, hp), out_dtype, xp, wp, bp),
        interpret=_use_interpret(),
    )(scalars, xp, wp, bp)
    return out[:, :h]


def tgmm(x, dy, block_groups, n_groups: int, *, block_rows: int = 512,
         block_h: int = 512, with_rowsum: bool = False, out_dtype=None):
    """Transposed grouped matmul: ``dw[e] = sum over e's row blocks of
    x_block^T @ dy_block`` (+ optionally ``db[e] = sum of dy rows``).

    The grid is (h_tiles, row_blocks) — row sweep INNERMOST so each
    group's (D, block_h) f32 accumulator persists in VMEM scratch across
    its contiguous segment; the prefetched ``block_groups`` map marks the
    boundaries.  Overallocation-tail blocks must carry the last live
    group's id with all-zero rows (nn/moe.py guarantees both), so they
    accumulate nothing and the final flush still fires at the grid edge.
    Groups with no rows anywhere are never visited: their output tiles are
    UNWRITTEN — the caller must mask them (nn/moe.py zeroes experts with
    zero tokens via the count vector).

    Args:
        x: (M, D); dy: (M, H); both sorted by group, M | block_rows.
        block_groups: (M // block_rows,) int32, non-decreasing.
        n_groups: E, the output's leading dim.
        with_rowsum: also return db (E, H) = per-group row sums of dy.
    Returns:
        dw (E, D, H) [, db (E, H)] in ``out_dtype`` (default x.dtype).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, d = x.shape
    m2, h = dy.shape
    if m2 != m:
        raise ValueError(f"x rows {m} != dy rows {m2}")
    if m % block_rows:
        raise ValueError(f"M={m} not a multiple of block_rows={block_rows}")
    out_dtype = out_dtype or x.dtype
    dp = _ceil_to(d, _LANE)
    block_h = min(block_h, _ceil_to(h, _LANE))
    br = block_rows
    block_rows, block_h = _fit_blocks(block_rows, block_h, dp,
                                      jnp.dtype(x.dtype).itemsize,
                                      scratch_rows=dp)
    if block_rows != br:
        block_groups = jnp.repeat(block_groups, br // block_rows)
    nb = m // block_rows
    hp = _ceil_to(h, block_h)
    xp = jnp.pad(x, ((0, 0), (0, dp - d)))
    dyp = jnp.pad(dy, ((0, 0), (0, hp - h)))
    scalars = block_groups.astype(jnp.int32)

    def kernel(scalar_ref, x_ref, dy_ref, dw_ref, db_ref, acc_scr, db_scr):
        i = pl.program_id(1)  # row-block index (inner)
        g = scalar_ref[i]
        prev = scalar_ref[jnp.maximum(i - 1, 0)]
        is_first = jnp.logical_or(i == 0, prev != g)

        @pl.when(is_first)
        def _():
            acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)
            db_scr[...] = jnp.zeros(db_scr.shape, jnp.float32)

        acc_scr[...] += jax.lax.dot_general(
            x_ref[...], dy_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if with_rowsum:
            db_scr[...] += jnp.sum(dy_ref[...].astype(jnp.float32), axis=0,
                                   keepdims=True)

        nxt = scalar_ref[jnp.minimum(i + 1, nb - 1)]
        is_last = jnp.logical_or(i == nb - 1, nxt != g)

        @pl.when(is_last)
        def _():
            dw_ref[0] = acc_scr[...].astype(dw_ref.dtype)
            db_ref[0] = db_scr[...].astype(db_ref.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(hp // block_h, nb),
        in_specs=[
            pl.BlockSpec((block_rows, dp), lambda j, i, s: (i, 0)),
            pl.BlockSpec((block_rows, block_h), lambda j, i, s: (i, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, dp, block_h), lambda j, i, s: (s[i], 0, j)),
            pl.BlockSpec((1, 1, block_h), lambda j, i, s: (s[i], 0, j)),
        ],
        scratch_shapes=[
            pltpu.VMEM((dp, block_h), jnp.float32),
            pltpu.VMEM((1, block_h), jnp.float32),
        ],
    )
    dw, db = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=[_out_struct((n_groups, dp, hp), out_dtype, xp, dyp),
                   _out_struct((n_groups, 1, hp), out_dtype, xp, dyp)],
        interpret=_use_interpret(),
    )(scalars, xp, dyp)
    dw = dw[:, :d, :h]
    return (dw, db[:, 0, :h]) if with_rowsum else dw


# ---------------------------------------------------------------------------
# differentiable wrapper
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def grouped_linear(x, w, bias, block_groups, n_live_blocks, group_present,
                   block_rows=512, block_h=512):
    """Differentiable grouped linear: ``gmm(x, w, ...) + bias[group]`` with
    the three backward passes expressed as grouped matmuls over the same
    block map (dx via gmm against w^T, dw/db via tgmm) — no scatters.

    ``group_present`` (E,) bool marks groups with at least one routed row:
    tgmm never visits an absent group, leaving its dw/db tiles unwritten
    (garbage), so the backward zero-masks them here.  Rows must be sorted
    by group with block-aligned segments and ZERO padding rows — pad rows
    then contribute nothing to any of the three grads (their x and dy are
    both zero).  Integer/bool args take no gradient."""
    return gmm(x, w, block_groups, n_live_blocks, bias=bias,
               block_rows=block_rows, block_h=block_h)


def _gl_fwd(x, w, bias, block_groups, n_live_blocks, group_present,
            block_rows, block_h):
    out = gmm(x, w, block_groups, n_live_blocks, bias=bias,
              block_rows=block_rows, block_h=block_h)
    return out, (x, w, block_groups, n_live_blocks, group_present)


def _gl_bwd(block_rows, block_h, res, dy):
    x, w, block_groups, n_live_blocks, group_present = res
    e, d, h = w.shape
    dx = gmm(dy, jnp.swapaxes(w, 1, 2), block_groups, n_live_blocks,
             block_rows=block_rows, block_h=block_h, out_dtype=x.dtype)
    if d <= h:
        dw, db = tgmm(x, dy, block_groups, e, block_rows=block_rows,
                      block_h=block_h, with_rowsum=True, out_dtype=w.dtype)
    else:
        # x wider than dy (e.g. the down-projection w2): tgmm's (D, bh)
        # f32 accumulator scales with the X side, so compute the
        # transposed product with the NARROW operand as x and swap —
        # measured necessary to keep 512-row tiles in VMEM at h=3072
        dw = jnp.swapaxes(
            tgmm(dy, x, block_groups, e, block_rows=block_rows,
                 block_h=block_h, out_dtype=w.dtype), 1, 2)
        # bias grad = per-group row sums of dy: one elementwise pass
        # (block partial sums, then a tiny scatter-add over blocks; dead
        # tail blocks carry zero dy rows and contribute nothing)
        nb = dy.shape[0] // block_rows
        blk = dy.astype(jnp.float32).reshape(nb, block_rows, h).sum(1)
        db = (jnp.zeros((e, h), jnp.float32).at[block_groups].add(blk)
              .astype(w.dtype))
    dw = jnp.where(group_present[:, None, None], dw, 0)
    db = jnp.where(group_present[:, None], db, 0)
    return dx, dw, db, None, None, None


grouped_linear.defvjp(_gl_fwd, _gl_bwd)
