"""tpu_dist.ops — custom Pallas TPU kernels (the cuDNN-extension analogue).

The reference's hot ops live in cuDNN/ATen (SURVEY.md §2b #15); tpu_dist gets
them from XLA, and this package holds the hand-written Pallas kernels for the
cases worth owning: ops where fusion XLA can't see saves HBM traffic."""

from .cross_entropy import fused_cross_entropy
from .flash_attention import flash_attention, flash_attention_with_lse
from .gmm import gmm, grouped_linear, tgmm

__all__ = ["fused_cross_entropy", "flash_attention",
           "flash_attention_with_lse", "gmm", "grouped_linear", "tgmm"]
