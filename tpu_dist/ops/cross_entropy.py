"""Fused softmax cross-entropy — Pallas TPU kernel with custom VJP.

The loss of every reference workload (`nn.CrossEntropyLoss`,
/root/reference/mpspawn_dist.py:63, example_mp.py:83).  The composed jnp
version (tpu_dist.nn.functional.cross_entropy) materializes log-softmax
(B, V) in HBM between ops; this kernel keeps each row block resident in
VMEM and emits only the per-row loss — one HBM read of the logits forward,
one read + one write backward.  Matters when V is large (LM heads), not for
V=10 image classifiers; `nn.CrossEntropyLoss(fused=True)` opts in.

Layout: grid over row blocks of ``TILE_B``; each kernel invocation sees the
full (padded-to-lane) vocab row.  Forward saves per-row logsumexp; backward
recomputes softmax from (logits, lse) — no (B, V) residual beyond the
logits themselves.

Runs on TPU via Mosaic; everywhere else (CPU tests) through
``interpret=True`` — same kernel, same numerics (tests compare against the
jnp composition and torch's own CrossEntropyLoss).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ._pallas import out_struct as _out_struct, use_interpret as _use_interpret

__all__ = ["fused_cross_entropy"]

_TILE_B = 8  # f32 sublane size; one row block per grid step
_LANE = 128


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _fwd_kernel(logits_ref, labels_ref, nll_ref, lse_ref, *, vocab: int):
    # body predicated on a trivially-true condition: the HLO interpreter's
    # discharge of a bare body trips shard_map's varying-axes check (see
    # _pallas.use_interpret) and this kernel runs under the DDP wrapper's
    # shard_map when CrossEntropyLoss(fused=True) is used
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) >= 0)
    def _():
        logits = logits_ref[:].astype(jnp.float32)       # (TILE_B, Vpad)
        cols = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        valid = cols < vocab
        logits = jnp.where(valid, logits, -jnp.inf)
        mx = jnp.max(logits, axis=1, keepdims=True)      # (TILE_B, 1)
        shifted = logits - mx
        sumexp = jnp.sum(jnp.where(valid, jnp.exp(shifted), 0.0), axis=1,
                         keepdims=True)
        lse = mx + jnp.log(sumexp)                       # (TILE_B, 1)
        onehot = cols == labels_ref[:]                   # (TILE_B, Vpad)
        picked = jnp.sum(jnp.where(onehot, logits, 0.0), axis=1,
                         keepdims=True)
        nll_ref[:] = lse - picked
        lse_ref[:] = lse


def _bwd_kernel(logits_ref, labels_ref, lse_ref, g_ref, dlogits_ref, *,
                vocab: int):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) >= 0)
    def _():
        logits = logits_ref[:].astype(jnp.float32)
        cols = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        valid = cols < vocab
        p = jnp.where(valid, jnp.exp(logits - lse_ref[:]), 0.0)
        onehot = (cols == labels_ref[:]) & valid
        dlogits_ref[:] = ((p - onehot.astype(jnp.float32)) * g_ref[:]
                          ).astype(dlogits_ref.dtype)


def _pad(logits, labels):
    b, v = logits.shape
    bp, vp = _ceil_to(b, _TILE_B), _ceil_to(v, _LANE)
    if (bp, vp) != (b, v):
        logits = jnp.pad(logits, ((0, bp - b), (0, vp - v)))
        labels = jnp.pad(labels, (0, bp - b))
    return logits, labels, bp, vp


def _call_fwd(logits, labels):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, v = logits.shape
    logits_p, labels_p, bp, vp = _pad(logits, labels)
    labels2d = labels_p.astype(jnp.int32)[:, None]       # (Bp, 1)
    grid = (bp // _TILE_B,)
    nll, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, vocab=v),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TILE_B, vp), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_TILE_B, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((_TILE_B, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_TILE_B, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _out_struct((bp, 1), jnp.float32, logits_p, labels2d),
            _out_struct((bp, 1), jnp.float32, logits_p, labels2d),
        ],
        interpret=_use_interpret(),
    )(logits_p, labels2d)
    return nll[:b, 0], lse[:b, 0]


def _call_bwd(logits, labels, lse, g_rows):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, v = logits.shape
    logits_p, labels_p, bp, vp = _pad(logits, labels)
    labels2d = labels_p.astype(jnp.int32)[:, None]
    lse2d = jnp.pad(lse, (0, bp - b))[:, None]
    g2d = jnp.pad(g_rows, (0, bp - b))[:, None].astype(jnp.float32)
    grid = (bp // _TILE_B,)
    dlogits = pl.pallas_call(
        functools.partial(_bwd_kernel, vocab=v),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TILE_B, vp), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_TILE_B, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_TILE_B, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_TILE_B, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((_TILE_B, vp), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=_out_struct((bp, vp), logits.dtype, logits_p, labels2d,
                              lse2d, g2d),
        interpret=_use_interpret(),
    )(logits_p, labels2d, lse2d, g2d)
    return dlogits[:b, :v]


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _fused_nll(logits, labels):
    nll, _ = _call_fwd(logits, labels)
    return nll


def _fused_nll_fwd(logits, labels):
    nll, lse = _call_fwd(logits, labels)
    return nll, (logits, labels, lse)


def _fused_nll_bwd(res, g):
    logits, labels, lse = res
    return _call_bwd(logits, labels, lse, g), None


_fused_nll.defvjp(_fused_nll_fwd, _fused_nll_bwd)


def fused_cross_entropy(logits, labels, reduction: str = "mean"):
    """Drop-in for :func:`tpu_dist.nn.functional.cross_entropy`, computed by
    the Pallas kernel.  ``logits``: (..., V); ``labels``: integer (...)."""
    v = logits.shape[-1]
    flat_logits = logits.reshape(-1, v)
    flat_labels = labels.reshape(-1)
    nll = _fused_nll(flat_logits, flat_labels)
    if reduction == "mean":
        return nll.mean()
    if reduction == "sum":
        return nll.sum()
    if reduction == "none":
        return nll.reshape(labels.shape)
    raise ValueError(f"Unknown reduction {reduction!r}")
