"""SGD with momentum / nesterov / weight decay — torch.optim.SGD parity.

The reference uses ``torch.optim.SGD(params, 1e-4)`` for MNIST
(/root/reference/mpspawn_dist.py:64) and ``SGD(lr=0.02, momentum=0.9,
weight_decay=1e-4, nesterov=True)`` for CIFAR (/root/reference/example_mp.py:84-90).

Pure-pytree design: the optimizer owns no arrays; ``init`` builds the state
pytree and ``update`` is a pure function — so the whole update fuses into the
jitted train step alongside the gradient ``psum``.

Update rule (torch semantics, dampening=0):

    g   = grad + weight_decay * param
    buf = momentum * buf + g
    g   = g + momentum * buf        (nesterov)    |    g = buf   (classic)
    param -= lr * g

Zero-initialized buffers reproduce torch's first-step ``buf = g`` exactly
when dampening is 0 (the only configuration the reference uses).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Union

import jax
import jax.numpy as jnp

__all__ = ["SGD"]

LrLike = Union[float, Callable[[jnp.ndarray], jnp.ndarray]]


class SGD:
    def __init__(self, lr: LrLike, momentum: float = 0.0,
                 weight_decay: float = 0.0, nesterov: bool = False,
                 dampening: float = 0.0):
        """``lr`` may be a float or a compiled-in schedule
        (:mod:`tpu_dist.optim.lr_scheduler`): a callable of the update
        count, evaluated on-device inside the jitted step."""
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError("Nesterov momentum requires momentum > 0 and "
                             "dampening = 0")
        self.lr = lr
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.dampening = dampening

    def init(self, params) -> Dict[str, Any]:
        state: Dict[str, Any] = {}
        if callable(self.lr):
            state["step"] = jnp.zeros((), jnp.int32)
        if self.momentum != 0.0:
            state["momentum"] = jax.tree.map(jnp.zeros_like, params)
        return state

    def update(self, grads, opt_state, params):
        """Return ``(new_params, new_opt_state)``; pure function of inputs."""
        mom, wd, damp = self.momentum, self.weight_decay, self.dampening
        if callable(self.lr):
            # schedule of the pre-update step count: the first update uses
            # lr(0), matching a torch scheduler set before optimizer.step()
            lr = self.lr(opt_state["step"])
            opt_state = dict(opt_state, step=opt_state["step"] + 1)
        else:
            lr = self.lr

        if mom == 0.0:
            def step(p, g):
                if wd:
                    g = g + wd * p
                return p - lr * g
            return jax.tree.map(step, params, grads), opt_state

        if wd:
            grads = jax.tree.map(lambda g, p: g + wd * p, grads, params)
        new_buf = jax.tree.map(lambda buf, g: mom * buf + (1.0 - damp) * g,
                               opt_state["momentum"], grads)
        if self.nesterov:
            new_params = jax.tree.map(
                lambda p, g, buf: p - lr * (g + mom * buf),
                params, grads, new_buf)
        else:
            new_params = jax.tree.map(lambda p, buf: p - lr * buf,
                                      params, new_buf)
        return new_params, dict(opt_state, momentum=new_buf)
