"""tpu_dist.optim — pure-pytree optimizers + compiled-in lr schedules."""

from .adagrad import Adagrad
from .adamw import Adam, AdamW
from .clip import (clip_grad_norm, global_norm, sharded_clip_grad_norm,
                   sharded_global_norm)
from .ema import EMA
from .lr_scheduler import (constant_lr, cosine_annealing_lr, exponential_lr,
                           linear_lr, multistep_lr, sequential_lr, step_lr,
                           warmup_cosine)
from .rmsprop import RMSprop
from .sgd import SGD

__all__ = ["SGD", "Adam", "AdamW", "RMSprop", "Adagrad", "EMA",
           "clip_grad_norm", "global_norm",
           "sharded_clip_grad_norm", "sharded_global_norm",
           "step_lr", "multistep_lr", "exponential_lr", "linear_lr",
           "cosine_annealing_lr", "constant_lr", "sequential_lr",
           "warmup_cosine"]
