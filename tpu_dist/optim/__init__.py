"""tpu_dist.optim — pure-pytree optimizers."""

from .adamw import Adam, AdamW
from .clip import clip_grad_norm, global_norm
from .sgd import SGD

__all__ = ["SGD", "Adam", "AdamW", "clip_grad_norm", "global_norm"]
