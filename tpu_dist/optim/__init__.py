"""tpu_dist.optim — pure-pytree optimizers."""

from .sgd import SGD

__all__ = ["SGD"]
