"""AdamW (+ plain Adam) — torch.optim.AdamW parity, pure-pytree.

The reference uses only SGD (its workloads are small image classifiers,
/root/reference/mpspawn_dist.py:64, example_mp.py:84-90); AdamW exists
because tpu_dist's beyond-parity workload is LM training
(models/transformer.py), where Adam-family optimizers are the default.

Same pure-pytree contract as :class:`tpu_dist.optim.SGD`: ``init`` builds
the state, ``update(grads, opt_state, params)`` is a pure function, so the
whole update fuses into the jitted train step (and shards under the DDP
wrapper's ZeRO-1 option, which is optimizer-agnostic).

Update rule (torch semantics):

    m   = b1*m + (1-b1)*g;     v = b2*v + (1-b2)*g^2
    mh  = m / (1 - b1^t);      vh = v / (1 - b2^t)
    p  -= lr * weight_decay * p                 (decoupled, AdamW)
    p  -= lr * mh / (sqrt(vh) + eps)

``decoupled=False`` gives classic Adam (L2 folded into the gradient
pre-moments, torch.optim.Adam's ``weight_decay`` semantics).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Union

import jax
import jax.numpy as jnp

__all__ = ["AdamW", "Adam"]

LrLike = Union[float, Callable[[jnp.ndarray], jnp.ndarray]]


class AdamW:
    def __init__(self, lr: LrLike = 1e-3, betas=(0.9, 0.999),
                 eps: float = 1e-8, weight_decay: float = 1e-2,
                 decoupled: bool = True):
        """``lr`` may be a float or a compiled-in schedule
        (:mod:`tpu_dist.optim.lr_scheduler`): a callable of the update
        count, evaluated on-device inside the jitted step."""
        if not 0.0 <= betas[0] < 1.0 or not 0.0 <= betas[1] < 1.0:
            raise ValueError(f"Invalid betas {betas}")
        if eps <= 0.0:
            raise ValueError(f"Invalid eps {eps}")
        self.lr = lr
        self.betas = tuple(betas)
        self.eps = eps
        self.weight_decay = weight_decay
        self.decoupled = decoupled

    def init(self, params) -> Dict[str, Any]:
        zeros = lambda: jax.tree.map(jnp.zeros_like, params)
        return {"m": zeros(), "v": zeros(),
                "step": jnp.zeros((), jnp.int32)}

    def update(self, grads, opt_state, params):
        """Return ``(new_params, new_opt_state)``; pure function."""
        b1, b2 = self.betas
        t = opt_state["step"] + 1
        # bias corrections in f32 (t is an int32 scalar on device)
        c1 = 1.0 - b1 ** t.astype(jnp.float32)
        c2 = 1.0 - b2 ** t.astype(jnp.float32)
        wd = self.weight_decay
        # callable lr = a compiled-in schedule of the pre-update step count
        # (tpu_dist.optim.lr_scheduler); first update uses lr(0)
        lr = self.lr(opt_state["step"]) if callable(self.lr) else self.lr

        if wd and not self.decoupled:
            grads = jax.tree.map(lambda g, p: g + wd * p, grads, params)

        new_m = jax.tree.map(lambda m, g: b1 * m + (1.0 - b1) * g,
                             opt_state["m"], grads)
        new_v = jax.tree.map(lambda v, g: b2 * v + (1.0 - b2) * jnp.square(g),
                             opt_state["v"], grads)

        def step(p, m, v):
            upd = (m / c1) / (jnp.sqrt(v / c2) + self.eps)
            if wd and self.decoupled:
                p = p - lr * wd * p                  # AdamW decoupled decay
            return p - lr * upd

        new_params = jax.tree.map(step, params, new_m, new_v)
        return new_params, {"m": new_m, "v": new_v, "step": t}


def Adam(lr: LrLike = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
         weight_decay: float = 0.0) -> AdamW:
    """torch.optim.Adam semantics: L2 weight decay folded into gradients."""
    return AdamW(lr=lr, betas=betas, eps=eps, weight_decay=weight_decay,
                 decoupled=False)
