"""Adagrad — torch.optim.Adagrad parity, pure-pytree.

Same pure-pytree contract as :class:`tpu_dist.optim.SGD` (see rmsprop.py
for the rationale).  Update rule (torch semantics, including the built-in
lr decay over update count t = 1, 2, ...):

    g    = g + wd * p
    clr  = lr / (1 + (t - 1) * lr_decay)
    sum += g^2
    p   -= clr * g / (sqrt(sum) + eps)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Union

import jax
import jax.numpy as jnp

__all__ = ["Adagrad"]

LrLike = Union[float, Callable[[jnp.ndarray], jnp.ndarray]]


class Adagrad:
    def __init__(self, lr: LrLike = 1e-2, lr_decay: float = 0.0,
                 weight_decay: float = 0.0,
                 initial_accumulator_value: float = 0.0,
                 eps: float = 1e-10):
        if lr_decay < 0.0:
            raise ValueError(f"Invalid lr_decay {lr_decay}")
        if eps <= 0.0:
            raise ValueError(f"Invalid eps {eps}")
        if initial_accumulator_value < 0.0:
            raise ValueError(
                f"Invalid initial_accumulator_value "
                f"{initial_accumulator_value}")
        self.lr = lr
        self.lr_decay = lr_decay
        self.weight_decay = weight_decay
        self.initial_accumulator_value = initial_accumulator_value
        self.eps = eps

    def init(self, params) -> Dict[str, Any]:
        iv = self.initial_accumulator_value
        return {"sum": jax.tree.map(
                    lambda p: jnp.full_like(p, iv), params),
                "step": jnp.zeros((), jnp.int32)}

    def update(self, grads, opt_state, params):
        """Return ``(new_params, new_opt_state)``; pure function."""
        wd = self.weight_decay
        t = opt_state["step"]  # prior update count; torch's t-1 with t>=1
        lr = self.lr(t) if callable(self.lr) else self.lr
        clr = lr / (1.0 + t.astype(jnp.float32) * self.lr_decay)

        if wd:
            grads = jax.tree.map(lambda g, p: g + wd * p, grads, params)
        new_sum = jax.tree.map(lambda s, g: s + jnp.square(g),
                               opt_state["sum"], grads)
        new_params = jax.tree.map(
            lambda p, g, s: p - clr * g / (jnp.sqrt(s) + self.eps),
            params, grads, new_sum)
        return new_params, {"sum": new_sum, "step": t + 1}

    def __repr__(self):
        return (f"Adagrad(lr={self.lr}, lr_decay={self.lr_decay}, "
                f"weight_decay={self.weight_decay})")
