"""Learning-rate schedules — torch.optim.lr_scheduler parity, compiled in.

torch schedulers are *stateful objects* mutating ``optimizer.param_groups``
between steps; on TPU that would force a recompile (or a host round-trip)
every time the lr changes.  Here a schedule is a **pure function of the
on-device step counter** ``f(step) -> lr`` built from ``jnp`` ops, passed
*as* the optimizer's ``lr``: the optimizer evaluates it inside the jitted
train step, so the whole schedule compiles into the XLA graph once and the
lr changes every step for free.

The reference never schedules (its scripts use fixed lr,
/root/reference/mpspawn_dist.py:64, example_mp.py:84-90); this exists for
torch API completeness (torch.optim.lr_scheduler is part of the surface
its README's training flow implies) and for the LM workloads where
warmup+decay is the default recipe.

Semantics note: torch schedulers usually ``.step()`` once per *epoch*;
these are functions of whatever counter the optimizer maintains (one tick
per ``update``).  To schedule per-epoch, scale boundaries by
steps-per-epoch.  All match their torch namesakes exactly as sequences:
``schedule(i) == torch_scheduler_lr_after_i_steps`` (tested).

Usage::

    sched = optim.warmup_cosine(peak_lr=3e-4, warmup_steps=1000,
                                total_steps=100_000)
    opt = optim.AdamW(lr=sched)          # optimizers accept callables
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax.numpy as jnp

__all__ = ["step_lr", "multistep_lr", "exponential_lr", "linear_lr",
           "cosine_annealing_lr", "constant_lr", "warmup_cosine",
           "sequential_lr"]

Schedule = Callable[[jnp.ndarray], jnp.ndarray]


def _f32(step):
    return jnp.asarray(step).astype(jnp.float32)


def step_lr(lr: float, step_size: int, gamma: float = 0.1) -> Schedule:
    """``torch.optim.lr_scheduler.StepLR``: decay by ``gamma`` every
    ``step_size`` steps."""
    return lambda step: lr * gamma ** jnp.floor(_f32(step) / step_size)


def multistep_lr(lr: float, milestones: Sequence[int],
                 gamma: float = 0.1) -> Schedule:
    """``MultiStepLR``: decay by ``gamma`` at each milestone step."""
    ms = jnp.asarray(sorted(milestones), jnp.float32)
    return lambda step: lr * gamma ** jnp.sum(_f32(step) >= ms)


def exponential_lr(lr: float, gamma: float) -> Schedule:
    """``ExponentialLR``: multiply by ``gamma`` every step."""
    return lambda step: lr * gamma ** _f32(step)


def linear_lr(lr: float, start_factor: float = 1.0 / 3,
              end_factor: float = 1.0, total_iters: int = 5) -> Schedule:
    """``LinearLR``: interpolate the lr factor from ``start_factor`` to
    ``end_factor`` over ``total_iters`` steps (constant after)."""
    def f(step):
        t = jnp.clip(_f32(step) / total_iters, 0.0, 1.0)
        return lr * (start_factor + (end_factor - start_factor) * t)
    return f


def cosine_annealing_lr(lr: float, t_max: int,
                        eta_min: float = 0.0) -> Schedule:
    """``CosineAnnealingLR``: cosine from ``lr`` to ``eta_min`` over
    ``t_max`` steps (continues the cosine past t_max, like torch)."""
    def f(step):
        return eta_min + 0.5 * (lr - eta_min) * (
            1.0 + jnp.cos(jnp.pi * _f32(step) / t_max))
    return f


def constant_lr(lr: float, factor: float = 1.0 / 3,
                total_iters: int = 5) -> Schedule:
    """``ConstantLR``: ``lr * factor`` for the first ``total_iters`` steps,
    then ``lr``."""
    return lambda step: lr * jnp.where(_f32(step) < total_iters, factor, 1.0)


def sequential_lr(schedules: Sequence[Schedule],
                  milestones: Sequence[int]) -> Schedule:
    """``SequentialLR``: switch between schedules at the milestone steps;
    each schedule sees a counter restarted at its milestone."""
    if len(schedules) != len(milestones) + 1:
        raise ValueError(f"{len(schedules)} schedules need "
                         f"{len(schedules) - 1} milestones, got "
                         f"{len(milestones)}")
    bounds = [0] + list(milestones)

    def f(step):
        s = _f32(step)
        out = schedules[0](s)
        for sched, b in zip(schedules[1:], bounds[1:]):
            out = jnp.where(s >= b, sched(s - b), out)
        return out
    return f


def warmup_cosine(peak_lr: float, warmup_steps: int, total_steps: int,
                  end_lr: float = 0.0) -> Schedule:
    """Linear warmup 0 → ``peak_lr`` then cosine decay to ``end_lr`` — the
    standard LM recipe (no single torch class; equals SequentialLR of
    LinearLR + CosineAnnealingLR)."""
    def f(step):
        s = _f32(step)
        warm = peak_lr * s / jnp.maximum(warmup_steps, 1)
        t = jnp.clip((s - warmup_steps) / jnp.maximum(
            total_steps - warmup_steps, 1), 0.0, 1.0)
        decay = end_lr + 0.5 * (peak_lr - end_lr) * (1.0 + jnp.cos(jnp.pi * t))
        return jnp.where(s < warmup_steps, warm, decay)
    return f
