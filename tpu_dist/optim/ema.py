"""Exponential moving average of parameters — torch AveragedModel parity.

torch's ``swa_utils.AveragedModel(..., avg_fn=get_ema_avg_fn(decay))``
shadows a stateful module; here the EMA is a pure pytree transform in the
same ``init``/``update`` contract as the optimizers, so the shadow update
fuses into the jitted train step (one extra fma per parameter, free under
the HBM roofline) instead of running as a host-side module copy.

    ema = optim.EMA(decay=0.999)
    ema_state = ema.init(params)
    ...inside the train step...
    ema_state = ema.update(ema_state, new_params)
    ...at eval time...
    eval_params = ema.params(ema_state)   # bias-corrected average

Bias correction (``debias=True``, default): early steps correct the
zero-ish initialization the same way Adam corrects its moments
(shadow / (1 - decay^t)) — with the torch-style raw shadow available via
``debias=False`` (AveragedModel seeds the shadow with the first params
instead; seeded-init equals debiased-init after the first update).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

__all__ = ["EMA"]


class EMA:
    def __init__(self, decay: float = 0.999, debias: bool = True):
        if not 0.0 < decay < 1.0:
            raise ValueError(f"decay must be in (0, 1), got {decay}")
        self.decay = decay
        self.debias = debias

    def init(self, params) -> Dict[str, Any]:
        """Build the shadow state.

        ``debias=True``: zero-initialized shadow, reconstructed by the
        correction in :meth:`params`.  ``debias=False``: seeded with
        ``params`` (counts as the first update) — exactly AveragedModel's
        first ``update_parameters`` call, so the raw shadow is meaningful
        from step one instead of spending ~1/(1-decay) steps near zero.
        """
        if self.debias:
            return {"shadow": jax.tree.map(jnp.zeros_like, params),
                    "step": jnp.zeros((), jnp.int32)}
        return {"shadow": jax.tree.map(jnp.array, params),
                "step": jnp.ones((), jnp.int32)}

    def update(self, ema_state, params):
        """Fold the current params into the shadow; pure function."""
        d = self.decay
        shadow = jax.tree.map(lambda s, p: d * s + (1.0 - d) * p,
                              ema_state["shadow"], params)
        return {"shadow": shadow, "step": ema_state["step"] + 1}

    def params(self, ema_state):
        """The averaged parameters (bias-corrected when ``debias``)."""
        if not self.debias:
            return ema_state["shadow"]
        t = ema_state["step"].astype(jnp.float32)
        c = 1.0 - self.decay ** t
        c = jnp.maximum(c, jnp.finfo(jnp.float32).tiny)
        return jax.tree.map(lambda s: s / c, ema_state["shadow"])
