"""Gradient clipping — torch.nn.utils.clip_grad_norm_ parity, pure-pytree.

No reference counterpart (its scripts never clip); provided because global-
norm clipping is standard for the LM workloads tpu_dist adds.  Pure
function of the gradient pytree, so it fuses into the jitted step; under
the DDP wrapper call it on the *averaged* gradients (inside a custom step)
— the global norm is then identical on every replica, like torch DDP
clipping after allreduce.

**Sharded path (ZeRO)**: when each rank holds only its owned flat shard of
every gradient leaf (``Bucketer.reduce_scatter``,
tpu_dist/parallel/zero.py), :func:`sharded_global_norm` computes the local
sum of squares over the owned chunks and folds the rank partials with ONE
scalar host all-reduce — no rank ever materializes the full gradient.
:func:`global_norm` accumulates over each leaf *flattened* so that a
world-1 shard (the whole leaf, flat) produces the bit-identical partial
sum: sharded clipping equals replicated clipping bitwise at world 1, and
numerically (the rank partials associate differently) across worlds.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["clip_grad_norm", "global_norm",
           "sharded_clip_grad_norm", "sharded_global_norm"]


def _leaf_sq(g) -> jax.Array:
    # flattened before the sum: XLA's reduction order depends on layout, so
    # flattening here is what lets a flat ZeRO shard covering the whole
    # leaf (world 1) reproduce this partial bit-for-bit
    return jnp.sum(jnp.square(jnp.reshape(g, (-1,)).astype(jnp.float32)))


def global_norm(grads) -> jax.Array:
    """L2 norm over every leaf of the pytree (torch: total_norm)."""
    leaves = jax.tree.leaves(grads)
    return jnp.sqrt(sum(_leaf_sq(g) for g in leaves))


def clip_grad_norm(grads, max_norm: float):
    """Scale ``grads`` so their global L2 norm is at most ``max_norm``.

    Returns ``(clipped_grads, total_norm)`` — like torch's
    ``clip_grad_norm_``, which returns the pre-clip norm.
    """
    norm = global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return jax.tree.map(lambda g: (g.astype(jnp.float32) * scale
                                   ).astype(g.dtype), grads), norm


def sharded_global_norm(shards, group=None, all_reduce=None) -> jax.Array:
    """Global L2 norm from per-rank owned shards: local sum of squares over
    this rank's fragments (same pytree structure as the gradient tree,
    leaves = owned flat chunks) + one scalar host all-reduce.

    Every element of every leaf is owned by exactly one rank
    (``Bucketer.reduce_scatter``'s partition), so the summed partials cover
    the gradient exactly once.  At world 1 (shards are whole flattened
    leaves) this is bitwise-equal to :func:`global_norm`.

    ``all_reduce`` overrides the scalar sum collective (signature
    ``f(np.float32 scalar) -> scalar``) — in-process multi-rank test rigs
    route it over a pinned DataPlane; the default is the eager
    ``all_reduce_host`` on ``group``."""
    import numpy as np

    local = sum((_leaf_sq(g) for g in jax.tree.leaves(shards)),
                jnp.float32(0.0))
    if all_reduce is None:
        from ..collectives import eager as _eager
        total = _eager.all_reduce_host(np.float32(local), group=group,
                                       op="sum")
    else:
        total = all_reduce(np.float32(local))
    return jnp.sqrt(jnp.float32(np.asarray(total)))


def sharded_clip_grad_norm(shards, max_norm: float, group=None,
                           all_reduce=None):
    """:func:`clip_grad_norm` over per-rank owned shards: ONE scalar
    all-reduce computes the global norm, then each rank scales only the
    fragments it owns.  Returns ``(clipped_shards, total_norm)``.

    The scale factor is computed with the exact expression
    :func:`clip_grad_norm` uses, from a bitwise-identical norm at world 1 —
    so clipping under ZeRO matches replicated clipping bit-for-bit there,
    and numerically across worlds."""
    norm = sharded_global_norm(shards, group=group, all_reduce=all_reduce)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return jax.tree.map(lambda g: (g.astype(jnp.float32) * scale
                                   ).astype(g.dtype), shards), norm
