"""Gradient clipping — torch.nn.utils.clip_grad_norm_ parity, pure-pytree.

No reference counterpart (its scripts never clip); provided because global-
norm clipping is standard for the LM workloads tpu_dist adds.  Pure
function of the gradient pytree, so it fuses into the jitted step; under
the DDP wrapper call it on the *averaged* gradients (inside a custom step)
— the global norm is then identical on every replica, like torch DDP
clipping after allreduce.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["clip_grad_norm", "global_norm"]


def global_norm(grads) -> jax.Array:
    """L2 norm over every leaf of the pytree (torch: total_norm)."""
    leaves = jax.tree.leaves(grads)
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                        for g in leaves))


def clip_grad_norm(grads, max_norm: float):
    """Scale ``grads`` so their global L2 norm is at most ``max_norm``.

    Returns ``(clipped_grads, total_norm)`` — like torch's
    ``clip_grad_norm_``, which returns the pre-clip norm.
    """
    norm = global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return jax.tree.map(lambda g: (g.astype(jnp.float32) * scale
                                   ).astype(g.dtype), grads), norm
