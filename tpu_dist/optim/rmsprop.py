"""RMSprop — torch.optim.RMSprop parity, pure-pytree.

The reference uses only SGD (/root/reference/mpspawn_dist.py:64,
example_mp.py:84-90); RMSprop rounds out the torch.optim surface a
reference user would reach for next (RNN-style workloads).

Same pure-pytree contract as :class:`tpu_dist.optim.SGD`: ``init`` builds
the state, ``update(grads, opt_state, params)`` is a pure function, so the
whole update fuses into the jitted train step (and shards under the DDP
wrapper's ZeRO-1 option, which is optimizer-agnostic).

Update rule (torch semantics — eps is added AFTER the square root, and
weight decay folds into the gradient before the moment update):

    g   = g + wd * p
    sa  = alpha * sa + (1 - alpha) * g^2
    ga  = alpha * ga + (1 - alpha) * g          (centered only)
    den = sqrt(sa - ga^2) + eps                 (sa alone if not centered)
    buf = momentum * buf + g / den;  p -= lr * buf      (momentum > 0)
    p  -= lr * g / den                                  (momentum == 0)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Union

import jax
import jax.numpy as jnp

__all__ = ["RMSprop"]

LrLike = Union[float, Callable[[jnp.ndarray], jnp.ndarray]]


class RMSprop:
    def __init__(self, lr: LrLike = 1e-2, alpha: float = 0.99,
                 eps: float = 1e-8, weight_decay: float = 0.0,
                 momentum: float = 0.0, centered: bool = False):
        """``lr`` may be a float or a compiled-in schedule
        (:mod:`tpu_dist.optim.lr_scheduler`)."""
        if not 0.0 <= alpha < 1.0:
            raise ValueError(f"Invalid alpha {alpha}")
        if eps <= 0.0:
            raise ValueError(f"Invalid eps {eps}")
        if momentum < 0.0:
            raise ValueError(f"Invalid momentum {momentum}")
        self.lr = lr
        self.alpha = alpha
        self.eps = eps
        self.weight_decay = weight_decay
        self.momentum = momentum
        self.centered = centered

    def init(self, params) -> Dict[str, Any]:
        zeros = lambda: jax.tree.map(jnp.zeros_like, params)
        state: Dict[str, Any] = {"square_avg": zeros(),
                                 "step": jnp.zeros((), jnp.int32)}
        if self.momentum > 0.0:
            state["momentum_buffer"] = zeros()
        if self.centered:
            state["grad_avg"] = zeros()
        return state

    def update(self, grads, opt_state, params):
        """Return ``(new_params, new_opt_state)``; pure function."""
        a = self.alpha
        wd = self.weight_decay
        lr = self.lr(opt_state["step"]) if callable(self.lr) else self.lr

        if wd:
            grads = jax.tree.map(lambda g, p: g + wd * p, grads, params)

        new_sa = jax.tree.map(lambda s, g: a * s + (1.0 - a) * jnp.square(g),
                              opt_state["square_avg"], grads)
        new_state: Dict[str, Any] = {"square_avg": new_sa,
                                     "step": opt_state["step"] + 1}

        if self.centered:
            new_ga = jax.tree.map(lambda m, g: a * m + (1.0 - a) * g,
                                  opt_state["grad_avg"], grads)
            new_state["grad_avg"] = new_ga
            den = jax.tree.map(
                lambda s, m: jnp.sqrt(s - jnp.square(m)) + self.eps,
                new_sa, new_ga)
        else:
            den = jax.tree.map(lambda s: jnp.sqrt(s) + self.eps, new_sa)

        if self.momentum > 0.0:
            new_buf = jax.tree.map(
                lambda b, g, d: self.momentum * b + g / d,
                opt_state["momentum_buffer"], grads, den)
            new_state["momentum_buffer"] = new_buf
            new_params = jax.tree.map(lambda p, b: p - lr * b,
                                      params, new_buf)
        else:
            new_params = jax.tree.map(lambda p, g, d: p - lr * g / d,
                                      params, grads, den)
        return new_params, new_state

    def __repr__(self):
        return (f"RMSprop(lr={self.lr}, alpha={self.alpha}, "
                f"momentum={self.momentum}, centered={self.centered})")
