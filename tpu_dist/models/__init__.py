"""tpu_dist.models — reference workload architectures."""

from .convnet import ConvNet
from .resnet import ResNet, resnet18, resnet34, resnet50
from .transformer import TransformerBlock, TransformerLM

__all__ = ["ConvNet", "ResNet", "resnet18", "resnet34", "resnet50",
           "TransformerLM", "TransformerBlock"]
