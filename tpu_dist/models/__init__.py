"""tpu_dist.models — reference workload architectures."""

from .convnet import ConvNet
from .resnet import ResNet, resnet18, resnet34, resnet50

__all__ = ["ConvNet", "ResNet", "resnet18", "resnet34", "resnet50"]
