"""tpu_dist.models — reference workload architectures."""

from .convnet import ConvNet
from .resnet import ResNet, resnet18, resnet34, resnet50
from .transformer import TransformerBlock, TransformerLM
from .vgg import (VGG, vgg11, vgg11_bn, vgg13, vgg13_bn, vgg16, vgg16_bn,
                  vgg19, vgg19_bn)

__all__ = ["ConvNet", "ResNet", "resnet18", "resnet34", "resnet50",
           "TransformerLM", "TransformerBlock",
           "VGG", "vgg11", "vgg13", "vgg16", "vgg19",
           "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn"]
