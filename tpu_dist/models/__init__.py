"""tpu_dist.models — reference workload architectures."""

from .convnet import ConvNet
from .resnet import ResNet, resnet18, resnet34, resnet50
from .transformer import TransformerBlock, TransformerLM
from .vgg import (VGG, vgg11, vgg11_bn, vgg13, vgg13_bn, vgg16, vgg16_bn,
                  vgg19, vgg19_bn)
from .vit import VisionTransformer, vit_b_16, vit_b_32, vit_l_16, vit_l_32

__all__ = ["ConvNet", "ResNet", "resnet18", "resnet34", "resnet50",
           "TransformerLM", "TransformerBlock",
           "VGG", "vgg11", "vgg13", "vgg16", "vgg19",
           "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn",
           "VisionTransformer", "vit_b_16", "vit_b_32", "vit_l_16",
           "vit_l_32"]
