"""MNIST ConvNet — exact architecture of the reference tutorial.

Mirrors ``ConvNet`` at /root/reference/mpspawn_dist.py:11-43 (duplicated at
/root/reference/launch_dist.py:9-41) layer by layer, including its quirks:

- conv1: 5x5, stride 1, padding **1** (not 2) → 28x28 → 26x26
- maxpool1: 2x2 stride 2 → 13x13
- conv2: 3x3, no padding → 11x11; maxpool2: 2x2 **stride 1** → 10x10
- conv3: 3x3, no padding → 8x8; maxpool3: 2x2 stride 2 → 4x4
- fc: 128*4*4 → 10
- a Dropout(0.5) layer is *defined but never used in forward* (dead layer in
  the reference; reproduced for parameter/architecture parity).

Input layout is NHWC (TPU-first): (batch, 28, 28, 1).
"""

from __future__ import annotations

from .. import nn

__all__ = ["ConvNet"]


class ConvNet(nn.Module):
    def __init__(self):
        super().__init__()
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2d(1, 32, kernel_size=5, stride=1, padding=1)
        self.maxpool1 = nn.MaxPool2d(kernel_size=2, stride=2)
        self.conv2 = nn.Conv2d(32, 64, kernel_size=3, stride=1)
        self.maxpool2 = nn.MaxPool2d(kernel_size=2, stride=1)
        self.conv3 = nn.Conv2d(64, 128, kernel_size=3, stride=1)
        self.maxpool3 = nn.MaxPool2d(kernel_size=2, stride=2)
        self.dropout = nn.Dropout(p=0.5)  # defined, never called (as in ref)
        self.fc1 = nn.Linear(128 * 4 * 4, 10)

    def forward(self, x):
        x = self.maxpool1(self.relu(self.conv1(x)))
        x = self.maxpool2(self.relu(self.conv2(x)))
        x = self.maxpool3(self.relu(self.conv3(x)))
        x = x.reshape(x.shape[0], -1)
        x = self.fc1(x)
        return x
