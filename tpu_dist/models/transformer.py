"""Decoder-only Transformer LM — the long-context workload.

No counterpart in the reference (its workloads are image classifiers,
SURVEY.md §2a); this model exists because tpu_dist treats sequence
parallelism as first-class: with ``sequence_axis`` set, every attention
layer runs ring (or Ulysses) attention over the mesh's sequence axis and
the same model trains on contexts far beyond one core's memory.

Architecture: pre-LN blocks (LN → MHSA → residual, LN → MLP(4x, GELU) →
residual), learned positional embeddings, weight-untied LM head.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .. import nn

__all__ = ["TransformerLM", "TransformerBlock"]


class TransformerBlock(nn.Module):
    def __init__(self, dim: int, num_heads: int, causal: bool = True,
                 sequence_axis: Optional[str] = None, mode: str = "ring"):
        super().__init__()
        self.ln1 = nn.LayerNorm(dim)
        self.attn = nn.MultiheadSelfAttention(dim, num_heads, causal=causal,
                                              sequence_axis=sequence_axis,
                                              mode=mode)
        self.ln2 = nn.LayerNorm(dim)
        self.mlp = nn.Sequential(nn.Linear(dim, 4 * dim), nn.GELU(),
                                 nn.Linear(4 * dim, dim))

    def forward(self, x):
        x = x + self.attn(self.ln1(x))
        x = x + self.mlp(self.ln2(x))
        return x


class TransformerLM(nn.Module):
    """Causal LM: tokens (B, T) → logits (B, T, vocab).

    ``sequence_axis``: mesh axis name for sequence parallelism.  Embeddings
    are computed on the local sequence shard; the shard's global position
    offset is derived **automatically** from ``lax.axis_index(sequence_axis)``
    when tracing inside ``shard_map`` — callers never plumb it.  Pass
    ``pos_offset`` only to override (e.g. sliding-window training on
    unsharded models).
    """

    def __init__(self, vocab_size: int, dim: int = 128, depth: int = 2,
                 num_heads: int = 4, max_seq_len: int = 1024,
                 causal: bool = True, sequence_axis: Optional[str] = None,
                 mode: str = "ring", remat: bool = False):
        super().__init__()
        self.vocab_size = vocab_size
        self.max_seq_len = max_seq_len
        self.tok = nn.Embedding(vocab_size, dim)
        self.pos = nn.Embedding(max_seq_len, dim)
        for i in range(depth):
            setattr(self, f"block{i}", TransformerBlock(
                dim, num_heads, causal=causal,
                sequence_axis=sequence_axis, mode=mode))
        self.depth = depth
        self.sequence_axis = sequence_axis
        # remat=True wraps each block in jax.checkpoint: activations inside
        # a block are recomputed during backward instead of living in HBM
        # for the whole step — the standard long-context memory/FLOPs trade
        # (per-layer residual-boundary policy, like torch's
        # checkpoint_sequential over blocks)
        self.remat = remat
        self.ln_f = nn.LayerNorm(dim)
        self.head = nn.Linear(dim, vocab_size)

    def forward(self, idx, pos_offset=None):
        t = idx.shape[1]
        if pos_offset is None:
            if self.sequence_axis is not None:
                from jax import lax
                pos_offset = lax.axis_index(self.sequence_axis) * t
            else:
                pos_offset = 0
        x = self.tok(idx) + self.pos(pos_offset + jnp.arange(t))
        for i in range(self.depth):
            block = getattr(self, f"block{i}")
            if self.remat:
                # params reach the block through the apply() context as
                # closed-over tracers; jax.checkpoint differentiates through
                # closures, so no explicit param plumbing is needed
                x = jax.checkpoint(lambda y, _b=block: _b(y))(x)
            else:
                x = block(x)
        return self.head(self.ln_f(x))
