"""Decoder-only Transformer LM — the long-context workload.

No counterpart in the reference (its workloads are image classifiers,
SURVEY.md §2a); this model exists because tpu_dist treats sequence
parallelism as first-class: with ``sequence_axis`` set, every attention
layer runs ring (or Ulysses) attention over the mesh's sequence axis and
the same model trains on contexts far beyond one core's memory.

Architecture: pre-LN blocks (LN → MHSA → residual, LN → MLP(4x, GELU) →
residual), learned positional embeddings, weight-untied LM head.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .. import nn
from ..nn.module import current_context, run_capturing_state

__all__ = ["TransformerLM", "TransformerBlock", "write_slot_rows"]


def write_slot_rows(cache, rows, slot):
    """Scatter ONE request's per-layer batch-1 cache rows into slot
    ``slot`` of a slot-cache pool, leaving every other slot untouched —
    the write half of :meth:`TransformerLM.prefill_into_slot`, factored
    out so the disaggregated-serving path (tpu_dist/serve/disagg.py) can
    land *transferred* KV rows in a decode rank's pool through the exact
    same scatter the unified engine uses (the two paths cannot drift).

    ``rows`` carries one ``{"k": (1, Tmax, ...), ...}`` entry per layer
    path; only keys present in the pool entry are written (a row's extra
    ``index`` is ignored)."""
    slot = jnp.asarray(slot, jnp.int32)
    out = {}
    for path, pool in cache.items():
        row = rows[path]
        out[path] = {
            k: jax.lax.dynamic_update_slice(
                pool[k], row[k].astype(pool[k].dtype),
                (slot,) + (0,) * (pool[k].ndim - 1))
            for k in pool}
    return out


def _norm_cls(norm: str):
    if norm == "layernorm":
        return nn.LayerNorm
    if norm == "rmsnorm":
        return nn.RMSNorm
    raise ValueError(f"Unknown norm {norm!r} (layernorm|rmsnorm)")


class TransformerBlock(nn.Module):
    def __init__(self, dim: int, num_heads: int, causal: bool = True,
                 sequence_axis: Optional[str] = None, mode: str = "ring",
                 mlp: Optional[nn.Module] = None, norm: str = "layernorm",
                 rope: bool = False, rope_theta: float = 10000.0,
                 norm_eps: Optional[float] = None):
        super().__init__()
        norm_cls = _norm_cls(norm)
        # norm_eps=None keeps each norm class's own default (LayerNorm
        # 1e-5, RMSNorm 1e-6); ViT passes 1e-6 for torchvision parity
        mk_norm = (norm_cls if norm_eps is None
                   else lambda d: norm_cls(d, eps=norm_eps))
        self.ln1 = mk_norm(dim)
        self.attn = nn.MultiheadSelfAttention(dim, num_heads, causal=causal,
                                              sequence_axis=sequence_axis,
                                              mode=mode, rope=rope,
                                              rope_theta=rope_theta)
        self.ln2 = mk_norm(dim)
        # mlp override: e.g. an nn.MoELayer for mixture-of-experts blocks
        self.mlp = mlp if mlp is not None else nn.Sequential(
            nn.Linear(dim, 4 * dim), nn.GELU(), nn.Linear(4 * dim, dim))

    def forward(self, x):
        x = x + self.attn(self.ln1(x))
        x = x + self.mlp(self.ln2(x))
        return x


class TransformerLM(nn.Module):
    """Causal LM: tokens (B, T) → logits (B, T, vocab).

    ``sequence_axis``: mesh axis name for sequence parallelism.  Embeddings
    are computed on the local sequence shard; the shard's global position
    offset is derived **automatically** from ``lax.axis_index(sequence_axis)``
    when tracing inside ``shard_map`` — callers never plumb it.  Pass
    ``pos_offset`` only to override (e.g. sliding-window training on
    unsharded models).
    """

    def __init__(self, vocab_size: int, dim: int = 128, depth: int = 2,
                 num_heads: int = 4, max_seq_len: int = 1024,
                 causal: bool = True, sequence_axis: Optional[str] = None,
                 mode: str = "ring", remat: bool = False,
                 num_experts: int = 0, moe_top_k: int = 2,
                 moe_every: int = 1, moe_capacity_factor: float = 1.25,
                 moe_dispatch: str = "einsum",
                 norm: str = "layernorm", rope: bool = False,
                 rope_theta: float = 10000.0):
        """``num_experts > 0`` makes every ``moe_every``-th block's MLP a
        routed :class:`~tpu_dist.nn.MoELayer` (expert-parallel under
        :data:`~tpu_dist.parallel.MOE_EP_RULES`); aux load-balance losses
        surface in the model state, see nn/moe.py.  ``moe_dispatch=
        "gather"`` selects the index-map dispatch (cheaper off the GSPMD
        'expert' axis — see nn/moe.py).

        ``norm="rmsnorm"`` + ``rope=True`` gives the LLaMA-family recipe:
        RMS normalization and rotary position embeddings instead of the
        learned position table (``self.pos`` is then absent — attention
        scores depend only on relative distance)."""
        super().__init__()
        if num_experts > 0 and moe_every < 1:
            raise ValueError(f"moe_every must be >= 1, got {moe_every}")
        self.vocab_size = vocab_size
        self.max_seq_len = max_seq_len
        self.num_experts = num_experts
        self.tok = nn.Embedding(vocab_size, dim)
        self.pos = None if rope else nn.Embedding(max_seq_len, dim)
        for i in range(depth):
            moe = (num_experts > 0 and i % moe_every == moe_every - 1)
            setattr(self, f"block{i}", TransformerBlock(
                dim, num_heads, causal=causal,
                sequence_axis=sequence_axis, mode=mode, norm=norm,
                rope=rope, rope_theta=rope_theta,
                mlp=nn.MoELayer(dim, num_experts, top_k=moe_top_k,
                                capacity_factor=moe_capacity_factor,
                                dispatch=moe_dispatch)
                if moe else None))
        self.depth = depth
        self.causal = causal
        self.sequence_axis = sequence_axis
        # remat=True wraps each block in jax.checkpoint: activations inside
        # a block are recomputed during backward instead of living in HBM
        # for the whole step — the standard long-context memory/FLOPs trade
        # (per-layer residual-boundary policy, like torch's
        # checkpoint_sequential over blocks)
        self.remat = remat
        self.ln_f = _norm_cls(norm)(dim)
        self.head = nn.Linear(dim, vocab_size)

    def embed_tokens(self, idx, pos_offset=None):
        """Token (+ learned positional) embeddings for ``idx`` (B, T) —
        the input half of :meth:`forward`, factored out so the
        tensor-parallel serving path (tpu_dist/serve/sharded.py) runs the
        byte-identical embedding on every shard.  ``pos_offset`` may be a
        scalar or a (B,) vector (per-slot decode positions)."""
        t = idx.shape[1]
        if pos_offset is None:
            if self.sequence_axis is not None:
                from jax import lax
                pos_offset = lax.axis_index(self.sequence_axis) * t
            else:
                pos_offset = 0
        if self.pos is not None:
            off = jnp.asarray(pos_offset)
            # vector pos_offset = per-slot decode positions (decode_step):
            # (B,) offsets index a (B, t) position table row per sequence
            pos_idx = (off[..., None] + jnp.arange(t) if off.ndim
                       else pos_offset + jnp.arange(t))
            return self.tok(idx) + self.pos(pos_idx)
        # rope: positions enter through the attention rotations
        return self.tok(idx)

    def forward(self, idx, pos_offset=None):
        x = self.embed_tokens(idx, pos_offset)
        # remat is a training-memory trade; during cached decode it must be
        # off — the attention layers' put_state writes would leak tracers
        # out of the jax.checkpoint sub-trace (and inference keeps no
        # activations anyway)
        use_remat = self.remat and not self._decoding()
        for i in range(self.depth):
            block = getattr(self, f"block{i}")
            if use_remat:
                # params reach the block through the apply() context as
                # closed-over tracers; jax.checkpoint differentiates through
                # closures, so no explicit param plumbing is needed.  State
                # updates (MoE aux losses) must NOT be written to the outer
                # context from inside the remat sub-trace — that leaks
                # tracers — so they are captured and returned as explicit
                # checkpoint outputs, then re-published outside.
                x, updates = jax.checkpoint(
                    lambda y, _b=block: run_capturing_state(_b, (y,)))(x)
                ctx = current_context()
                for path, val in updates.items():
                    ctx.put_state(path, val)
            else:
                x = block(x)
        return self.head(self.ln_f(x))

    def _decoding(self) -> bool:
        """True when the current apply() carries a KV cache for this model's
        attention layers (i.e. we are inside prefill/decode)."""
        from ..nn.module import current_context
        ctx = current_context()
        if ctx is None or not ctx.state:
            return False
        return any(getattr(self, f"block{i}").attn._path in ctx.state
                   for i in range(self.depth))

    # -- autoregressive inference ------------------------------------------

    def init_cache(self, batch: int, max_len: Optional[int] = None,
                   dtype=jnp.float32):
        """KV-cache state pytree for :meth:`generate` — one
        ``{"k", "v", "index"}`` entry per attention layer, keyed by module
        path, threaded through ``apply(state=...)`` like any mutable state."""
        if self.sequence_axis is not None:
            raise ValueError("KV-cache decode runs on gathered sequences; "
                             "build the model without sequence_axis for "
                             "generation")
        if not self.causal:
            raise ValueError("KV-cache decode requires causal attention: a "
                             "bidirectional model's logits depend on future "
                             "tokens and cannot be decoded incrementally")
        max_len = self.max_seq_len if max_len is None else max_len
        self._assign_paths()
        return {attn._path: attn.init_cache(batch, max_len, dtype)
                for attn in (getattr(self, f"block{i}").attn
                             for i in range(self.depth))}

    # -- slot-pool decode (continuous batching; tpu_dist.serve) ------------

    def init_slot_cache(self, slots: int, max_len: Optional[int] = None,
                        dtype=jnp.float32):
        """KV-cache pool for slot-based continuous-batching decode: the
        :meth:`init_cache` layout WITHOUT the per-layer scalar write index
        — each call to :meth:`decode_step` supplies every slot's position
        as the ``lengths`` vector instead, so the host-side engine
        (:class:`tpu_dist.serve.SlotEngine`) holds the single source of
        truth for slot occupancy."""
        return {path: {k: v for k, v in entry.items() if k != "index"}
                for path, entry in
                self.init_cache(slots, max_len, dtype).items()}

    def decode_step(self, params, tokens, lengths, cache):
        """ONE decode iteration over a slot pool: feed each slot's current
        last token, get each slot's next-token logits.

        ``tokens``: (B,) int — the token each slot decoded last (or the
        prompt's last token right after prefill).  ``lengths``: (B,) int —
        tokens already resident in each slot's cache row, i.e. the write
        position.  ``cache``: from :meth:`init_slot_cache` /
        :meth:`prefill_into_slot`.  Returns ``(logits (B, vocab),
        new_cache)``.  Free slots decode garbage rows the caller masks;
        their cache writes land in rows the next prefill overwrites.
        The math per row is exactly :meth:`generate`'s decode scan — the
        scan *uses* this method — so slot decode and offline generation
        cannot drift."""
        lengths = jnp.asarray(lengths, jnp.int32)
        state = {path: dict(entry, index=lengths)
                 for path, entry in cache.items()}
        tokens = jnp.asarray(tokens)[:, None]
        logits, state = self.apply(params, tokens, pos_offset=lengths,
                                   state=state)
        new_cache = {path: {k: v for k, v in state[path].items()
                            if k != "index"}
                     for path in cache}
        return logits[:, -1], new_cache

    def prefill_into_slot(self, params, prompt, length, slot, cache):
        """Prefill ONE request into slot ``slot`` of a slot-cache pool
        while other slots' rows are untouched — the admission half of
        continuous batching.

        ``prompt``: (P,) int tokens, padded past ``length`` with any valid
        token id (padding K/V lands at positions ``>= length``, which
        every later decode step either masks out or overwrites before
        attending).  ``length``: true token count (traced OK).  Returns
        ``(last-real-token logits (vocab,), new_cache)`` — sample the
        request's first generated token from those logits.  One padded
        prompt length = one compiled program; bucket prompt lengths to
        bound retraces."""
        entry = next(iter(cache.values()))
        max_len, dtype = entry["k"].shape[1], entry["k"].dtype
        pre = self.init_cache(1, max_len, dtype)
        logits, st = self.apply(params, jnp.asarray(prompt)[None, :],
                                state=pre)
        new_cache = write_slot_rows(cache, st, slot)
        return jax.lax.dynamic_index_in_dim(
            logits[0], jnp.asarray(length, jnp.int32) - 1, axis=0,
            keepdims=False), new_cache

    def prefill_rows(self, params, prompt, length, max_len,
                     dtype=jnp.float32, prefix_rows=None, prefix_len=0):
        """Prefill ONE request into fresh batch-1 cache rows with NO slot
        pool in sight — the disaggregated-prefill primitive: a prefill
        rank computes these rows and ships them to a decode rank, where
        :func:`write_slot_rows` lands them in a free slot.

        ``prompt``: (S,) int suffix tokens, padded past the true suffix
        length with any valid id (padding K/V lands at positions
        ``>= length`` and is masked/overwritten exactly as in
        :meth:`prefill_into_slot`).  ``length``: TOTAL true token count
        including any cached prefix.  With ``prefix_rows`` (batch-1 rows
        holding the first ``prefix_len`` tokens' K/V — a prefix-cache
        hit), only the suffix runs the forward: positions start at
        ``prefix_len`` (learned table via ``pos_offset``, rope via the
        cache write index) and the suffix K/V appends at
        ``[prefix_len, prefix_len + S)``.  Returns ``(last-real-token
        logits (vocab,), rows)`` where ``rows`` are full-width
        ``(1, max_len)`` per-layer entries (no ``index``).  With no
        prefix this is bitwise-identical to the forward inside
        :meth:`prefill_into_slot` (same apply, same padding discipline);
        one padded suffix length = one compiled program."""
        length = jnp.asarray(length, jnp.int32)
        plen = jnp.asarray(prefix_len, jnp.int32)
        if prefix_rows is None:
            pre = self.init_cache(1, max_len, dtype)
            logits, st = self.apply(params, jnp.asarray(prompt)[None, :],
                                    state=pre)
        else:
            pre = {path: dict(entry, index=plen)
                   for path, entry in prefix_rows.items()}
            logits, st = self.apply(params, jnp.asarray(prompt)[None, :],
                                    pos_offset=plen, state=pre)
        rows = {path: {k: v for k, v in st[path].items() if k != "index"}
                for path in st}
        return jax.lax.dynamic_index_in_dim(
            logits[0], length - plen - 1, axis=0, keepdims=False), rows

    def generate(self, params, prompt, max_new_tokens: int,
                 temperature: float = 0.0, rng=None, cache_dtype=None,
                 top_k: int = 0, top_p: float = 1.0):
        """Autoregressive decoding with a KV cache.

        ``prompt``: int tokens (B, Tp).  Returns (B, Tp + max_new_tokens) —
        the prompt with the continuation appended.  ``temperature`` 0 is
        greedy argmax; > 0 samples categorically (``rng`` required), with
        optional truncation: ``top_k`` > 0 restricts sampling to the k
        highest-probability tokens, ``top_p`` < 1 to the smallest set
        whose cumulative probability reaches p (nucleus sampling; the
        highest-probability token always stays eligible).  Both filters
        are static-shape masks over the fixed vocab, so they trace into
        the same single XLA program.  The prompt is prefilled in ONE
        forward pass (cache index advances by Tp), then each new token is
        one t=1 forward through the cache — the whole loop is a
        ``lax.scan``, so generate() jits with no per-token dispatch.
        """
        b, tp = prompt.shape
        if max_new_tokens <= 0:
            if max_new_tokens == 0:
                return prompt
            raise ValueError(f"max_new_tokens must be >= 0, got "
                             f"{max_new_tokens}")
        total = tp + max_new_tokens
        if total > self.max_seq_len:
            raise ValueError(f"prompt ({tp}) + max_new_tokens "
                             f"({max_new_tokens}) exceeds max_seq_len "
                             f"({self.max_seq_len})")
        if temperature > 0 and rng is None:
            raise ValueError("temperature > 0 sampling requires rng=")
        if top_k < 0 or top_k > self.vocab_size:
            raise ValueError(f"top_k must be in [0, vocab_size], got "
                             f"{top_k}")
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")

        def sample(logits, key):
            if temperature <= 0:
                return logits.argmax(-1)
            logits = logits / temperature
            if top_k:
                kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
                logits = jnp.where(logits < kth, -jnp.inf, logits)
            if top_p < 1.0:
                desc = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
                probs = jax.nn.softmax(desc, axis=-1)
                # keep tokens whose cumulative probability BEFORE them is
                # < p: the argmax token (exclusive cumsum 0) always stays
                keep = (jnp.cumsum(probs, axis=-1) - probs) < top_p
                thresh = jnp.min(jnp.where(keep, desc, jnp.inf),
                                 axis=-1, keepdims=True)
                logits = jnp.where(logits < thresh, -jnp.inf, logits)
            return jax.random.categorical(key, logits, axis=-1)

        cache = self.init_cache(b, total, cache_dtype or jnp.float32)
        logits, cache = self.apply(params, prompt, state=cache)
        key0 = rng if rng is not None else jax.random.key(0)
        first = sample(logits[:, -1], jax.random.fold_in(key0, 0))
        # the decode loop runs on the slot-pool primitive (decode_step):
        # lengths = tp + i for every row, so offline generation and the
        # serving engine's continuous-batching decode share ONE code path
        slot_cache = {path: {k: v for k, v in entry.items() if k != "index"}
                      for path, entry in cache.items()}

        def step(carry, i):
            tok, cache = carry
            lengths = jnp.full((b,), tp, jnp.int32) + i
            logits, cache = self.decode_step(params, tok, lengths, cache)
            nxt = sample(logits, jax.random.fold_in(key0, i + 1))
            return (nxt, cache), tok

        (last, _), toks = jax.lax.scan(
            step, (first, slot_cache), jnp.arange(max_new_tokens - 1))
        # toks holds tokens emitted *before* each step; append the final one
        out = jnp.concatenate(
            [prompt, jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1)
        return out
