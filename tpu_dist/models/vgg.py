"""VGG family — torchvision-architecture parity, TPU-native implementation.

Rounds out the torchvision-classifier coverage the reference leans on
(/root/reference/example_mp.py:50 instantiates torchvision models by name;
ResNet is covered in resnet.py): configs A/B/D/E (vgg11/13/16/19) with the
optional BatchNorm variants, the 7x7 adaptive-pool + 4096-4096 classifier
head, and torchvision initialization (kaiming_normal fan_out/relu convs,
BN weight=1/bias=0, classifier Linear N(0, 0.01)).  Parameter counts match
torchvision's published numbers exactly (tests/test_models.py).

Layout NHWC; input (batch, H, W, 3).  Like the ResNets, BatchNorm is
per-replica by default; pass ``bn_axis_name`` for SyncBN.
"""

from __future__ import annotations

from typing import List, Optional, Union

from .. import nn
from ..nn import init as init_lib
from .resnet import _KaimingConv2d

__all__ = ["VGG", "vgg11", "vgg13", "vgg16", "vgg19",
           "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn"]

_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
          512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
          "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
          512, 512, "M", 512, 512, 512, 512, "M"],
}


class _ClassifierLinear(nn.Linear):
    """Linear with torchvision VGG classifier init: N(0, 0.01), zero bias."""

    def create_params(self, key):
        p = {"weight": init_lib.normal(key, (self.in_features,
                                             self.out_features), std=0.01)}
        if self.use_bias:
            p["bias"] = init_lib.zeros((self.out_features,))
        return p


class VGG(nn.Module):
    def __init__(self, cfg: Union[str, List], num_classes: int = 1000,
                 batch_norm: bool = False, dropout: float = 0.5,
                 bn_axis_name: Optional[str] = None):
        super().__init__()
        layers: List[nn.Module] = []
        in_ch = 3
        for v in (_CFGS[cfg] if isinstance(cfg, str) else cfg):
            if v == "M":
                layers.append(nn.MaxPool2d(kernel_size=2, stride=2))
                continue
            # torchvision quirk kept for parameter-count parity: the BN
            # variants do NOT drop the conv bias (unlike ResNet)
            layers.append(_KaimingConv2d(in_ch, v, kernel_size=3, padding=1,
                                         bias=True))
            if batch_norm:
                layers.append(nn.BatchNorm2d(v, axis_name=bn_axis_name))
            layers.append(nn.ReLU())
            in_ch = v
        self.features = nn.Sequential(*layers)
        self.avgpool = nn.AdaptiveAvgPool2d((7, 7))
        self.flatten = nn.Flatten()
        self.classifier = nn.Sequential(
            _ClassifierLinear(512 * 7 * 7, 4096), nn.ReLU(),
            nn.Dropout(dropout),
            _ClassifierLinear(4096, 4096), nn.ReLU(), nn.Dropout(dropout),
            _ClassifierLinear(4096, num_classes))

    def forward(self, x):
        return self.classifier(self.flatten(self.avgpool(self.features(x))))


def vgg11(**kw) -> VGG:
    return VGG("A", **kw)


def vgg13(**kw) -> VGG:
    return VGG("B", **kw)


def vgg16(**kw) -> VGG:
    return VGG("D", **kw)


def vgg19(**kw) -> VGG:
    return VGG("E", **kw)


def vgg11_bn(**kw) -> VGG:
    return VGG("A", batch_norm=True, **kw)


def vgg13_bn(**kw) -> VGG:
    return VGG("B", batch_norm=True, **kw)


def vgg16_bn(**kw) -> VGG:
    return VGG("D", batch_norm=True, **kw)


def vgg19_bn(**kw) -> VGG:
    return VGG("E", batch_norm=True, **kw)
