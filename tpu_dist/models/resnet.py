"""ResNet family — torchvision-architecture parity, TPU-native implementation.

The reference instantiates ``torchvision.models.resnet18(pretrained=False,
num_classes=10)`` (/root/reference/example_mp.py:50,
/root/reference/example_launch.py:26) and trains it on 32x32 CIFAR-10 with the
*ImageNet* stem (7x7 stride-2 conv + 3x3 stride-2 maxpool).  We reproduce that
architecture exactly (BasicBlock [2,2,2,2]) so parameter counts and shapes
match, plus ResNet-50 (Bottleneck [3,4,6,3]) for the scaling ladder
(BASELINE.md config #5).

Initialization follows torchvision: kaiming_normal(fan_out, relu) for convs,
BN weight=1/bias=0, default Linear init for the classifier head.  BatchNorm is
per-replica by default (DDP semantics — DDP does not sync BN stats); pass
``bn_axis_name='data'`` for cross-replica SyncBN.

Layout NHWC; input (batch, H, W, 3).
"""

from __future__ import annotations

from typing import List, Optional, Type, Union

import jax

from .. import nn
from ..nn import init as init_lib

__all__ = ["ResNet", "BasicBlock", "Bottleneck", "resnet18", "resnet34",
           "resnet50"]


class _KaimingConv2d(nn.Conv2d):
    """Conv2d with torchvision ResNet init (kaiming_normal fan_out, relu)."""

    def create_params(self, key):
        kh, kw = self.kernel_size
        shape = (kh, kw, self.in_channels // self.groups, self.out_channels)
        p = {"weight": init_lib.kaiming_normal(key, shape, mode="fan_out",
                                               nonlinearity="relu")}
        if self.use_bias:
            p["bias"] = init_lib.zeros((self.out_channels,))
        return p


def conv3x3(in_ch: int, out_ch: int, stride: int = 1) -> nn.Conv2d:
    return _KaimingConv2d(in_ch, out_ch, kernel_size=3, stride=stride,
                          padding=1, bias=False)


def conv1x1(in_ch: int, out_ch: int, stride: int = 1) -> nn.Conv2d:
    return _KaimingConv2d(in_ch, out_ch, kernel_size=1, stride=stride,
                          bias=False)


class BasicBlock(nn.Module):
    expansion = 1

    def __init__(self, in_ch: int, planes: int, stride: int = 1,
                 downsample: Optional[nn.Module] = None,
                 bn_axis_name: Optional[str] = None):
        super().__init__()
        bn = lambda c: nn.BatchNorm2d(c, axis_name=bn_axis_name)
        self.conv1 = conv3x3(in_ch, planes, stride)
        self.bn1 = bn(planes)
        self.relu = nn.ReLU()
        self.conv2 = conv3x3(planes, planes)
        self.bn2 = bn(planes)
        self.downsample = downsample if downsample is not None else nn.Identity()
        self.has_downsample = downsample is not None

    def forward(self, x):
        identity = self.downsample(x) if self.has_downsample else x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        return self.relu(out + identity)


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, in_ch: int, planes: int, stride: int = 1,
                 downsample: Optional[nn.Module] = None,
                 bn_axis_name: Optional[str] = None):
        super().__init__()
        bn = lambda c: nn.BatchNorm2d(c, axis_name=bn_axis_name)
        self.conv1 = conv1x1(in_ch, planes)
        self.bn1 = bn(planes)
        self.conv2 = conv3x3(planes, planes, stride)
        self.bn2 = bn(planes)
        self.conv3 = conv1x1(planes, planes * self.expansion)
        self.bn3 = bn(planes * self.expansion)
        self.relu = nn.ReLU()
        self.downsample = downsample if downsample is not None else nn.Identity()
        self.has_downsample = downsample is not None

    def forward(self, x):
        identity = self.downsample(x) if self.has_downsample else x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        return self.relu(out + identity)


class ResNet(nn.Module):
    def __init__(self, block: Type[Union[BasicBlock, Bottleneck]],
                 layers: List[int], num_classes: int = 1000,
                 bn_axis_name: Optional[str] = None):
        super().__init__()
        self.bn_axis_name = bn_axis_name
        self.inplanes = 64
        self.conv1 = _KaimingConv2d(3, 64, kernel_size=7, stride=2, padding=3,
                                    bias=False)
        self.bn1 = nn.BatchNorm2d(64, axis_name=bn_axis_name)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2d(kernel_size=3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        self.avgpool = nn.AdaptiveAvgPool2d(1)
        self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes: int, blocks: int,
                    stride: int = 1) -> nn.Sequential:
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                conv1x1(self.inplanes, planes * block.expansion, stride),
                nn.BatchNorm2d(planes * block.expansion,
                               axis_name=self.bn_axis_name),
            )
        blocks_list = [block(self.inplanes, planes, stride, downsample,
                             self.bn_axis_name)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            blocks_list.append(block(self.inplanes, planes,
                                     bn_axis_name=self.bn_axis_name))
        return nn.Sequential(*blocks_list)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        x = self.avgpool(x)
        x = x.reshape(x.shape[0], -1)
        return self.fc(x)


def resnet18(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(BasicBlock, [2, 2, 2, 2], num_classes, **kw)


def resnet34(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(BasicBlock, [3, 4, 6, 3], num_classes, **kw)


def resnet50(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(Bottleneck, [3, 4, 6, 3], num_classes, **kw)
