"""Vision Transformer — torchvision ``vit_b_16``-family parity, NHWC.

The reference's model zoo is torchvision (``torchvision.models.resnet18``
at /root/reference/example_mp.py:50); ViT rounds out the same zoo for the
attention era, reusing the framework's own pieces end to end: the patch
embedding is :class:`~tpu_dist.nn.Conv2d` (NHWC, stride = patch), the
encoder is the same pre-LN :class:`~tpu_dist.models.TransformerBlock` the
LM uses (attention auto-dispatch picks the XLA-fused dense path at ViT's
197-token sequence — measured 1.5x faster than the flash kernel there,
see nn/attention.py ``_FLASH_MIN_SEQ``), and the classification head is a
plain :class:`~tpu_dist.nn.Linear`.

Parity points (torchvision ``VisionTransformer``):

- architecture and parameter counts match exactly (``vit_b_16`` =
  86,567,656 params at 1000 classes — verified in tests/test_models.py
  against the published torchvision counts);
- class token prepended to the patch sequence, learned position
  embeddings over ``num_patches + 1`` positions, encoder LayerNorm eps
  1e-6, final LayerNorm before the head;
- init follows torchvision: zeros class token, N(0, 0.02) position
  embeddings, zero-initialized head, ``trunc_normal(std=sqrt(1/fan_in))``
  patch-projection conv, xavier-uniform MLP weights with ``N(0, 1e-6)``
  biases (torchvision's ``MLPBlock`` init), and xavier-uniform attention
  in-proj with zero attention biases (``nn.MultiheadAttention`` reset) —
  so from-scratch training starts from the same distributions.  (The
  attention out-proj weight and LayerNorms use torch's defaults, which
  are also ours.)

Layout is NHWC throughout (TPU-native; torchvision is NCHW) — images are
``(B, H, W, 3)`` like every other model here.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .. import nn
from .transformer import TransformerBlock

__all__ = ["VisionTransformer", "vit_b_16", "vit_b_32", "vit_l_16",
           "vit_l_32"]


def _stable_fold(name: str) -> int:
    """Deterministic string→int for ``jax.random.fold_in`` (``hash()`` is
    PYTHONHASHSEED-salted, which would make init differ across processes)."""
    import zlib
    return zlib.crc32(name.encode()) & 0x7FFFFFFF


class _TokenEmbeddings(nn.Module):
    """Class token + learned position table, one param path.

    torchvision init semantics: ``class_token`` zeros, ``pos_embedding``
    N(0, 0.02) (``VisionTransformer.__init__``'s ``normal_(std=0.02)``).
    """

    def __init__(self, seq_len: int, dim: int):
        super().__init__()
        self.seq_len = seq_len
        self.dim = dim

    def create_params(self, key):
        return {"class_token": jnp.zeros((1, 1, self.dim)),
                "pos_embedding": 0.02 * jax.random.normal(
                    key, (1, self.seq_len, self.dim))}

    def forward(self, x):
        from ..nn.module import _ctx
        p = _ctx().get_params(self._path)
        b = x.shape[0]
        cls = jnp.broadcast_to(p["class_token"].astype(x.dtype),
                               (b, 1, x.shape[-1]))
        x = jnp.concatenate([cls, x], axis=1)
        return x + p["pos_embedding"].astype(x.dtype)


class VisionTransformer(nn.Module):
    """ViT encoder classifier: images (B, H, W, 3) → logits (B, classes).

    Args mirror torchvision's ``VisionTransformer``: ``image_size`` must
    be divisible by ``patch_size``; ``hidden_dim`` is the encoder width.
    There is no ``mlp_dim`` argument — ``TransformerBlock`` fixes the MLP
    hidden width at ``4 * hidden_dim``, which every standard ViT config
    (B, L, H) satisfies.
    """

    def __init__(self, image_size: int = 224, patch_size: int = 16,
                 num_layers: int = 12, num_heads: int = 12,
                 hidden_dim: int = 768, num_classes: int = 1000):
        super().__init__()
        if image_size % patch_size:
            raise ValueError(f"image_size {image_size} not divisible by "
                             f"patch_size {patch_size}")
        self.image_size = image_size
        self.patch_size = patch_size
        self.hidden_dim = hidden_dim
        n_patches = (image_size // patch_size) ** 2
        self.conv_proj = nn.Conv2d(3, hidden_dim, patch_size,
                                   stride=patch_size)
        self.tokens = _TokenEmbeddings(n_patches + 1, hidden_dim)
        for i in range(num_layers):
            setattr(self, f"block{i}", TransformerBlock(
                hidden_dim, num_heads, causal=False, norm_eps=1e-6))
        self.num_layers = num_layers
        self.ln = nn.LayerNorm(hidden_dim, eps=1e-6)
        self.head = nn.Linear(hidden_dim, num_classes)

    def forward(self, x):
        b, h, w, c = x.shape
        if (h, w, c) != (self.image_size, self.image_size, 3):
            raise ValueError(f"expected (B, {self.image_size}, "
                             f"{self.image_size}, 3) NHWC images, got "
                             f"{x.shape}")
        x = self.conv_proj(x)                      # (B, H/p, W/p, d)
        x = x.reshape(b, -1, self.hidden_dim)      # (B, N, d)
        x = self.tokens(x)                         # (B, N+1, d)
        for i in range(self.num_layers):
            x = getattr(self, f"block{i}")(x)
        x = self.ln(x)
        return self.head(x[:, 0])                  # class token only

    def init(self, key):
        from ..nn import init as I
        # split before handing a key to Module.init: the re-init stream
        # below must be independent of the base stream, or a fold_in
        # collision could correlate a re-initialized leaf with a kept one
        # (e.g. out_weight, which keeps its Module.init draw)
        init_key, reinit_key = jax.random.split(key)
        params = super().init(init_key)

        def k(name):
            return jax.random.fold_in(reinit_key, _stable_fold(name))

        # torchvision zero-initializes the classification head
        params["head"]["weight"] = jnp.zeros_like(params["head"]["weight"])
        params["head"]["bias"] = jnp.zeros_like(params["head"]["bias"])
        # conv_proj: trunc_normal(std=sqrt(1/fan_in)), zero bias
        # (torchvision VisionTransformer.__init__; fan_in = 3*p*p)
        w = params["conv_proj"]["weight"]
        params["conv_proj"]["weight"] = I.trunc_normal(
            k("conv_proj"), w.shape,
            std=math.sqrt(1.0 / (w.shape[0] * w.shape[1] * w.shape[2])),
            dtype=w.dtype)
        params["conv_proj"]["bias"] = jnp.zeros_like(
            params["conv_proj"]["bias"])
        for path, leaves in params.items():
            # encoder MLP Linears: xavier_uniform weight, N(0, 1e-6) bias
            # (torchvision MLPBlock init loop).  Weights here are (in, out).
            if ".mlp." in path:
                leaves["weight"] = I.xavier_uniform(
                    k(path + "/w"), leaves["weight"].shape,
                    dtype=leaves["weight"].dtype)
                leaves["bias"] = 1e-6 * jax.random.normal(
                    k(path + "/b"), leaves["bias"].shape,
                    leaves["bias"].dtype)
            # encoder attention: torch nn.MultiheadAttention._reset_parameters
            # — xavier_uniform in_proj weight, zero in_proj and out_proj
            # biases.  (out_proj WEIGHT keeps torch's Linear default, which
            # is also our Linear default.)  xavier's limit is symmetric in
            # fan_in+fan_out, so our (d, 3d) qkv layout gives the same bound
            # as torch's (3d, d) in_proj_weight.
            elif path.endswith(".attn"):
                leaves["qkv_weight"] = I.xavier_uniform(
                    k(path + "/qkv"), leaves["qkv_weight"].shape,
                    dtype=leaves["qkv_weight"].dtype)
                leaves["qkv_bias"] = jnp.zeros_like(leaves["qkv_bias"])
                leaves["out_bias"] = jnp.zeros_like(leaves["out_bias"])
        return params


def vit_b_16(num_classes: int = 1000, image_size: int = 224):
    """ViT-Base/16 (torchvision ``vit_b_16``: 86,567,656 params @ 1000)."""
    return VisionTransformer(image_size, 16, 12, 12, 768,
                             num_classes=num_classes)


def vit_b_32(num_classes: int = 1000, image_size: int = 224):
    """ViT-Base/32 (torchvision ``vit_b_32``: 88,224,232 params @ 1000)."""
    return VisionTransformer(image_size, 32, 12, 12, 768,
                             num_classes=num_classes)


def vit_l_16(num_classes: int = 1000, image_size: int = 224):
    """ViT-Large/16 (torchvision ``vit_l_16``: 304,326,632 params @ 1000)."""
    return VisionTransformer(image_size, 16, 24, 16, 1024,
                             num_classes=num_classes)


def vit_l_32(num_classes: int = 1000, image_size: int = 224):
    """ViT-Large/32 (torchvision ``vit_l_32``: 306,535,400 params @ 1000)."""
    return VisionTransformer(image_size, 32, 24, 16, 1024,
                             num_classes=num_classes)
