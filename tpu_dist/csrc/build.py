"""Lazy build of the native (C++) components.

Compiles ``csrc/*.cpp`` into ``libtpudist.so`` with g++ on first use and
caches by source mtime.  No pybind11 in this environment — the library
exposes a plain C ABI consumed via ctypes (tpu_dist/dist/store.py).
"""

from __future__ import annotations

import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = [os.path.join(_DIR, "tcpstore.cpp"),
            os.path.join(_DIR, "image_ops.cpp")]
_LIB = os.path.join(_DIR, "libtpudist.so")
# RLock: load_native holds it while calling ensure_built (same lock)
_lock = threading.RLock()


class NativeBuildError(RuntimeError):
    pass


def lib_path() -> str:
    return _LIB


def load_native(env_disable: str, bind):
    """Shared lazy native-loader idiom: build + dlopen ``libtpudist.so``
    once (thread-safe), call ``bind(lib)`` to declare/bind symbols, and
    return its result — or None forever after the first failure or when
    ``env_disable`` is set.  Serves the store (dist/store.py) and the
    image kernels (data/_native.py)."""
    import ctypes

    cache = {}

    def loader():
        with _lock:
            if "v" in cache:
                return cache["v"]
            result = None
            if not os.environ.get(env_disable):
                try:
                    result = bind(ctypes.CDLL(ensure_built()))
                except Exception:
                    result = None
            cache["v"] = result
            return result

    loader.reset = cache.clear  # tests: re-evaluate after env changes
    return loader


def _stale() -> bool:
    if not os.path.exists(_LIB):
        return True
    lib_mtime = os.path.getmtime(_LIB)
    return any(os.path.getmtime(s) > lib_mtime for s in _SOURCES)


def ensure_built(quiet: bool = True) -> str:
    """Compile if missing/stale; returns the .so path."""
    with _lock:
        if not _stale():
            return _LIB
        # Cross-process safety (N ranks importing simultaneously): hold an
        # fcntl lock for the compile, emit to a per-pid temp file, and
        # os.replace() it into place so no process ever dlopens a
        # half-written library.
        import fcntl
        lockfile = _LIB + ".lock"
        with open(lockfile, "w") as lf:
            fcntl.flock(lf, fcntl.LOCK_EX)
            try:
                if not _stale():  # another process built it while we waited
                    return _LIB
                tmp = f"{_LIB}.{os.getpid()}.tmp"
                cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                       "-pthread", "-o", tmp] + _SOURCES
                try:
                    proc = subprocess.run(cmd, capture_output=True, text=True,
                                          timeout=120)
                except (OSError, subprocess.TimeoutExpired) as e:
                    raise NativeBuildError(
                        f"native build failed to run: {e}") from e
                if proc.returncode != 0:
                    raise NativeBuildError(
                        f"native build failed:\n{proc.stderr[-2000:]}")
                os.replace(tmp, _LIB)
            finally:
                fcntl.flock(lf, fcntl.LOCK_UN)
        if not quiet:
            print(f"[tpu_dist] built native library {_LIB}")
        return _LIB
