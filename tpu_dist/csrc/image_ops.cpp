// Native image-augmentation kernels for the host input pipeline.
//
// The hot op of the ImageNet-ladder loader (tpu_dist/data/transforms.py
// RandomResizedCrop / Resize / CenterCrop all funnel into one batched
// bilinear crop+resize) costs ~13ms/image at 224x224 in vectorized numpy:
// the gather formulation materializes four (N, oh, ow, C) corner tensors
// plus weight broadcasts, all memory traffic.  This kernel walks each
// output row once with per-column interpolation state precomputed, no
// temporaries — the role torchvision's libjpeg-turbo/Pillow-SIMD native
// layer plays for the reference's pipeline (/root/reference/example_mp.py:74-80).
//
// Exposed as a plain C ABI (this environment has no pybind11) and loaded
// via ctypes from tpu_dist/data/_native.py; same contract as the numpy
// reference implementation, which remains both the fallback and the
// parity oracle (tests/test_data.py).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <vector>

extern "C" {

// x:    (n, h, w, c) float32, C-contiguous
// top/left/crop_h/crop_w: (n,) float32 per-image source boxes
// out:  (n, oh, ow, c) float32, preallocated
// Half-pixel-centered sampling, clamped to the image, identical to the
// numpy reference in transforms.py.
int tpu_dist_bilinear_crop_resize(
    const float* x, int64_t n, int64_t h, int64_t w, int64_t c,
    const float* top, const float* left,
    const float* crop_h, const float* crop_w,
    int64_t oh, int64_t ow, float* out) {
  if (n < 0 || h <= 0 || w <= 0 || c <= 0 || oh <= 0 || ow <= 0) return 1;
  std::vector<int64_t> x0(ow), x1(ow);
  std::vector<float> wx(ow);
  for (int64_t i = 0; i < n; ++i) {
    const float* img = x + i * h * w * c;
    float* dst = out + i * oh * ow * c;
    const float sx = crop_w[i] / static_cast<float>(ow);
    const float sy = crop_h[i] / static_cast<float>(oh);
    for (int64_t j = 0; j < ow; ++j) {
      float xs = left[i] + (static_cast<float>(j) + 0.5f) * sx - 0.5f;
      xs = std::min(std::max(xs, 0.0f), static_cast<float>(w - 1));
      const int64_t xf = static_cast<int64_t>(std::floor(xs));
      x0[j] = xf;
      x1[j] = std::min(xf + 1, w - 1);
      wx[j] = xs - static_cast<float>(xf);
    }
    for (int64_t r = 0; r < oh; ++r) {
      float ys = top[i] + (static_cast<float>(r) + 0.5f) * sy - 0.5f;
      ys = std::min(std::max(ys, 0.0f), static_cast<float>(h - 1));
      const int64_t y0 = static_cast<int64_t>(std::floor(ys));
      const int64_t y1 = std::min(y0 + 1, h - 1);
      const float wy = ys - static_cast<float>(y0);
      const float* r0 = img + y0 * w * c;
      const float* r1 = img + y1 * w * c;
      float* o = dst + r * ow * c;
      for (int64_t j = 0; j < ow; ++j) {
        const float* p00 = r0 + x0[j] * c;
        const float* p01 = r0 + x1[j] * c;
        const float* p10 = r1 + x0[j] * c;
        const float* p11 = r1 + x1[j] * c;
        const float fx = wx[j];
        float* oj = o + j * c;
        for (int64_t k = 0; k < c; ++k) {
          // same association as the numpy oracle: row lerps, then column
          const float t0 = p00[k] * (1.0f - fx) + p01[k] * fx;
          const float t1 = p10[k] * (1.0f - fx) + p11[k] * fx;
          oj[k] = t0 * (1.0f - wy) + t1 * wy;
        }
      }
    }
  }
  return 0;
}

}  // extern "C"
