// TCPStore — native key-value rendezvous store (c10d TCPStore parity).
//
// The reference's rendezvous rides torch's C++ TCPStore: a TCP server on the
// master node (MASTER_ADDR/PORT, /root/reference/mpspawn_dist.py:137-138)
// that ranks use to exchange bootstrap info and barrier on.  This is the
// TPU-framework's native equivalent: launchers and user code use it for
// cross-host coordination that must work *before* (or without) the JAX
// runtime — free-port negotiation, worker health, barriers.
//
// Wire protocol (all integers little-endian):
//   request : u8 op | u32 key_len | key bytes | u32 payload_len | payload
//   response: u32 status(0=ok) | u32 data_len | data
// Ops: 1=SET 2=GET(blocking) 3=ADD(i64 delta -> i64 new) 4=CHECK 5=DELETE
//      6=NUMKEYS 7=WAIT_GE(i64 target; blocks until int(key) >= target)
//      8=DELETE_PREFIX(erase every key starting with `key` -> i64 count;
//        the restart-time reaper for a crashed generation's stale
//        tpu_dist/g{gen}/... payload keys)
//
// Exposed via a C ABI (ctypes-friendly); the Python wrapper lives in
// tpu_dist/dist/store.py and has a pure-Python implementation of the same
// protocol as fallback.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

enum Op : uint8_t {
  OP_SET = 1,
  OP_GET = 2,
  OP_ADD = 3,
  OP_CHECK = 4,
  OP_DELETE = 5,
  OP_NUMKEYS = 6,
  OP_WAIT_GE = 7,
  OP_DELETE_PREFIX = 8,
};

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t k = ::send(fd, p, n, MSG_NOSIGNAL);
    if (k <= 0) return false;
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t k = ::recv(fd, p, n, 0);
    if (k <= 0) return false;
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

bool send_u32(int fd, uint32_t v) { return send_all(fd, &v, 4); }
bool recv_u32(int fd, uint32_t* v) { return recv_all(fd, v, 4); }

struct Server {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stopping{false};
  std::thread accept_thread;
  std::vector<std::thread> handlers;
  std::vector<int> client_fds;
  std::mutex handlers_mu;

  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::string> kv;

  ~Server() { stop(); }

  bool start(int want_port) {
    listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd < 0) return false;
    int one = 1;
    setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(want_port));
    if (::bind(listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0)
      return false;
    socklen_t len = sizeof(addr);
    getsockname(listen_fd, reinterpret_cast<sockaddr*>(&addr), &len);
    port = ntohs(addr.sin_port);
    if (::listen(listen_fd, 128) < 0) return false;
    accept_thread = std::thread([this] { accept_loop(); });
    return true;
  }

  void stop() {
    if (stopping.exchange(true)) return;
    if (listen_fd >= 0) {
      ::shutdown(listen_fd, SHUT_RDWR);
      ::close(listen_fd);
    }
    cv.notify_all();
    if (accept_thread.joinable()) accept_thread.join();
    // Wake handler threads blocked in recv on idle client connections —
    // without this, join() below deadlocks on any still-connected client.
    // Then join WITHOUT holding handlers_mu: a handler's exit path locks
    // it to deregister its fd (handle() epilogue), so joining under the
    // mutex deadlocks whenever a client disconnects concurrently with
    // stop — observed as an intermittent hang when two elastic launchers
    // tear down at the same moment.
    std::vector<std::thread> to_join;
    {
      std::lock_guard<std::mutex> g(handlers_mu);
      for (int fd : client_fds) ::shutdown(fd, SHUT_RDWR);
      to_join.swap(handlers);
    }
    for (auto& t : to_join)
      if (t.joinable()) t.join();
  }

  void accept_loop() {
    while (!stopping) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (stopping) break;
        continue;
      }
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> g(handlers_mu);
      client_fds.push_back(fd);
      handlers.emplace_back([this, fd] { handle(fd); });
    }
  }

  void reply(int fd, uint32_t status, const std::string& data) {
    send_u32(fd, status);
    send_u32(fd, static_cast<uint32_t>(data.size()));
    if (!data.empty()) send_all(fd, data.data(), data.size());
  }

  static int64_t as_i64(const std::string& s) {
    int64_t v = 0;
    std::memcpy(&v, s.data(), std::min(s.size(), sizeof(v)));
    return v;
  }

  void handle(int fd) {
    while (!stopping) {
      uint8_t op;
      if (!recv_all(fd, &op, 1)) break;
      uint32_t klen;
      if (!recv_u32(fd, &klen) || klen > (1u << 20)) break;
      std::string key(klen, '\0');
      if (klen && !recv_all(fd, &key[0], klen)) break;
      uint32_t plen;
      if (!recv_u32(fd, &plen) || plen > (1u << 30)) break;
      std::string payload(plen, '\0');
      if (plen && !recv_all(fd, &payload[0], plen)) break;

      switch (op) {
        case OP_SET: {
          {
            std::lock_guard<std::mutex> g(mu);
            kv[key] = payload;
          }
          cv.notify_all();
          reply(fd, 0, "");
          break;
        }
        case OP_GET: {
          std::unique_lock<std::mutex> g(mu);
          cv.wait(g, [&] { return stopping || kv.count(key); });
          if (stopping) {
            reply(fd, 1, "");
            break;
          }
          std::string v = kv[key];
          g.unlock();
          reply(fd, 0, v);
          break;
        }
        case OP_ADD: {
          int64_t delta = as_i64(payload);
          int64_t nv;
          {
            std::lock_guard<std::mutex> g(mu);
            int64_t cur = kv.count(key) ? as_i64(kv[key]) : 0;
            nv = cur + delta;
            std::string s(sizeof(nv), '\0');
            std::memcpy(&s[0], &nv, sizeof(nv));
            kv[key] = s;
          }
          cv.notify_all();
          std::string out(sizeof(nv), '\0');
          std::memcpy(&out[0], &nv, sizeof(nv));
          reply(fd, 0, out);
          break;
        }
        case OP_CHECK: {
          std::lock_guard<std::mutex> g(mu);
          reply(fd, 0, kv.count(key) ? "1" : "0");
          break;
        }
        case OP_DELETE: {
          size_t n;
          {
            std::lock_guard<std::mutex> g(mu);
            n = kv.erase(key);
          }
          reply(fd, 0, n ? "1" : "0");
          break;
        }
        case OP_NUMKEYS: {
          std::lock_guard<std::mutex> g(mu);
          uint32_t n = static_cast<uint32_t>(kv.size());
          std::string out(4, '\0');
          std::memcpy(&out[0], &n, 4);
          reply(fd, 0, out);
          break;
        }
        case OP_WAIT_GE: {
          int64_t target = as_i64(payload);
          std::unique_lock<std::mutex> g(mu);
          cv.wait(g, [&] {
            return stopping || (kv.count(key) && as_i64(kv[key]) >= target);
          });
          reply(fd, stopping ? 1 : 0, "");
          break;
        }
        case OP_DELETE_PREFIX: {
          int64_t n = 0;
          {
            std::lock_guard<std::mutex> g(mu);
            // std::map is ordered: every key with this prefix is a
            // contiguous range starting at lower_bound(prefix)
            auto it = kv.lower_bound(key);
            while (it != kv.end() &&
                   it->first.compare(0, key.size(), key) == 0) {
              it = kv.erase(it);
              ++n;
            }
          }
          std::string out(sizeof(n), '\0');
          std::memcpy(&out[0], &n, sizeof(n));
          reply(fd, 0, out);
          break;
        }
        default:
          reply(fd, 2, "");
          break;
      }
    }
    ::close(fd);
    // Prune so stop() never calls shutdown() on a reused fd number.
    std::lock_guard<std::mutex> g(handlers_mu);
    client_fds.erase(std::remove(client_fds.begin(), client_fds.end(), fd),
                     client_fds.end());
  }
};

struct Client {
  int fd = -1;
  std::mutex mu;

  ~Client() {
    if (fd >= 0) ::close(fd);
  }

  bool connect_to(const char* host, int port, int timeout_ms) {
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    char portbuf[16];
    snprintf(portbuf, sizeof(portbuf), "%d", port);
    // Retry until the server comes up (ranks may start before the master),
    // bounded by timeout_ms — the behavior c10d's TCPStore client has.
    const int step_ms = 50;
    int waited = 0;
    for (;;) {
      if (getaddrinfo(host, portbuf, &hints, &res) == 0) {
        fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
        if (fd >= 0 &&
            ::connect(fd, res->ai_addr, res->ai_addrlen) == 0) {
          freeaddrinfo(res);
          int one = 1;
          setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          return true;
        }
        if (fd >= 0) ::close(fd);
        fd = -1;
        freeaddrinfo(res);
        res = nullptr;
      }
      if (waited >= timeout_ms) return false;
      usleep(step_ms * 1000);
      waited += step_ms;
    }
  }

  // Returns status, fills out (caller frees via tpudist_store_free).
  int request(uint8_t op, const char* key, const uint8_t* payload,
              uint32_t plen, uint8_t** out, uint32_t* out_len) {
    std::lock_guard<std::mutex> g(mu);
    uint32_t klen = static_cast<uint32_t>(strlen(key));
    if (!send_all(fd, &op, 1) || !send_u32(fd, klen) ||
        !send_all(fd, key, klen) || !send_u32(fd, plen) ||
        (plen && !send_all(fd, payload, plen)))
      return -1;
    uint32_t status, dlen;
    if (!recv_u32(fd, &status) || !recv_u32(fd, &dlen)) return -1;
    uint8_t* data = nullptr;
    if (dlen) {
      data = static_cast<uint8_t*>(malloc(dlen));
      if (!recv_all(fd, data, dlen)) {
        free(data);
        return -1;
      }
    }
    if (out) {
      *out = data;
      *out_len = dlen;
    } else {
      free(data);
    }
    return static_cast<int>(status);
  }
};

}  // namespace

extern "C" {

void* tpudist_store_server_start(int port) {
  auto* s = new Server();
  if (!s->start(port)) {
    delete s;
    return nullptr;
  }
  return s;
}

int tpudist_store_server_port(void* h) {
  return h ? static_cast<Server*>(h)->port : -1;
}

void tpudist_store_server_stop(void* h) {
  if (h) delete static_cast<Server*>(h);
}

void* tpudist_store_client_connect(const char* host, int port,
                                   int timeout_ms) {
  auto* c = new Client();
  if (!c->connect_to(host, port, timeout_ms)) {
    delete c;
    return nullptr;
  }
  return c;
}

void tpudist_store_client_close(void* h) {
  if (h) delete static_cast<Client*>(h);
}

int tpudist_store_set(void* h, const char* key, const uint8_t* val, int len) {
  return static_cast<Client*>(h)->request(OP_SET, key, val,
                                          static_cast<uint32_t>(len), nullptr,
                                          nullptr);
}

int tpudist_store_get(void* h, const char* key, uint8_t** out, int* out_len) {
  uint32_t n = 0;
  int st = static_cast<Client*>(h)->request(OP_GET, key, nullptr, 0, out, &n);
  *out_len = static_cast<int>(n);
  return st;
}

// Returns status (0 ok); the new counter value lands in *result so that
// negative counters are not conflated with errors.
int tpudist_store_add(void* h, const char* key, long long delta,
                      long long* result) {
  uint8_t buf[8];
  std::memcpy(buf, &delta, 8);
  uint8_t* out = nullptr;
  uint32_t n = 0;
  int st =
      static_cast<Client*>(h)->request(OP_ADD, key, buf, 8, &out, &n);
  long long v = 0;
  if (st == 0 && out && n >= 8) std::memcpy(&v, out, 8);
  free(out);
  if (result) *result = v;
  return st;
}

int tpudist_store_check(void* h, const char* key) {
  uint8_t* out = nullptr;
  uint32_t n = 0;
  int st = static_cast<Client*>(h)->request(OP_CHECK, key, nullptr, 0, &out, &n);
  int r = (st == 0 && out && n && out[0] == '1') ? 1 : 0;
  free(out);
  return st == 0 ? r : -1;
}

int tpudist_store_delete(void* h, const char* key) {
  uint8_t* out = nullptr;
  uint32_t n = 0;
  int st =
      static_cast<Client*>(h)->request(OP_DELETE, key, nullptr, 0, &out, &n);
  int r = (st == 0 && out && n && out[0] == '1') ? 1 : 0;
  free(out);
  return st == 0 ? r : -1;
}

int tpudist_store_num_keys(void* h) {
  uint8_t* out = nullptr;
  uint32_t n = 0;
  int st =
      static_cast<Client*>(h)->request(OP_NUMKEYS, "", nullptr, 0, &out, &n);
  uint32_t v = 0;
  if (st == 0 && out && n >= 4) std::memcpy(&v, out, 4);
  free(out);
  return st == 0 ? static_cast<int>(v) : -1;
}

// Returns status (0 ok); the number of erased keys lands in *count.
int tpudist_store_delete_prefix(void* h, const char* prefix,
                                long long* count) {
  uint8_t* out = nullptr;
  uint32_t n = 0;
  int st = static_cast<Client*>(h)->request(OP_DELETE_PREFIX, prefix, nullptr,
                                            0, &out, &n);
  long long v = 0;
  if (st == 0 && out && n >= 8) std::memcpy(&v, out, 8);
  free(out);
  if (count) *count = v;
  return st;
}

int tpudist_store_wait_ge(void* h, const char* key, long long target) {
  uint8_t buf[8];
  std::memcpy(buf, &target, 8);
  return static_cast<Client*>(h)->request(OP_WAIT_GE, key, buf, 8, nullptr,
                                          nullptr);
}

void tpudist_store_free(uint8_t* p) { free(p); }

}  // extern "C"
