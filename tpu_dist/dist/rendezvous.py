"""Rendezvous: how processes find each other (TCPStore/NCCL-bootstrap parity).

The reference supports two styles:

- ``env://`` — MASTER_ADDR/MASTER_PORT (+ WORLD_SIZE/RANK) env vars, set in
  code (/root/reference/mpspawn_dist.py:137-138) or by the launcher
  (/root/reference/README.md:341-343), consumed at
  /root/reference/launch_dist.py:49;
- ``tcp://host:port`` — explicit URL with world_size/rank kwargs
  (/root/reference/example_mp.py:18,37-42).

TPU-native both resolve to one thing: the address of JAX's coordination
service (a gRPC server on process 0 — the TCPStore analogue), passed to
``jax.distributed.initialize(coordinator, num_processes, process_id)``.
After that call every process sees the whole slice via ``jax.devices()``
and XLA collectives ride ICI/DCN directly — there is no NCCL-communicator
bootstrap step because communicator construction is part of XLA compilation.

**Control-plane store.**  Alongside the coordination service, a
:class:`~tpu_dist.dist.store.TCPStore` carries the *control plane* — the
role torch's TCPStore plays at /root/reference/mpspawn_dist.py:137-138:

- **liveness keys**: every process writes ``tpu_dist/alive/<rank>`` (its
  pid) on arrival, so the launcher and the pre-flight error can name
  exactly which ranks are missing instead of hanging;
- **pre-flight barrier**: all processes meet in the store *before*
  ``jax.distributed.initialize``, converting a misconfigured WORLD_SIZE or
  a dead peer from an opaque gRPC timeout into a clear error;
- **teardown barrier**: processes meet again in :func:`shutdown` before the
  coordination service goes away, so no rank tears down while another is
  still flushing its last collective.

The store is used when either (a) ``TPU_DIST_STORE_ADDR=host:port`` is set
(``tpu_dist.launch`` hosts the server and sets this for its children), or
(b) ``TPU_DIST_STORE_PREFLIGHT=1`` with ``tcp://`` rendezvous, in which
case process 0 hosts the server on ``coordinator_port + 1``.  Loss of the
store degrades with a warning — it is diagnostics, not the data path.
"""

from __future__ import annotations

import os
import warnings
from typing import Optional, Tuple
from urllib.parse import urlparse

__all__ = ["rendezvous", "shutdown", "parse_init_method", "generation",
           "get_store"]

_distributed_started = False
_store = None            # control-plane TCPStore client (see module docstring)
_store_num_processes = 0

# Store key holding the gang's current incarnation number (the supervisor
# bumps it before every restart round); see _fence_generation.
GENERATION_KEY = "tpu_dist/generation"


def generation() -> int:
    """This process's gang incarnation (``TPU_DIST_RESTART_COUNT``, set by
    the launch CLI's supervisor loop / ``spawn(max_restarts=...)``; 0 on a
    fresh launch or outside any launcher)."""
    try:
        return int(os.environ.get("TPU_DIST_RESTART_COUNT", "0") or 0)
    except ValueError:
        return 0


def get_store():
    """The control-plane store client (None before :func:`rendezvous`, or
    when the job runs without a store)."""
    return _store


def _fence_generation(store, process_id: int) -> None:
    """Reject a rank from a previous gang incarnation.

    The supervisor publishes the current generation to the store before
    (re)spawning a round; a process whose ``TPU_DIST_RESTART_COUNT`` is
    older was left over from an incarnation that already failed (e.g. it
    was hung in a collective while the gang restarted around it) and must
    not write liveness keys or join the new rendezvous.  One-directional:
    a store generation *behind* this rank's just means the supervisor has
    not published yet (spawn/publish ordering), which is harmless."""
    gen = generation()
    try:
        if not store.check(GENERATION_KEY):
            return
        current = int(store.get(GENERATION_KEY))
    except Exception:
        return  # store trouble degrades diagnostics, not correctness
    if current > gen:
        raise RuntimeError(
            f"rank {process_id} fenced out: it belongs to gang generation "
            f"{gen} but the supervisor has moved on to generation {current} "
            f"(the gang restarted while this process was stalled); exiting "
            f"instead of corrupting the new incarnation's rendezvous")


def parse_init_method(init_method: Optional[str],
                      world_size: int = -1,
                      rank: int = -1) -> Tuple[Optional[str], int, int]:
    """Resolve ``(coordinator_address, num_processes, process_id)``.

    Returns ``(None, 1, 0)`` when the configuration is single-process (no
    init_method and no multi-process env contract).
    """
    if init_method is None:
        # Bare init_process_group(): single process unless the launcher's env
        # contract says otherwise (torch treats this as env:// too).
        if "MASTER_ADDR" in os.environ and "WORLD_SIZE" in os.environ:
            init_method = "env://"
        else:
            return None, 1, 0

    if init_method.startswith("env"):
        addr = os.environ.get("MASTER_ADDR")
        port = os.environ.get("MASTER_PORT")
        if addr is None or port is None:
            raise ValueError(
                "init_method='env://' requires MASTER_ADDR and MASTER_PORT "
                "env vars (set by tpu_dist.launch or by hand, as the "
                "reference does at mpspawn_dist.py:137-138)")
        if world_size < 0:
            if "WORLD_SIZE" not in os.environ:
                # Fail fast rather than silently training N independent
                # single-process worlds (torch env:// requires it too).
                raise ValueError(
                    "init_method='env://' requires WORLD_SIZE (env var or "
                    "world_size= argument)")
            world_size = int(os.environ["WORLD_SIZE"])
        if rank < 0:
            if "RANK" not in os.environ and world_size > 1:
                raise ValueError(
                    "init_method='env://' requires RANK (env var or rank= "
                    "argument) when WORLD_SIZE > 1")
            rank = int(os.environ.get("RANK", 0))
        return f"{addr}:{port}", world_size, rank

    if init_method.startswith("tcp://"):
        parsed = urlparse(init_method)
        if parsed.hostname is None or parsed.port is None:
            raise ValueError(f"Malformed tcp:// init_method: {init_method!r}")
        if world_size < 0 or rank < 0:
            raise ValueError(
                "tcp:// rendezvous requires explicit world_size and rank "
                "(as /root/reference/example_mp.py:37-42 passes them)")
        return f"{parsed.hostname}:{parsed.port}", world_size, rank

    raise ValueError(
        f"Unsupported init_method {init_method!r}; use 'env://' or "
        f"'tcp://host:port'")


def _pf_timeout(timeout: Optional[float]) -> float:
    return (timeout if timeout is not None else
            float(os.environ.get("TPU_DIST_PREFLIGHT_TIMEOUT", "300")))


def _setup_store(coordinator: str, num_processes: int, process_id: int,
                 timeout: Optional[float]):
    """Create (or return) the control-plane store client; None if unused."""
    global _store, _store_num_processes
    if _store is not None:
        return _store
    from .store import TCPStore

    addr = os.environ.get("TPU_DIST_STORE_ADDR")
    if addr:
        host, _, port = addr.rpartition(":")
        store = TCPStore(host, int(port), timeout=_pf_timeout(timeout))
    elif os.environ.get("TPU_DIST_STORE_PREFLIGHT"):
        host, _, port = coordinator.rpartition(":")
        store = TCPStore(host, int(port) + 1, is_master=(process_id == 0),
                         timeout=_pf_timeout(timeout))
    else:
        return None
    _store, _store_num_processes = store, num_processes
    return store


def _preflight(store, num_processes: int, process_id: int,
               timeout: Optional[float]) -> None:
    """Check in + wait for every peer's liveness key before the gRPC
    rendezvous.

    Per-rank keys rather than an arrival-counter barrier: idempotent under
    retry (a second ``init_process_group`` attempt re-asserts the same key
    instead of double-counting), and a timeout can name exactly the ranks
    that never appeared.
    """
    import time

    pf_timeout = _pf_timeout(timeout)
    store.set(f"tpu_dist/alive/{process_id}", str(os.getpid()))
    # host fingerprint, published with the liveness check-in: topology
    # detection (tpu_dist/collectives/topology.py — SHM lane pairing, the
    # hierarchical ring, algorithm autoselection) reads every rank's key.
    # The DataPlane re-publishes the same key at construction, so
    # store-injected test rigs that skip rendezvous stay covered.
    try:
        from ..collectives.topology import publish_host_fingerprint
        publish_host_fingerprint(store, process_id, generation())
    except Exception as e:
        warnings.warn(f"host-fingerprint publish failed ({e!r}); topology "
                      f"autoselection will fall back to the flat ring")
    deadline = time.monotonic() + pf_timeout
    waiting = set(range(num_processes))
    delay = 0.01
    while waiting:
        waiting = {r for r in waiting
                   if not store.check(f"tpu_dist/alive/{r}")}
        if not waiting:
            return
        if time.monotonic() > deadline:
            raise RuntimeError(
                f"rendezvous pre-flight: only "
                f"{num_processes - len(waiting)}/{num_processes} processes "
                f"checked in within {pf_timeout:.0f}s; missing ranks: "
                f"{sorted(waiting)}. Check WORLD_SIZE/--nnodes and that "
                f"every rank was actually launched.")
        time.sleep(delay)
        delay = min(delay * 2, 0.5)  # back off: don't hammer the server


def rendezvous(init_method: Optional[str], world_size: int = -1,
               rank: int = -1, timeout: Optional[float] = None) -> None:
    """Join the coordination service (blocking, like the NCCL rendezvous).

    Single-process configurations return immediately.  Multi-process: start
    JAX's distributed client pointed at the coordinator; process 0 hosts the
    service.  Safe to call once per process.
    """
    global _distributed_started
    chaos_active = None
    if os.environ.get("TPU_DIST_CHAOS"):
        # deterministic fault injection rides along with any worker, no
        # code changes needed (tpu_dist/resilience/chaos.py)
        from ..resilience import chaos as _chaos
        chaos_active = _chaos.install_from_env()
    netchaos_active = None
    if os.environ.get("TPU_DIST_NETCHAOS"):
        # network fault injection (tpu_dist/resilience/netchaos.py):
        # partitions/delays/resets/bit-flips at the transport, store and
        # serve wire boundaries
        from ..resilience import netchaos as _netchaos
        netchaos_active = _netchaos.install_from_env()
    # flight recorder (tpu_dist.obs; armed via TPU_DIST_OBS / launcher
    # --flight-recorder): install the crash-dump paths — unhandled
    # exception, SIGTERM, exit — before anything distributed can hang
    from ..obs import hooks as _obs_hooks
    obs_rec = _obs_hooks.install_from_env()
    coordinator, num_processes, process_id = parse_init_method(
        init_method, world_size, rank)
    if chaos_active is not None:
        # install_from_env could only guess from the RANK env var; the
        # resolved process_id is authoritative (mp.spawn and explicit
        # tcp:// ranks never set RANK)
        chaos_active.rank = process_id
    if netchaos_active is not None:
        netchaos_active.rank = process_id  # same correction: store/serve
        # surface faults scope by this process's rank
    if obs_rec is not None:
        # same correction for the recorder: its rank keys the store tail
        # (tpu_dist/g{gen}/obs/{rank}) and the dump filename — a guessed
        # rank 0 would make every rank overwrite the same key and file
        obs_rec.rank = process_id
        obs_rec.world = num_processes
    if coordinator is None or num_processes <= 1:
        return

    if _distributed_started:
        return  # already joined

    try:
        store = _setup_store(coordinator, num_processes, process_id, timeout)
    except Exception as e:
        if os.environ.get("TPU_DIST_STORE_PREFLIGHT"):
            # explicit opt-in: a silent one-sided degradation would leave
            # the peers stalling against a server that never came up
            raise RuntimeError(
                f"TPU_DIST_STORE_PREFLIGHT is set but the pre-flight store "
                f"could not be set up: {e!r}") from e
        warnings.warn(f"control-plane store unavailable ({e!r}); continuing "
                      f"without liveness/pre-flight diagnostics")
        store = None
    if store is not None:
        _fence_generation(store, process_id)
        _preflight(store, num_processes, process_id, timeout)
    # NOTE: must not touch any backend-initializing JAX API here
    # (jax.devices()/process_count()): jax.distributed.initialize has to run
    # before XLA backends exist or it raises.
    import jax

    kwargs = {}
    if timeout is not None:
        kwargs["initialization_timeout"] = int(timeout)
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id, **kwargs)
    _distributed_started = True
    # re-chain the SIGTERM crash-dump handler over whatever handler
    # jax.distributed may have just installed (preemption notifier): the
    # chained call preserves jax's behavior, ours adds the dump first
    _obs_hooks.install_signal_handlers()


def shutdown() -> None:
    global _distributed_started, _store, _store_num_processes
    if _store is not None:
        # teardown barrier: nobody dismantles the coordination service while
        # a peer is still flushing its last collective.  Short timeout: a
        # peer that died will never arrive, and the launcher's TERM->KILL
        # escalation handles us if we linger.
        try:
            _store.barrier(
                _store_num_processes, tag="teardown",
                timeout=float(os.environ.get("TPU_DIST_TEARDOWN_TIMEOUT",
                                             "10")))
        except Exception as e:
            warnings.warn(f"store teardown barrier failed ({e!r})")
    # close the p2p data plane AFTER the barrier (a peer may still be
    # flushing a last send at our listener until everyone has arrived) but
    # while the store is still up (the addr key is deleted through it)
    try:
        from ..collectives import transport as _transport
        _transport.close_data_plane()
    except Exception:
        pass
    if _store is not None:
        try:
            _store.close()
        except Exception:
            pass
        _store, _store_num_processes = None, 0
    if _distributed_started:
        import jax
        jax.distributed.shutdown()
        _distributed_started = False
