"""Rendezvous: how processes find each other (TCPStore/NCCL-bootstrap parity).

The reference supports two styles:

- ``env://`` — MASTER_ADDR/MASTER_PORT (+ WORLD_SIZE/RANK) env vars, set in
  code (/root/reference/mpspawn_dist.py:137-138) or by the launcher
  (/root/reference/README.md:341-343), consumed at
  /root/reference/launch_dist.py:49;
- ``tcp://host:port`` — explicit URL with world_size/rank kwargs
  (/root/reference/example_mp.py:18,37-42).

TPU-native both resolve to one thing: the address of JAX's coordination
service (a gRPC server on process 0 — the TCPStore analogue), passed to
``jax.distributed.initialize(coordinator, num_processes, process_id)``.
After that call every process sees the whole slice via ``jax.devices()``
and XLA collectives ride ICI/DCN directly — there is no NCCL-communicator
bootstrap step because communicator construction is part of XLA compilation.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple
from urllib.parse import urlparse

__all__ = ["rendezvous", "shutdown", "parse_init_method"]

_distributed_started = False


def parse_init_method(init_method: Optional[str],
                      world_size: int = -1,
                      rank: int = -1) -> Tuple[Optional[str], int, int]:
    """Resolve ``(coordinator_address, num_processes, process_id)``.

    Returns ``(None, 1, 0)`` when the configuration is single-process (no
    init_method and no multi-process env contract).
    """
    if init_method is None:
        # Bare init_process_group(): single process unless the launcher's env
        # contract says otherwise (torch treats this as env:// too).
        if "MASTER_ADDR" in os.environ and "WORLD_SIZE" in os.environ:
            init_method = "env://"
        else:
            return None, 1, 0

    if init_method.startswith("env"):
        addr = os.environ.get("MASTER_ADDR")
        port = os.environ.get("MASTER_PORT")
        if addr is None or port is None:
            raise ValueError(
                "init_method='env://' requires MASTER_ADDR and MASTER_PORT "
                "env vars (set by tpu_dist.launch or by hand, as the "
                "reference does at mpspawn_dist.py:137-138)")
        if world_size < 0:
            if "WORLD_SIZE" not in os.environ:
                # Fail fast rather than silently training N independent
                # single-process worlds (torch env:// requires it too).
                raise ValueError(
                    "init_method='env://' requires WORLD_SIZE (env var or "
                    "world_size= argument)")
            world_size = int(os.environ["WORLD_SIZE"])
        if rank < 0:
            if "RANK" not in os.environ and world_size > 1:
                raise ValueError(
                    "init_method='env://' requires RANK (env var or rank= "
                    "argument) when WORLD_SIZE > 1")
            rank = int(os.environ.get("RANK", 0))
        return f"{addr}:{port}", world_size, rank

    if init_method.startswith("tcp://"):
        parsed = urlparse(init_method)
        if parsed.hostname is None or parsed.port is None:
            raise ValueError(f"Malformed tcp:// init_method: {init_method!r}")
        if world_size < 0 or rank < 0:
            raise ValueError(
                "tcp:// rendezvous requires explicit world_size and rank "
                "(as /root/reference/example_mp.py:37-42 passes them)")
        return f"{parsed.hostname}:{parsed.port}", world_size, rank

    raise ValueError(
        f"Unsupported init_method {init_method!r}; use 'env://' or "
        f"'tcp://host:port'")


def rendezvous(init_method: Optional[str], world_size: int = -1,
               rank: int = -1, timeout: Optional[float] = None) -> None:
    """Join the coordination service (blocking, like the NCCL rendezvous).

    Single-process configurations return immediately.  Multi-process: start
    JAX's distributed client pointed at the coordinator; process 0 hosts the
    service.  Safe to call once per process.
    """
    global _distributed_started
    coordinator, num_processes, process_id = parse_init_method(
        init_method, world_size, rank)
    if coordinator is None or num_processes <= 1:
        return

    if _distributed_started:
        return  # already joined
    # NOTE: must not touch any backend-initializing JAX API here
    # (jax.devices()/process_count()): jax.distributed.initialize has to run
    # before XLA backends exist or it raises.
    import jax

    kwargs = {}
    if timeout is not None:
        kwargs["initialization_timeout"] = int(timeout)
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id, **kwargs)
    _distributed_started = True


def shutdown() -> None:
    global _distributed_started
    if _distributed_started:
        import jax
        jax.distributed.shutdown()
        _distributed_started = False
