"""Process groups over TPU device meshes — the c10d equivalent (L1).

The reference initializes torch.distributed process groups
(``init_process_group('nccl', 'env://', world_size, rank)`` at
/root/reference/mpspawn_dist.py:49-54, /root/reference/launch_dist.py:49,
``tcp://`` at /root/reference/example_mp.py:37-42) where **one process drives
one GPU**, so *rank*, *process* and *device* are the same thing.

On TPU the natural topology is different and this module embraces it:

- **one process per host** drives all local cores (SPMD);
- a :class:`ProcessGroup` is a set of *devices* wrapped in a
  :class:`jax.sharding.Mesh`; collectives ride the ICI torus between them;
- cross-host coordination happens over DCN via JAX's coordination service
  (the TCPStore/NCCL-bootstrap analogue).

Terminology used throughout the framework:

===================  ========================================================
``world_size``       number of **devices** (cores) in the group — the DDP
                     replica count (what the reference calls total GPUs,
                     ``gpus × nodes``, /root/reference/mpspawn_dist.py:136)
``rank``             this **process**'s rank (0..num_processes-1) — what the
                     launcher env contract calls ``RANK``
``num_processes``    host processes participating (= nnodes on TPU)
``local_world_size`` devices addressable by this process
===================  ========================================================

Usage (single host, 8 cores — the ``mp.spawn`` scenario collapsed into one
process)::

    import tpu_dist.dist as dist
    dist.init_process_group(backend="tpu")
    dist.get_world_size()   # 8  (devices)
    dist.get_rank()         # 0  (process)

Multi-host (launched via ``python -m tpu_dist.launch`` or manually with the
MASTER_ADDR/PORT env contract)::

    dist.init_process_group(backend="tpu", init_method="env://")
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Sequence

import numpy as np

from . import rendezvous as _rdzv

__all__ = [
    "ProcessGroup",
    "init_process_group",
    "destroy_process_group",
    "is_initialized",
    "get_default_group",
    "get_world_size",
    "get_rank",
    "get_local_rank",
    "get_local_world_size",
    "get_num_processes",
    "new_group",
    "barrier",
    "monitored_barrier",
    "abort",
    "DATA_AXIS",
]

# Default mesh axis name for data parallelism; parallel/ and collectives/
# assume this unless a group was built with custom axes.
DATA_AXIS = "data"

_DEFAULT_GROUP: Optional["ProcessGroup"] = None
_lock = threading.Lock()


class ProcessGroup:
    """A set of devices + the mesh over them.

    The torch analogue is the opaque ``ProcessGroup`` handle returned by
    ``init_process_group``/``new_group`` (/root/reference/README.md:38-43);
    here the handle *is* the mesh, and every collective or parallel wrapper
    takes it (or defaults to the global group).
    """

    def __init__(self, devices: Sequence, axis_names: Sequence[str] = (DATA_AXIS,),
                 mesh_shape: Optional[Sequence[int]] = None,
                 parent: Optional["ProcessGroup"] = None):
        import jax
        from jax.sharding import Mesh

        devices = tuple(devices)
        if not devices:
            raise ValueError("ProcessGroup needs at least one device")
        if mesh_shape is None:
            mesh_shape = (len(devices),)
        if int(np.prod(mesh_shape)) != len(devices):
            raise ValueError(
                f"mesh_shape {tuple(mesh_shape)} does not cover {len(devices)} devices")
        if len(axis_names) != len(mesh_shape):
            raise ValueError("axis_names and mesh_shape must have equal length")
        self._devices = devices
        self._axis_names = tuple(axis_names)
        self._mesh = Mesh(np.array(devices).reshape(tuple(mesh_shape)),
                          self._axis_names)
        self._parent = parent
        self._process_index = jax.process_index()
        self._num_processes = jax.process_count()
        self._destroyed = False

    # -- topology ------------------------------------------------------------
    @property
    def mesh(self):
        """The :class:`jax.sharding.Mesh` over this group's devices."""
        self._check_alive()
        return self._mesh

    @property
    def devices(self):
        return self._devices

    @property
    def axis_name(self) -> str:
        """Primary (data) axis name."""
        return self._axis_names[0]

    @property
    def axis_names(self):
        return self._axis_names

    def size(self) -> int:
        """Device count — DDP replica count."""
        return len(self._devices)

    @property
    def world_size(self) -> int:
        return self.size()

    @property
    def rank(self) -> int:
        """Process rank (the launcher-env ``RANK``)."""
        return self._process_index

    @property
    def num_processes(self) -> int:
        return self._num_processes

    def local_devices(self):
        """Devices of this group addressable by the current process."""
        import jax
        local = set(d.id for d in jax.local_devices())
        return tuple(d for d in self._devices if d.id in local)

    def local_device_ranks(self):
        """Global (group-wise) ranks of this process's devices — what the
        reference computes per worker as ``nr*gpus+gpu``
        (/root/reference/mpspawn_dist.py:47)."""
        import jax
        local = set(d.id for d in jax.local_devices())
        return tuple(i for i, d in enumerate(self._devices) if d.id in local)

    @property
    def local_world_size(self) -> int:
        return len(self.local_devices())

    # -- lifecycle -----------------------------------------------------------
    def _check_alive(self):
        if self._destroyed:
            raise RuntimeError(
                "ProcessGroup used after destroy_process_group()")

    def destroy(self):
        self._destroyed = True

    def __repr__(self):
        return (f"ProcessGroup(world_size={len(self._devices)}, "
                f"rank={self._process_index}/{self._num_processes}, "
                f"axes={dict(zip(self._axis_names, self._mesh.devices.shape))})")


def init_process_group(backend: str = "tpu",
                       init_method: Optional[str] = None,
                       world_size: int = -1,
                       rank: int = -1,
                       timeout: Optional[float] = None,
                       axis_names: Sequence[str] = (DATA_AXIS,),
                       mesh_shape: Optional[Sequence[int]] = None) -> ProcessGroup:
    """Bring up the default process group (c10d ``init_process_group`` parity).

    ``backend``: ``'tpu'`` (XLA collectives over ICI/DCN — the NCCL
    equivalent) or ``'cpu'`` (host-platform devices — the gloo equivalent;
    requires JAX_PLATFORMS=cpu before first jax import).  The reference's
    backend strings appear at /root/reference/README.md:133.

    ``init_method``: ``None`` (single process), ``'env://'`` (read
    MASTER_ADDR/MASTER_PORT/WORLD_SIZE/RANK — /root/reference/launch_dist.py:49),
    or ``'tcp://host:port'`` (explicit coordinator —
    /root/reference/example_mp.py:37-42).  ``world_size``/``rank`` here are
    **process** counts, exactly the launcher env contract; they override env
    values when given.

    Blocks (like the NCCL rendezvous barrier) until all processes join,
    then builds the group over every device in the slice.
    """
    global _DEFAULT_GROUP
    with _lock:
        if _DEFAULT_GROUP is not None and not _DEFAULT_GROUP._destroyed:
            raise RuntimeError(
                "Default process group already initialized; call "
                "destroy_process_group() first.")

        backend = backend.lower()
        if backend in ("gloo",):
            backend = "cpu"
        # mpi: the reference name-checks it as an alternative accelerator
        # backend (/root/reference/README.md:133); on TPU the accelerator
        # data plane is XLA collectives either way
        if backend in ("nccl", "xla", "mpi"):
            backend = "tpu"
        if backend not in ("tpu", "cpu"):
            raise ValueError(f"Unknown backend {backend!r}; use 'tpu' or 'cpu'")

        _rdzv.rendezvous(init_method, world_size=world_size, rank=rank,
                         timeout=timeout)

        import jax
        devices = jax.devices()
        group = ProcessGroup(devices, axis_names=axis_names,
                             mesh_shape=mesh_shape)
        group._backend = backend
        _DEFAULT_GROUP = group
        return group


def is_initialized() -> bool:
    return _DEFAULT_GROUP is not None and not _DEFAULT_GROUP._destroyed


def get_default_group() -> ProcessGroup:
    if not is_initialized():
        raise RuntimeError(
            "Default process group has not been initialized; call "
            "tpu_dist.dist.init_process_group() first.")
    return _DEFAULT_GROUP


def _group(group: Optional[ProcessGroup]) -> ProcessGroup:
    return group if group is not None else get_default_group()


def get_world_size(group: Optional[ProcessGroup] = None) -> int:
    """Device count of the group — the DDP replica count.

    NOTE: on TPU this counts *cores*, not processes; the reference's
    ``world_size = gpus × nodes`` (/root/reference/mpspawn_dist.py:136) counts
    the same thing because there one process == one GPU.
    """
    return _group(group).size()


def get_rank(group: Optional[ProcessGroup] = None) -> int:
    """This process's rank (launcher ``RANK`` env)."""
    return _group(group).rank


def get_backend(group: Optional[ProcessGroup] = None) -> str:
    """torch ``dist.get_backend`` parity: the group's normalized backend
    string — ``'tpu'`` (XLA collectives; accepts the aliases nccl/xla/mpi
    at init) or ``'cpu'`` (accepts gloo).  Subgroups inherit their parent's
    backend at creation (stamped in :func:`new_group`, so the answer
    stays right even after the default group is recycled)."""
    return getattr(_group(group), "_backend", None) or "tpu"


def get_num_processes(group: Optional[ProcessGroup] = None) -> int:
    return _group(group).num_processes


def get_local_world_size(group: Optional[ProcessGroup] = None) -> int:
    return _group(group).local_world_size


def get_local_rank() -> int:
    """Local rank from the launcher env (``LOCAL_RANK``,
    /root/reference/launch_dist.py:46); 0 when not launched."""
    return int(os.environ.get("LOCAL_RANK", 0))


def new_group(ranks: Optional[Sequence[int]] = None,
              axis_names: Sequence[str] = (DATA_AXIS,),
              mesh_shape: Optional[Sequence[int]] = None) -> ProcessGroup:
    """Sub-group over a subset of *device ranks* (c10d ``new_group``,
    /root/reference/README.md:27-28,39).

    Every process must call this collectively with identical ``ranks``.  The
    sub-group's mesh spans only those devices; collectives over it ride the
    sub-torus.
    """
    default = get_default_group()
    if ranks is None:
        ranks = range(default.size())
    devices = [default.devices[r] for r in ranks]
    group = ProcessGroup(devices, axis_names=axis_names,
                         mesh_shape=mesh_shape, parent=default)
    group._backend = getattr(default, "_backend", None)
    return group


def barrier(group: Optional[ProcessGroup] = None) -> None:
    """Block until all processes in the group reach the barrier.

    Implemented as a tiny psum over one device per process (the TPU analogue
    of a store-based barrier); a no-op single-process.
    """
    g = _group(group)
    if g.num_processes <= 1:
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("tpu_dist.barrier")


_MB_SEQ = [0]  # per-process monitored_barrier call counter (all processes
               # must call it in the same order, like every collective)
_MB_PASSED = [-1]  # last seq THIS process passed successfully — gates GC of
                   # the previous generation's keys (below)


def monitored_barrier(group: Optional[ProcessGroup] = None,
                      timeout: float = 300.0) -> None:
    """Barrier that NAMES the ranks that failed to arrive (torch
    ``dist.monitored_barrier`` parity — its debugging use-case is finding
    the hung rank in a deadlocked job).

    Each process posts an arrival key on the control-plane store; process
    0 collects them under ``timeout`` seconds and raises ``RuntimeError``
    listing every missing process rank (c10d's ``wait_all_ranks=True``
    behavior — all stragglers, not just the first), then publishes the
    release key the others wait on.  No-op single-process; raises
    ``RuntimeError`` when the job has no control-plane store (pure
    ``tcp://``-less bring-up) — fall back to :func:`barrier` there.

    Default-group only (like :func:`barrier`'s global sync): a subgroup's
    process membership is not tracked against store keys, so passing one
    raises rather than produce a wrong diagnosis.
    """
    g = _group(group)
    if g._parent is not None:
        raise ValueError("monitored_barrier supports the default group "
                         "only (a subgroup diagnosis would misname "
                         "non-member ranks as missing)")
    if g.num_processes <= 1:
        return
    store = _rdzv.get_store()
    if store is None:
        raise RuntimeError(
            "monitored_barrier needs the control-plane store (launcher or "
            "env:// / tcp:// bring-up); use dist.barrier() instead")
    seq = _MB_SEQ[0]
    _MB_SEQ[0] += 1
    rank = get_rank()
    n = g.num_processes
    prefix = f"__monitored_barrier__/{seq}"
    if seq > 0 and _MB_PASSED[0] == seq - 1:
        # GC this rank's previous-generation arrival key so periodic calls
        # (per-epoch debugging) don't grow the store without bound.  Safe
        # only because this rank PASSED seq-1 (rank 0 finished reading
        # arrived/* before publishing the /go we saw) — a rank that timed
        # out on seq-1 and retried must NOT delete: rank 0 may still be
        # polling seq-1 and would falsely name this rank missing (that
        # error path leaks one key, which is fine).  The seq-1 /go key
        # itself must not be deleted yet either — a straggler may still be
        # waiting on it (rank 0 returns the moment it sets /go); it is
        # GC'd below once rank 0 has seen every rank arrive at THIS
        # barrier, which proves all left the previous one.
        store.delete_key(f"__monitored_barrier__/{seq - 1}/arrived/{rank}")
    store.set(f"{prefix}/arrived/{rank}", b"1")
    import time as _time
    deadline = _time.monotonic() + timeout
    if rank == 0:
        missing = list(range(1, n))
        while True:  # poll at least once: timeout=0 must not misdiagnose
            missing = [r for r in missing
                       if not store.check(f"{prefix}/arrived/{r}")]
            if not missing or _time.monotonic() >= deadline:
                break
            _time.sleep(0.01)
        if missing:
            raise RuntimeError(
                f"monitored_barrier timed out after {timeout}s; process "
                f"rank(s) {missing} did not reach the barrier")
        if seq > 0:
            # Everyone arrived here, so everyone left barrier seq-1: its
            # release key has no remaining readers and can be GC'd.
            store.delete_key(f"__monitored_barrier__/{seq - 1}/go")
        store.set(f"{prefix}/go", b"1")
        _MB_PASSED[0] = seq
    else:
        try:
            store.wait([f"{prefix}/go"],
                       timeout=max(deadline - _time.monotonic(), 0.0))
        except TimeoutError:
            raise RuntimeError(
                f"monitored_barrier timed out after {timeout}s waiting "
                f"for process 0's release") from None
        _MB_PASSED[0] = seq


def abort(exit_code: int = 1, reason: str = "") -> None:
    """Terminate this process IMMEDIATELY without distributed teardown
    (torch ``ProcessGroup.abort`` / NCCL error-handling parity).

    Why it exists: ``sys.exit`` after a distributed failure can HANG —
    jax.distributed's atexit shutdown runs a peer barrier, so a process
    exiting because a *peer* is hung blocks on that same hung peer, the
    launcher sees every child still alive, and fail-fast never fires
    (measured: a worker that raised on :func:`monitored_barrier` timeout
    then ``sys.exit(7)``-ed kept the whole world up for the coordination
    service's multi-minute shutdown timeout).  ``abort`` flushes stdio and
    ``os._exit``-s, so the launcher reaps the exit code at once and kills
    the rest of the world.  Use it in except-handlers around collectives::

        try:
            dist.monitored_barrier(timeout=60)
        except RuntimeError as e:
            print(e, file=sys.stderr)
            dist.abort(7)
    """
    import sys as _sys

    if reason:
        print(f"tpu_dist.abort: {reason}", file=_sys.stderr)
    try:
        # os._exit skips atexit, so the flight recorder (if armed) must
        # flush here — the abort path IS the interesting crash dump
        from ..obs import recorder as _obs_recorder
        _obs_recorder.dump_now(f"abort:{exit_code}")
    except Exception:
        pass
    try:
        _sys.stdout.flush()
        _sys.stderr.flush()
    except Exception:
        pass
    os._exit(exit_code)


def destroy_process_group(group: Optional[ProcessGroup] = None) -> None:
    """Tear down the group (c10d parity, /root/reference/README.md:43).

    Destroying the default group also shuts down the JAX distributed client
    when one was started.
    """
    global _DEFAULT_GROUP
    with _lock:
        g = group if group is not None else _DEFAULT_GROUP
        if g is None:
            return
        g.destroy()
        if g is _DEFAULT_GROUP:
            _DEFAULT_GROUP = None
            _rdzv.shutdown()
