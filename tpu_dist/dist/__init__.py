"""tpu_dist.dist — process groups, rendezvous, stores (L1 of SURVEY.md §1).

The c10d equivalent: ``init_process_group`` and friends
(/root/reference/mpspawn_dist.py:49-54, README.md:36-43), redesigned for the
TPU topology (one process per host, a mesh of cores, XLA collectives).
"""

from .process_group import (DATA_AXIS, ProcessGroup, abort, barrier,
                            monitored_barrier,
                            destroy_process_group, get_backend,
                            get_default_group, get_local_rank,
                            get_local_world_size, get_num_processes,
                            get_rank, get_world_size, init_process_group,
                            is_initialized, new_group)
from .rendezvous import generation, get_store, parse_init_method, rendezvous
from .store import Store, TCPStore, FileStore
from ..collectives.eager import ReduceOp  # torch `dist.ReduceOp` parity

__all__ = [
    "ProcessGroup", "init_process_group", "destroy_process_group",
    "is_initialized", "get_default_group", "get_world_size", "get_rank",
    "get_backend",
    "get_local_rank", "get_local_world_size", "get_num_processes",
    "new_group", "barrier", "monitored_barrier", "abort", "DATA_AXIS",
    "rendezvous", "parse_init_method", "generation", "get_store",
    "Store", "TCPStore", "FileStore", "ReduceOp",
]
