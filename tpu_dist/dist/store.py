"""Key-value stores for rendezvous/coordination — c10d Store parity.

The reference's rendezvous rides torch's C++ TCPStore behind ``env://`` and
``tcp://`` (/root/reference/mpspawn_dist.py:137-138, example_mp.py:18).  Here:

- :class:`TCPStore` — native implementation: C++ server/client
  (tpu_dist/csrc/tcpstore.cpp, built lazily via g++) speaking a
  length-prefixed protocol; a pure-Python client/server of the *same*
  protocol is the fallback when the toolchain is unavailable, so the two
  interoperate (Python client ↔ C++ server and vice versa).
- :class:`FileStore` — shared-filesystem store for single-host testing.

API (torch Store parity): ``set/get/add/wait/check/delete_key/num_keys`` plus
``barrier(world_size)`` built on ``add`` + a server-side blocking WAIT_GE.
``get`` blocks until the key exists — the property rendezvous relies on.
"""

from __future__ import annotations

import collections
import ctypes
import json
import os
import socket
import struct
import threading
import time
from typing import List, Optional

__all__ = ["Store", "TCPStore", "FileStore", "PyTCPStoreServer",
           "StoreFailoverError"]

# Wire protocol op codes (must match csrc/tcpstore.cpp).
(_OP_SET, _OP_GET, _OP_ADD, _OP_CHECK, _OP_DELETE, _OP_NUMKEYS, _OP_WAIT_GE,
 _OP_DELETE_PREFIX) = range(1, 9)
# Replication ops — pure-Python servers only (absent from csrc/tcpstore.cpp;
# the cluster layer forces the Python wire path when replication or endpoint
# failover is armed, see TCPStore.__init__).
(_OP_SNAPSHOT, _OP_LOG_SINCE) = (9, 10)


class StoreFailoverError(ConnectionError):
    """An at-most-once store op (SET/ADD/DELETE) was in flight while the
    control-plane leader changed.

    The op is NOT replayed against the new leader — the old leader may have
    applied it before dying, and a blind resend would double-apply (fatal
    for ADD-based barrier generations).  The error names both leaders and
    the new epoch so the caller can decide whether its op is safe to
    re-issue (idempotent re-publish: yes; counter bump: read first)."""

    def __init__(self, msg: str, old: Optional[str] = None,
                 new: Optional[str] = None, epoch: Optional[int] = None):
        super().__init__(msg)
        self.old_leader = old
        self.new_leader = new
        self.epoch = epoch


class Store:
    """Abstract store interface (torch.distributed.Store parity)."""

    def set(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> bytes:
        """Blocks until ``key`` exists, then returns its value."""
        raise NotImplementedError

    def add(self, key: str, delta: int) -> int:
        raise NotImplementedError

    def check(self, key: str) -> bool:
        raise NotImplementedError

    def delete_key(self, key: str) -> bool:
        raise NotImplementedError

    def delete_prefix(self, prefix: str) -> int:
        """Delete every key starting with ``prefix``; returns the count.

        The restart-time reaper: a crashed generation's in-flight
        ``tpu_dist/g{gen}/...`` payload keys are removed in one server-side
        pass instead of leaking until the server dies
        (tpu_dist/launch/cli.py `_reset_round_state`)."""
        raise NotImplementedError

    def num_keys(self) -> int:
        raise NotImplementedError

    def wait(self, keys: List[str], timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        for k in keys:
            while not self.check(k):
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(f"wait timed out on key {k!r}")
                time.sleep(0.01)

    def barrier(self, world_size: int, tag: str = "default",
                timeout: Optional[float] = None) -> None:
        """All ``world_size`` callers block until everyone arrives.

        Reusable with the same tag: the arrival counter only grows, and each
        caller waits for the next full multiple of ``world_size`` (generation
        scheme, as c10d's store barrier does).
        """
        key = f"__barrier__/{tag}"
        n = self.add(key, 1)
        generation = (n - 1) // world_size
        self.wait_value_ge(key, (generation + 1) * world_size,
                           timeout=timeout)

    def wait_value_ge(self, key: str, target: int,
                      timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.add(key, 0) < target:
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"wait_value_ge timed out on {key!r}")
            time.sleep(0.01)


# ---------------------------------------------------------------------------
# Pure-Python protocol server (fallback when g++/ctypes path unavailable;
# same wire protocol as csrc/tcpstore.cpp, so clients interoperate).
# ---------------------------------------------------------------------------

class PyTCPStoreServer:
    """Python store server.  With ``replicate=True`` (or
    ``TPU_DIST_STORE_REPLICATE=1``) it additionally keeps a bounded
    in-memory mutation log that follower replicas tail via
    ``_OP_SNAPSHOT``/``_OP_LOG_SINCE``:

    - Every applied mutation gets a monotonically increasing sequence
      number.  Only SET/DELETE/DELETE_PREFIX appear in the log — ADD is
      logged as a SET of its *resulting* packed value, so replaying the log
      is idempotent and order-safe (a replayed ADD would double-count).
    - The log is bounded by entries (``TPU_DIST_STORE_LOG_MAX``) and bytes
      (``TPU_DIST_STORE_LOG_BYTES``); a follower that asks for a sequence
      older than the retained base is told to re-snapshot.
    - :meth:`install_snapshot`/:meth:`apply_mutation` are the follower-side
      entry points (tpu_dist/cluster/replica.py): they apply under the same
      condition variable and ``notify_all``, so a blocked GET/WAIT_GE on a
      *promoted* follower wakes exactly like one on the original leader —
      that is the waiter re-arm guarantee.
    """

    def __init__(self, port: int = 0, replicate: bool = False):
        self._kv = {}
        self._mu = threading.Condition()
        self._replicate = bool(replicate) or (
            os.environ.get("TPU_DIST_STORE_REPLICATE", "") not in ("", "0"))
        self._seq = 0  # newest applied mutation sequence number
        self._log = collections.deque()  # (seq, op, key:str, payload:bytes)
        self._log_bytes = 0
        self._log_max = int(os.environ.get("TPU_DIST_STORE_LOG_MAX",
                                           "65536"))
        self._log_max_bytes = int(os.environ.get("TPU_DIST_STORE_LOG_BYTES",
                                                 str(64 << 20)))
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", port))
        self._sock.listen(128)
        self.port = self._sock.getsockname()[1]
        self._stopping = False
        self._threads: List[threading.Thread] = []
        self._accept = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept.start()

    def _accept_loop(self):
        while not self._stopping:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._handle, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    @staticmethod
    def _recv_all(conn, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def _handle(self, conn):
        try:
            while not self._stopping:
                hdr = self._recv_all(conn, 1)
                if hdr is None:
                    return
                op = hdr[0]
                raw = self._recv_all(conn, 4)
                if raw is None:
                    return
                (klen,) = struct.unpack("<I", raw)
                key = self._recv_all(conn, klen) if klen else b""
                raw = self._recv_all(conn, 4)
                if raw is None:
                    return
                (plen,) = struct.unpack("<I", raw)
                payload = self._recv_all(conn, plen) if plen else b""
                if key is None or payload is None:
                    return
                key = key.decode()
                self._dispatch(conn, op, key, payload)
        except OSError:
            pass
        finally:
            conn.close()

    @staticmethod
    def _i64(b: bytes) -> int:
        return struct.unpack("<q", b[:8].ljust(8, b"\0"))[0]

    def _reply(self, conn, status: int, data: bytes = b""):
        conn.sendall(struct.pack("<II", status, len(data)) + data)

    # -- replication log (all three helpers run under self._mu) -------------

    def _log_base(self) -> int:
        return self._log[0][0] if self._log else self._seq + 1

    def _log_append(self, seq: int, op: int, key: str,
                    payload: bytes) -> None:
        self._seq = seq
        if not self._replicate:
            return
        self._log.append((seq, op, key, payload))
        self._log_bytes += len(key) + len(payload) + 16
        while self._log and (len(self._log) > self._log_max
                             or self._log_bytes > self._log_max_bytes):
            old = self._log.popleft()
            self._log_bytes -= len(old[2]) + len(old[3]) + 16

    def _log_mut(self, op: int, key: str, payload: bytes) -> None:
        self._log_append(self._seq + 1, op, key, payload)

    # -- follower-side apply (tpu_dist/cluster/replica.py) ------------------

    def replication_seq(self) -> int:
        with self._mu:
            return self._seq

    def snapshot_items(self, prefix: str = "") -> dict:
        """Copy of the kv map (optionally filtered by key prefix) — the
        election reads lease/candidate tables from its local replica
        through this, never over the (dead) wire."""
        with self._mu:
            return {k: v for k, v in self._kv.items()
                    if k.startswith(prefix)}

    def install_snapshot(self, seq: int, items) -> None:
        with self._mu:
            self._kv = dict(items)
            self._log.clear()
            self._log_bytes = 0
            self._seq = seq
            self._mu.notify_all()

    def apply_mutation(self, seq: int, op: int, key: str,
                       payload: bytes) -> None:
        with self._mu:
            if seq <= self._seq:
                return  # duplicate tail poll — already applied
            if op == _OP_SET:
                self._kv[key] = payload
            elif op == _OP_DELETE:
                self._kv.pop(key, None)
            elif op == _OP_DELETE_PREFIX:
                for k in [k for k in self._kv if k.startswith(key)]:
                    del self._kv[k]
            else:
                raise ValueError(f"bad replicated op {op}")
            # Keep the follower's own log too (with the LEADER's sequence
            # numbers): after promotion, new mutations continue the same
            # sequence and a future follower can tail this server in turn.
            self._log_append(seq, op, key, payload)
            self._mu.notify_all()

    def _dispatch(self, conn, op, key, payload):
        if op == _OP_SET:
            with self._mu:
                self._kv[key] = payload
                self._log_mut(_OP_SET, key, payload)
                self._mu.notify_all()
            self._reply(conn, 0)
        elif op == _OP_GET:
            with self._mu:
                while key not in self._kv and not self._stopping:
                    self._mu.wait(0.1)
                if self._stopping:
                    self._reply(conn, 1)
                    return
                val = self._kv[key]
            self._reply(conn, 0, val)
        elif op == _OP_ADD:
            delta = self._i64(payload)
            with self._mu:
                cur = self._i64(self._kv.get(key, b""))
                nv = cur + delta
                self._kv[key] = struct.pack("<q", nv)
                # logged as a SET of the RESULT: replay stays idempotent
                self._log_mut(_OP_SET, key, self._kv[key])
                self._mu.notify_all()
            self._reply(conn, 0, struct.pack("<q", nv))
        elif op == _OP_CHECK:
            with self._mu:
                ok = key in self._kv
            self._reply(conn, 0, b"1" if ok else b"0")
        elif op == _OP_DELETE:
            with self._mu:
                existed = self._kv.pop(key, None) is not None
                self._log_mut(_OP_DELETE, key, b"")
            self._reply(conn, 0, b"1" if existed else b"0")
        elif op == _OP_DELETE_PREFIX:
            with self._mu:
                doomed = [k for k in self._kv if k.startswith(key)]
                for k in doomed:
                    del self._kv[k]
                self._log_mut(_OP_DELETE_PREFIX, key, b"")
            self._reply(conn, 0, struct.pack("<q", len(doomed)))
        elif op == _OP_NUMKEYS:
            with self._mu:
                n = len(self._kv)
            self._reply(conn, 0, struct.pack("<I", n))
        elif op == _OP_WAIT_GE:
            target = self._i64(payload)
            with self._mu:
                while (self._i64(self._kv.get(key, b"")) < target
                       and not self._stopping):
                    self._mu.wait(0.1)
            self._reply(conn, 1 if self._stopping else 0)
        elif op == _OP_SNAPSHOT:
            # atomic kv image: <q seq> <I count> then per entry
            # <I klen> key <I vlen> value
            with self._mu:
                parts = [struct.pack("<qI", self._seq, len(self._kv))]
                for k, v in self._kv.items():
                    kb = k.encode()
                    parts.append(struct.pack("<I", len(kb)) + kb
                                 + struct.pack("<I", len(v)) + v)
            self._reply(conn, 0, b"".join(parts))
        elif op == _OP_LOG_SINCE:
            # payload: <q since> (the follower's applied seq).  Reply body:
            # <B flag> — flag 1 means the log was truncated past `since`
            # (re-snapshot required); flag 0 is followed by <q leader_seq>
            # <I count> then per entry <q seq> <B op> <I klen> key
            # <I plen> payload.
            since = self._i64(payload)
            with self._mu:
                if since + 1 < self._log_base():
                    body = struct.pack("<B", 1)
                else:
                    ents = [e for e in self._log if e[0] > since]
                    parts = [struct.pack("<BqI", 0, self._seq, len(ents))]
                    for s, eop, ekey, epay in ents:
                        kb = ekey.encode()
                        parts.append(struct.pack("<qBI", s, eop, len(kb))
                                     + kb + struct.pack("<I", len(epay))
                                     + epay)
                    body = b"".join(parts)
            self._reply(conn, 0, body)
        else:
            self._reply(conn, 2)

    def stop(self):
        self._stopping = True
        with self._mu:
            self._mu.notify_all()
        try:
            self._sock.close()
        except OSError:
            pass


# Chaos fault hook (tpu_dist.resilience.chaos): called as fn(client, op, key)
# at the top of every _PyClient request; may close the client socket or sleep
# to inject deterministic connection faults.  None in production.
FAULT_HOOK = None

def _net_active():
    """The active network-fault injector, or None.  Guarded by
    sys.modules + the env var so processes that never arm netchaos never
    even import it.  Deliberately a local copy of the canonical probe
    (``tpu_dist.collectives.transport._net_chaos``, which the serve wire
    reuses) rather than an import of it: a bare store client must stay
    light, and importing the transport module would pull numpy into
    store-only processes.  Keep the two four-line guards in sync."""
    import sys
    if "tpu_dist.resilience.netchaos" not in sys.modules \
            and not os.environ.get("TPU_DIST_NETCHAOS"):
        return None
    from ..resilience import netchaos
    return netchaos.install_from_env()


def _net_store_fault(client, op: int, key: str, payload: bytes) -> bytes:
    """Network-chaos consultation for one store request (the ``store``
    surface of tpu_dist/resilience/netchaos.py; pure-Python client only,
    like :data:`FAULT_HOOK`).  May sleep (``delay``/``slow-drip``), close
    the socket (``conn-reset``/``truncate`` — the reconnect/at-most-once
    machinery owns recovery), raise a named ``ConnectionError``
    (``partition`` — unreachable server), or return a bit-flipped payload
    (``corrupt`` — the consumer's sealed-payload checksum catches it)."""
    nc = _net_active()
    if nc is None:
        return payload
    f = nc.plan("store")
    if f is None:
        return payload
    if f.kind == "partition":
        sock = getattr(client, "_sock", None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        raise ConnectionError(
            f"netchaos: injected store partition — control-plane server "
            f"unreachable (op={op} key={key!r})")
    if f.kind == "delay":
        time.sleep(f.delay)
    elif f.kind == "slow-drip":
        time.sleep(len(payload) / max(1.0, f.rate))
    elif f.kind in ("conn-reset", "truncate"):
        sock = getattr(client, "_sock", None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
    elif f.kind == "corrupt" and payload:
        return bytes(nc.corrupt_parts(f, (payload,))[0])
    return payload

# Reads (and the server-side blocking wait) are safe to replay after a lost
# connection; SET/ADD/DELETE are NOT — the server may have applied the op
# before the connection died, and a blind resend would double-apply (fatal
# for ADD-based barrier generations).  Those stay at-most-once.
# DELETE_PREFIX replays safely (re-deleting an already-swept prefix removes
# nothing more; only the returned count could differ) so it reconnects too.
_IDEMPOTENT_OPS = frozenset({_OP_GET, _OP_CHECK, _OP_NUMKEYS, _OP_WAIT_GE,
                             _OP_DELETE_PREFIX})
_RECONNECT_ATTEMPTS = 4
_RECONNECT_BACKOFF = 0.05  # doubles per attempt


def _read_endpoints(path: str):
    """Parse a cluster endpoints file → ``(host, port, epoch)`` or None.

    The file (written atomically by tpu_dist/cluster/endpoints.py) names the
    current store leader; a mid-rewrite or missing file reads as None and
    the client keeps its current address — the next reconnect attempt
    re-reads."""
    try:
        with open(path, encoding="utf-8") as f:
            d = json.load(f)
        leader = str(d.get("leader") or "")
        host, _, port = leader.rpartition(":")
        if not host:
            return None
        return host, int(port), int(d.get("epoch", 0))
    except (OSError, ValueError, TypeError):
        return None


class _PyClient:
    """Pure-Python client for the store wire protocol.

    A dropped connection (ECONNRESET, server restart, injected fault)
    mid-request is retried with bounded reconnect-and-backoff for
    idempotent ops (GET/CHECK/NUMKEYS/WAIT_GE) and surfaces as
    ``ConnectionError`` for the at-most-once ops (SET/ADD/DELETE).

    When ``TPU_DIST_STORE_ENDPOINTS`` names an endpoints file (and
    ``follow_endpoints`` is left on), every reconnect first re-resolves the
    leader address from that file, so the same bounded machinery that
    absorbs a server restart also rides out a leader *failover*: blocked
    GET/WAIT_GE waiters re-arm against the promoted follower, and a failed
    at-most-once op that crossed a detected leader change surfaces as
    :class:`StoreFailoverError` (still never replayed).  The reconnect
    budget defaults higher (8, env ``TPU_DIST_STORE_RECONNECT_ATTEMPTS``)
    when endpoints are configured — it must cover an election window."""

    def __init__(self, host: str, port: int, timeout: float,
                 follow_endpoints: bool = True):
        self._endpoints = (os.environ.get("TPU_DIST_STORE_ENDPOINTS") or None
                           if follow_endpoints else None)
        self._epoch = -1
        if self._endpoints:
            ep = _read_endpoints(self._endpoints)
            if ep is not None:
                host, port, self._epoch = ep
        env_attempts = os.environ.get("TPU_DIST_STORE_RECONNECT_ATTEMPTS")
        self._attempts = (int(env_attempts) if env_attempts
                          else (8 if self._endpoints
                                else _RECONNECT_ATTEMPTS))
        self._host, self._port = host, port
        self._sock = self._connect(host, port, timeout)
        self._mu = threading.Lock()

    def _refresh_endpoints(self) -> bool:
        """Re-resolve the leader from the endpoints file (if configured);
        True when the address changed — a failover happened."""
        if not self._endpoints:
            return False
        ep = _read_endpoints(self._endpoints)
        if ep is None:
            return False
        host, port, epoch = ep
        if (host, port) == (self._host, self._port):
            self._epoch = max(self._epoch, epoch)
            return False
        old = f"{self._host}:{self._port}"
        self._host, self._port, self._epoch = host, port, epoch
        new = f"{host}:{port}"
        try:  # diagnostics must never break a store op
            from ..utils.logging import log_event
            log_event("store-failover", old=old, new=new, epoch=epoch)
        except Exception:
            pass
        try:
            from ..obs.recorder import safe_record
            safe_record("store", "failover", key=new, old=old, epoch=epoch)
        except Exception:
            pass
        return True

    @staticmethod
    def _connect(host: str, port: int, timeout: float):
        # bounded exponential backoff under an overall deadline — the
        # shared retry shape (tpu_dist/utils/backoff.py) replacing the old
        # flat 50 ms dial loop
        from ..utils.backoff import BackoffDeadlineError, retry_call
        try:
            sock = retry_call(
                lambda: socket.create_connection((host, port), timeout=5),
                timeout=timeout, what=f"connect to store at {host}:{port}")
        except BackoffDeadlineError as e:
            raise TimeoutError(
                f"could not connect to store at {host}:{port}: "
                f"{e.last}") from e
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(None)  # GET/WAIT_GE block indefinitely
        return sock

    def request(self, op: int, key: str, payload: bytes = b"") -> bytes:
        if FAULT_HOOK is not None:
            FAULT_HOOK(self, op, key)  # once per logical request, not retry
        payload = _net_store_fault(self, op, key, payload)
        kb = key.encode()
        msg = (struct.pack("<BI", op, len(kb)) + kb
               + struct.pack("<I", len(payload)) + payload)
        with self._mu:
            attempt = 0
            epoch0 = self._epoch  # leader epoch when this op started
            old_addr = f"{self._host}:{self._port}"
            while True:
                try:
                    self._sock.sendall(msg)
                    hdr = PyTCPStoreServer._recv_all(self._sock, 8)
                    if hdr is None:
                        raise ConnectionError("store connection closed")
                    status, dlen = struct.unpack("<II", hdr)
                    data = (PyTCPStoreServer._recv_all(self._sock, dlen)
                            if dlen else b"")
                    if dlen and data is None:
                        raise ConnectionError("store connection closed")
                    if (status == 1 and self._endpoints
                            and op in (_OP_GET, _OP_WAIT_GE)):
                        # "server stopping" on a blocked op.  Under a
                        # cluster endpoints file that is a leader going
                        # away, not a terminal answer: convert to the
                        # retryable class so the waiter re-arms against
                        # the promoted follower.  (Without endpoints the
                        # historical status!=0 RuntimeError stands.)
                        raise ConnectionError(
                            "store stopping while blocked (leader "
                            "shutdown) — re-arming")
                    break
                except OSError as e:  # ConnectionError/TimeoutError included
                    if (op not in _IDEMPOTENT_OPS
                            or attempt >= self._attempts):
                        # best-effort fresh socket (re-resolving the leader)
                        # so the NEXT request is not doomed by this one's
                        # dead connection (this op is NOT replayed:
                        # at-most-once)
                        self._refresh_endpoints()
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        try:
                            self._sock = self._connect(self._host,
                                                       self._port,
                                                       timeout=2.0)
                        except (TimeoutError, OSError):
                            pass
                        if self._epoch != epoch0:
                            raise StoreFailoverError(
                                f"store request op={op} was in flight "
                                f"across a leader failover "
                                f"({old_addr} -> {self._host}:{self._port}, "
                                f"epoch {self._epoch}) and is not replayed: "
                                f"{e}", old=old_addr,
                                new=f"{self._host}:{self._port}",
                                epoch=self._epoch) from e
                        raise ConnectionError(
                            f"store request op={op} failed after {attempt} "
                            f"reconnect attempt(s): {e}") from e
                    attempt += 1
                    time.sleep(_RECONNECT_BACKOFF * (2 ** (attempt - 1)))
                    self._refresh_endpoints()
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    try:
                        self._sock = self._connect(self._host, self._port,
                                                   timeout=2.0)
                    except (TimeoutError, OSError):
                        pass  # next sendall fails fast -> consumes an attempt
        if status != 0:
            raise RuntimeError(f"store request op={op} failed (status {status})")
        return data or b""

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


# store ops worth a flight-recorder event; CHECK/NUMKEYS are the polling
# primitives (a blocked rank fires them at 10ms cadence) and would only
# flood the ring buffer with what the pending collective span already says
_OBS_OP_NAMES = {_OP_SET: "set", _OP_GET: "get", _OP_ADD: "add",
                 _OP_DELETE: "delete", _OP_WAIT_GE: "wait_ge",
                 _OP_DELETE_PREFIX: "delete_prefix"}


class _ObservedClient:
    """Flight-recorder shim around a store client: one ``kind="store"``
    event per completed request (op name, key, payload bytes, outcome).

    Installed at :class:`TCPStore` construction only when the recorder is
    armed (``TPU_DIST_OBS``), so disarmed stores keep the raw client and
    the hot path pays nothing."""

    def __init__(self, inner):
        self._inner = inner

    def request(self, op: int, key: str, payload: bytes = b"") -> bytes:
        t0 = time.monotonic_ns()
        try:
            out = self._inner.request(op, key, payload)
        except BaseException as e:
            self._rec(op, key, payload, t0, f"error:{type(e).__name__}")
            raise
        self._rec(op, key, payload, t0, "ok")
        return out

    @staticmethod
    def _rec(op, key, payload, t0, outcome):
        name = _OBS_OP_NAMES.get(op)
        if name is None:
            return
        try:  # diagnostics must never break a store op
            from ..obs.recorder import safe_record
        except Exception:
            return
        safe_record("store", name, t0=t0, key=key, bytes=len(payload),
                    outcome=outcome)

    def close(self):
        self._inner.close()


class _NativeClient:
    """ctypes wrapper over the C++ client in libtpudist.so."""

    def __init__(self, lib, host: str, port: int, timeout: float):
        self._lib = lib
        self._h = lib.tpudist_store_client_connect(
            host.encode(), port, int(timeout * 1000))
        if not self._h:
            raise TimeoutError(f"could not connect to store at {host}:{port}")

    def request(self, op: int, key: str, payload: bytes = b"") -> bytes:
        lib, h, kb = self._lib, self._h, key.encode()
        if op == _OP_SET:
            buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload) \
                if payload else None
            if lib.tpudist_store_set(h, kb, buf, len(payload)) != 0:
                raise RuntimeError("store set failed")
            return b""
        if op == _OP_GET:
            out = ctypes.POINTER(ctypes.c_uint8)()
            n = ctypes.c_int()
            if lib.tpudist_store_get(h, kb, ctypes.byref(out),
                                     ctypes.byref(n)) != 0:
                raise RuntimeError("store get failed")
            data = bytes(bytearray(out[i] for i in range(n.value)))
            if n.value:
                lib.tpudist_store_free(out)
            return data
        if op == _OP_ADD:
            delta = struct.unpack("<q", payload[:8].ljust(8, b"\0"))[0]
            result = ctypes.c_longlong()
            if lib.tpudist_store_add(h, kb, delta,
                                     ctypes.byref(result)) != 0:
                raise ConnectionError("store add failed")
            return struct.pack("<q", result.value)
        if op == _OP_CHECK:
            r = lib.tpudist_store_check(h, kb)
            if r < 0:
                raise ConnectionError("store check failed")
            return b"1" if r == 1 else b"0"
        if op == _OP_DELETE:
            r = lib.tpudist_store_delete(h, kb)
            if r < 0:
                raise ConnectionError("store delete failed")
            return b"1" if r == 1 else b"0"
        if op == _OP_NUMKEYS:
            r = lib.tpudist_store_num_keys(h)
            if r < 0:
                raise ConnectionError("store num_keys failed")
            return struct.pack("<I", r)
        if op == _OP_WAIT_GE:
            target = struct.unpack("<q", payload[:8].ljust(8, b"\0"))[0]
            if lib.tpudist_store_wait_ge(h, kb, target) != 0:
                raise RuntimeError("store wait_ge failed")
            return b""
        if op == _OP_DELETE_PREFIX:
            result = ctypes.c_longlong()
            if lib.tpudist_store_delete_prefix(h, kb,
                                               ctypes.byref(result)) != 0:
                raise ConnectionError("store delete_prefix failed")
            return struct.pack("<q", result.value)
        raise ValueError(f"bad op {op}")

    def close(self):
        if self._h:
            self._lib.tpudist_store_client_close(self._h)
            self._h = None


def _bind_store(lib):
    lib.tpudist_store_server_start.restype = ctypes.c_void_p
    lib.tpudist_store_server_start.argtypes = [ctypes.c_int]
    lib.tpudist_store_server_port.restype = ctypes.c_int
    lib.tpudist_store_server_port.argtypes = [ctypes.c_void_p]
    lib.tpudist_store_server_stop.argtypes = [ctypes.c_void_p]
    lib.tpudist_store_client_connect.restype = ctypes.c_void_p
    lib.tpudist_store_client_connect.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.tpudist_store_client_close.argtypes = [ctypes.c_void_p]
    lib.tpudist_store_set.restype = ctypes.c_int
    lib.tpudist_store_set.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int]
    lib.tpudist_store_get.restype = ctypes.c_int
    lib.tpudist_store_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_int)]
    lib.tpudist_store_add.restype = ctypes.c_int
    lib.tpudist_store_add.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_longlong)]
    lib.tpudist_store_check.restype = ctypes.c_int
    lib.tpudist_store_check.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tpudist_store_delete.restype = ctypes.c_int
    lib.tpudist_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tpudist_store_num_keys.restype = ctypes.c_int
    lib.tpudist_store_num_keys.argtypes = [ctypes.c_void_p]
    lib.tpudist_store_wait_ge.restype = ctypes.c_int
    lib.tpudist_store_wait_ge.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong]
    lib.tpudist_store_delete_prefix.restype = ctypes.c_int
    lib.tpudist_store_delete_prefix.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_longlong)]
    lib.tpudist_store_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    return lib


def _make_loader():
    from ..csrc.build import load_native
    return load_native("TPU_DIST_PURE_PYTHON_STORE", _bind_store)


_load_native = _make_loader()


class TCPStore(Store):
    """TCP key-value store (c10d TCPStore parity).

    ``is_master=True`` additionally hosts the server (native C++ when the
    toolchain allows, else the in-process Python server); every instance is
    a client.  ``port=0`` with ``is_master`` picks a free port (see
    ``.port``).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 is_master: bool = False, timeout: float = 300.0):
        lib = _load_native()
        if (os.environ.get("TPU_DIST_STORE_ENDPOINTS")
                or os.environ.get("TPU_DIST_STORE_REPLICATE", "")
                not in ("", "0")):
            # Leader failover re-resolution and the replication mutation log
            # live in the Python wire implementation only; the native
            # client/server have neither, so a cluster-armed process must
            # not split-brain across the two paths.
            lib = None
        self._server = None
        self._native_server = None
        if is_master:
            if lib is not None:
                self._native_server = lib.tpudist_store_server_start(port)
                if not self._native_server:
                    raise OSError(f"could not bind store server on port {port}")
                port = lib.tpudist_store_server_port(self._native_server)
            else:
                self._server = PyTCPStoreServer(port)
                port = self._server.port
            host = "127.0.0.1" if host in ("0.0.0.0", "") else host
        self.host, self.port = host, port
        self.native = lib is not None
        self._lib = lib  # close() must stop the server with the same lib
        # A hosting instance IS the leader — it must not chase the
        # endpoints file away from its own server.
        client = (_NativeClient(lib, host, port, timeout)
                  if lib is not None
                  else _PyClient(host, port, timeout,
                                 follow_endpoints=not is_master))
        from ..obs import recorder as _obs_recorder
        if _obs_recorder.enabled():
            client = _ObservedClient(client)
        self._client = client

    # -- Store API -----------------------------------------------------------
    def set(self, key: str, value) -> None:
        if isinstance(value, str):
            value = value.encode()
        self._client.request(_OP_SET, key, bytes(value))

    def get(self, key: str) -> bytes:
        return self._client.request(_OP_GET, key)

    def add(self, key: str, delta: int) -> int:
        out = self._client.request(_OP_ADD, key, struct.pack("<q", delta))
        return struct.unpack("<q", out)[0]

    def check(self, key: str) -> bool:
        return self._client.request(_OP_CHECK, key) == b"1"

    def delete_key(self, key: str) -> bool:
        return self._client.request(_OP_DELETE, key) == b"1"

    def delete_prefix(self, prefix: str) -> int:
        out = self._client.request(_OP_DELETE_PREFIX, prefix)
        return struct.unpack("<q", out)[0]

    def num_keys(self) -> int:
        return struct.unpack(
            "<I", self._client.request(_OP_NUMKEYS, ""))[0]

    def wait_value_ge(self, key: str, target: int,
                      timeout: Optional[float] = None) -> None:
        # Server-side blocking wait (no polling); timeout falls back to poll.
        if timeout is None:
            self._client.request(_OP_WAIT_GE, key, struct.pack("<q", target))
        else:
            super().wait_value_ge(key, target, timeout)

    def close(self) -> None:
        self._client.close()
        if self._server is not None:
            self._server.stop()
            self._server = None
        if self._native_server:
            self._lib.tpudist_store_server_stop(self._native_server)
            self._native_server = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class FileStore(Store):
    """Shared-filesystem store — single-host testing convenience."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._mu = threading.Lock()

    def _file(self, key: str) -> str:
        safe = key.replace("/", "_slash_")
        return os.path.join(self.path, safe)

    def set(self, key: str, value) -> None:
        if isinstance(value, str):
            value = value.encode()
        tmp = self._file(key) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(bytes(value))
        os.replace(tmp, self._file(key))

    def get(self, key: str) -> bytes:
        while not os.path.exists(self._file(key)):
            time.sleep(0.01)
        with open(self._file(key), "rb") as f:
            return f.read()

    def _lock_file(self, key: str) -> str:
        # own namespace (dot-dir): can't collide with a key named
        # '<key>.lock', and num_keys/check never see it
        d = os.path.join(self.path, ".locks")
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, os.path.basename(self._file(key)))

    def add(self, key: str, delta: int) -> int:
        # Cross-process atomicity via flock on a persistent lock file: the
        # kernel releases the lock when the holder dies, so a crash between
        # acquire and release cannot wedge every other rank (unlike a
        # create/unlink lockfile scheme).
        import fcntl

        fd = os.open(self._lock_file(key), os.O_CREAT | os.O_WRONLY, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            cur = 0
            if os.path.exists(self._file(key)):
                with open(self._file(key), "rb") as f:
                    raw = f.read()
                cur = struct.unpack("<q", raw[:8].ljust(8, b"\0"))[0]
            nv = cur + delta
            self.set(key, struct.pack("<q", nv))
            return nv
        finally:
            os.close(fd)  # releases the flock; lock file stays

    def check(self, key: str) -> bool:
        return os.path.exists(self._file(key))

    def delete_key(self, key: str) -> bool:
        # lock files in .locks/ are deliberately NOT unlinked: removing a
        # lock while a peer holds its flock would let a third process
        # create a fresh inode and enter the critical section concurrently.
        # They are tiny, invisible to num_keys/check, and bounded by the
        # number of distinct counter keys.
        try:
            os.unlink(self._file(key))
            return True
        except FileNotFoundError:
            return False

    def delete_prefix(self, prefix: str) -> int:
        # the same "/"-flattening as _file: a key prefix maps to a filename
        # prefix, so a directory listing finds every matching key
        safe = prefix.replace("/", "_slash_")
        n = 0
        for f in os.listdir(self.path):
            if f.startswith(".") or f.endswith(".tmp"):
                continue
            if f.startswith(safe):
                try:
                    os.unlink(os.path.join(self.path, f))
                    n += 1
                except FileNotFoundError:
                    pass
        return n

    def num_keys(self) -> int:
        return len([f for f in os.listdir(self.path)
                    if not f.startswith(".") and not f.endswith(".tmp")])
