"""GSPMD parallelism: shard by annotation, let XLA insert the collectives.

The second parallel programming model next to the explicit ``shard_map`` DDP
wrapper (ddp.py).  Here you write ordinary single-device training code; the
*placement of the inputs* (params sharded per rules, batch sharded over
'data') drives XLA's SPMD partitioner to cut every matmul and insert every
collective — the scaling-book recipe: pick a mesh, annotate shardings,
profile, iterate.

This is how tensor parallelism is done TPU-first: no Megatron-style
Column/RowParallelLinear classes — a *rule* maps parameter paths to
PartitionSpecs (e.g. attention QKV sharded on the 'model' axis column-wise,
the output projection row-wise) and XLA emits exactly the all-reduces those
hand-written layers would contain.  Works combined with data parallelism on
an N-D mesh (('data', 'model') tested in tests/test_gspmd.py against the
single-device step).

The reference has no TP (SURVEY.md §2c) — this exists so the mesh design
demonstrably extends beyond DDP, as §2c's implication row requires.
"""

from __future__ import annotations

import re
from typing import Callable, Optional, Sequence, Tuple

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from . import rules as _rules

__all__ = ["PartitionRules", "shard_pytree", "make_gspmd_train_step",
           "TRANSFORMER_TP_RULES", "MOE_EP_RULES"]


class PartitionRules:
    """Ordered (path-regex → PartitionSpec) rules; first match wins.

    Paths are the flattened pytree key strings, e.g.
    ``"['block0.attn']['qkv_weight']"``; regexes are searched, not
    fullmatched.  Unmatched leaves replicate (P()).
    """

    def __init__(self, rules: Sequence[Tuple[str, P]]):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(self, path: str, leaf=None) -> P:
        for pat, spec in self.rules:
            if pat.search(path):
                return spec
        return P()

    def tree_specs(self, tree):
        """Pytree of PartitionSpecs matching ``tree``'s structure."""
        flat = jax.tree_util.tree_leaves_with_path(tree)
        leaves = [self.spec_for(jax.tree_util.keystr(p), l) for p, l in flat]
        treedef = jax.tree_util.tree_structure(tree)
        return jax.tree_util.tree_unflatten(treedef, leaves)


# Megatron-style transformer sharding over a 'model' mesh axis:
# - fused QKV and MLP-in sharded column-wise (output features),
# - attention-out and MLP-out sharded row-wise (input features) — XLA
#   places the single all-reduce after each row-parallel matmul,
# - embeddings and LM head sharded on the vocab/feature dimension.
# Derived from the unified rule plane (parallel/rules.py): the same
# DEFAULT_RULES + layout table that drives ZeRO shards, reshard
# manifests, serving spans, and host dp×tp training produces these
# specs, so the compiled mesh program and the eager host twin cannot
# drift (golden-pinned to the pre-refactor literals in tests/test_rules).
TRANSFORMER_TP_RULES = PartitionRules(_rules.partition_pairs())

# Expert parallelism over an 'expert' mesh axis: every stacked MoE leaf
# (w1/b1/w2/b2, leading dim = num_experts; see nn/moe.py) shards its expert
# axis; the router and everything else replicate.  The dispatch/combine
# einsums then partition over 'expert' and XLA inserts the token
# all-to-alls the GShard paper wires by hand.
MOE_EP_RULES = PartitionRules(_rules.partition_pairs({"expert": "expert"}))


def shard_pytree(tree, mesh, rules: Optional[PartitionRules] = None):
    """``device_put`` every leaf onto ``mesh`` per ``rules`` (default:
    replicate everything).  The committed shardings then steer jit."""
    specs = (rules.tree_specs(tree) if rules is not None
             else jax.tree.map(lambda _: P(), tree))
    return jax.tree.map(
        lambda leaf, spec: (None if leaf is None else
                            jax.device_put(leaf, NamedSharding(mesh, spec))),
        tree, specs,
        is_leaf=lambda x: x is None)


def make_gspmd_train_step(model, loss_fn, optimizer, donate: bool = True,
                          aux_loss_coeff: float = 0.0) -> Callable:
    """Build the jitted GSPMD step: ordinary single-device code, sharded by
    its inputs.  Callers place params/opt_state with :func:`shard_pytree`
    and the batch with a ``P('data', ...)`` sharding; returns
    ``step(params, opt_state, x, y) -> (params, opt_state, metrics)`` —
    or, when the model carries mutable state (BatchNorm stats, MoE aux
    losses), ``step(params, opt_state, mstate, x, y) -> (params, opt_state,
    new_mstate, metrics)``.

    ``aux_loss_coeff``: weight on the sum of every ``aux_loss`` entry the
    state carries (MoE load balancing, nn/moe.py) — the entries are traced
    values of the same forward, so gradients flow through the routers.

    NOTE vs the shard_map DDP wrapper: under GSPMD, batch statistics (e.g.
    BatchNorm) are computed over the **global** batch — sync-BN semantics —
    because the program is written globally.  The shard_map wrapper is the
    one matching torch DDP's per-replica BN exactly.
    """
    has_state = model.has_state()

    def run_model(p, ms, x):
        # dense attention under GSPMD: XLA's SPMD partitioner cannot cut
        # a Pallas custom call, so the flash kernel must not be
        # auto-dispatched inside a sharded jit (see nn.attention)
        from ..nn.attention import attention_impl
        with attention_impl("dense"):
            if has_state:
                return model.apply(p, x, state=ms, training=True)
            return model.apply(p, x), ms

    def objective(p, ms, x, y):
        out, new_ms = run_model(p, ms, x)
        loss = loss_fn(out, y)
        aux = sum((v["aux_loss"] for v in new_ms.values()
                   if isinstance(v, dict) and "aux_loss" in v),
                  start=0.0) if has_state else 0.0
        return loss + aux_loss_coeff * aux, (loss, out, new_ms)

    def stateless_step(params, opt_state, x, y):
        (_, (loss, out, _)), grads = jax.value_and_grad(
            objective, has_aux=True)(params, {}, x, y)
        new_params, new_opt = optimizer.update(grads, opt_state, params)
        correct = (out.argmax(-1) == y).sum()
        return new_params, new_opt, {"loss": loss, "correct": correct}

    def stateful_step(params, opt_state, mstate, x, y):
        (_, (loss, out, new_ms)), grads = jax.value_and_grad(
            objective, has_aux=True)(params, mstate, x, y)
        new_params, new_opt = optimizer.update(grads, opt_state, params)
        correct = (out.argmax(-1) == y).sum()
        return new_params, new_opt, new_ms, {"loss": loss,
                                             "correct": correct}

    fn = stateful_step if has_state else stateless_step
    return jax.jit(fn, donate_argnums=(0, 1) if donate else ())
