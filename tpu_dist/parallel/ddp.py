"""DistributedDataParallel — the L3 wrapper, compiled instead of hooked.

torch's DDP (`/root/reference/mpspawn_dist.py:68`, `example_mp.py:53`) is a
*runtime object*: it broadcasts parameters from rank 0 at wrap time, then
hooks autograd to fire bucketed NCCL all-reduces overlapped with backward.

On TPU none of that machinery exists at runtime — it is **compiled in**
(SURVEY.md §7 design stance; BASELINE.json north star: "fwd/bwd + gradient
all-reduce in a single XLA graph").  This wrapper builds ONE jitted step:

    forward → loss → pmean(loss) over the data axis → grad → SGD update

under ``shard_map`` over the group's mesh.  Two properties make the gradient
all-reduce both correct and free:

- **JAX 0.9 VMA autodiff**: inside ``shard_map``, parameters enter replicated
  (``P()`` in_spec).  Differentiating w.r.t. a replicated value auto-inserts
  the ``psum`` of per-device cotangents.  Taking the gradient *of the
  pmean-ed loss* therefore yields exactly the DDP-averaged gradient — adding
  an explicit ``pmean`` on grads afterwards would double-count (verified the
  hard way; see .claude/skills/verify/SKILL.md).
- **XLA fusion/scheduling**: the all-reduce is an op in the backward graph,
  so XLA overlaps it with remaining backward compute on ICI — the same
  overlap DDP's Reducer implements by hand with buckets and streams.

BatchNorm semantics (SURVEY.md §2b #16): batch statistics stay **per-replica**
(DDP parity — torch DDP does not sync BN).  Running-stat *updates* are
pmean-ed across replicas to keep the state replicated; this is a documented,
deliberate improvement over torch's keep-rank-0's-stats (identical in
distribution, strictly less variance).  ``sync_batchnorm=True`` converts BN
layers to cross-replica batch stats (torch SyncBatchNorm parity).
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..nn.layers import BatchNorm2d
from ..nn.module import Module

__all__ = ["TrainState", "DistributedDataParallel", "convert_sync_batchnorm"]


class TrainState(NamedTuple):
    """Replicated training state threaded through the jitted step."""
    params: Any
    model_state: Any      # BN running stats etc.; {} for stateless nets
    opt_state: Any
    step: jnp.ndarray     # scalar int32
    rng: jnp.ndarray      # base PRNG key; per-step/per-replica keys derive


def convert_sync_batchnorm(module: Module, axis_name: str) -> Module:
    """Set every BatchNorm layer to compute cross-replica batch statistics
    (torch ``SyncBatchNorm.convert_sync_batchnorm`` parity).  Mutates and
    returns the module (topology objects hold no arrays, so this is safe
    before ``init``/``apply``)."""
    for _, m in module.named_modules():
        if isinstance(m, BatchNorm2d):
            m.axis_name = axis_name
    return module


class DistributedDataParallel:
    """Data-parallel training driver over a process group's mesh.

    Usage (the reference loop shape, /root/reference/mpspawn_dist.py:97-118)::

        pg = dist.init_process_group()
        ddp = DistributedDataParallel(model, optimizer=SGD(lr),
                                      loss_fn=nn.CrossEntropyLoss(), group=pg)
        state = ddp.init(seed=0)        # == manual_seed(0) on every rank
        for epoch in range(E):
            loader.set_epoch(epoch)
            for xb, yb in device_loader:
                state, metrics = ddp.train_step(state, xb, yb)

    ``metrics`` holds ``loss`` (global mean) and ``correct`` (global count),
    as on-device scalars — don't block on them every step (SURVEY.md §7:
    ``loss.item()`` per step kills pipelining; log every N).
    """

    def __init__(self, module: Module, optimizer=None, loss_fn=None,
                 group=None, sync_batchnorm: bool = False,
                 donate: bool = True):
        if group is None:
            from .. import dist as _dist
            group = _dist.get_default_group()
        self.module = module
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.group = group
        self.axis = group.axis_name
        self.donate = donate
        if sync_batchnorm:
            convert_sync_batchnorm(module, self.axis)
        self._train_step = None
        self._eval_step = None
        self._forward = None

    # -- state ----------------------------------------------------------------
    def init(self, seed: int = 0, rng: Optional[jax.Array] = None) -> TrainState:
        """Build replicated TrainState.

        Deterministic given ``seed`` — every process constructs identical
        parameters, the TPU analogue of ``torch.manual_seed(0)`` before DDP
        wrap (/root/reference/mpspawn_dist.py:56).  (DDP's alternative —
        rank-0 broadcast at wrap time, /root/reference/example_mp.py:53 —
        is unnecessary when init is deterministic, but available as
        ``collectives.broadcast_host`` for externally-loaded params.)
        """
        key = rng if rng is not None else jax.random.key(seed)
        params = self.module.init(key)
        model_state = self.module.init_state()
        opt_state = (self.optimizer.init(params)
                     if self.optimizer is not None else {})
        state = TrainState(params, model_state, opt_state,
                           jnp.zeros((), jnp.int32),
                           jax.random.key_data(jax.random.fold_in(key, 0x5eed)))
        # commit replicated onto the mesh so donation reuses buffers
        repl = NamedSharding(self.group.mesh, P())
        return jax.tree.map(lambda a: jax.device_put(a, repl), state)

    # -- compiled steps --------------------------------------------------------
    def _build_train_step(self):
        module, loss_fn, optimizer, axis = (self.module, self.loss_fn,
                                            self.optimizer, self.axis)
        has_state = module.has_state()

        def local_step(state: TrainState, x, y):
            params, mstate, opt_state, step, rng_data = state
            # per-step, per-replica key (dropout/augment must differ by rank
            # — SURVEY.md §7 per-replica RNG)
            key = jax.random.wrap_key_data(rng_data)
            key = jax.random.fold_in(jax.random.fold_in(key, step),
                                     lax.axis_index(axis))

            def loss_local(p):
                if has_state:
                    out, new_ms = module.apply(p, x, state=mstate,
                                               training=True, rng=key)
                else:
                    out = module.apply(p, x, training=True, rng=key)
                    new_ms = mstate
                loss = loss_fn(out, y)
                # global mean; grad w.r.t. replicated p then carries the
                # automatic psum of cotangents = DDP-averaged gradient
                return lax.pmean(loss, axis), (out, new_ms)

            (loss, (out, new_ms)), grads = jax.value_and_grad(
                loss_local, has_aux=True)(params)
            new_params, new_opt = optimizer.update(grads, opt_state, params)
            if has_state:
                # keep replicated-state invariant: average the per-replica
                # running-stat updates (see module docstring)
                new_ms = jax.tree.map(lambda v: lax.pmean(v, axis), new_ms)
            correct = lax.psum((out.argmax(-1) == y).sum(), axis)
            new_state = TrainState(new_params, new_ms, new_opt, step + 1,
                                   rng_data)
            return new_state, {"loss": loss, "correct": correct}

        mesh = self.group.mesh
        state_spec = P()  # fully replicated
        fn = jax.shard_map(local_step, mesh=mesh,
                           in_specs=(state_spec, P(axis), P(axis)),
                           out_specs=(state_spec, state_spec))
        return jax.jit(fn, donate_argnums=(0,) if self.donate else ())

    def _build_eval_step(self):
        module, loss_fn, axis = self.module, self.loss_fn, self.axis
        has_state = module.has_state()

        def local_eval(state: TrainState, x, y):
            out = module.apply(state.params, x,
                               **({"state": state.model_state} if has_state
                                  else {}))
            if has_state:
                out, _ = out
            loss = lax.pmean(loss_fn(out, y), axis)
            correct = lax.psum((out.argmax(-1) == y).sum(), axis)
            return {"loss": loss, "correct": correct}

        fn = jax.shard_map(local_eval, mesh=self.group.mesh,
                           in_specs=(P(), P(axis), P(axis)),
                           out_specs=P())
        return jax.jit(fn)

    # -- public API ------------------------------------------------------------
    def train_step(self, state: TrainState, x, y):
        """One fused fwd+bwd+allreduce+update step; returns
        ``(new_state, {"loss": scalar, "correct": count})``."""
        if self.optimizer is None or self.loss_fn is None:
            raise ValueError("train_step requires optimizer= and loss_fn=")
        if self._train_step is None:
            self._train_step = self._build_train_step()
        return self._train_step(state, x, y)

    def eval_step(self, state: TrainState, x, y):
        if self.loss_fn is None:
            raise ValueError("eval_step requires loss_fn=")
        if self._eval_step is None:
            self._eval_step = self._build_eval_step()
        return self._eval_step(state, x, y)

    def forward(self, state: TrainState, x):
        """Inference forward on a (data-axis-sharded) batch; returns logits
        sharded the same way (torch ``ddp_model(images)`` parity)."""
        if self._forward is None:
            module, has_state = self.module, self.module.has_state()

            def local_fwd(params, mstate, xx):
                out = module.apply(params, xx,
                                   **({"state": mstate} if has_state else {}))
                return out[0] if has_state else out

            fn = jax.shard_map(local_fwd, mesh=self.group.mesh,
                               in_specs=(P(), P(), P(self.axis)),
                               out_specs=P(self.axis))
            self._forward = jax.jit(fn)
        return self._forward(state.params, state.model_state, x)
