"""DistributedDataParallel — the L3 wrapper, compiled instead of hooked.

torch's DDP (`/root/reference/mpspawn_dist.py:68`, `example_mp.py:53`) is a
*runtime object*: it broadcasts parameters from rank 0 at wrap time, then
hooks autograd to fire bucketed NCCL all-reduces overlapped with backward.

On TPU none of that machinery exists at runtime — it is **compiled in**
(SURVEY.md §7 design stance; BASELINE.json north star: "fwd/bwd + gradient
all-reduce in a single XLA graph").  This wrapper builds ONE jitted step:

    forward → loss → pmean(loss) over the data axis → grad → SGD update

under ``shard_map`` over the group's mesh.  Two properties make the gradient
all-reduce both correct and free:

- **JAX 0.9 VMA autodiff**: inside ``shard_map``, parameters enter replicated
  (``P()`` in_spec).  Differentiating w.r.t. a replicated value auto-inserts
  the ``psum`` of per-device cotangents.  Taking the gradient *of the
  pmean-ed loss* therefore yields exactly the DDP-averaged gradient — adding
  an explicit ``pmean`` on grads afterwards would double-count (verified the
  hard way; see .claude/skills/verify/SKILL.md).
- **XLA fusion/scheduling**: the all-reduce is an op in the backward graph,
  so XLA overlaps it with remaining backward compute on ICI — the same
  overlap DDP's Reducer implements by hand with buckets and streams.

BatchNorm semantics (SURVEY.md §2b #16): batch statistics stay **per-replica**
(DDP parity — torch DDP does not sync BN).  Running-stat *updates* are
pmean-ed across replicas to keep the state replicated; this is a documented,
deliberate improvement over torch's keep-rank-0's-stats (identical in
distribution, strictly less variance).  ``sync_batchnorm=True`` converts BN
layers to cross-replica batch stats (torch SyncBatchNorm parity).
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..nn.layers import BatchNorm2d
from ..nn.module import Module

__all__ = ["TrainState", "DistributedDataParallel", "convert_sync_batchnorm"]


class TrainState(NamedTuple):
    """Training state threaded through the jitted step.  Replicated over the
    group — except ``opt_state`` under ``shard_optimizer=True`` (ZeRO-1),
    which is sharded 1/world per device as a flat vector."""
    params: Any
    model_state: Any      # BN running stats etc.; {} for stateless nets
    opt_state: Any
    step: jnp.ndarray     # scalar int32
    rng: jnp.ndarray      # base PRNG key; per-step/per-replica keys derive


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _zero1_spec(leaf, axis: str) -> P:
    """ZeRO-1 opt-state placement rule: rank>=1 leaves shard 1/world over
    the data axis; scalar leaves (schedule/Adam step counters) replicate.
    Single source of truth for state_shardings() and the train-step
    in/out_specs — they must agree or restore-time placement breaks."""
    return P(axis) if getattr(leaf, "ndim", 0) >= 1 else P()


def _flatten_params(tree):
    """Concatenate all leaves, raveled, in tree-flatten order."""
    leaves = jax.tree.leaves(tree)
    return jnp.concatenate([l.ravel() for l in leaves])


def _unflatten_params(flat, template):
    """Inverse of :func:`_flatten_params` (padding tail ignored)."""
    leaves, treedef = jax.tree_util.tree_flatten(template)
    out, off = [], 0
    for l in leaves:
        out.append(flat[off:off + l.size].reshape(l.shape).astype(l.dtype))
        off += l.size
    return jax.tree_util.tree_unflatten(treedef, out)


def convert_sync_batchnorm(module: Module, axis_name: str) -> Module:
    """Set every BatchNorm layer to compute cross-replica batch statistics
    (torch ``SyncBatchNorm.convert_sync_batchnorm`` parity).  Mutates and
    returns the module (topology objects hold no arrays, so this is safe
    before ``init``/``apply``)."""
    for _, m in module.named_modules():
        if isinstance(m, BatchNorm2d):
            m.axis_name = axis_name
    return module


class DistributedDataParallel:
    """Data-parallel training driver over a process group's mesh.

    Usage (the reference loop shape, /root/reference/mpspawn_dist.py:97-118)::

        pg = dist.init_process_group()
        ddp = DistributedDataParallel(model, optimizer=SGD(lr),
                                      loss_fn=nn.CrossEntropyLoss(), group=pg)
        state = ddp.init(seed=0)        # == manual_seed(0) on every rank
        for epoch in range(E):
            loader.set_epoch(epoch)
            for xb, yb in device_loader:
                state, metrics = ddp.train_step(state, xb, yb)

    ``metrics`` holds ``loss`` (global mean) and ``correct`` (global count),
    as on-device scalars — don't block on them every step (SURVEY.md §7:
    ``loss.item()`` per step kills pipelining; log every N).
    """

    def __init__(self, module: Module, optimizer=None, loss_fn=None,
                 group=None, sync_batchnorm: bool = False,
                 donate: bool = True, compute_dtype=None,
                 accum_steps: int = 1, shard_optimizer: bool = False,
                 comm_dtype=None):
        """Options beyond torch-DDP parity (all default off):

        ``compute_dtype``: run forward/backward in this dtype (bf16 for the
        MXU) while parameters, gradients and optimizer state stay float32
        master copies — the mixed-precision recipe of BASELINE.md ladder #4.

        ``accum_steps``: split each incoming batch into k microbatches,
        accumulate gradients locally, and all-reduce ONCE per step — the
        comms pattern of torch DDP's ``no_sync`` accumulation, compiled as a
        ``lax.scan``.

        ``shard_optimizer``: ZeRO-1 / cross-replica weight-update sharding
        (Xu et al., arXiv:2004.13336 — the XLA data-parallel paper): the
        gradient all-reduce splits into reduce-scatter + all-gather around
        an optimizer update that each replica performs on only 1/world of
        the (flattened) parameters, so optimizer state is sharded 1/world
        per device.  Numerics identical to the dense path (tested).

        ``comm_dtype``: compress the gradient all-reduce to this dtype
        (torch DDP *comm hook* parity — ``fp16_compress_hook`` /
        ``bf16_compress_hook``): local grads are divided by world size,
        cast to ``comm_dtype`` for the wire (pre-division keeps the fp16
        sum under 65504 at any world size, as the torch hook does), summed,
        and cast back to the gradient's dtype before the optimizer update.
        Halves ICI/DCN bytes per step with 16-bit dtypes; composes with
        ``accum_steps`` (compression happens once, at sync time, like the
        torch hook) and ZeRO-1 (the reduce-scatter runs compressed).
        """
        if group is None:
            from .. import dist as _dist
            group = _dist.get_default_group()
        if accum_steps < 1:
            raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
        self.module = module
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.group = group
        self.axis = group.axis_name
        self.donate = donate
        self.compute_dtype = compute_dtype
        self.accum_steps = accum_steps
        self.shard_optimizer = shard_optimizer
        self.comm_dtype = comm_dtype
        if sync_batchnorm:
            convert_sync_batchnorm(module, self.axis)
        self._train_step = None
        self._train_chunk = None
        self._train_repeat_cache = {}
        self._eval_step = None
        self._forward = None

    # -- state ----------------------------------------------------------------
    def init(self, seed: int = 0, rng: Optional[jax.Array] = None) -> TrainState:
        """Build replicated TrainState.

        Deterministic given ``seed`` — every process constructs identical
        parameters, the TPU analogue of ``torch.manual_seed(0)`` before DDP
        wrap (/root/reference/mpspawn_dist.py:56).  (DDP's alternative —
        rank-0 broadcast at wrap time, /root/reference/example_mp.py:53 —
        is unnecessary when init is deterministic, but available as
        ``collectives.broadcast_host`` for externally-loaded params.)
        """
        key = rng if rng is not None else jax.random.key(seed)
        params = self.module.init(key)
        model_state = self.module.init_state()
        if self.optimizer is None:
            opt_state = {}
        elif self.shard_optimizer:
            # ZeRO-1: optimizer state lives on the flattened-and-padded
            # parameter vector, sharded 1/world per device
            n = self.group.size()
            flat = _flatten_params(params)
            padded = _ceil_to(flat.size, n)
            opt_state = self.optimizer.init({"flat": jnp.zeros(padded)})
        else:
            opt_state = self.optimizer.init(params)
        state = TrainState(params, model_state, opt_state,
                           jnp.zeros((), jnp.int32),
                           jax.random.key_data(jax.random.fold_in(key, 0x5eed)))
        # commit onto the mesh so donation reuses buffers; the layout policy
        # (replicated everywhere, ZeRO-1-sharded opt_state) lives in
        # state_shardings so checkpoints restore to exactly this placement
        return jax.tree.map(jax.device_put, state, self.state_shardings(state))

    def state_shardings(self, state: TrainState) -> TrainState:
        """Pytree of :class:`NamedSharding` mirroring ``state``'s layout:
        everything replicated except ZeRO-1-sharded ``opt_state``
        (``P(axis)``).  Feed to ``tpu_dist.checkpoint.restore(sharding=...)``
        so a restored TrainState lands with its original placement."""
        repl = NamedSharding(self.group.mesh, P())
        shardings = jax.tree.map(lambda _: repl, state)
        if self.shard_optimizer and self.optimizer is not None:
            shardings = shardings._replace(
                opt_state=jax.tree.map(
                    lambda l: NamedSharding(self.group.mesh,
                                            _zero1_spec(l, self.axis)),
                    state.opt_state))
        return shardings

    # -- compiled steps --------------------------------------------------------
    def _state_pspec(self, template: TrainState) -> TrainState:
        """PartitionSpec pytree for TrainState: replicated, except ZeRO-1
        opt_state sharded over the data axis (must agree with
        :meth:`state_shardings`)."""
        if self.shard_optimizer:
            opt_spec = jax.tree.map(lambda l: _zero1_spec(l, self.axis),
                                    template.opt_state)
        else:
            opt_spec = P()
        return TrainState(params=P(), model_state=P(), opt_state=opt_spec,
                          step=P(), rng=P())

    def _make_local_step(self, template: TrainState):
        module, loss_fn, optimizer, axis = (self.module, self.loss_fn,
                                            self.optimizer, self.axis)
        has_state = module.has_state()
        accum = self.accum_steps
        cdtype = self.compute_dtype
        comm_dtype = self.comm_dtype
        zero1 = self.shard_optimizer
        n = self.group.size()

        def local_step(state: TrainState, x, y):
            params, mstate, opt_state, step, rng_data = state
            base_key = jax.random.wrap_key_data(rng_data)

            # Microbatch gradient: params are made device-varying (pvary) so
            # jax.grad yields LOCAL gradients with no implicit collective —
            # the all-reduce happens exactly once, after accumulation
            # (torch DDP `no_sync` accumulation semantics).
            p_var = jax.tree.map(lambda v: lax.pcast(v, axis, to="varying"), params)

            def micro(carry, xy):
                g_acc, loss_acc, correct_acc, ms, i = carry
                xb, yb = xy
                # per-step, per-microbatch, per-replica key (SURVEY.md §7:
                # dropout must differ across ranks)
                key = jax.random.fold_in(
                    jax.random.fold_in(base_key, step * accum + i),
                    lax.axis_index(axis))

                def loss_local(p):
                    if cdtype is not None:
                        p = jax.tree.map(
                            lambda v: v.astype(cdtype)
                            if jnp.issubdtype(v.dtype, jnp.floating) else v,
                            p)
                    xc = (xb.astype(cdtype)
                          if cdtype is not None and
                          jnp.issubdtype(xb.dtype, jnp.floating) else xb)
                    if has_state:
                        out, new_ms = module.apply(p, xc, state=ms,
                                                   training=True, rng=key)
                        # keep the f32 state master under bf16 compute:
                        # purely activation-derived leaves (MoE aux_loss)
                        # come back in compute_dtype, which would flip the
                        # scan carry's dtype (BatchNorm stats hide this —
                        # blending with the f32 running value re-promotes)
                        if cdtype is not None:
                            new_ms = jax.tree.map(
                                lambda n, o: n.astype(o.dtype), new_ms, ms)
                    else:
                        out = module.apply(p, xc, training=True, rng=key)
                        new_ms = ms
                    return loss_fn(out, yb), (out, new_ms)

                (loss, (out, new_ms)), g = jax.value_and_grad(
                    loss_local, has_aux=True)(p_var)
                g_acc = jax.tree.map(jnp.add, g_acc, g)
                correct = (out.argmax(-1) == yb).sum()
                return (g_acc, loss_acc + loss, correct_acc + correct,
                        new_ms, i + 1), None

            if accum > 1:
                xm = x.reshape((accum, x.shape[0] // accum) + x.shape[1:])
                ym = y.reshape((accum, y.shape[0] // accum) + y.shape[1:])
                g0 = jax.tree.map(
                    lambda v: lax.pcast(jnp.zeros(v.shape, jnp.float32),
                                        axis, to="varying"), params)
                init = (g0,
                        lax.pcast(jnp.zeros((), jnp.float32), axis, to="varying"),
                        lax.pcast(jnp.zeros((), jnp.int32), axis, to="varying"),
                        mstate, 0)
                (g_sum, loss_sum, correct_sum, new_ms, _), _ = lax.scan(
                    micro, init, (xm, ym))
                local_grads = jax.tree.map(lambda g: g / accum, g_sum)
                loss = lax.pmean(loss_sum / accum, axis)
                correct = lax.psum(correct_sum, axis)
            else:
                # fast path: no accumulation scaffolding in the graph
                zero = jax.tree.map(jnp.zeros_like, p_var)
                (g_sum, loss_sum, correct_sum, new_ms, _), _ = micro(
                    (zero, 0.0, 0, mstate, 0), (x, y))
                local_grads = g_sum
                loss = lax.pmean(loss_sum, axis)
                correct = lax.psum(correct_sum, axis)

            # comm-hook compression (torch DDP fp16/bf16_compress_hook
            # semantics): divide by world size BEFORE the cast so the
            # compressed-dtype sum cannot overflow (fp16 max 65504), move
            # comm_dtype bytes on the wire, and decompress to the original
            # grad dtype after the reduce — accumulation and the optimizer
            # update stay in the uncompressed dtype
            if zero1:
                # reduce-scatter averaged grads; update 1/n of the flat
                # parameter vector per device; all-gather updated params
                flat_g = _flatten_params(local_grads)
                padded = _ceil_to(flat_g.size, n)
                flat_g = jnp.pad(flat_g, (0, padded - flat_g.size))
                if comm_dtype is None:
                    g_shard = lax.psum_scatter(
                        flat_g, axis, scatter_dimension=0, tiled=True) / n
                else:
                    g_shard = lax.psum_scatter(
                        (flat_g / n).astype(comm_dtype), axis,
                        scatter_dimension=0, tiled=True).astype(flat_g.dtype)
                flat_p = _flatten_params(params)
                flat_p = jnp.pad(flat_p, (0, padded - flat_p.size))
                chunk = padded // n
                me = lax.axis_index(axis)
                p_shard = lax.dynamic_slice_in_dim(flat_p, me * chunk, chunk)
                new_shard, new_opt = optimizer.update(
                    {"flat": g_shard}, opt_state, {"flat": p_shard})
                # all-gather the updated shards as a psum of offset-placed
                # contributions: psum of varying inputs yields a VMA-invariant
                # (replicated) output, which the P() params out_spec needs —
                # lax.all_gather would leave the value marked varying
                contrib = jnp.zeros((padded,), new_shard["flat"].dtype)
                contrib = lax.dynamic_update_slice_in_dim(
                    contrib, new_shard["flat"], me * chunk, 0)
                flat_new = lax.psum(contrib, axis)
                new_params = _unflatten_params(flat_new, params)
            else:
                if comm_dtype is None:
                    grads = jax.tree.map(lambda g: lax.pmean(g, axis),
                                         local_grads)
                else:
                    grads = jax.tree.map(
                        lambda g: lax.psum((g / n).astype(comm_dtype),
                                           axis).astype(g.dtype)
                        if jnp.issubdtype(g.dtype, jnp.floating) else
                        lax.pmean(g, axis),
                        local_grads)
                new_params, new_opt = optimizer.update(grads, opt_state,
                                                       params)

            if has_state:
                # keep replicated-state invariant: average the per-replica
                # running-stat updates (see module docstring)
                new_ms = jax.tree.map(lambda v: lax.pmean(v, axis), new_ms)
            new_state = TrainState(new_params, new_ms, new_opt, step + 1,
                                   rng_data)
            return new_state, {"loss": loss, "correct": correct}

        return local_step

    def _build_train_step(self, template: TrainState):
        state_spec = self._state_pspec(template)
        fn = jax.shard_map(self._make_local_step(template),
                           mesh=self.group.mesh,
                           in_specs=(state_spec, P(self.axis), P(self.axis)),
                           out_specs=(state_spec, P()))
        return jax.jit(fn, donate_argnums=(0,) if self.donate else ())

    def _build_train_chunk(self, template: TrainState):
        local_step = self._make_local_step(template)

        def local_chunk(state, xs, ys):
            def body(st, xy):
                return local_step(st, xy[0], xy[1])
            return lax.scan(body, state, (xs, ys))

        state_spec = self._state_pspec(template)
        fn = jax.shard_map(local_chunk, mesh=self.group.mesh,
                           in_specs=(state_spec, P(None, self.axis),
                                     P(None, self.axis)),
                           out_specs=(state_spec, P()))
        return jax.jit(fn, donate_argnums=(0,) if self.donate else ())

    def _build_train_repeat(self, template: TrainState, num_steps: int):
        local_step = self._make_local_step(template)

        def local_repeat(state, x, y):
            def body(st, _):
                return local_step(st, x, y)
            return lax.scan(body, state, None, length=num_steps)

        state_spec = self._state_pspec(template)
        fn = jax.shard_map(local_repeat, mesh=self.group.mesh,
                           in_specs=(state_spec, P(self.axis), P(self.axis)),
                           out_specs=(state_spec, P()))
        return jax.jit(fn, donate_argnums=(0,) if self.donate else ())

    def _build_eval_step(self):
        module, loss_fn, axis = self.module, self.loss_fn, self.axis
        has_state = module.has_state()
        ignore = getattr(loss_fn, "ignore_index", None)

        # takes only (params, model_state): feeding the whole TrainState
        # would re-lay-out ZeRO-1-sharded opt_state to replicated (an
        # all-gather of optimizer moments) on every eval batch
        def local_eval(params, mstate, x, y, n_valid):
            out = module.apply(params, x,
                               **({"state": mstate} if has_state else {}))
            if has_state:
                out, _ = out
            # rows at global index >= n_valid are evaluate()'s batch
            # padding; under P(axis) sharding device d holds the
            # contiguous slice starting at d * rows_per_device
            rows = y.shape[0]
            gidx = lax.axis_index(axis) * rows + jnp.arange(rows)
            row_keep = (gidx < n_valid).reshape(
                (rows,) + (1,) * (y.ndim - 1))
            hit = out.argmax(-1) == y
            if ignore is not None:
                # scored = labels the loss actually counts (ignore_index
                # excluded) — exact even when padding lands unevenly
                # across devices: loss_sum = sum over scored labels, not
                # a mean of per-device means.  Padding rows carry
                # ignore_index labels, so row_keep only re-excludes them;
                # it additionally guards a pathological loss_fn whose
                # ignore_index the padding labels can't use.  (For
                # weight= losses the mean's denominator is the weight
                # sum, so loss_sum is approximate there.)
                local_mean = loss_fn(out, y)
                keep = (y != ignore) & row_keep
                kept = keep.sum()
                # mask the numerator too: if ignore_index is a valid class
                # id (torch permits >= 0), argmax CAN equal it at ignored
                # positions — unmasked, accuracy would exceed 1.0
                hit = hit & keep
                loss_sum = local_mean * kept
            else:
                # loss_fn has no ignore_index: it would score padding
                # rows.  Recover exact per-row losses by running the
                # black-box loss on batch-1 slices (a vmapped mean over
                # one row IS that row's loss) and sum only valid rows,
                # each weighted by its element count.
                per_row = jax.vmap(
                    lambda o, t: loss_fn(o[None], t[None]))(out, y)
                elems = y[0].size if y.ndim > 1 else 1
                keep_rows = row_keep.reshape(rows)
                loss_sum = (per_row * keep_rows).sum() * elems
                kept = keep_rows.sum() * elems
                hit = hit & jnp.broadcast_to(row_keep, hit.shape)
            loss_sum = lax.psum(loss_sum, axis)
            correct = lax.psum(hit.sum(), axis)
            scored = lax.psum(kept, axis)
            return {"loss": loss_sum / jnp.maximum(scored, 1),
                    "loss_sum": loss_sum, "correct": correct,
                    "scored": scored}

        fn = jax.shard_map(local_eval, mesh=self.group.mesh,
                           in_specs=(P(), P(), P(axis), P(axis), P()),
                           out_specs=P())
        return jax.jit(fn)

    # -- public API ------------------------------------------------------------
    def train_step(self, state: TrainState, x, y):
        """One fused fwd+bwd+allreduce+update step; returns
        ``(new_state, {"loss": scalar, "correct": count})``."""
        if self.optimizer is None or self.loss_fn is None:
            raise ValueError("train_step requires optimizer= and loss_fn=")
        if self._train_step is None:
            self._train_step = self._build_train_step(state)
        return self._train_step(state, x, y)

    def train_chunk(self, state: TrainState, xs, ys):
        """Run ``xs.shape[0]`` fused train steps in ONE dispatch.

        ``xs``/``ys`` carry a leading steps axis: ``xs[i]`` is step *i*'s
        global batch (sharded over the data axis like ``train_step``'s).
        The steps execute as a ``lax.scan`` on device — semantically
        identical to ``k`` sequential :meth:`train_step` calls (tested),
        but with a single host dispatch and readback.  This is the
        TPU-idiomatic inner loop: host→device latency (or a slow tunnel)
        stops mattering when k steps ride one XLA program.

        Returns ``(new_state, metrics)`` where each metrics leaf is stacked
        per-step, shape ``(k,)`` — log ``metrics["loss"][-1]`` or the mean.
        """
        if self.optimizer is None or self.loss_fn is None:
            raise ValueError("train_chunk requires optimizer= and loss_fn=")
        if self._train_chunk is None:
            self._train_chunk = self._build_train_chunk(state)
        return self._train_chunk(state, xs, ys)

    def train_repeat(self, state: TrainState, x, y, num_steps: int):
        """``num_steps`` fused steps on the SAME batch in one dispatch.

        Like :meth:`train_chunk` but the batch is scan-invariant, so no
        ``(k, batch, ...)`` input is materialized — the per-step rng still
        advances (the step counter seeds dropout keys).  Uses: throughput
        measurement (benchmarks/timing.py) and overfit-one-batch debugging.
        Returns ``(new_state, metrics)`` with per-step ``(k,)`` leaves.
        """
        if self.optimizer is None or self.loss_fn is None:
            raise ValueError("train_repeat requires optimizer= and loss_fn=")
        fn = self._train_repeat_cache.get(num_steps)
        if fn is None:
            fn = self._build_train_repeat(state, num_steps)
            self._train_repeat_cache[num_steps] = fn
        return fn(state, x, y)

    def eval_step(self, state: TrainState, x, y, n_valid=None):
        """``n_valid`` = number of real (non-padding) leading rows in the
        global batch; defaults to all rows."""
        if self.loss_fn is None:
            raise ValueError("eval_step requires loss_fn=")
        if self._eval_step is None:
            self._eval_step = self._build_eval_step()
        if n_valid is None:
            n_valid = int(x.shape[0])
        return self._eval_step(state.params, state.model_state, x, y,
                               jnp.asarray(n_valid, jnp.int32))

    def evaluate(self, state: TrainState, loader) -> dict:
        """Drive :meth:`eval_step` over a loader of ``(x, y)`` batches;
        returns global ``{"loss", "accuracy", "count"}`` (the torch
        eval-loop idiom; metrics are identical on every process since
        ``eval_step`` reduces over the whole mesh).

        Partial batches are padded with ``ignore_index`` labels up to the
        first batch's size rounded to a multiple of the mesh's device count
        (one compiled shape, always divisible over the data axis).
        ``count`` is the number of labels the loss actually *scored*:
        samples for classification, non-``ignore_index`` tokens for
        sequence models — batch-padding rows and data-inherent padding
        tokens are both excluded, from the loss, the accuracy denominator,
        and the count (a padded label can never count as correct: argmax is
        in [0, C)).  Works for any loss_fn: with an ``ignore_index``
        attribute padding rows carry that label and the loss skips them;
        without one, padding rows carry label 0 and ``eval_step`` masks
        them positionally via the true row count (exact per-row losses via
        a vmapped batch-1 loss call).  Loss aggregates as
        sum-over-scored-labels / total-scored — exact under any padding
        distribution.  Metrics accumulate on device; the single host
        readback happens at the end (per-step ``float()`` would serialize
        eval over the dispatch latency).
        """
        ignore = getattr(self.loss_fn, "ignore_index", None)
        # without ignore_index semantics, pad with a valid label (0): the
        # padded rows are masked out positionally, and an arbitrary custom
        # loss may index with the label (-100 would be out of range)
        pad_label = 0 if ignore is None else ignore
        n_dev = self.group.size()
        pad_to = None
        total_loss = total_correct = total_scored = None
        for x, y in loader:
            b = int(x.shape[0])
            target = _ceil_to(b, n_dev)
            pad_to = target if pad_to is None else max(pad_to, target)
            if b < pad_to:
                x = jnp.concatenate(
                    [x, jnp.zeros((pad_to - b,) + x.shape[1:], x.dtype)])
                y = jnp.concatenate(
                    [y, jnp.full((pad_to - b,) + y.shape[1:], pad_label,
                                 y.dtype)])
            m = self.eval_step(state, x, y, n_valid=b)
            if total_loss is None:
                total_loss = m["loss_sum"]
                total_correct = m["correct"]
                total_scored = m["scored"]
            else:
                total_loss = total_loss + m["loss_sum"]
                total_correct = total_correct + m["correct"]
                total_scored = total_scored + m["scored"]
        if total_loss is None:
            return {"loss": 0.0, "accuracy": 0.0, "count": 0}
        n = int(total_scored)
        if n == 0:
            return {"loss": 0.0, "accuracy": 0.0, "count": 0}
        return {"loss": float(total_loss) / n,
                "accuracy": int(total_correct) / n, "count": n}

    def forward(self, state: TrainState, x):
        """Inference forward on a (data-axis-sharded) batch; returns logits
        sharded the same way (torch ``ddp_model(images)`` parity)."""
        if self._forward is None:
            module, has_state = self.module, self.module.has_state()

            def local_fwd(params, mstate, xx):
                out = module.apply(params, xx,
                                   **({"state": mstate} if has_state else {}))
                return out[0] if has_state else out

            fn = jax.shard_map(local_fwd, mesh=self.group.mesh,
                               in_specs=(P(), P(), P(self.axis)),
                               out_specs=P(self.axis))
            self._forward = jax.jit(fn)
        return self._forward(state.params, state.model_state, x)
