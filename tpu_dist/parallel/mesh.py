"""Thin mesh builder for the ("data", "model") rule plane.

The rule tables in :mod:`tpu_dist.parallel.rules` name mesh dims; this is
the one place those names become a ``jax.sharding.Mesh``.  Kept separate
from rules.py so the layout arithmetic stays importable without jax.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

__all__ = ["get_mesh", "mesh_shape_for"]


def mesh_shape_for(rules: Dict[str, Optional[str]], world: int,
                   model_parallel: int = 1,
                   axis_names: Tuple[str, str] = ("data", "model")
                   ) -> Dict[str, int]:
    """dp×mp factorization of ``world`` for a rule binding: the model dim
    gets ``model_parallel`` only when some logical axis actually rides it
    (an all-``None`` table collapses to pure dp — editing only the rule
    table re-partitions the run)."""
    data_name, model_name = axis_names
    mp = model_parallel if any(m == model_name for m in rules.values()) \
        else 1
    if world % mp:
        raise ValueError(f"world {world} not divisible by model_parallel "
                         f"{mp}")
    return {data_name: world // mp, model_name: mp}


def get_mesh(dp: Optional[int] = None, mp: int = 1,
             axis_names: Sequence[str] = ("data", "model"),
             devices=None):
    """``Mesh`` of shape (dp, mp) over ``axis_names``.  ``dp=None`` takes
    every available device: ``get_mesh(mp=2)`` on 8 devices is a 4×2
    dp×tp mesh."""
    import jax
    from jax.sharding import Mesh
    if devices is None:
        devices = jax.devices()
    if dp is None:
        if len(devices) % mp:
            raise ValueError(f"{len(devices)} devices not divisible by "
                             f"mp={mp}")
        dp = len(devices) // mp
    need = dp * mp
    if len(devices) < need:
        raise ValueError(f"need {need} devices for a {dp}x{mp} mesh, "
                         f"have {len(devices)}")
    arr = np.asarray(devices[:need], dtype=object).reshape(dp, mp)
    return Mesh(arr, tuple(axis_names))
