"""Fully-sharded data parallelism (torch FSDP / ZeRO-3) — as a placement.

torch's FSDP is a wrapper that hooks module forward/backward to all-gather
flattened parameter shards and reduce-scatter gradients.  On TPU the same
execution plan is a *sharding decision*, not code: store every parameter
(and its optimizer state) sharded over the 'data' axis and run the
ordinary global train step — XLA's SPMD partitioner inserts the parameter
all-gather right before each use, frees the gathered copy after, and turns
the gradient all-reduce into reduce-scatter + sharded update.  That is
bitwise the ZeRO-3 schedule, derived from placements alone (the
scaling-book recipe; contrast with ddp.py's ZeRO-1, which shards only
optimizer state inside an explicit shard_map).

``fsdp_specs`` picks, per leaf, the largest dimension divisible by the
axis size (ties → first); small/indivisible leaves (biases, LayerNorm
scales) stay replicated — their memory is negligible and gathering them
would cost latency, the same heuristic torch FSDP applies via its
min-param-size wrapping policy.

Usage::

    pg = dist.init_process_group()        # 1-D 'data' mesh
    params = fsdp_shard(model.init(key), pg.mesh)
    opt_state = fsdp_shard(opt.init(params), pg.mesh)   # sharded with them
    step = make_gspmd_train_step(model, loss_fn, opt)   # ordinary step
    ...batch placed P('data'), exactly like the gspmd tp recipe...

Composable with tensor parallelism: on a ('data', 'model') (or 3-D
('data', 'fsdp', 'model')) mesh apply TRANSFORMER_TP_RULES first, then
``fsdp_shard`` — existing placements keep their axes and gain the fsdp
axis on their largest still-replicated divisible dim (2-D weight
sharding, the Megatron+ZeRO-3 hybrid); leaves already carrying the fsdp
axis are left alone.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["fsdp_specs", "fsdp_shard"]


def _existing_spec(leaf) -> Optional[P]:
    """The leaf's current non-trivial PartitionSpec, if it has one."""
    sh = getattr(leaf, "sharding", None)
    spec = getattr(sh, "spec", None)
    if spec is not None and any(a is not None for a in spec):
        return spec
    return None


def _rule_spec_fn(rules):
    """keystr -> base PartitionSpec (or None) from a rule source: a
    logical-axis rule table dict (the unified plane, parallel/rules.py),
    a gspmd.PartitionRules, or None."""
    if rules is None:
        return lambda key: None
    if isinstance(rules, dict):
        from .rules import spec_for_key

        def from_table(key):
            spec = spec_for_key(key, rules)
            return spec if any(a is not None for a in spec) else None
        return from_table
    # PartitionRules-shaped: anything answering spec_for(path)
    def from_rules(key):
        spec = rules.spec_for(key)
        return spec if any(a is not None for a in spec) else None
    return from_rules


def _leaf_spec(leaf, axis: str, axis_size: int, min_size: int,
               base: Optional[P] = None) -> P:
    if leaf is None:
        return P()
    shape = getattr(leaf, "shape", ())
    existing = base if base is not None else _existing_spec(leaf)
    if existing is not None:
        # already placed by another strategy (e.g. TP rules on a
        # ('data','fsdp','model') mesh): keep those axes and ADD the fsdp
        # axis on the largest still-replicated divisible dim — 2-D weight
        # sharding (ZeRO-3 x TP), the Megatron+FSDP hybrid.  No free dim,
        # or this axis already placed (re-sharding an already-FSDP leaf,
        # e.g. opt states inheriting param shardings) → leave it alone.
        already = any(axis == a or (isinstance(a, tuple) and axis in a)
                      for a in existing)
        if not already and int(np.prod(shape)) >= min_size:
            free = [d for d in range(len(shape))
                    if d >= len(existing) or existing[d] is None]
            for d in sorted(free, key=lambda d: shape[d], reverse=True):
                if shape[d] % axis_size == 0:
                    spec = list(existing) + [None] * (len(shape)
                                                      - len(existing))
                    spec[d] = axis
                    return P(*spec)
        return existing
    if not shape or int(np.prod(shape)) < min_size:
        return P()
    order = sorted(range(len(shape)), key=lambda d: shape[d], reverse=True)
    for d in order:
        if shape[d] % axis_size == 0:
            spec = [None] * len(shape)
            spec[d] = axis
            return P(*spec)
    return P()


def fsdp_specs(tree, mesh, axis: str = "data", min_size: int = 2 ** 12,
               rules=None):
    """PartitionSpec pytree: each leaf's largest ``axis_size``-divisible
    dim sharded over ``axis``; leaves smaller than ``min_size`` elements
    (or with no divisible dim) replicate.  Leaves already carrying a
    non-trivial sharding (TP/EP placements) keep those axes and gain
    ``axis`` on their largest free divisible dim (2-D weight sharding);
    if ``axis`` is already placed on the leaf, it is left unchanged.

    ``rules``: base placement source applied BEFORE the fsdp axis — a
    logical-axis rule table dict (parallel/rules.py, the unified plane)
    or a ``PartitionRules`` — so tp×fsdp hybrids compose from specs
    alone, without a device_put round-trip to stamp the tp axes."""
    size = mesh.shape[axis]
    base_of = _rule_spec_fn(rules)
    is_leaf = lambda x: x is None  # noqa: E731
    flat = jax.tree_util.tree_leaves_with_path(tree, is_leaf=is_leaf)
    specs = [_leaf_spec(l, axis, size, min_size,
                        base=base_of(jax.tree_util.keystr(p)))
             for p, l in flat]
    treedef = jax.tree_util.tree_structure(tree, is_leaf=is_leaf)
    return jax.tree_util.tree_unflatten(treedef, specs)


def fsdp_shard(tree, mesh, axis: str = "data",
               min_size: int = 2 ** 12,
               specs: Optional[object] = None,
               rules=None):
    """``device_put`` every leaf per :func:`fsdp_specs` (or explicit
    ``specs``).  Apply to params AND optimizer state — the committed
    shardings then steer the jitted step into the ZeRO-3 schedule."""
    if specs is None:
        specs = fsdp_specs(tree, mesh, axis, min_size, rules=rules)
    return jax.tree.map(
        lambda l, s: (None if l is None
                      else jax.device_put(l, NamedSharding(mesh, s))),
        tree, specs,
        is_leaf=lambda x: x is None)
