"""ZeRO-1/2 on the host collective path: reduce-scatter gradients, shard
the optimizer update, overlap the parameter all-gather.

The gradient bucketer (tpu_dist/collectives/bucketer.py) lays every bucket
out **chunk-major**: mid-all-reduce, each rank already materializes exactly
its ring chunk of every reduced bucket — its ZeRO shard — and then the
all-gather phase throws that sharding away so every rank can run a fully
replicated optimizer update over fully replicated optimizer state.
:class:`ZeroOptimizer` stops at the reduce-scatter phase instead
(:meth:`Bucketer.reduce_scatter`), keeps optimizer state (Adam m/v, SGD
momentum, ...) only for the owned chunks — **optimizer-state memory ÷
world_size** — runs the wrapped update on the flat owned shard (a handful
of fused elementwise ops instead of per-leaf dispatch over the whole
tree), and redistributes the updated parameters with an **async** chunk
all-gather (:func:`~tpu_dist.collectives.ring.ring_chunk_all_gather`) on
the ordered engine, so the next step's input staging (DeviceLoader
prefetch) and host work overlap the gather.  This is the classic
cross-replica weight-update sharding of Xu et al. ("Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training"),
mirrored from the mesh path's placement-derived ZeRO-3
(tpu_dist/parallel/fsdp.py) onto the host data plane every CPU-backend,
chaos/elastic, and store-transport job takes.

**Bitwise story.**  The reduce-scattered shard is bit-identical to the
span a full all-reduce would have folded there (chunk-major layout: same
chunk owner ⇒ same accumulation order, same owner-side avg division and
``comm_dtype`` re-quantization).  Every tpu_dist optimizer update is
elementwise, so updating the flat shard produces bit-identical parameters
to the replicated update — at world 1 *and* across worlds (tested); only
``max_grad_norm`` clipping couples elements, and its sharded form
(:func:`tpu_dist.optim.sharded_clip_grad_norm`) is bitwise at world 1 and
numerically equal across worlds.

Usage (the elastic-training loop shape)::

    zopt   = parallel.ZeroOptimizer(optim.Adam(1e-3), group=pg)
    zstate = zopt.init(params)                    # shards live here
    handle = None
    for step in range(start, num_steps):
        x, y = batch(step)                        # overlaps the gather …
        if handle is not None:
            params = handle.wait(timeout=300)     # … waited lazily
        loss, grads = fwd_bwd(params, x, y)
        rs = zopt.reduce_scatter(jax.tree.map(np.asarray, grads))
        loss_now = float(loss)                    # overlaps reduce-scatter
        handle, zstate = zopt.update(rs, zstate)  # shard update + async AG

``zstate`` is a plain pytree (flat parameter shards + wrapped optimizer
state + chunk-bounds metadata), checkpointable per rank via
``resilience.TrainState(..., shard=(rank, world), sharded_keys=("zero",))``.
The metadata records the full partition inputs (per-leaf sizes *and*
dtypes), so shard checkpoints are **world-size-portable**: a run
checkpointed at world N resumes at world M through elastic resharding
(tpu_dist/resilience/reshard.py) — each new rank fetches only the byte
spans it will own from the old shards (disk when visible, peers over the
p2p data plane otherwise) into a fresh ``init(params)`` at the new world.
Direct ``checkpoint.restore(shard=...)`` stays exact-match; elastic
restores go through ``resilience.TrainState.resume``.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["ZeroOptimizer", "ZeroParams", "ZeroStateError"]


class ZeroStateError(RuntimeError):
    """A ZeRO optimizer state does not match this run's shard layout
    (different world size / rank / parameter structure).  A state built
    at another world size is carried over by elastic resharding
    (resilience.TrainState.resume / resilience.reshard), never loaded
    directly."""


class _LeafInfo:
    __slots__ = ("shape", "dtype", "size", "span")

    def __init__(self, shape, dtype, size, span):
        self.shape, self.dtype, self.size, self.span = (
            shape, dtype, size, span)


class _Plan:
    """The static shard layout for one parameter structure at one (rank,
    world): per-leaf owned spans plus dtype groups — each group is ONE flat
    shard (the concat of its member leaves' owned chunks, in leaf order),
    which is also exactly bucket chunk ``rank`` of a chunk-major bucket
    holding those leaves, so the updated shard drops straight into the
    ring all-gather buffer."""

    def __init__(self, treedef, leaves: List[_LeafInfo], rank: int,
                 world: int, groups: List[Tuple[str, List[int]]]):
        self.treedef = treedef
        self.leaves = leaves
        self.rank = rank
        self.world = world
        self.groups = groups          # [(group_key, [leaf indices])]


class ZeroParams:
    """Handle for the in-flight parameter all-gather: ``wait(timeout)``
    returns the full (replicated) parameter tree, re-raising any error the
    gather hit on the engine (``PeerGoneError``, ...).  Hold it across the
    next step's input staging so the gather rides under it — that overlap
    is the ZeRO-2 half of the win."""

    def __init__(self, works: List, assemble, label: str):
        self.works = list(works)
        self._assemble = assemble
        self._label = label
        self._result = None
        self._done = False

    def wait(self, timeout: Optional[float] = None):
        if self._done:
            return self._result
        from ..collectives.work import wait_all as _wait_all
        results = _wait_all(self.works, timeout)
        self._result = self._assemble(results)
        self._done = True
        return self._result

    # BucketWork-flavored aliases so generic handle code is polymorphic
    wait_all = wait

    def is_completed(self) -> bool:
        return self._done or all(w.is_completed() for w in self.works)

    def exception(self) -> Optional[BaseException]:
        for w in self.works:
            exc = w.exception()
            if exc is not None:
                return exc
        return None

    def __repr__(self):
        state = "done" if self._done else f"{len(self.works)} gathers"
        return f"ZeroParams({self._label!r}, {state})"


class ZeroOptimizer:
    """Wrap any :mod:`tpu_dist.optim` optimizer with ZeRO-1/2 sharding
    over the host collective path.

    Args:
        opt: the wrapped optimizer (``SGD``/``Adam``/``AdamW``/... — any
            object with the pure ``init(params)`` / ``update(grads, state,
            params)`` contract; updates must be elementwise, which every
            tpu_dist optimizer is).
        group: process group (default: the default group, resolved per
            call like the eager collectives).
        bucket_bytes: wire bucket size for the gradient reduce-scatter
            (``TPU_DIST_BUCKET_BYTES`` default, as the Bucketer).
        max_grad_norm: optional global-norm clip applied to the *sharded*
            gradients (one scalar all-reduce,
            :func:`tpu_dist.optim.sharded_clip_grad_norm`).
        reduce_op: "avg" (DDP convention, default) or "sum".
        dp: pin a specific DataPlane — in-process multi-rank test rigs
            only, like ``Bucketer(dp=...)`` (ring-only).
        comm_dtype: wire compression for the gradient reduce-scatter — a
            dtype name (cast) or an int8 block-quant scheme
            (``"int8_block256"``, tpu_dist/collectives/quant.py); pinned
            mode only, production reads ``TPU_DIST_COMM_DTYPE``.
        error_feedback: keep a per-group **error-feedback residual** (the
            owner's compression loss, shard-shaped) in the ZeRO state and
            fold it back before compression each step — opt into this
            whenever a lossy ``comm_dtype`` is configured, it is what
            keeps training accuracy inside noise under aggressive wire
            compression.  The residual lives in ``zstate["ef"]`` with the
            exact flat per-dtype-group shard layout, so it rides sharded
            checkpoints and the elastic reshard manifest like any other
            shard-resident state.
        gather_comm_dtype: optional wire compression for the parameter
            all-gather (``ring_chunk_all_gather``) — **lossy on the
            replicated parameters** (the master shards stay exact, like
            a low-precision parameter broadcast in mixed-precision
            training).  Default None: parameters move exact.
    """

    def __init__(self, opt, group=None, bucket_bytes: Optional[int] = None,
                 max_grad_norm: Optional[float] = None,
                 reduce_op: str = "avg", dp=None, comm_dtype=None,
                 error_feedback: bool = False, gather_comm_dtype=None):
        from ..collectives.bucketer import Bucketer
        self.opt = opt
        self.max_grad_norm = max_grad_norm
        self.reduce_op = str(reduce_op).lower()
        self._dp = dp
        self._bucketer = Bucketer(bucket_bytes=bucket_bytes, dp=dp,
                                  comm_dtype=comm_dtype)
        self._group = group
        self.error_feedback = bool(error_feedback)
        self.gather_comm_dtype = gather_comm_dtype
        self._plan: Optional[_Plan] = None
        # pinned-mode gather tag counter (same rationale as the Bucketer's)
        self._seq = 0
        self._seq_mu = threading.Lock()

    # -- plan ----------------------------------------------------------------

    def _resolve(self, group):
        from ..collectives import eager as _eager
        if self._dp is not None:
            return None, self._dp.num_processes, self._dp.rank
        group = _eager._default_group(group if group is not None
                                      else self._group)
        return group, group.num_processes, group.rank

    def _build_plan(self, params, group) -> _Plan:
        import jax
        # per-leaf shard spans come from the unified rule plane's flat
        # chunk contract (parallel/rules.py -> ring._bounds), shared with
        # the ring reduce-scatter and reshard manifests — existing
        # sharded checkpoints stay bitwise-compatible by construction
        from .rules import chunk_bounds as _bounds
        group, n, r = self._resolve(group)
        leaves, treedef = jax.tree.flatten(params)
        infos = []
        for l in leaves:
            a = np.asarray(l)
            infos.append(_LeafInfo(a.shape, a.dtype, a.size,
                                   _bounds(a.size, n)[r] if a.size
                                   else (0, 0)))
        # dtype groups in leaf order: one flat shard (and one gather
        # collective) per dtype keeps ranks' collective sequences identical
        groups: List[Tuple[str, List[int]]] = []
        by_key: Dict[str, List[int]] = {}
        for i, info in enumerate(infos):
            key = np.dtype(info.dtype).str
            if key not in by_key:
                by_key[key] = []
                groups.append((key, by_key[key]))
            by_key[key].append(i)
        return _Plan(treedef, infos, r, n, groups)

    def init(self, params) -> Dict[str, Any]:
        """Build the ZeRO state for ``params``: this rank's flat parameter
        shards, wrapped-optimizer state over those shards only, and the
        chunk-bounds metadata that pins the layout (validated on every
        update and on checkpoint restore)."""
        import jax
        plan = self._plan = self._build_plan(params, None)
        leaves = [np.ascontiguousarray(np.asarray(l)).reshape(-1)
                  for l in jax.tree.leaves(params)]
        shards = {}
        for key, idxs in plan.groups:
            frags = [leaves[i][slice(*plan.leaves[i].span)] for i in idxs]
            shards[key] = (np.concatenate(frags) if frags
                           else np.zeros(0, np.dtype(key)))
        meta = {
            "rank": np.int64(plan.rank),
            "world": np.int64(plan.world),
            "span_lo": np.array([i.span[0] for i in plan.leaves], np.int64),
            "span_hi": np.array([i.span[1] for i in plan.leaves], np.int64),
            "leaf_size": np.array([i.size for i in plan.leaves], np.int64),
            # per-leaf dtype strings: with leaf_size these are the FULL
            # partition inputs, so a checkpointed shard is reshardable to
            # any world size (resilience/reshard.py builds its manifest
            # and N->M plan from exactly these two arrays)
            "leaf_dtype": np.array([np.dtype(i.dtype).str
                                    for i in plan.leaves]),
        }
        state = {"shards": shards, "opt": self.opt.init(shards),
                 "meta": meta}
        if self.error_feedback:
            # shard-shaped error-feedback residual, one flat array per
            # dtype group in the EXACT shard layout — so it checkpoints,
            # reshards (the manifest auto-detects group-length 1-D arrays
            # as sharded), and slices into per-leaf views for the ring's
            # owner-compression hook
            state["ef"] = {k: np.zeros_like(v) for k, v in shards.items()}
        return state

    def _check_state(self, state, plan: _Plan) -> None:
        meta = state.get("meta") if isinstance(state, dict) else None
        if meta is None:
            raise ZeroStateError(
                "not a ZeroOptimizer state (no 'meta'): pass the pytree "
                "returned by ZeroOptimizer.init/update")
        want = {
            "rank": plan.rank, "world": plan.world,
            "span_lo": [i.span[0] for i in plan.leaves],
            "span_hi": [i.span[1] for i in plan.leaves],
            "leaf_size": [i.size for i in plan.leaves],
            "leaf_dtype": [np.dtype(i.dtype).str for i in plan.leaves],
        }
        for k, v in want.items():
            got = np.asarray(meta[k]).tolist() if k in meta else None
            if got != (v if isinstance(v, list) else int(v)):
                raise ZeroStateError(
                    f"ZeRO state layout mismatch on {k!r}: state has {got}, "
                    f"this run needs {v}.  A ZeRO state is valid only at "
                    f"the (rank, world) and parameter structure it was "
                    f"built for; to carry a checkpointed state to a "
                    f"different world size, restore it through elastic "
                    f"resharding (resilience.TrainState.resume or "
                    f"resilience.reshard.reshard_restore) into a fresh "
                    f"init(params) at the new world.")

    # -- step ----------------------------------------------------------------

    def _ef_views(self, state, plan: _Plan):
        """An :class:`~tpu_dist.collectives.quant.ErrorFeedback` whose
        per-leaf arrays are VIEWS into ``state['ef']``'s flat group
        shards: the ring's owner-compression hook updates them in place,
        which writes straight through to the checkpointable state — one
        storage, two layouts.  Missing/mislaid ``ef`` (a pre-quant
        checkpoint, or EF newly enabled) resets to zeros — losing a
        residual costs one step of compression error, never correctness."""
        from ..collectives.quant import ErrorFeedback
        if not self.error_feedback:
            return None
        ef = ErrorFeedback()
        ef_state = state.get("ef")
        if not isinstance(ef_state, dict):
            ef_state = state["ef"] = {}
        for key, idxs in plan.groups:
            want = sum(plan.leaves[i].span[1] - plan.leaves[i].span[0]
                       for i in idxs)
            flat = ef_state.get(key)
            if flat is None or np.asarray(flat).size != want \
                    or np.asarray(flat).dtype != np.dtype(key):
                from ..utils import log_event
                log_event("zero-ef-reset", group=key,
                          have=(int(np.asarray(flat).size)
                                if flat is not None else None),
                          want=want)
                flat = ef_state[key] = np.zeros(want, np.dtype(key))
            else:
                flat = ef_state[key] = np.ascontiguousarray(flat)
            pos = 0
            for i in idxs:
                lo, hi = plan.leaves[i].span
                ef.residuals[i] = flat[pos:pos + (hi - lo)]
                pos += hi - lo
        return ef

    def reduce_scatter(self, grads, group=None, state=None):
        """Issue the bucketed async reduce-scatter of ``grads``; returns
        the :class:`~tpu_dist.collectives.bucketer.BucketWork` whose
        ``wait_all()`` yields this rank's owned flat gradient shards.
        Issue it right after the backward pass and let the loss readback /
        logging overlap the wire (the PR 5 discipline), then hand it to
        :meth:`update`.

        With ``error_feedback=True`` pass the current ZeRO ``state`` so
        the shard-resident residual is folded in at the owner-compression
        point (``update`` raises if you forget — the residual loop must
        not silently drop out)."""
        ef = None
        if self.error_feedback:
            if state is None:
                raise ZeroStateError(
                    "ZeroOptimizer(error_feedback=True).reduce_scatter "
                    "needs the current state: call reduce_scatter(grads, "
                    "state=zstate) so the shard-resident residual rides "
                    "the compression hook")
            if self._plan is None:
                raise ZeroStateError(
                    "ZeroOptimizer.reduce_scatter before init: call "
                    "init(params) in this process first")
            ef = self._ef_views(state, self._plan)
        return self._bucketer.reduce_scatter(grads, op=self.reduce_op,
                                             group=group,
                                             error_feedback=ef)

    def update(self, grads, state, group=None,
               timeout: Optional[float] = None):
        """One sharded optimizer step.  ``grads`` is either the full
        gradient tree (reduce-scattered here) or the handle returned by
        :meth:`reduce_scatter` (already in flight).  Returns
        ``(handle, new_state)``: ``handle.wait()`` yields the full updated
        parameter tree — wait it lazily, after the next step's input
        staging, so the all-gather runs under that work."""
        import jax
        from ..collectives.bucketer import BucketWork

        group, n, r = self._resolve(group)
        if self._plan is None or self._plan.world != n \
                or self._plan.rank != r:
            raise ZeroStateError(
                "ZeroOptimizer.update before init (or the process group "
                "changed): call init(params) in this process first")
        plan = self._plan
        self._check_state(state, plan)

        if isinstance(grads, (BucketWork, ZeroParams)):
            frag_tree = grads.wait_all(timeout)
        else:
            frag_tree = self.reduce_scatter(grads, group=group,
                                            state=state) \
                .wait_all(timeout)
        frags = jax.tree.leaves(frag_tree)
        if len(frags) != len(plan.leaves):
            raise ZeroStateError(
                f"gradient tree has {len(frags)} leaves, ZeRO plan was "
                f"built for {len(plan.leaves)}")

        if self.max_grad_norm is not None:
            from ..optim.clip import sharded_clip_grad_norm
            frag_tree, _ = sharded_clip_grad_norm(
                frag_tree, self.max_grad_norm, group=group,
                all_reduce=self._pinned_scalar_sum())
            frags = jax.tree.leaves(frag_tree)

        gshards = {}
        for key, idxs in plan.groups:
            parts = [np.ascontiguousarray(np.asarray(frags[i]).reshape(-1))
                     for i in idxs]
            gshards[key] = (np.concatenate(parts) if parts
                            else np.zeros(0, np.dtype(key)))

        new_shards, new_opt = self.opt.update(gshards, state["opt"],
                                              state["shards"])
        new_shards = {k: np.asarray(v) for k, v in new_shards.items()}
        handle = self._issue_gather(new_shards, plan, group)
        new_state = {"shards": new_shards, "opt": new_opt,
                     "meta": state["meta"]}
        if self.error_feedback:
            # same arrays the reduce-scatter's views write through to —
            # the residual carries across steps and checkpoints with the
            # shards (zeros until the first compressed step touches it)
            new_state["ef"] = state.get("ef") or {
                k: np.zeros_like(v) for k, v in new_shards.items()}
        return handle, new_state

    def _pinned_scalar_sum(self):
        """In pinned (in-process test-rig) mode the clip's scalar
        all-reduce must ride this instance's plane, not the process-global
        eager path — production (dp=None) uses the eager default."""
        if self._dp is None:
            return None
        dp = self._dp

        def _sum(v):
            from ..collectives.ring import ring_all_reduce
            return ring_all_reduce(dp, v, op="sum", tag="zero_clip")
        return _sum

    # -- parameter all-gather -------------------------------------------------

    def _issue_gather(self, new_shards: Dict[str, np.ndarray], plan: _Plan,
                      group) -> ZeroParams:
        """Submit one async chunk all-gather per dtype group; the handle
        assembles the full parameter tree on wait.  The gather buffer is
        chunk-major (chunk *c* = concat of member leaves' chunk *c*), so
        this rank's updated shard IS bucket chunk ``rank`` — it drops in
        without reshuffling, and unpacking inverts the layout."""
        from ..collectives import eager as _eager
        from ..collectives.work import completed_work, engine_for
        from .rules import chunk_bounds as _bounds

        n, r = plan.world, plan.rank
        pinned = self._dp is not None
        engine = engine_for(self._dp)
        issue_seq = self._next_issue_seq() if pinned else -1
        use_ring = n > 1 and (pinned or (_eager._dp_enabled()
                                         and not _eager._prefer_mesh(group)
                                         and _eager._coll_store()
                                         is not None))

        works, plans = [], []
        for gi, (key, idxs) in enumerate(plan.groups):
            shard = new_shards[key]
            # updated dtype may differ from the param dtype (mixed-precision
            # promotion inside the wrapped optimizer) — every rank promotes
            # identically, so the layout stays rank-consistent
            dt = shard.dtype
            leaf_bounds = [_bounds(plan.leaves[i].size, n) for i in idxs]
            total = sum(plan.leaves[i].size for i in idxs)
            bucket_bounds = []
            pos = 0
            for c in range(n):
                lo = pos
                pos += sum(b[c][1] - b[c][0] for b in leaf_bounds)
                bucket_bounds.append((lo, pos))
            if n <= 1:
                works.append(completed_work(shard.copy(), "zero_gather"))
            elif use_ring and self._ring_ok(dt):
                buf = np.empty(total, dtype=dt)
                lo, hi = bucket_bounds[r]
                buf[lo:hi] = shard
                works.append(engine.submit(
                    self._gather_body(buf, bucket_bounds, group, issue_seq,
                                      gi),
                    label=f"zero_gather/g{gi}"))
            else:
                works.append(engine.submit(
                    self._gather_body_store(shard, group),
                    label=f"zero_gather/g{gi}/store"))
            plans.append((idxs, leaf_bounds, total))

        def assemble(results):
            leaves_out: List = [None] * len(plan.leaves)
            for (idxs, leaf_bounds, total), buf in zip(plans, results):
                outs = [np.empty(plan.leaves[i].size, dtype=buf.dtype)
                        for i in idxs]
                pos = 0
                for c in range(n):
                    for out, b in zip(outs, leaf_bounds):
                        flo, fhi = b[c]
                        if fhi > flo:
                            out[flo:fhi] = buf[pos:pos + (fhi - flo)]
                            pos += fhi - flo
                for i, out in zip(idxs, outs):
                    leaves_out[i] = out.reshape(plan.leaves[i].shape)
            import jax
            return jax.tree.unflatten(plan.treedef, leaves_out)

        return ZeroParams(works, assemble, f"zero_params x{len(works)}")

    @staticmethod
    def _ring_ok(dt: np.dtype) -> bool:
        """Can the wire carry this dtype raw?  (The gather only moves
        bytes — no reduce-op constraint.)"""
        if dt.kind in "iufb":
            return True
        if dt.kind == "V" and dt.fields is None:
            from ..collectives.transport import _decode_dtype
            try:
                return _decode_dtype(dt.name) == dt
            except Exception:
                return False
        return False

    def _next_issue_seq(self) -> int:
        with self._seq_mu:
            s = self._seq
            self._seq += 1
            return s

    def _gather_body(self, buf, bucket_bounds, group, issue_seq: int,
                     gi: int):
        """Deferred per-group ring chunk all-gather; runs on the ordered
        engine, so its obs span carries ``queue_ns`` — the time the gather
        sat behind earlier collectives — and the overlap with the next
        step's staging is visible in the trace."""

        def body():
            import time as _time
            from ..collectives import eager as _eager
            from ..collectives import ring as _ring
            if self._dp is not None:
                dp = self._dp
                tag = f"zag/i{issue_seq}/{gi}"
            else:
                store = _eager._coll_store()
                seq = _eager._next_seq("zero_ag", 0)
                tag = f"{_eager._ns()}/coll/zag/{seq}"
                _eager._sanitize("zero_param_gather", group, store,
                                 value=buf)
                dp = _eager._maybe_data_plane(group, store)
            with _eager._obs_span("zero_param_gather", value=buf):
                t0 = _time.perf_counter()
                stats: dict = {}
                out = _ring.ring_chunk_all_gather(
                    dp, buf, bucket_bounds, tag=tag,
                    comm_dtype=self.gather_comm_dtype, stats=stats)
                _eager._record("zero_param_gather", "dataplane",
                               buf.nbytes, t0,
                               wire_bytes=stats.get("wire_bytes"),
                               raw_wire_bytes=stats.get("raw_wire_bytes"))
            return out

        return body

    def _gather_body_store(self, shard, group):
        """Store-transport fallback (exotic dtypes / forced store mode):
        object-gather every rank's shard — chunk-major means chunk *c* IS
        rank *c*'s shard, so the full buffer is just the rank-ordered
        concat."""

        def body():
            from ..collectives import eager as _eager
            with _eager._obs_span("zero_param_gather", value=shard):
                rows = _eager.all_gather_object(shard, group=group)
            return np.concatenate([np.asarray(x).reshape(-1) for x in rows])

        return body
