"""One logical-axis sharding-rule table driving every partition layout.

ROADMAP item 1: the host path (bucketer/ZeRO/rings/pipeline), the XLA mesh
path (fsdp/gspmd pjit specs), and tensor-parallel serving each grew their
own span math — three places where partition layouts could silently drift.
This module is the single source of truth they all derive from, following
veScale's eager-mode-consistent SPMD (PAPERS.md) and the portable
redistribution formulation of arXiv 2112.01075:

* a **rule table** maps logical axis names (``batch``/``heads``/``mlp``/
  ``vocab``/``embed``/...) to mesh dims — SNIPPETS [2]/[3]'s
  ``DEFAULT_RULES`` idiom (``{"heads": "model", ...}``);
* a **layout table** maps parameter paths to the logical factorization of
  each tensor dim (e.g. a fused qkv weight's columns are
  ``(qkv3, heads, head_dim)``);
* consumers bind the two:
  - :func:`spec_for` / :func:`partition_pairs` → ``PartitionSpec`` trees
    for pjit (``parallel/gspmd.py``, ``parallel/fsdp.py``);
  - :func:`spans_for` → contiguous flat element spans for host-path
    sharding (``serve/sharded.py`` shard slicing and checkpoint
    range-reads, ``parallel/tensor.py`` dp×tp training);
  - :func:`chunk_bounds` / :func:`chunk_span` → the flat ZeRO/reshard
    chunk contract (``parallel/zero.py``, ``resilience/reshard.py``).

Changing only the rule table re-partitions every consumer coherently; the
eager host collectives are the debuggable twin of the compiled mesh
program (verified bitwise in benchmarks/bench_mesh_rules.py --smoke).

Everything here is pure layout arithmetic over numpy/ints — jax is
imported lazily and only when PartitionSpecs are requested, so the host
path (resilience, serving) never pays for it.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["DEFAULT_RULES", "SERVING_RULES", "LeafLayout",
           "TRANSFORMER_LAYOUTS", "ShardLayoutError", "layout_for",
           "spec_for", "spec_for_key", "partition_pairs", "spans_for",
           "shard_leaf", "chunk_bounds", "chunk_span", "model_axes",
           "mapped_axes"]


class ShardLayoutError(ValueError):
    """A leaf cannot be laid out as asked: logical-axis size not divisible
    by the shard world, a dim factored by two different mesh axes, or a
    factorization that does not multiply out to the tensor's shape."""


# ---------------------------------------------------------------------------
# rule tables: logical axis -> mesh dim (None = replicated along that axis)
# ---------------------------------------------------------------------------

#: Training default — dp×tp on a ("data", "model") mesh.  ``batch`` rides
#: the data dim; attention heads, the MLP hidden width, and the vocab
#: (head/embedding) split over the model dim.  Megatron column/row pairing
#: falls out of the layout table below: qkv/up are column-parallel, out/
#: down are row-parallel with partial-sum outputs.
DEFAULT_RULES: Dict[str, Optional[str]] = {
    "batch": "data",
    "seq": None,
    "embed": None,
    "qkv3": None,
    "heads": "model",
    "head_dim": None,
    "mlp": "model",
    "vocab": "model",
    # expert parallelism is its own binding (gspmd.MOE_EP_RULES): the
    # default dp×tp table leaves expert banks replicated so dense and MoE
    # models shard identically under it
    "expert": None,
}

#: Serving binding (serve/sharded.py): the shard gang splits heads and the
#: MLP hidden width only — head/tok stay full on every rank (lockstep
#: sampling needs full logits, and the decode hot path is attention/MLP).
SERVING_RULES: Dict[str, Optional[str]] = {
    "batch": None,
    "seq": None,
    "embed": None,
    "qkv3": None,
    "heads": "shard",
    "head_dim": None,
    "mlp": "shard",
    "vocab": None,
    "expert": None,
}


# ---------------------------------------------------------------------------
# layout table: parameter path -> per-dim logical factorization
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class LeafLayout:
    """Logical factorization of one parameter tensor.

    ``dims``: for each tensor dim (leading; trailing dims default to
    unfactored/replicated), the tuple of logical axis names whose sizes
    multiply to that dim — row-major, so ``("qkv3", "heads", "head_dim")``
    describes the fused qkv column layout ``[3][H][hd]``.

    ``partial_axis``: set on row-parallel output biases (attn out_bias,
    mlp down bias).  When the named axis is sharded, the matmul feeding
    this bias produces rank-partial sums; the bias must be added exactly
    once after the combine.  Consumers choose the policy via
    ``spans_for(..., partial=...)``: serving keeps the shard-0-owns-it
    convention, dp×tp training replicates it and adds it post-all-reduce
    (the order XLA's psum+bias takes, which is what keeps the eager twin
    bitwise against pjit)."""

    dims: Tuple[Tuple[str, ...], ...]
    partial_axis: Optional[str] = None


#: (path regex, name regex, layout) — first fullmatch wins.  Paths are the
#: module paths of tpu_dist.models.transformer.TransformerLM; the MoE row
#: covers parallel.gspmd's expert-parallel binding.
TRANSFORMER_LAYOUTS: Tuple[Tuple[str, str, LeafLayout], ...] = (
    (r"block\d+\.attn", r"qkv_weight",
     LeafLayout((("embed",), ("qkv3", "heads", "head_dim")))),
    (r"block\d+\.attn", r"qkv_bias",
     LeafLayout((("qkv3", "heads", "head_dim"),))),
    (r"block\d+\.attn", r"out_weight",
     LeafLayout((("heads", "head_dim"), ("embed",)))),
    (r"block\d+\.attn", r"out_bias",
     LeafLayout((("embed",),), partial_axis="heads")),
    (r"block\d+\.mlp\.0", r"weight", LeafLayout((("embed",), ("mlp",)))),
    (r"block\d+\.mlp\.0", r"bias", LeafLayout((("mlp",),))),
    (r"block\d+\.mlp\.2", r"weight", LeafLayout((("mlp",), ("embed",)))),
    (r"block\d+\.mlp\.2", r"bias",
     LeafLayout((("embed",),), partial_axis="mlp")),
    (r"head", r"weight", LeafLayout((("embed",), ("vocab",)))),
    (r"head", r"bias", LeafLayout((("vocab",),))),
    (r"tok", r"weight", LeafLayout((("vocab",), ("embed",)))),
    (r"pos", r"weight", LeafLayout((("seq",), ("embed",)))),
    # MoE expert banks (nn.moe): leading dim is the expert bank
    (r"block\d+\.mlp", r"[wb][12]", LeafLayout((("expert",),))),
)


def layout_for(path: str, name: str,
               table: Sequence[Tuple[str, str, LeafLayout]] = None
               ) -> Optional[LeafLayout]:
    """First layout row whose (path, name) regexes fullmatch, else None
    (= unfactored: replicated under every rule binding)."""
    for ppat, npat, lay in (TRANSFORMER_LAYOUTS if table is None else table):
        if re.fullmatch(ppat, path) and re.fullmatch(npat, name):
            return lay
    return None


def mapped_axes(rules: Dict[str, Optional[str]], mesh_axis: str
                ) -> Tuple[str, ...]:
    """Logical axes the rule table binds to ``mesh_axis``."""
    return tuple(a for a, m in rules.items() if m == mesh_axis)


# ---------------------------------------------------------------------------
# pjit specs
# ---------------------------------------------------------------------------

def _dim_mesh_axis(factors: Tuple[str, ...],
                   rules: Dict[str, Optional[str]]) -> Optional[str]:
    mapped = [rules.get(f) for f in factors if rules.get(f) is not None]
    if len(set(mapped)) > 1:
        raise ShardLayoutError(
            f"dim factored as {factors} maps to multiple mesh axes "
            f"{sorted(set(mapped))} — a tensor dim shards along at most one")
    return mapped[0] if mapped else None


def spec_for(path: str, name: str, rules: Dict[str, Optional[str]] = None,
             table: Sequence[Tuple[str, str, LeafLayout]] = None):
    """``PartitionSpec`` for one parameter under a rule binding.  Trailing
    replicated dims are trimmed, so fully-replicated leaves give ``P()``
    (the same spec an unmatched leaf gets from ``PartitionRules``)."""
    from jax.sharding import PartitionSpec as P
    if rules is None:
        rules = DEFAULT_RULES
    lay = layout_for(path, name, table)
    if lay is None:
        return P()
    entries = [_dim_mesh_axis(factors, rules) for factors in lay.dims]
    if not any(e is not None for e in entries):
        return P()  # fully replicated — the unmatched-leaf default
    return P(*entries)


_KEY_RE = re.compile(r"^\['([^']+)'\]\['([^']+)'\]$")


def spec_for_key(keystr: str, rules: Dict[str, Optional[str]] = None,
                 table: Sequence[Tuple[str, str, LeafLayout]] = None):
    """:func:`spec_for` addressed by a jax ``keystr`` path like
    ``['block0.attn']['qkv_weight']`` (the form gspmd's rule regexes
    match against)."""
    from jax.sharding import PartitionSpec as P
    m = _KEY_RE.match(keystr)
    if m is None:
        return P()
    return spec_for(m.group(1), m.group(2), rules, table)


def partition_pairs(rules: Dict[str, Optional[str]] = None,
                    table: Sequence[Tuple[str, str, LeafLayout]] = None
                    ) -> List[Tuple[str, object]]:
    """Derive ``(keystr regex, PartitionSpec)`` pairs for
    :class:`parallel.gspmd.PartitionRules` from the layout + rule tables.
    Rows that come out fully replicated are dropped (the PartitionRules
    default already answers ``P()`` for unmatched leaves)."""
    from jax.sharding import PartitionSpec as P
    if rules is None:
        rules = DEFAULT_RULES
    pairs = []
    for ppat, npat, lay in (TRANSFORMER_LAYOUTS if table is None else table):
        entries = [_dim_mesh_axis(factors, rules) for factors in lay.dims]
        if not any(e is not None for e in entries):
            continue  # replicated — PartitionRules' default

        pairs.append((r"\['" + ppat + r"'\]\['" + npat + r"'\]",
                      P(*entries)))
    return pairs


# ---------------------------------------------------------------------------
# host-path spans (eager twin of the specs above)
# ---------------------------------------------------------------------------

def _find_sharded(lay: LeafLayout, rules: Dict[str, Optional[str]],
                  mesh_axis: str) -> Optional[Tuple[int, int]]:
    """(dim index, factor index) of the factor riding ``mesh_axis``."""
    hits = []
    for d, factors in enumerate(lay.dims):
        for j, f in enumerate(factors):
            if rules.get(f) == mesh_axis:
                hits.append((d, j))
    if len(hits) > 1:
        raise ShardLayoutError(
            f"layout {lay.dims} maps {len(hits)} factors to mesh axis "
            f"{mesh_axis!r} — host-path sharding splits exactly one")
    return hits[0] if hits else None


def _full(shape: Tuple[int, ...]):
    return [(0, int(np.prod(shape, dtype=np.int64)) if shape else 1)], shape


def spans_for(path: str, name: str, shape: Tuple[int, ...],
              axes: Dict[str, int], rank: int, world: int,
              rules: Dict[str, Optional[str]] = None,
              mesh_axis: str = "model", partial: str = "first",
              table: Sequence[Tuple[str, str, LeafLayout]] = None
              ) -> Optional[Tuple[List[Tuple[int, int]], Tuple[int, ...]]]:
    """``(contiguous flat element spans, local shape)`` of shard ``rank``'s
    slice of a parameter, or None when this rank holds nothing (a
    partial-sum bias under the ``partial="first"`` policy on rank > 0).

    ``axes`` gives the logical axis sizes (:func:`model_axes`).  Every
    span is contiguous in the flat row-major layout — what lets both
    in-memory slicing and checkpoint range-reads assemble identical
    shards (serve/sharded.py's contract, now generalized).

    ``partial``: policy for partial-sum biases when their controlling
    axis is sharded — ``"first"`` = rank 0 owns the full bias (serving's
    pre-reduce convention), ``"replicate"`` = every rank holds it and the
    consumer adds it once after the combine (training's post-reduce
    order, bitwise-matching XLA's psum+bias)."""
    if rules is None:
        rules = DEFAULT_RULES
    lay = layout_for(path, name, table)
    if lay is None:
        return _full(shape)
    if lay.partial_axis is not None and rules.get(lay.partial_axis) \
            == mesh_axis and world > 1:
        if partial == "replicate":
            return _full(shape)
        return _full(shape) if rank == 0 else None
    sh = _find_sharded(lay, rules, mesh_axis)
    if sh is None:
        return _full(shape)
    d, j = sh
    factors = lay.dims[d]
    try:
        sizes = [int(axes[f]) for f in factors]
    except KeyError as e:
        raise ShardLayoutError(
            f"axis size for {e.args[0]!r} missing (leaf {path}.{name}); "
            f"pass it in `axes` (see model_axes)") from None
    if d >= len(shape) or int(np.prod(sizes, dtype=np.int64)) != shape[d]:
        raise ShardLayoutError(
            f"leaf {path}.{name} dim {d} is {shape[d] if d < len(shape) else None}, "
            f"but factors {factors} multiply to {sizes}")
    nj = sizes[j]
    if nj % world:
        raise ShardLayoutError(
            f"logical axis {factors[j]!r} of size {nj} not divisible by "
            f"shard world {world} (leaf {path}.{name})")
    chunk = nj // world
    start = rank * chunk
    outer = int(np.prod(shape[:d], dtype=np.int64)) * \
        int(np.prod(sizes[:j], dtype=np.int64))
    inner = int(np.prod(sizes[j + 1:], dtype=np.int64)) * \
        int(np.prod(shape[d + 1:], dtype=np.int64))
    spans = [(o * nj * inner + start * inner,
              o * nj * inner + (start + chunk) * inner)
             for o in range(outer)]
    out_shape = shape[:d] + (shape[d] // world,) + shape[d + 1:]
    return spans, out_shape


def shard_leaf(arr: np.ndarray, plan) -> Optional[np.ndarray]:
    """Materialize one shard from a :func:`spans_for` plan (None passes
    through: the rank holds nothing)."""
    if plan is None:
        return None
    spans, out_shape = plan
    flat = np.ascontiguousarray(arr).reshape(-1)
    if len(spans) == 1:
        lo, hi = spans[0]
        return flat[lo:hi].reshape(out_shape).copy()
    return np.concatenate([flat[lo:hi] for lo, hi in spans]
                          ).reshape(out_shape)


# ---------------------------------------------------------------------------
# flat chunk bounds — the ZeRO / reshard contract
# ---------------------------------------------------------------------------

def chunk_bounds(n_elems: int, world: int) -> List[Tuple[int, int]]:
    """Per-rank [lo, hi) bounds of a flat buffer split into ``world``
    near-equal contiguous chunks — THE layout contract shared by the ring
    reduce-scatter, ZeroOptimizer shards, and reshard manifests (the
    first ``n_elems % world`` chunks get one extra element).  Delegates
    to the ring implementation so existing sharded checkpoints stay
    bitwise-compatible by construction."""
    from ..collectives.ring import _bounds
    return _bounds(n_elems, world)


def chunk_span(n_elems: int, world: int, rank: int) -> Tuple[int, int]:
    """Rank's own [lo, hi) from :func:`chunk_bounds`."""
    from ..collectives.ring import ring_chunk_span
    return ring_chunk_span(n_elems, world, rank)


# ---------------------------------------------------------------------------
# logical axis sizes
# ---------------------------------------------------------------------------

def model_axes(model) -> Dict[str, int]:
    """Logical axis sizes of a ``TransformerLM``-shaped model, keyed by
    the names the layout table uses.  Probes the modules (block0.attn,
    head) rather than constructor args so quantized/subclassed variants
    answer too."""
    axes: Dict[str, int] = {"qkv3": 3}
    attn = getattr(getattr(model, "block0", None), "attn", None)
    if attn is not None:
        axes["embed"] = attn.embed_dim
        axes["heads"] = attn.num_heads
        axes["head_dim"] = attn.head_dim
    mlp = getattr(getattr(model, "block0", None), "mlp", None)
    try:
        up = mlp[0] if mlp is not None else None
    except (TypeError, IndexError, KeyError):
        up = None
    if up is not None and hasattr(up, "out_features"):
        axes["mlp"] = up.out_features
    head = getattr(model, "head", None)
    if head is not None and hasattr(head, "out_features"):
        axes["vocab"] = head.out_features
    pos = getattr(model, "pos", None)
    if pos is not None and hasattr(pos, "num_embeddings"):
        axes["seq"] = pos.num_embeddings
    if mlp is not None and hasattr(mlp, "num_experts"):
        axes["expert"] = mlp.num_experts
    return axes
