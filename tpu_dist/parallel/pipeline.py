"""Pipeline parallelism (GPipe schedule) — the 'pipe' mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2c: data-parallel
only); this module exists because tpu_dist's mesh design treats pp as a
first-class axis alongside dp/tp/sp (ProcessGroup accepts arbitrary
axis_names), and the driver's multi-chip dry-run exercises it.

TPU-first design — one SPMD program, not per-stage processes:

- The transformer trunk is cut into S **stages of identical topology**
  (``depth % S == 0``), so stage parameters can be **stacked** on a leading
  axis of size S and sharded ``P('pipe')``: every device holds exactly its
  stage's weights, and the stage function is the *same* traced program on
  every device (SPMD), selected purely by the parameter shard it holds.
- The GPipe schedule is a ``lax.scan`` over ``M + S - 1`` ticks.  Each tick
  ``lax.ppermute``s the activation carry one hop down the pipe (stage i →
  i+1 over ICI), stage 0 swaps in the next microbatch's embeddings, every
  stage applies its block-stack, and the last stage's trunk outputs
  accumulate into an on-device buffer via clamped ``dynamic_update`` writes
  (early garbage writes land on slot 0 and are overwritten at tick S-1 —
  no masks in the hot loop).
- Embedding and LM head stay **replicated** (P()): each device traces the
  same embed/head compute, but gradients flow only through the copies that
  feed the pipe (embed on stage 0, head on the last stage).  The loss is
  ``psum`` of the last-stage-masked local loss, so JAX's VMA autodiff
  (see ddp.py) inserts exactly the right cross-stage gradient ``psum`` for
  the replicated leaves — stage-stacked leaves are pipe-varying and get
  **no** collective, their gradients are local by construction.
- Composes with data parallelism on a ('data', 'pipe') mesh: the batch
  shards over 'data', each data row runs an independent pipeline, and the
  same VMA autodiff inserts the gradient allreduce over 'data' because the
  loss is ``pmean``-ed over it.  The optimizer update runs inside the
  ``shard_map``, so stage parameters *and their optimizer state* stay
  sharded 1/S per device — pipeline parallelism gives ZeRO-style optimizer
  sharding of the trunk for free.

Backward through the schedule is the transpose of the scan: XLA reverses
the ``ppermute`` direction and replays ticks in reverse — the standard
bubble of (S-1)/(M + S - 1) idle ticks on both passes; raise
``num_microbatches`` to amortize it.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import nn

__all__ = ["PipelineParallel", "PipeTrainState"]


class PipeTrainState(NamedTuple):
    """State threaded through the jitted pipeline step.

    ``params`` / ``opt_state`` are two-key dicts: ``"repl"`` (embedding +
    head, replicated) and ``"stages"`` (trunk blocks stacked on a leading
    stage axis, sharded ``P('pipe')``)."""
    params: Any
    opt_state: Any
    step: jnp.ndarray


class _Embed(nn.Module):
    """Token + learned positional embeddings (the model's own modules, so
    parameter pytrees transfer 1:1 between pipeline and plain layouts).
    ``pos`` is None for rope models — positions then enter through the
    attention rotations inside the stages."""

    def __init__(self, tok, pos):
        super().__init__()
        self.tok = tok
        self.pos = pos

    def forward(self, idx):
        if self.pos is None:
            return self.tok(idx)
        t = idx.shape[1]
        return self.tok(idx) + self.pos(jnp.arange(t))


class _Head(nn.Module):
    """Final LayerNorm + LM head."""

    def __init__(self, ln_f, head):
        super().__init__()
        self.ln_f = ln_f
        self.head = head

    def forward(self, x):
        return self.head(self.ln_f(x))


class PipelineParallel:
    """GPipe-parallel training driver for :class:`~tpu_dist.models.TransformerLM`.

    Usage::

        pg = dist.init_process_group(axis_names=("pipe",))   # or (data, pipe)
        pp = PipelineParallel(model, optimizer=optim.AdamW(3e-4),
                              loss_fn=nn.CrossEntropyLoss(), group=pg,
                              num_microbatches=8)
        state = pp.init(seed=0)
        state, metrics = pp.train_step(state, tokens, targets)

    ``tokens``/``targets`` are ``(B, T)`` int arrays; ``B`` must divide by
    ``num_microbatches`` (and by the data-axis size when present).
    """

    def __init__(self, model, optimizer=None, loss_fn=None, group=None,
                 num_microbatches: Optional[int] = None,
                 pipe_axis: str = "pipe", data_axis: Optional[str] = None,
                 donate: bool = True, compute_dtype=None,
                 schedule: str = "gpipe"):
        """``compute_dtype``: run forward/backward (and the inter-stage
        ppermute traffic) in this dtype — bf16 halves the ICI bytes per
        hop and keeps the MXU on its fast path — while parameters,
        gradients, and optimizer state stay float32 master copies (same
        mixed-precision recipe as the DDP wrapper's ``compute_dtype``).

        ``schedule``: ``"gpipe"`` (all-forward-then-all-backward via
        autodiff of the tick scan) or ``"1f1b"`` (one-forward-one-backward
        — a hand-scheduled scan interleaving each microbatch's backward
        with later microbatches' forwards, see _build_1f1b_step).  Same
        math, same bubble fraction; 1F1B bounds the stashed activations
        at ``min(2S-1, M)`` microbatch inputs per device instead of the
        autodiff scan's ``M+S-1`` saved ticks — the standard memory
        argument for 1F1B, here with recompute-based stage backward (the
        memory regime GPipe needs ``remat=True`` to reach)."""
        if group is None:
            from .. import dist as _dist
            group = _dist.get_default_group()
        if pipe_axis not in group.mesh.axis_names:
            raise ValueError(f"mesh {group.mesh.axis_names} has no "
                             f"{pipe_axis!r} axis")
        if data_axis is None and len(group.mesh.axis_names) > 1:
            others = [a for a in group.mesh.axis_names if a != pipe_axis]
            if len(others) == 1:
                data_axis = others[0]
            else:
                raise ValueError("pass data_axis= explicitly on a >2-D mesh")
        if getattr(model, "sequence_axis", None) is not None:
            raise ValueError("pipeline parallelism microbatches over the "
                             "batch dim; build the model without "
                             "sequence_axis (pp x sp needs a 3-D mesh recipe)")
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"schedule must be 'gpipe' or '1f1b', "
                             f"got {schedule!r}")
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.group = group
        self.pipe_axis = pipe_axis
        self.data_axis = data_axis
        self.donate = donate
        self.compute_dtype = compute_dtype
        self.schedule = schedule
        self.num_stages = group.mesh.shape[pipe_axis]
        if model.depth % self.num_stages:
            raise ValueError(f"depth {model.depth} not divisible by "
                             f"{self.num_stages} pipeline stages")
        self.blocks_per_stage = model.depth // self.num_stages
        self.num_microbatches = num_microbatches or self.num_stages
        # canonical stage program: the first blocks_per_stage blocks.  Module
        # objects hold topology only (nn/module.py design), so one stage's
        # module tree serves as the traced program for every stage — which
        # weights it runs with is decided by the P('pipe') parameter shard.
        self._stage = nn.Sequential(*[getattr(model, f"block{i}")
                                      for i in range(self.blocks_per_stage)])
        self._embed = _Embed(model.tok, model.pos)
        self._head = _Head(model.ln_f, model.head)
        self._canon_paths = None  # stage-relative -> block0-rooted paths
        self._train_step = None

    # -- parameter layout ------------------------------------------------------

    def _stage_paths(self):
        """Canonical stage-relative leaf paths ("0.ln1", "1.mlp.0", ...) and
        their block-index/suffix decomposition."""
        if self._canon_paths is None:
            self._stage._assign_paths()
            paths = []
            for path, mod in self._stage.named_modules():
                if type(mod).create_params is not nn.Module.create_params:
                    j, _, suffix = path.partition(".")
                    paths.append((path, int(j), suffix))
            self._canon_paths = paths
        return self._canon_paths

    def pack_params(self, model_params):
        """Plain ``model.init()`` pytree → pipeline layout ``{"repl",
        "stages"}`` (stage leaves stacked on a leading S axis)."""
        s, k = self.num_stages, self.blocks_per_stage
        stages = {}
        for canon, j, suffix in self._stage_paths():
            def src(stage):
                base = f"block{stage * k + j}"
                return model_params[f"{base}.{suffix}" if suffix else base]
            names = src(0).keys()
            stages[canon] = {n: jnp.stack([src(st)[n] for st in range(s)])
                            for n in names}
        embed = {"tok": model_params["tok"]}
        if "pos" in model_params:
            embed["pos"] = model_params["pos"]
        repl = {"embed": embed,
                "head": {"ln_f": model_params["ln_f"],
                         "head": model_params["head"]}}
        return {"repl": repl, "stages": stages}

    def unpack_params(self, pipe_params):
        """Inverse of :meth:`pack_params` — e.g. to checkpoint in the plain
        layout or hand weights to an unsharded model for decoding."""
        k = self.blocks_per_stage
        out = {"tok": pipe_params["repl"]["embed"]["tok"],
               "ln_f": pipe_params["repl"]["head"]["ln_f"],
               "head": pipe_params["repl"]["head"]["head"]}
        if "pos" in pipe_params["repl"]["embed"]:
            out["pos"] = pipe_params["repl"]["embed"]["pos"]
        for canon, j, suffix in self._stage_paths():
            stacked = pipe_params["stages"][canon]
            for st in range(self.num_stages):
                base = f"block{st * k + j}"
                path = f"{base}.{suffix}" if suffix else base
                out[path] = {n: v[st] for n, v in stacked.items()}
        return out

    def _param_specs(self, params):
        """PartitionSpec pytree: stages P('pipe') on the stacked axis,
        everything else replicated."""
        pipe = self.pipe_axis
        return {"repl": jax.tree.map(lambda _: P(), params["repl"]),
                "stages": jax.tree.map(lambda _: P(pipe), params["stages"])}

    def _opt_specs(self, opt_state):
        """Optimizer-state specs: leaves mirroring stacked stage params keep
        P('pipe'); scalars (step counters) replicate."""
        pipe = self.pipe_axis

        def split(sub, stacked):
            return jax.tree.map(
                lambda l: P(pipe) if (stacked and getattr(l, "ndim", 0) >= 1)
                else P(), sub)

        return {"repl": split(opt_state["repl"], False),
                "stages": split(opt_state["stages"], True)}

    # -- state -----------------------------------------------------------------

    def init(self, seed: int = 0) -> PipeTrainState:
        """Deterministic state build: plain ``model.init`` then repack, so
        pipeline training starts from bit-identical weights to a
        single-device run with the same seed."""
        params = self.pack_params(self.model.init(jax.random.key(seed)))
        if self.optimizer is None:
            opt_state = {"repl": {}, "stages": {}}
        else:
            opt_state = {"repl": self.optimizer.init(params["repl"]),
                         "stages": self.optimizer.init(params["stages"])}
        state = PipeTrainState(params, opt_state, jnp.zeros((), jnp.int32))
        return jax.tree.map(jax.device_put, state, self.state_shardings(state))

    def state_shardings(self, state: PipeTrainState) -> PipeTrainState:
        """NamedSharding pytree mirroring ``state``'s placement (for
        ``tpu_dist.checkpoint.restore(sharding=...)``)."""
        mesh = self.group.mesh
        spec = PipeTrainState(self._param_specs(state.params),
                              self._opt_specs(state.opt_state), P())
        return jax.tree.map(lambda sp: NamedSharding(mesh, sp), spec,
                            is_leaf=lambda x: isinstance(x, P))

    # -- compiled step ---------------------------------------------------------

    def _build_train_step(self):
        stage, embed, head = self._stage, self._embed, self._head
        loss_fn, optimizer = self.loss_fn, self.optimizer
        pipe, data = self.pipe_axis, self.data_axis
        s, m = self.num_stages, self.num_microbatches
        vocab = self.model.vocab_size
        cdtype = self.compute_dtype

        def cast(tree):
            if cdtype is None:
                return tree
            return jax.tree.map(
                lambda v: v.astype(cdtype)
                if jnp.issubdtype(v.dtype, jnp.floating) else v, tree)

        def local_step(state: PipeTrainState, x, y):
            params, opt_state, step = state
            idx = lax.axis_index(pipe)
            is_last = idx == s - 1
            b_loc, t = x.shape
            mb = b_loc // m
            x_mb = x.reshape(m, mb, t)

            def trunk(repl_p, stages_p, x_mb):
                """GPipe loop → last-stage trunk outputs (m, mb, t, d)."""
                stage_local = jax.tree.map(lambda v: v[0], stages_p)
                perm = [(i, (i + 1) % s) for i in range(s)]

                def tick(carry, tick_t):
                    h, out = carry
                    prev = lax.ppermute(h, pipe, perm)
                    inj = embed.apply(repl_p["embed"],
                                      x_mb[jnp.minimum(tick_t, m - 1)])
                    h = jnp.where(idx == 0, inj, prev)
                    if self.model.remat:
                        # honor the model's per-block remat policy: recompute
                        # the stage's activations during backward instead of
                        # holding every tick's intermediates across the scan
                        h = jax.checkpoint(stage.apply)(stage_local, h)
                    else:
                        h = stage.apply(stage_local, h)
                    # clamped write: ticks < s-1 land on slot 0 and are
                    # overwritten at tick s-1, so no validity mask is needed
                    slot = jnp.clip(tick_t - (s - 1), 0, m - 1)
                    out = lax.dynamic_update_index_in_dim(out, h, slot, 0)
                    return (h, out), None

                dim = self.model.tok.embedding_dim
                # the carry crosses stages (ppermute), mixes with the
                # pipe-varying stage index, and holds data-sharded
                # activations — it must start varying over every mesh axis
                # the tick output is varying over, or scan rejects the body
                axes = (pipe,) if data is None else (data, pipe)
                adtype = cdtype or jnp.float32
                h0 = jnp.zeros(x_mb.shape[1:] + (dim,), adtype)
                out0 = jnp.zeros((m,) + h0.shape, adtype)
                for ax in axes:
                    h0 = lax.pcast(h0, ax, to="varying")
                    out0 = lax.pcast(out0, ax, to="varying")
                (_, out), _ = lax.scan(tick, (h0, out0), jnp.arange(m + s - 1))
                return out

            def loss_of(p):
                # the cast is differentiable: bf16 compute, f32 master
                # params/grads (cotangents cast back on the way out)
                p = cast(p)
                out = trunk(p["repl"], p["stages"], x_mb)
                logits = head.apply(p["repl"]["head"],
                                    out.reshape(b_loc, t, -1))
                local = loss_fn(logits.reshape(-1, vocab), y.reshape(-1))
                correct = (logits.argmax(-1) == y).sum()
                # only the last stage's buffer holds the real trunk output;
                # psum of the masked loss broadcasts it pipe-invariant, and
                # its VMA transpose routes gradient only into that copy
                loss = lax.psum(jnp.where(is_last, local, 0.0), pipe)
                correct = lax.psum(jnp.where(is_last, correct, 0), pipe)
                if data is not None:
                    loss = lax.pmean(loss, data)
                    correct = lax.psum(correct, data)
                return loss, correct

            (loss, correct), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)

            new_repl, opt_repl = optimizer.update(
                grads["repl"], opt_state["repl"], params["repl"])
            new_stages, opt_stages = optimizer.update(
                grads["stages"], opt_state["stages"], params["stages"])
            new_params = {"repl": new_repl, "stages": new_stages}
            new_opt = {"repl": opt_repl, "stages": opt_stages}

            new_state = PipeTrainState(new_params, new_opt, step + 1)
            return new_state, {"loss": loss, "correct": correct}

        def specs_of(state):
            return PipeTrainState(self._param_specs(state.params),
                                  self._opt_specs(state.opt_state), P())

        def build(state):
            state_spec = specs_of(state)
            batch_spec = P(data) if data is not None else P()
            fn = jax.shard_map(local_step, mesh=self.group.mesh,
                               in_specs=(state_spec, batch_spec, batch_spec),
                               out_specs=(state_spec, P()))
            return jax.jit(fn, donate_argnums=(0,) if self.donate else ())

        return build

    def _build_1f1b_step(self):
        """One-forward-one-backward schedule, hand-written (autodiff of the
        GPipe scan cannot interleave passes — the backward IS the scan's
        transpose).  One ``lax.scan`` over ``2S + M - 1`` ticks; at tick t,
        the device holding stage ``i``:

        - **forward unit**: runs microbatch ``f = t - i`` through its block
          stack (activations arrive by forward ``ppermute``; stage 0
          injects embeddings), stashing the stage INPUT in an
          ``K = min(2S-1, M)``-slot ring — the 1F1B in-flight bound.  The
          last stage immediately runs head + loss and their VJP, parking
          the trunk-output cotangent in a 2-slot ring (it is consumed one
          tick later) and banking the head gradients;
        - **backward unit**: runs the VJP of its stage for microbatch
          ``j = t - (2S - 1 - i)`` — the incoming cotangent is the
          reverse-``ppermute``d carry (or the parked head cotangent at the
          last stage), the stage input is popped from the ring and the
          forward RECOMPUTED inside ``jax.vjp`` (remat-style backward, so
          nothing beyond the ring is ever stored); stage 0 routes the
          resulting input cotangent through the embedding VJP.

        Gradients accumulate in f32 buffers in the carry; after the scan
        they get the collectives VMA autodiff inserted for GPipe: psum
        over 'pipe' for the replicated embed/head leaves (each is nonzero
        on one stage only), pmean over 'data' for everything.  Losses and
        correct-counts bank at the last stage's forward unit.
        """
        stage, embed, head = self._stage, self._embed, self._head
        loss_fn, optimizer = self.loss_fn, self.optimizer
        pipe, data = self.pipe_axis, self.data_axis
        s, m = self.num_stages, self.num_microbatches
        vocab = self.model.vocab_size
        cdtype = self.compute_dtype
        k_slots = min(2 * s - 1, m)

        def cast(tree):
            if cdtype is None:
                return tree
            return jax.tree.map(
                lambda v: v.astype(cdtype)
                if jnp.issubdtype(v.dtype, jnp.floating) else v, tree)

        def local_step(state: PipeTrainState, x, y):
            params, opt_state, step = state
            idx = lax.axis_index(pipe)
            is_first = idx == 0
            is_last = idx == s - 1
            b_loc, t_len = x.shape
            mb = b_loc // m
            x_mb = x.reshape(m, mb, t_len)
            y_mb = y.reshape(m, mb, t_len)
            dim = self.model.tok.embedding_dim
            adtype = cdtype or jnp.float32

            # CRITICAL: every params pytree fed to a jax.vjp below must be
            # device-VARYING on every mesh axis first.  Inside shard_map,
            # vjp w.r.t. a mesh-INVARIANT input auto-inserts a psum of the
            # per-device cotangents (the VMA autodiff rule the GPipe path
            # exploits on purpose) — which here would mix other stages'
            # masked-out garbage head/embed gradients in BEFORE our bank
            # masks can drop them (measured: ~3x-wrong repl grads).  With
            # varying inputs the vjps stay collective-free and the
            # explicit psums/pmeans after the scan do the reductions.
            axes = (pipe,) if data is None else (data, pipe)

            def vary(tree, over):
                def one(v):
                    for ax in over:
                        v = lax.pcast(v, ax, to="varying")
                    return v
                return jax.tree.map(one, tree)

            cparams = cast(params)
            # stage shards are already pipe-varying; repl leaves are
            # invariant on every axis
            stage_local = vary(jax.tree.map(lambda v: v[0],
                                            cparams["stages"]),
                               () if data is None else (data,))
            repl_embed = vary(cparams["repl"]["embed"], axes)
            repl_head = vary(cparams["repl"]["head"], axes)

            fwd_perm = [(i, (i + 1) % s) for i in range(s)]
            bwd_perm = [(i, (i - 1) % s) for i in range(s)]

            def stage_fn(sp, h):
                return stage.apply(sp, h)

            def head_loss(hp, out, y_j):
                logits = head.apply(hp, out)
                loss = loss_fn(logits.reshape(-1, vocab), y_j.reshape(-1))
                correct = (logits.argmax(-1) == y_j).sum()
                return loss, correct

            def tick(carry, tick_t):
                (h, g, stash, cot_ring, g_stage, g_embed, g_head,
                 loss_sum, correct_sum) = carry

                # backward-unit READS of the rings happen before the
                # forward unit writes them: at stage 0 the microbatch
                # being stashed and the one being back-propagated can
                # collide on a slot in the same tick (f - j = 2S-1-2i)
                j = tick_t - (2 * s - 1 - idx)
                bwd_on = (j >= 0) & (j < m)
                j_c = jnp.clip(j, 0, m - 1)
                h_saved = lax.dynamic_index_in_dim(stash, j_c % k_slots, 0,
                                                   keepdims=False)
                parked = lax.dynamic_index_in_dim(cot_ring, j_c % 2, 0,
                                                  keepdims=False)

                # ---- forward unit -----------------------------------
                f = tick_t - idx
                fwd_on = (f >= 0) & (f < m)
                f_c = jnp.clip(f, 0, m - 1)
                prev = lax.ppermute(h, pipe, fwd_perm)
                inj = embed.apply(repl_embed, x_mb[f_c]).astype(adtype)
                h_in = jnp.where(is_first, inj, prev)
                h_out = stage_fn(stage_local, h_in)
                h_new = h_out
                # ring write, masked against clobbering a live slot when
                # this tick's forward is idle (warmup/drain)
                slot = f_c % k_slots
                old_slot = lax.dynamic_index_in_dim(stash, slot, 0,
                                                    keepdims=False)
                stash = lax.dynamic_update_index_in_dim(
                    stash, jnp.where(fwd_on, h_in, old_slot), slot, 0)

                # last stage: head + loss VJP on the fresh trunk output;
                # the cotangent is consumed by the backward unit next tick
                (loss_f, hl_vjp, correct_f) = jax.vjp(
                    lambda hp, out: head_loss(hp, out, y_mb[f_c]),
                    repl_head, h_out, has_aux=True)
                # the seed must carry loss_f's varying-mesh-axes type
                # (a fresh constant is mesh-invariant and vjp rejects it)
                d_head, d_out = hl_vjp(loss_f * 0 + 1)
                bank = fwd_on & is_last
                g_head = jax.tree.map(
                    lambda a, d: a + jnp.where(bank, 1.0, 0.0)
                    * d.astype(jnp.float32), g_head, d_head)
                loss_sum = loss_sum + jnp.where(bank, loss_f, 0.0)
                correct_sum = correct_sum + jnp.where(bank, correct_f, 0)
                cslot = f_c % 2
                old_c = lax.dynamic_index_in_dim(cot_ring, cslot, 0,
                                                 keepdims=False)
                cot_ring = lax.dynamic_update_index_in_dim(
                    cot_ring, jnp.where(bank, d_out.astype(adtype), old_c),
                    cslot, 0)

                # ---- backward unit ----------------------------------
                g_prev = lax.ppermute(g, pipe, bwd_perm)
                g_in = jnp.where(is_last, parked, g_prev)
                _, st_vjp = jax.vjp(stage_fn, stage_local, h_saved)
                d_stage, d_h = st_vjp(g_in.astype(adtype))
                live = jnp.where(bwd_on, 1.0, 0.0)
                g_stage = jax.tree.map(
                    lambda a, d: a + live * d.astype(jnp.float32),
                    g_stage, d_stage)
                # stage 0: the input cotangent belongs to the embeddings
                _, em_vjp = jax.vjp(
                    lambda ep: embed.apply(ep, x_mb[j_c]).astype(adtype),
                    repl_embed)
                (d_embed,) = em_vjp(d_h)
                g_embed = jax.tree.map(
                    lambda a, d: a + jnp.where(bwd_on & is_first, 1.0, 0.0)
                    * d.astype(jnp.float32), g_embed, d_embed)
                g_new = d_h

                return (h_new, g_new, stash, cot_ring, g_stage, g_embed,
                        g_head, loss_sum, correct_sum), None

            # carries start varying over every mesh axis the tick outputs
            # vary over (same requirement as the GPipe trunk scan)
            def varying(a):
                for ax in axes:
                    a = lax.pcast(a, ax, to="varying")
                return a

            h0 = varying(jnp.zeros((mb, t_len, dim), adtype))
            g0 = varying(jnp.zeros((mb, t_len, dim), adtype))
            stash0 = varying(jnp.zeros((k_slots, mb, t_len, dim), adtype))
            cot0 = varying(jnp.zeros((2, mb, t_len, dim), adtype))
            zeros_f32 = lambda tree: jax.tree.map(
                lambda v: varying(jnp.zeros(v.shape, jnp.float32)), tree)
            carry0 = (h0, g0, stash0, cot0, zeros_f32(stage_local),
                      zeros_f32(repl_embed), zeros_f32(repl_head),
                      varying(jnp.zeros((), jnp.float32)),
                      varying(jnp.zeros((), jnp.int32)))
            (_, _, _, _, g_stage, g_embed, g_head, loss_sum,
             correct_sum), _ = lax.scan(tick, carry0,
                                        jnp.arange(2 * s + m - 1))

            # collectives the GPipe path gets from VMA autodiff: repl
            # grads live on one stage each -> psum over pipe; everything
            # averages over data; per-token loss normalizes by microbatch
            # count (loss_fn averages within a microbatch)
            loss = lax.psum(loss_sum, pipe) / m
            correct = lax.psum(correct_sum, pipe)
            g_embed = lax.psum(g_embed, pipe)
            g_head = lax.psum(g_head, pipe)
            g_stage = jax.tree.map(lambda v: v / m, g_stage)
            g_embed = jax.tree.map(lambda v: v / m, g_embed)
            g_head = jax.tree.map(lambda v: v / m, g_head)
            if data is not None:
                loss = lax.pmean(loss, data)
                correct = lax.psum(correct, data)
                g_stage = jax.tree.map(lambda v: lax.pmean(v, data), g_stage)
                g_embed = jax.tree.map(lambda v: lax.pmean(v, data), g_embed)
                g_head = jax.tree.map(lambda v: lax.pmean(v, data), g_head)

            # back to the {"repl", "stages"} layout: stage grads gain the
            # leading stage axis (this device's slice), repl grads merge
            grads = {
                "repl": {"embed": g_embed, "head": g_head},
                "stages": jax.tree.map(lambda v: v[None].astype(jnp.float32),
                                       g_stage),
            }
            grads = jax.tree.map(lambda g_, p_: g_.astype(p_.dtype),
                                 grads, params)

            new_repl, opt_repl = optimizer.update(
                grads["repl"], opt_state["repl"], params["repl"])
            new_stages, opt_stages = optimizer.update(
                grads["stages"], opt_state["stages"], params["stages"])
            new_state = PipeTrainState(
                {"repl": new_repl, "stages": new_stages},
                {"repl": opt_repl, "stages": opt_stages}, step + 1)
            return new_state, {"loss": loss, "correct": correct}

        def build(state):
            state_spec = PipeTrainState(self._param_specs(state.params),
                                        self._opt_specs(state.opt_state),
                                        P())
            batch_spec = P(data) if data is not None else P()
            fn = jax.shard_map(local_step, mesh=self.group.mesh,
                               in_specs=(state_spec, batch_spec, batch_spec),
                               out_specs=(state_spec, P()))
            return jax.jit(fn, donate_argnums=(0,) if self.donate else ())

        return build

    def train_step(self, state: PipeTrainState, x, y):
        """One fused pipeline step (all S stages, all M microbatches, grads,
        update) → ``(new_state, {"loss", "correct"})``."""
        if self.optimizer is None or self.loss_fn is None:
            raise ValueError("train_step requires optimizer= and loss_fn=")
        if self._train_step is None:
            build = (self._build_1f1b_step() if self.schedule == "1f1b"
                     else self._build_train_step())
            self._train_step = build(state)
        return self._train_step(state, x, y)
