"""Host-path tensor parallelism driven by the unified rule table.

The mesh path already does Megatron-style tp by annotation
(``gspmd.TRANSFORMER_TP_RULES``: XLA cuts the matmuls and inserts the
all-reduces).  This module is its **eager host twin**: the same
``parallel/rules.py`` table decides which logical axes shard, the forward
runs column-parallel (fused qkv / mlp-up / lm-head) and row-parallel
(attn-out / mlp-down) matmuls per rank, and the partial sums combine over
``new_group`` sub-groups on the typed data plane.  Because both paths are
derived from ONE table, changing only the rule table re-partitions the
compiled program and the host program together — and the host forward is
verified **bitwise** against rule-driven pjit in
``benchmarks/bench_mesh_rules.py --smoke`` (veScale's eager-mode-consistent
SPMD, PAPERS.md).

Layout contract (what makes the twin bitwise):

- every tp rank holds the shard :func:`rules.spans_for` assigns it
  (``partial="replicate"``: row-parallel output biases replicate);
- row-parallel matmuls emit **bias-free partials**; the bias is added
  AFTER the combine — the association XLA's psum+bias takes;
- partial sums fold in **rank order** on every rank (the serving
  ``_exchange_all_reduce`` discipline), so all ranks hold identical bytes;
  at tp=2 the bandwidth-optimal ring produces the same bits (two-operand
  fp adds commute);
- per-head attention and per-column projections are exact slices of the
  full computation, so only the row-parallel reductions reassociate —
  and those reassociate identically on host and mesh.

Composes three ways: ``dp`` × ``tp`` in :class:`TPTrainer` (tp gangs and
dp gangs are ``new_group`` sub-groups of one flat world; gradients ride
the bucketer over the dp gang), ``tp`` inside a pipeline stage via
:func:`build_tp_stage_fns` (dp×tp×pp), and a threaded in-process oracle
:class:`SerialTPRunner` for bitwise tests without sockets.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .rules import (DEFAULT_RULES, ShardLayoutError, model_axes, shard_leaf,
                    spans_for)

__all__ = ["TPConfigError", "LocalCombiner", "PlaneCombiner",
           "tp_shard_params", "TPTrainer", "SerialTPRunner",
           "build_tp_stage_fns"]

# direct-exchange / ring crossover for tp partial-sum combines — same
# constant and rationale as serve/sharded.py: training partials are
# (B, T, dim) activations, usually above this, but tiny test models and
# the deferred norm-grad tree sit below it where the exchange's single
# one-way latency wins
_EXCHANGE_MAX_BYTES = 128 << 10

#: logical axes the host engine knows how to split (a table binding any
#: OTHER axis to the tp mesh dim is a config error here, though the pjit
#: path may well support it)
_HOST_SHARDABLE = ("heads", "mlp", "vocab")


class TPConfigError(ValueError):
    """The model/table cannot run host tensor-parallel as asked (axis not
    divisible by tp, unsupported sharded axis, MoE/sequence-parallel
    model, world not divisible by tp) — named at construction."""


def _tp_span(op: str, value, group: str):
    try:
        from ..obs.hooks import collective_span
    except Exception:
        import contextlib
        return contextlib.nullcontext()
    return collective_span(op, value=value, reduce_op="sum", group=group)


def _note_algo(algo: str) -> None:
    try:
        from ..obs.hooks import note_algo
        note_algo(algo)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# combiners: rank-order partial-sum folds (the serving exchange discipline)
# ---------------------------------------------------------------------------

class _LocalPort:
    """One rank's handle on a :class:`LocalCombiner`."""

    def __init__(self, combiner: "LocalCombiner", rank: int):
        self._c = combiner
        self.rank = int(rank)
        self.world = combiner.world
        self.bytes_sent = 0

    def all_reduce(self, arr: np.ndarray) -> np.ndarray:
        self.bytes_sent += (self.world - 1) * arr.nbytes
        return self._c._combine(self.rank, arr, "sum")

    def all_gather_last(self, arr: np.ndarray) -> np.ndarray:
        self.bytes_sent += (self.world - 1) * arr.nbytes
        return self._c._combine(self.rank, arr, "gather")

    def tree_all_reduce(self, tree: Dict[str, Dict[str, np.ndarray]]):
        return {p: {k: self.all_reduce(v) for k, v in d.items()}
                for p, d in tree.items()}


class LocalCombiner:
    """In-process tp gang for the threaded oracle: shared slots + a
    barrier, rank 0 folds **in rank order** (``acc = s0.copy(); acc =
    acc + s1; ...`` — exactly the serving exchange fold), every rank
    reads the same result bytes."""

    def __init__(self, world: int, timeout: float = 120.0):
        self.world = int(world)
        self.timeout = float(timeout)
        self._barrier = threading.Barrier(self.world)
        self._slots: List[Optional[np.ndarray]] = [None] * self.world
        self._out: Optional[np.ndarray] = None

    def bound(self, rank: int) -> _LocalPort:
        return _LocalPort(self, rank)

    def _combine(self, rank: int, arr, how: str) -> np.ndarray:
        arr = np.asarray(arr)
        if self.world == 1:
            return arr.copy()
        self._slots[rank] = arr
        self._barrier.wait(timeout=self.timeout)
        if rank == 0:
            if how == "sum":
                acc: Optional[np.ndarray] = None
                for s in self._slots:
                    acc = s.copy() if acc is None else acc + s
                self._out = acc
            else:
                self._out = np.concatenate(self._slots, axis=-1)
        self._barrier.wait(timeout=self.timeout)
        out = np.array(self._out)
        # third wait: nobody re-deposits into the slots before every rank
        # has copied this round's result out
        self._barrier.wait(timeout=self.timeout)
        return out


def _exchange_sum(dp, arr: np.ndarray, tag: str, timeout: float):
    """Direct-exchange SUM over a group data plane — fold order is RANK
    order on every rank (byte-identical everywhere), mirroring
    serve/sharded.py's ``_exchange_all_reduce``."""
    flat = np.ascontiguousarray(arr.reshape(-1))
    for dst in range(dp.num_processes):
        if dst != dp.rank:
            dp.send_array(dst, tag, flat)
    acc = None
    for src in range(dp.num_processes):
        part = flat if src == dp.rank else dp.recv_array(src, tag, timeout)
        acc = part.copy() if acc is None else acc + part
    return acc.reshape(arr.shape)


class PlaneCombiner:
    """Tp partial-sum combiner over a ``new_group`` sub-group of the data
    plane.  Small payloads take the latency-optimal direct exchange,
    large ones the bandwidth-optimal ring; every combine is an obs
    ``group=`` span stamped with the chosen ``algo=`` so ``obs diagnose``
    attributes tp traffic to the gang rather than the world's lockstep
    sequence.  ``bytes_sent`` accumulates this rank's wire bytes (the
    bench_mesh_rules per-step wire metric)."""

    def __init__(self, group, dp, timeout: float = 120.0):
        self.group = group
        self.world = group.num_processes
        self.rank = group.require_member("tp combine")
        self._view = group.view(dp) if self.world > 1 else None
        self.timeout = float(timeout)
        self.bytes_sent = 0
        self._seq = 0

    def _tag(self) -> str:
        self._seq += 1
        return f"tp{self._seq}"

    def all_reduce(self, arr: np.ndarray) -> np.ndarray:
        arr = np.ascontiguousarray(np.asarray(arr))
        if self.world == 1:
            return arr.copy()
        gid = f"tp:{self.group.group_id}"
        with _tp_span("tp_all_reduce", arr, gid):
            if arr.nbytes <= _EXCHANGE_MAX_BYTES:
                _note_algo("exchange")
                out = _exchange_sum(self._view, arr, self._tag(),
                                    self.timeout)
                self.bytes_sent += (self.world - 1) * arr.nbytes
            else:
                from ..collectives.ring import ring_all_reduce
                _note_algo("ring")
                out = ring_all_reduce(self._view, arr, op="sum",
                                      tag=self._tag())
                self.bytes_sent += (2 * arr.nbytes
                                    * (self.world - 1)) // self.world
        return out

    def all_gather_last(self, arr: np.ndarray) -> np.ndarray:
        """Concatenate every rank's block along the last axis, in rank
        order (column-parallel lm-head logits)."""
        arr = np.ascontiguousarray(np.asarray(arr))
        if self.world == 1:
            return arr.copy()
        gid = f"tp:{self.group.group_id}"
        with _tp_span("tp_all_gather", arr, gid):
            _note_algo("exchange")
            tag = self._tag()
            flat = arr.reshape(-1)
            for dst in range(self.world):
                if dst != self.rank:
                    self._view.send_array(dst, tag, flat)
            parts = []
            for src in range(self.world):
                p = (flat if src == self.rank
                     else self._view.recv_array(src, tag, self.timeout))
                parts.append(p.reshape(arr.shape))
            self.bytes_sent += (self.world - 1) * arr.nbytes
        return np.concatenate(parts, axis=-1)

    def tree_all_reduce(self, tree: Dict[str, Dict[str, np.ndarray]]):
        return {p: {k: self.all_reduce(v) for k, v in d.items()}
                for p, d in tree.items()}


# ---------------------------------------------------------------------------
# parameter sharding (rule-table driven)
# ---------------------------------------------------------------------------

def tp_shard_params(model, params, rank: int, world: int, rules=None):
    """This tp rank's local parameter tree: every leaf sliced per
    :func:`rules.spans_for` under the table's ``model``-axis bindings
    (``partial="replicate"``: row-parallel output biases live full on
    every rank and are added once, post-combine).  Keys are unchanged —
    merging all ranks' column/row slices reassembles ``model.init()``'s
    tree exactly."""
    if rules is None:
        rules = DEFAULT_RULES
    axes = model_axes(model)
    out: Dict[str, Dict[str, np.ndarray]] = {}
    try:
        for path, leaves in params.items():
            d = {}
            for name, arr in leaves.items():
                a = np.asarray(arr)
                plan = spans_for(path, name, a.shape, axes, rank, world,
                                 rules=rules, mesh_axis="model",
                                 partial="replicate")
                d[name] = shard_leaf(a, plan)
            out[path] = d
    except ShardLayoutError as e:
        raise TPConfigError(str(e)) from None
    return out


# ---------------------------------------------------------------------------
# jitted per-rank segments (shared cache: same shapes -> same executable)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class _SegCfg:
    norm: str          # "layernorm" | "rmsnorm"
    block_eps: float
    final_eps: float
    heads: int         # LOCAL head count
    head_dim: int
    rope: bool
    rope_theta: float
    causal: bool


_SEG_CACHE: Dict[_SegCfg, Dict[str, Callable]] = {}
_SEG_MU = threading.Lock()


def _norm_fwd(kind: str, eps: float, p, x):
    # byte-for-byte the op sequence of nn.LayerNorm / nn.RMSNorm.forward
    import jax
    import jax.numpy as jnp
    if kind == "layernorm":
        mean = x.mean((x.ndim - 1,), keepdims=True)
        var = ((x - mean) ** 2).mean((x.ndim - 1,), keepdims=True)
        y = (x - mean) * jax.lax.rsqrt(var + eps)
        return y * p["weight"] + p["bias"]
    xf = x.astype(jnp.float32)
    y = xf * jax.lax.rsqrt(jnp.mean(jnp.square(xf), (x.ndim - 1,),
                                    keepdims=True) + eps)
    y = y.astype(x.dtype)
    return y * p["weight"].astype(x.dtype)


def _segments(cfg: _SegCfg) -> Dict[str, Callable]:
    """The jitted segment set for one engine shape-config.  Cached on the
    config so every engine (trainer ranks, serial oracle lanes, pipeline
    stages) with the same local shapes shares ONE compiled executable —
    which is also what makes their outputs bitwise-identical."""
    with _SEG_MU:
        got = _SEG_CACHE.get(cfg)
        if got is not None:
            return got
    import jax
    import jax.numpy as jnp
    from ..nn.attention import rotary_embed, scaled_dot_product_attention

    def attn_branch(p, x):
        h = _norm_fwd(cfg.norm, cfg.block_eps, p["ln"], x)
        qkv = jnp.dot(h, p["qkv_w"])
        if "qkv_b" in p:
            qkv = qkv + p["qkv_b"]
        b, t = x.shape[0], x.shape[1]
        qkv = qkv.reshape(b, t, 3, cfg.heads, cfg.head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if cfg.rope:
            pos = jnp.arange(t)
            q = rotary_embed(q, pos, cfg.rope_theta)
            k = rotary_embed(k, pos, cfg.rope_theta)
        out = scaled_dot_product_attention(q, k, v, causal=cfg.causal,
                                           impl="dense")
        out = out.reshape(b, t, cfg.heads * cfg.head_dim)
        return jnp.dot(out, p["out_w"])  # bias-free partial

    def mlp_branch(p, x):
        h = _norm_fwd(cfg.norm, cfg.block_eps, p["ln"], x)
        u = jnp.dot(h, p["w0"])
        if "b0" in p:
            u = u + p["b0"]
        g = jax.nn.gelu(u, approximate=False)
        return jnp.dot(g, p["w2"])  # bias-free partial

    def head_branch(p, x):
        f = _norm_fwd(cfg.norm, cfg.final_eps, p["ln"], x)
        z = jnp.dot(f, p["w"])
        if "b" in p:
            z = z + p["b"]
        return z

    def tok_full(w, idx):
        return jnp.take(w, idx, axis=0)

    def tok_shard(w, idx, lo):
        rows = w.shape[0]
        rel = idx - lo
        ok = (rel >= 0) & (rel < rows)
        e = jnp.take(w, jnp.clip(rel, 0, rows - 1), axis=0)
        return jnp.where(ok[..., None], e, jnp.zeros((), e.dtype))

    def pos_rows(w, idx):
        return jnp.take(w, jnp.arange(idx.shape[1]), axis=0)

    def bwd_of(branch):
        def bwd(p, x, g):
            _, pull = jax.vjp(branch, p, x)
            return pull(g)
        return bwd

    def tok_full_bwd(w, idx, g):
        _, pull = jax.vjp(lambda ww: tok_full(ww, idx), w)
        return pull(g)[0]

    def tok_shard_bwd(w, idx, lo, g):
        _, pull = jax.vjp(lambda ww: tok_shard(ww, idx, lo), w)
        return pull(g)[0]

    def pos_bwd(w, idx, g):
        _, pull = jax.vjp(lambda ww: pos_rows(ww, idx), w)
        return pull(g.sum(axis=0))[0]

    segs = {"attn_fwd": jax.jit(attn_branch),
            "attn_bwd": jax.jit(bwd_of(attn_branch)),
            "mlp_fwd": jax.jit(mlp_branch),
            "mlp_bwd": jax.jit(bwd_of(mlp_branch)),
            "head_fwd": jax.jit(head_branch),
            "head_bwd": jax.jit(bwd_of(head_branch)),
            "tok_full": jax.jit(tok_full),
            "tok_full_bwd": jax.jit(tok_full_bwd),
            "tok_shard": jax.jit(tok_shard),
            "tok_shard_bwd": jax.jit(tok_shard_bwd),
            "pos_rows": jax.jit(pos_rows),
            "pos_bwd": jax.jit(pos_bwd)}
    with _SEG_MU:
        return _SEG_CACHE.setdefault(cfg, segs)


# keyed by id(); each entry keeps a reference to its loss_fn so the id
# can never be recycled under the cache
_LOSSGRAD_CACHE: Dict[int, Tuple[Callable, Callable]] = {}


def _lossgrad(loss_fn) -> Callable:
    """jit(value_and_grad) of ``loss_fn(logits.reshape(-1, V), y.reshape(
    -1))`` — the pipeline trainer's flattening, shared so host and mesh
    parity cells run the identical loss executable."""
    got = _LOSSGRAD_CACHE.get(id(loss_fn))
    if got is None:
        import jax

        def flat_loss(logits, y):
            v = logits.shape[-1]
            return loss_fn(logits.reshape(-1, v), y.reshape(-1))

        got = (loss_fn, jax.jit(jax.value_and_grad(flat_loss)))
        _LOSSGRAD_CACHE[id(loss_fn)] = got
    return got[1]


# ---------------------------------------------------------------------------
# the per-rank engine
# ---------------------------------------------------------------------------

class _TPEngine:
    """One tp rank's eager engine over a contiguous block span of a
    :class:`~tpu_dist.models.TransformerLM` (optionally with the
    embedding front / lm-head tail — the full model when ``lo=0, hi=
    depth, embed=head=True``; a pipeline stage otherwise).

    Forward: np activations between jitted per-branch segments; partial
    sums combine through the port immediately.  Backward: recompute +
    per-segment ``jax.vjp``; activation cotangents combine immediately
    (upstream needs them), the small norm-parameter partials are pooled
    and combined once per backward.  All port calls happen in identical
    program order on every rank of the gang — the lockstep contract."""

    def __init__(self, model, rules, port, *, lo: int = 0,
                 hi: Optional[int] = None, embed: bool = True,
                 head: bool = True, loss_fn=None):
        from ..nn.layers import RMSNorm
        if getattr(model, "num_experts", 0):
            raise TPConfigError("host tp engine supports dense "
                                "TransformerLM models only (MoE expert "
                                "banks ride gspmd.MOE_EP_RULES)")
        if getattr(model, "sequence_axis", None) is not None:
            raise TPConfigError("host tp composes with host pipeline/dp, "
                                "not mesh sequence parallelism — build "
                                "the model without sequence_axis")
        rules = DEFAULT_RULES if rules is None else rules
        tp = port.world
        for ax, m in rules.items():
            if m == "model" and ax not in _HOST_SHARDABLE:
                raise TPConfigError(
                    f"host tp engine cannot shard logical axis {ax!r}; "
                    f"supported: {_HOST_SHARDABLE}")
        self.axes = model_axes(model)
        self.port = port
        self.tp = tp
        self.heads_sharded = tp > 1 and rules.get("heads") == "model"
        self.mlp_sharded = tp > 1 and rules.get("mlp") == "model"
        self.vocab_sharded = tp > 1 and rules.get("vocab") == "model"
        for flag, ax in ((self.heads_sharded, "heads"),
                         (self.mlp_sharded, "mlp"),
                         (self.vocab_sharded, "vocab")):
            if flag and self.axes[ax] % tp:
                raise TPConfigError(
                    f"logical axis {ax!r} of size {self.axes[ax]} not "
                    f"divisible by tp={tp}")
        self.lo = lo
        self.hi = model.depth if hi is None else hi
        self.embed = embed
        self.head = head
        self.has_pos = model.pos is not None
        attn = model.block0.attn
        heads_local = (self.axes["heads"] // tp if self.heads_sharded
                       else self.axes["heads"])
        self.cfg = _SegCfg(
            norm="rmsnorm" if isinstance(model.ln_f, RMSNorm)
            else "layernorm",
            block_eps=float(model.block0.ln1.eps),
            final_eps=float(model.ln_f.eps),
            heads=heads_local, head_dim=int(attn.head_dim),
            rope=bool(attn.rope), rope_theta=float(attn.rope_theta),
            causal=bool(attn.causal))
        self.seg = _segments(self.cfg)
        self._lossgrad = _lossgrad(loss_fn) if loss_fn is not None else None
        self._vloc = (self.axes["vocab"] // tp if self.vocab_sharded
                      else self.axes["vocab"])

    # -- segment param views (local leaves, original key layout) ----------

    def _attn_p(self, params, i):
        p = params[f"block{i}.attn"]
        d = {"ln": params[f"block{i}.ln1"], "qkv_w": p["qkv_weight"],
             "out_w": p["out_weight"]}
        if "qkv_bias" in p:
            d["qkv_b"] = p["qkv_bias"]
        return d

    def _mlp_p(self, params, i):
        up, down = params[f"block{i}.mlp.0"], params[f"block{i}.mlp.2"]
        d = {"ln": params[f"block{i}.ln2"], "w0": up["weight"],
             "w2": down["weight"]}
        if "bias" in up:
            d["b0"] = up["bias"]
        return d

    def _head_p(self, params):
        p = params["head"]
        d = {"ln": params["ln_f"], "w": p["weight"]}
        if "bias" in p:
            d["b"] = p["bias"]
        return d

    # -- forward ----------------------------------------------------------

    def _run(self, params, x):
        """(output, stash): output is logits (head stages) or the span's
        activation; stash holds each branch's input for the vjp pass."""
        st: Dict[str, object] = {"a_in": {}, "m_in": {}}
        if self.embed:
            idx = np.asarray(x)
            st["idx"] = idx
            wtok = params["tok"]["weight"]
            if self.vocab_sharded:
                lo_row = self.port.rank * self._vloc
                part = np.asarray(self.seg["tok_shard"](wtok, idx, lo_row))
                h = self.port.all_reduce(part)
            else:
                h = np.asarray(self.seg["tok_full"](wtok, idx))
            if self.has_pos:
                h = h + np.asarray(self.seg["pos_rows"](
                    params["pos"]["weight"], idx))
        else:
            h = np.asarray(x)
        for i in range(self.lo, self.hi):
            st["a_in"][i] = h
            part = np.asarray(self.seg["attn_fwd"](self._attn_p(params, i),
                                                   h))
            comb = self.port.all_reduce(part) if self.heads_sharded \
                else part
            ob = params[f"block{i}.attn"].get("out_bias")
            if ob is not None:
                comb = comb + np.asarray(ob)
            h = st["a_in"][i] + comb
            st["m_in"][i] = h
            part = np.asarray(self.seg["mlp_fwd"](self._mlp_p(params, i),
                                                  h))
            comb = self.port.all_reduce(part) if self.mlp_sharded else part
            b2 = params[f"block{i}.mlp.2"].get("bias")
            if b2 is not None:
                comb = comb + np.asarray(b2)
            h = st["m_in"][i] + comb
        if self.head:
            st["h_in"] = h
            z = np.asarray(self.seg["head_fwd"](self._head_p(params), h))
            out = self.port.all_gather_last(z) if self.vocab_sharded else z
            return out, st
        return h, st

    def forward(self, params, x):
        return self._run(params, x)[0]

    def loss(self, params, x, y):
        logits, _ = self._run(params, x)
        val, _ = self._lossgrad(logits, np.asarray(y))
        return float(val)

    # -- backward (recompute + per-segment vjp) ---------------------------

    def backward(self, params, x, gy, *, from_loss: bool):
        """(loss_or_None, grads, dx_or_None).  ``gy`` is the target batch
        under ``from_loss`` (last stage), the output cotangent otherwise.
        ``dx`` is None on embedding stages (nothing upstream)."""
        out, st = self._run(params, x)
        loss = None
        if from_loss:
            val, dlogits = self._lossgrad(out, np.asarray(gy))
            loss = float(val)
            g = np.asarray(dlogits)
        else:
            g = np.asarray(gy)
        grads: Dict[str, Dict[str, np.ndarray]] = {}
        pool: Dict[str, Dict[str, np.ndarray]] = {}

        def norm_grad(path, d_ln, partial):
            got = {k: np.asarray(v) for k, v in d_ln.items()}
            (pool if partial else grads)[path] = got

        if self.head:
            if self.vocab_sharded:
                lo_col = self.port.rank * self._vloc
                gloc = np.ascontiguousarray(
                    g[..., lo_col:lo_col + self._vloc])
            else:
                gloc = g
            dp, dxp = self.seg["head_bwd"](self._head_p(params),
                                           st["h_in"], gloc)
            grads["head"] = {"weight": np.asarray(dp["w"])}
            if "b" in dp:
                grads["head"]["bias"] = np.asarray(dp["b"])
            norm_grad("ln_f", dp["ln"], self.vocab_sharded)
            dxp = np.asarray(dxp)
            g = self.port.all_reduce(dxp) if self.vocab_sharded else dxp
        for i in reversed(range(self.lo, self.hi)):
            down_path = f"block{i}.mlp.2"
            b2 = params[down_path].get("bias")
            dp, dxp = self.seg["mlp_bwd"](self._mlp_p(params, i),
                                          st["m_in"][i], g)
            grads[f"block{i}.mlp.0"] = {"weight": np.asarray(dp["w0"])}
            if "b0" in dp:
                grads[f"block{i}.mlp.0"]["bias"] = np.asarray(dp["b0"])
            grads[down_path] = {"weight": np.asarray(dp["w2"])}
            if b2 is not None:
                # row-parallel bias added post-combine on a replicated
                # cotangent: its grad is exact on every rank, no combine
                grads[down_path]["bias"] = g.sum(axis=(0, 1))
            norm_grad(f"block{i}.ln2", dp["ln"], self.mlp_sharded)
            dxc = np.asarray(dxp)
            if self.mlp_sharded:
                dxc = self.port.all_reduce(dxc)
            g = g + dxc
            attn_path = f"block{i}.attn"
            ob = params[attn_path].get("out_bias")
            dp, dxp = self.seg["attn_bwd"](self._attn_p(params, i),
                                           st["a_in"][i], g)
            grads[attn_path] = {"qkv_weight": np.asarray(dp["qkv_w"]),
                                "out_weight": np.asarray(dp["out_w"])}
            if "qkv_b" in dp:
                grads[attn_path]["qkv_bias"] = np.asarray(dp["qkv_b"])
            if ob is not None:
                grads[attn_path]["out_bias"] = g.sum(axis=(0, 1))
            norm_grad(f"block{i}.ln1", dp["ln"], self.heads_sharded)
            dxc = np.asarray(dxp)
            if self.heads_sharded:
                dxc = self.port.all_reduce(dxc)
            g = g + dxc
        dx = g
        if self.embed:
            idx, wtok = st["idx"], params["tok"]["weight"]
            if self.vocab_sharded:
                lo_row = self.port.rank * self._vloc
                grads["tok"] = {"weight": np.asarray(
                    self.seg["tok_shard_bwd"](wtok, idx, lo_row, g))}
            else:
                grads["tok"] = {"weight": np.asarray(
                    self.seg["tok_full_bwd"](wtok, idx, g))}
            if self.has_pos:
                grads["pos"] = {"weight": np.asarray(
                    self.seg["pos_bwd"](params["pos"]["weight"], idx, g))}
            dx = None
        if pool:
            # one deferred combine for all partial norm grads: they do not
            # gate any other backward work, so batching them keeps the
            # gang's small-message count flat in depth
            grads.update(self.port.tree_all_reduce(pool))
        return loss, grads, dx


# ---------------------------------------------------------------------------
# trainers
# ---------------------------------------------------------------------------

def _scale_tree(tree, factor: float):
    import jax
    return jax.tree.map(
        lambda a: np.asarray(a) * np.asarray(factor, np.asarray(a).dtype),
        tree)


def _sum_trees(trees):
    """Rank-order fold across dp lanes (lane 0 + lane 1 + ...)."""
    import jax
    acc = jax.tree.map(lambda a: np.array(a), trees[0])
    for t in trees[1:]:
        acc = jax.tree.map(lambda a, b: a + np.asarray(b), acc, t)
    return acc


def _np_params(tree):
    import jax
    return jax.tree.map(np.asarray, tree)


class TPTrainer:
    """dp×tp host-path training over one flat world: ranks ``[d*tp + t]``,
    tp gangs contiguous.  Every rank builds ALL tp groups then ALL dp
    groups in identical program order (the ``new_group`` contract,
    tpudlint TD008), keeps the rule-table shard of the replicated-init
    params, and steps with rule-driven partial-sum combines over its tp
    gang plus bucketed gradient sums over its dp gang (summed, then
    scaled by 1/dp on host — at dp=2 bitwise equal to the serial oracle's
    rank-order fold).

    ``step(x, y)``: all tp ranks of a lane feed the SAME microbatch (the
    lane's dp shard); returns the lane's loss.  Changing only ``rules``
    re-partitions the whole run — ``{}``/all-None falls back to pure dp
    with fully replicated params."""

    def __init__(self, model, optimizer, loss_fn, *, dp, tp: int = 1,
                 rules=None, grad_sync: str = "bucket",
                 bucket_bytes: Optional[int] = None, seed: int = 0,
                 timeout: float = 120.0, tp_group=None, dp_group=None):
        import jax
        from ..collectives.topology import new_group
        if grad_sync not in ("bucket", "none"):
            raise TPConfigError(f"unknown grad_sync {grad_sync!r}")
        world, rank = dp.num_processes, dp.rank
        if tp < 1 or world % tp:
            raise TPConfigError(
                f"world {world} not divisible by tp={tp}")
        self.rules = DEFAULT_RULES if rules is None else rules
        self.optimizer = optimizer
        self.dp_size = world // tp
        self.tp = tp
        self.dp_idx, self.tp_idx = divmod(rank, tp)
        self.timeout = float(timeout)
        if tp_group is None or dp_group is None:
            class _Parent:
                pass

            parent = _Parent()
            parent.rank, parent.num_processes = rank, world
            # identical program order on EVERY rank: all tp gangs, then
            # all dp gangs — group ids derive from (members, creation
            # index), so any divergence splits the gangs apart loudly.
            # NOTE in-process rigs (threads sharing new_group's process-
            # global creation counters) must instead pass pre-built
            # ``SubGroup(members, rank, world, instance=0)`` objects.
            tp_groups = [new_group([d * tp + t for t in range(tp)],
                                   group=parent)
                         for d in range(self.dp_size)]
            dp_groups = [new_group([d * tp + t
                                    for d in range(self.dp_size)],
                                   group=parent)
                         for t in range(tp)]
            tp_group = tp_groups[self.dp_idx]
            dp_group = dp_groups[self.tp_idx]
        self.tp_group = tp_group
        self.dp_group = dp_group
        self.port = PlaneCombiner(self.tp_group, dp, timeout=timeout)
        self.engine = _TPEngine(model, self.rules, self.port,
                                loss_fn=loss_fn)
        full = _np_params(model.init(jax.random.PRNGKey(seed)))
        self.params = tp_shard_params(model, full, self.tp_idx, tp,
                                      self.rules)
        self.opt_state = optimizer.init(self.params)
        self._bucketer = None
        if self.dp_size > 1 and grad_sync == "bucket":
            from ..collectives.bucketer import Bucketer
            self._bucketer = Bucketer(bucket_bytes,
                                      dp=self.dp_group.view(dp))

    @property
    def tp_bytes_sent(self) -> int:
        return self.port.bytes_sent

    def step(self, x, y) -> float:
        loss, grads, _ = self.engine.backward(self.params, x, y,
                                              from_loss=True)
        if self._bucketer is not None:
            work = self._bucketer.all_reduce(grads, op="sum")
            grads = work.wait_all(self.timeout)
            grads = _scale_tree(grads, 1.0 / self.dp_size)
        new_p, new_o = self.optimizer.update(grads, self.opt_state,
                                             self.params)
        self.params = _np_params(new_p)
        self.opt_state = new_o
        return loss


class SerialTPRunner:
    """In-process dp×tp oracle: a (dp, tp) engine grid on threads over
    :class:`LocalCombiner` gangs — no sockets, rank-order folds
    everywhere, so its step outputs are THE reference bytes the
    plane-backed :class:`TPTrainer` must reproduce.  Params/optimizer
    state are kept once per tp rank (dp lanes are exact replicas by
    construction).  ``step`` splits the global batch over dp lanes and
    returns the per-lane losses."""

    def __init__(self, model, optimizer, loss_fn, *, tp: int = 1,
                 dp: int = 1, rules=None, seed: int = 0):
        import jax
        self.rules = DEFAULT_RULES if rules is None else rules
        self.optimizer = optimizer
        self.tp, self.dp = int(tp), int(dp)
        self._combiners = [LocalCombiner(tp) for _ in range(dp)]
        self._engines = [[_TPEngine(model, self.rules,
                                    self._combiners[d].bound(t),
                                    loss_fn=loss_fn)
                          for t in range(tp)] for d in range(dp)]
        full = _np_params(model.init(jax.random.PRNGKey(seed)))
        self.params = [tp_shard_params(model, full, t, tp, self.rules)
                       for t in range(tp)]
        self.opt_state = [optimizer.init(p) for p in self.params]

    def step(self, x, y) -> List[float]:
        x, y = np.asarray(x), np.asarray(y)
        if x.shape[0] % self.dp:
            raise TPConfigError(
                f"batch {x.shape[0]} not divisible by dp={self.dp}")
        xs = np.split(x, self.dp)
        ys = np.split(y, self.dp)
        results: Dict[Tuple[int, int], Tuple] = {}
        errors: List[BaseException] = []

        def run(d, t):
            try:
                results[(d, t)] = self._engines[d][t].backward(
                    self.params[t], xs[d], ys[d], from_loss=True)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errors.append(e)

        threads = [threading.Thread(target=run, args=(d, t), daemon=True)
                   for d in range(self.dp) for t in range(self.tp)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        if errors:
            raise errors[0]
        losses = [results[(d, 0)][0] for d in range(self.dp)]
        for t in range(self.tp):
            lanes = [results[(d, t)][1] for d in range(self.dp)]
            grads = lanes[0] if self.dp == 1 else _scale_tree(
                _sum_trees(lanes), 1.0 / self.dp)
            new_p, new_o = self.optimizer.update(grads, self.opt_state[t],
                                                 self.params[t])
            self.params[t] = _np_params(new_p)
            self.opt_state[t] = new_o
        return losses


# ---------------------------------------------------------------------------
# pipeline composition (dp×tp×pp)
# ---------------------------------------------------------------------------

def build_tp_stage_fns(part, stage: int, loss_fn, port, rules=None):
    """Tensor-parallel :class:`~tpu_dist.pipeline.stage.StageFns` over
    ``part.spans[stage]`` of a
    :class:`~tpu_dist.pipeline.partition.TransformerPartition` — drop-in
    for ``pipeline.PipelineStage(fns=...)``, so a (pp stage × tp rank)
    grid runs 3D dp×tp×pp training entirely on the host path.

    Every tp peer of a stage runs the same pipeline schedule, hence
    issues the same combiner sequence per F/B op — the recompute inside
    ``bwd`` re-fires its forward combines in lockstep too.  Params are
    this tp rank's shard (:func:`tp_shard_params`) of
    ``part.stage_params(...)``."""
    from ..pipeline.stage import StageFns
    lo, hi = part.spans[stage]
    engine = _TPEngine(part.model, rules, port, lo=lo, hi=hi,
                       embed=part.is_first(stage),
                       head=part.is_last(stage), loss_fn=loss_fn)
    first, last = part.is_first(stage), part.is_last(stage)
    return StageFns(
        fwd=None if last else (lambda p, x: engine.forward(p, x)),
        fwd_loss=(lambda p, x, y: engine.loss(p, x, y)) if last else None,
        bwd=None if last else (
            lambda p, x, g: engine.backward(p, x, g, from_loss=False)[1:]),
        bwd_loss=(lambda p, x, y:
                  engine.backward(p, x, y, from_loss=True)[1:])
        if last else None)
