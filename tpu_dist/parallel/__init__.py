"""tpu_dist.parallel — parallelism wrappers (L3 of SURVEY.md §1).

Data parallelism is the reference's only strategy (SURVEY.md §2c); the mesh
design leaves room for tp/pp/sp axes (ProcessGroup accepts custom
axis_names/mesh_shape)."""

from .ddp import (DistributedDataParallel, TrainState,
                  convert_sync_batchnorm)
from .fsdp import fsdp_shard, fsdp_specs
from .gspmd import (MOE_EP_RULES, PartitionRules, TRANSFORMER_TP_RULES,
                    make_gspmd_train_step, shard_pytree)
from .mesh import get_mesh, mesh_shape_for
from .pipeline import PipelineParallel, PipeTrainState
from .ring_attention import ring_self_attention, ulysses_self_attention
from .rules import (DEFAULT_RULES, SERVING_RULES, LeafLayout,
                    ShardLayoutError, TRANSFORMER_LAYOUTS, chunk_bounds,
                    chunk_span, layout_for, mapped_axes, model_axes,
                    partition_pairs, shard_leaf, spans_for, spec_for,
                    spec_for_key)
from .tensor import (SerialTPRunner, TPConfigError, TPTrainer,
                     build_tp_stage_fns, tp_shard_params)
from .zero import ZeroOptimizer, ZeroParams, ZeroStateError

# torch-style alias (the reference imports nn.parallel.DistributedDataParallel)
DDP = DistributedDataParallel

__all__ = ["DistributedDataParallel", "DDP", "TrainState",
           "convert_sync_batchnorm",
           "PartitionRules", "TRANSFORMER_TP_RULES", "MOE_EP_RULES",
           "make_gspmd_train_step", "shard_pytree",
           "PipelineParallel", "PipeTrainState",
           "fsdp_shard", "fsdp_specs",
           "get_mesh", "mesh_shape_for",
           "DEFAULT_RULES", "SERVING_RULES", "LeafLayout",
           "ShardLayoutError", "TRANSFORMER_LAYOUTS", "chunk_bounds",
           "chunk_span", "layout_for", "mapped_axes", "model_axes",
           "partition_pairs", "shard_leaf", "spans_for", "spec_for",
           "spec_for_key",
           "TPTrainer", "SerialTPRunner", "TPConfigError",
           "tp_shard_params", "build_tp_stage_fns",
           "ring_self_attention", "ulysses_self_attention",
           "ZeroOptimizer", "ZeroParams", "ZeroStateError"]
