"""Sequence-parallel attention: ring attention + Ulysses all-to-all.

Long-context support (first-class here; absent in the reference, SURVEY.md
§5).  Both functions run *inside* ``shard_map`` over a mesh axis that shards
the sequence dimension; both are numerically equal to dense attention on the
gathered sequence (tested in tests/test_ring_attention.py).

**Ring attention** (`ring_self_attention`): each device keeps its Q shard
resident and rotates K/V shards around the ring with ``lax.ppermute`` —
the same two-phase neighbor-exchange structure as ring all-reduce
(/root/reference/README.md:9-20 teaches it for gradients; here it moves KV
blocks), accumulated with the online-softmax (flash) recurrence so the full
T×T score matrix never materializes.  Communication per device is O(T/n)
per hop × n hops = O(T) total, overlapped with the per-block attention
compute.  Per-device attention memory is O(T/n) on the default TPU path
(each block runs the Pallas flash kernel, see ``impl``); the portable
dense-block path materializes O((T/n)²) scores per block.  On TPU the hops
ride neighboring ICI links.

**Ulysses** (`ulysses_self_attention`): ``lax.all_to_all`` re-shards from
sequence-sharded to head-sharded, runs dense per-head attention locally,
and re-shards back.  Cheaper for moderate T (two all-to-alls instead of n
ppermutes) but requires num_heads divisible by the axis size.

Layout: q, k, v are (batch, T_local, heads, head_dim).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ring_self_attention", "ulysses_self_attention"]

_NEG_INF = -1e30  # finite sentinel: keeps the online-softmax max/correction
                  # arithmetic NaN-free when a whole block is causally masked


def _block_attend(q, k, v, scale, q_offset, k_offset, causal):
    """One (Q-shard × KV-block) flash step: returns (num, den, mx) pieces.

    q: (B, Tq, H, D); k/v: (B, Tk, H, D).  Positions are global offsets for
    causal masking.
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        qpos = q_offset + jnp.arange(tq)[:, None]
        kpos = k_offset + jnp.arange(tk)[None, :]
        scores = jnp.where(kpos <= qpos, scores, _NEG_INF)
    mx = scores.max(axis=-1)                                  # (B,H,Tq)
    p = jnp.exp(scores - mx[..., None])
    # fully-masked rows: mx == _NEG_INF and every p entry is exp(0)=1 — zero
    # them so they contribute nothing (den also stays 0 until a real block)
    if causal:
        p = jnp.where((mx == _NEG_INF)[..., None], 0.0, p)
    num = jnp.einsum("bhqk,bkhd->bqhd", p, v)                 # (B,Tq,H,D)
    den = p.sum(axis=-1)                                      # (B,H,Tq)
    return num, den, mx


def ring_self_attention(q, k, v, axis_name: str, causal: bool = False,
                        impl: Optional[str] = None):
    """Exact attention over the sequence sharded on ``axis_name``.

    Call inside ``shard_map``; per-device shapes (B, T/n, H, D).  Returns the
    local (B, T/n, H, D) output shard.

    ``impl``: how each local (Q-shard × KV-block) attention is computed —
    ``"flash"`` runs the Pallas flash kernel per block and merges partial
    results via their logsumexp (O(T/n) memory per device, MXU-tiled);
    ``"dense"`` materializes the (T/n, T/n) block scores (the portable
    path).  Default auto: flash on TPU, dense elsewhere.  Under ``"flash"``
    with ``causal``, blocks entirely above the diagonal skip the kernel
    call outright (``lax.cond``) instead of computing a fully-masked block.
    """
    if impl in (None, "auto"):
        impl = "flash" if jax.default_backend() == "tpu" else "dense"
    if impl == "flash":
        return _ring_flash(q, k, v, axis_name, causal)
    if impl != "dense":
        raise ValueError(f"Unknown ring attention impl {impl!r}")
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    scale = 1.0 / math.sqrt(q.shape[-1])
    t_local = q.shape[1]
    # KV blocks travel BACKWARD around the ring (device d sends to d-1), so
    # at hop i device d holds the block that originated at (d + i) mod n.
    perm = [(i, (i - 1) % n) for i in range(n)]

    q_offset = me * t_local

    # Accumulator layouts: num (B,Tq,H,D); den/mx (B,H,Tq).
    def accumulate(i, num, den, mx, kk, vv):
        src = jnp.mod(me + i, n)
        bnum, bden, bmx = _block_attend(q, kk, vv, scale,
                                        q_offset, src * t_local, causal)
        new_mx = jnp.maximum(mx, bmx)          # (B,H,Tq)
        c_old = jnp.exp(mx - new_mx)
        c_new = jnp.exp(bmx - new_mx)
        # broadcast (B,H,Tq) corrections onto (B,Tq,H,D)
        co = jnp.moveaxis(c_old, -1, 1)[..., None]   # (B,Tq,H,1)
        cn = jnp.moveaxis(c_new, -1, 1)[..., None]
        return num * co + bnum * cn, den * c_old + bden * c_new, new_mx

    def hop(i, carry):
        # permute-then-attend: the loop runs hops 1..n-1, so exactly n-1
        # ppermutes happen in total (no wasted final rotation)
        num, den, mx, kk, vv = carry
        kk, vv = lax.ppermute((kk, vv), axis_name, perm=perm)
        num, den, mx = accumulate(i, num, den, mx, kk, vv)
        return num, den, mx, kk, vv

    num0 = jnp.zeros_like(q)
    # Derive fresh accumulators from q so they inherit its full varying-axes
    # (VMA) set — a plain jnp.zeros would be "unvarying" and the fori_loop
    # carry type would change on the first iteration (works on any mesh,
    # 1-D 'seq' or N-D like ('data', 'seq')).
    zero_bht = jnp.moveaxis(q.sum(-1), 1, -1) * 0.0          # (B,H,Tq)
    num, den, mx = accumulate(0, num0, zero_bht, zero_bht + _NEG_INF, k, v)
    num, den, mx, _, _ = lax.fori_loop(1, n, hop, (num, den, mx, k, v))
    den = jnp.moveaxis(den, -1, 1)[..., None]        # (B,Tq,H,1)
    return num / jnp.maximum(den, 1e-37)


def _ring_flash(q, k, v, axis_name: str, causal: bool):
    """Ring attention with flash-kernel local blocks.

    Each hop computes its (Q-shard × KV-block) attention with
    tpu_dist.ops.flash_attention_with_lse and folds the partial result into
    the running one with the blockwise-merge identity (see that function's
    docstring) — the same online-softmax recurrence as the dense path, but
    carried as (out, lse) so the local block math lives in VMEM tiles.

    Causal block classification: hop 0 is statically the diagonal block
    (causal flash); for later hops the traced source index picks via
    ``lax.cond`` between plain flash (block fully below the diagonal) and a
    zero-contribution constant (lse = -1e30, block fully above) — the
    latter skips the kernel entirely, so a causal ring does ~half the
    kernel work at flash's memory footprint.
    """
    from ..ops.flash_attention import flash_attention_with_lse

    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    perm = [(i, (i - 1) % n) for i in range(n)]

    def block(i, kk, vv):
        """(out, lse) of q attending to the block that started at rank
        (me + i) mod n, as float32 (the loop-carry/merge dtype — bf16 inputs
        stay bf16 inside the kernel, the carry must not down-cast partials).
        i == 0 is always the diagonal block; for i in [1, n-1] the source
        can never be me again, so causal blocks are a two-way choice."""

        def flash(is_causal):
            o, l = flash_attention_with_lse(q, kk, vv, causal=is_causal)
            return o.astype(jnp.float32), l

        if not causal:
            return flash(False)
        if isinstance(i, int) and i == 0:
            return flash(True)
        src = jnp.mod(me + i, n)

        def full(_):
            return flash(False)

        def skip(_):
            # zero contribution; derive from q so the VMA set matches
            zero = (q * 0.0).astype(jnp.float32)
            return zero, zero.sum(-1) + _NEG_INF        # (B,T,H) lse

        return lax.cond(src < me, full, skip, None)

    def merge(o_a, l_a, o_b, l_b):
        m = jnp.maximum(l_a, l_b)
        w_a = jnp.exp(l_a - m)
        w_b = jnp.exp(l_b - m)
        den = jnp.maximum(w_a + w_b, 1e-37)
        o = (o_a * w_a[..., None] + o_b * w_b[..., None]) / den[..., None]
        return o, m + jnp.log(den)

    o, l = block(0, k, v)

    def hop(i, carry):
        o, l, kk, vv = carry
        kk, vv = lax.ppermute((kk, vv), axis_name, perm=perm)
        o_b, l_b = block(i, kk, vv)
        o, l = merge(o, l, o_b, l_b)
        return o, l, kk, vv

    o, _, _, _ = lax.fori_loop(1, n, hop, (o, l, k, v))
    return o.astype(q.dtype)


def ulysses_self_attention(q, k, v, axis_name: str, causal: bool = False,
                           impl: Optional[str] = None):
    """Sequence-parallel attention via head redistribution (Ulysses).

    Inside ``shard_map``: (B, T/n, H, D) → all-to-all → (B, T, H/n, D) →
    local attention (``impl`` as in scaled_dot_product_attention: auto =
    flash kernel on TPU) → all-to-all back.  Requires H % axis_size == 0.
    """
    n = lax.axis_size(axis_name)
    if q.shape[2] % n:
        raise ValueError(
            f"ulysses needs num_heads ({q.shape[2]}) divisible by the "
            f"sequence-axis size ({n}); use ring_self_attention instead")
    from ..nn.attention import scaled_dot_product_attention

    # split heads across devices, gather full sequence
    def seq_to_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = scaled_dot_product_attention(qh, kh, vh, causal=causal, impl=impl)
    return heads_to_seq(out)
