"""Per-rank collective flight recorder — a lock-cheap ring buffer of
structured events.

Every eager host collective, p2p send/recv, store client op, and heartbeat
beat appends one structured event (sequence number, op, reduce op, payload
digest, transport path, start/end monotonic ns, user call-site, outcome) to
a fixed-size ring buffer.  The recorder answers the question PR 1's
heartbeat and PR 3's sanitizer cannot: *where was every rank* when the gang
stalled — not just which rank went silent.

Arming: ``TPU_DIST_OBS=1`` (launcher ``--flight-recorder``).  Disarmed, the
hooks cost one environment lookup per call and allocate nothing; the only
always-on machinery is the per-(op, transport) byte/latency aggregation that
``tpu_dist.utils.metrics`` used to own (moved here so the counters and the
event stream share one ingestion point and can never disagree).

Hang-safety of the buffer itself: an *in-flight* span (a collective that
began but never finished) is additionally held in an open-span table, so a
flood of later events — e.g. store ``check`` polls while blocked — can
never evict the one event that explains the hang from the crash dump.

Dumps: :meth:`FlightRecorder.dump` writes one JSON file per (generation,
rank) under ``TPU_DIST_OBS_DIR``; crash paths (unhandled exception, fatal
signal, :func:`tpu_dist.dist.abort`) flush automatically once
:func:`tpu_dist.obs.hooks.install_from_env` has run (the rendezvous does
this).  ``python -m tpu_dist.obs`` merges the per-rank dumps into a Chrome
``trace_event`` timeline and emits a hang diagnosis.
"""

from __future__ import annotations

import collections
import json
import os
import tempfile
import threading
import time
from typing import Dict, List, Optional

import numpy as np

__all__ = ["FlightRecorder", "enabled", "get_recorder", "reset", "dump_now",
           "record_transport", "transport_counters",
           "reset_transport_counters", "obs_key", "default_dump_dir",
           "dump_path"]


def dump_path(dir: str, generation: int, rank: int) -> str:
    """Where rank ``rank``'s generation-``generation`` flight-recorder
    dump lands — THE definition of the filename contract; anything that
    waits on a dump file (the launchers' SIGUSR1 settle) must build the
    path here."""
    return os.path.join(dir, f"obs_g{generation}_r{rank}.json")

# the armed values (same parser as the sanitizer's TPU_DIST_SANITIZE gate)
_ON = ("1", "true", "yes", "on")
_DEF_CAPACITY = 4096


def enabled() -> bool:
    """True when the flight recorder is armed (``TPU_DIST_OBS``)."""
    return os.environ.get("TPU_DIST_OBS", "").strip().lower() in _ON


def _capacity() -> int:
    try:
        return max(16, int(os.environ.get("TPU_DIST_OBS_CAPACITY",
                                          str(_DEF_CAPACITY))))
    except ValueError:
        return _DEF_CAPACITY


def default_dump_dir() -> str:
    """Where dumps land: ``TPU_DIST_OBS_DIR``, else a shared tempdir."""
    return (os.environ.get("TPU_DIST_OBS_DIR")
            or os.path.join(tempfile.gettempdir(), "tpu_dist_obs"))


def obs_key(generation: int, rank: int) -> str:
    """Store key a rank posts its compact tail under — generation-namespaced
    so the launcher's ``DELETE_PREFIX`` reaper covers it with the rest of
    ``tpu_dist/g{gen}/``."""
    return f"tpu_dist/g{generation}/obs/{rank}"


def _generation() -> int:
    # one parser of TPU_DIST_RESTART_COUNT exists (rendezvous.generation)
    import importlib
    return importlib.import_module("tpu_dist.dist.rendezvous").generation()


# framework layers whose frames are instrumentation, not the user's line
# (parallel: the ZeroOptimizer / DDP wrappers issue collectives from inside
# tpu_dist.parallel — the user's line is their caller's, e.g. the train loop)
_SITE_SKIP = ("collectives", "obs", "analysis", "dist", "resilience",
              "parallel", "optim", "serve")


def call_site(skip_parts=_SITE_SKIP) -> str:
    """First stack frame outside the named ``tpu_dist`` subpackages — the
    user line the event should be attributed to.  THE shared attribution
    helper: the sanitizer delegates here (with a narrower skip set) so the
    two tools can never attribute the same call to different frames for
    different reasons."""
    import inspect
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # this helper's own frame lives in obs/ — it must always be skipped,
    # whatever narrower set a delegating caller (the sanitizer) passes
    skip = tuple(os.path.join(pkg, p)
                 for p in dict.fromkeys(tuple(skip_parts) + ("obs",)))
    frame = inspect.currentframe()
    try:
        while frame is not None:
            # normpath: a module imported through an unnormalized sys.path
            # entry (e.g. examples/ scripts inserting "<repo>/examples/..")
            # carries that path in co_filename verbatim — it must still
            # match the normalized skip prefixes
            fname = os.path.normpath(frame.f_code.co_filename)
            if not fname.startswith(skip):
                return f"{os.path.basename(fname)}:{frame.f_lineno}"
            frame = frame.f_back
        return "<unknown>"
    finally:
        del frame


def _leaf_sig(leaf) -> tuple:
    """(dtype+shape string, payload bytes) without materializing the leaf
    on host — digesting must never force a device transfer."""
    shape = getattr(leaf, "shape", None)
    dt = getattr(leaf, "dtype", None)
    if shape is None or dt is None:
        return type(leaf).__name__, 0
    try:
        dt = np.dtype(dt)
        n = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
        return f"{dt.name}{list(shape)}", n
    except Exception:
        return "?", 0


def digest(value) -> tuple:
    """``(digest_string, total_payload_bytes)`` over a pytree's leaves
    (first 16 leaves spelled out, the rest counted)."""
    import jax
    leaves = jax.tree.flatten(value)[0]
    parts: List[str] = []
    total = 0
    for i, leaf in enumerate(leaves):
        sig, n = _leaf_sig(leaf)
        total += n
        if i < 16:
            parts.append(sig)
    if len(leaves) > 16:
        parts.append(f"+{len(leaves) - 16} more")
    return ",".join(parts), total


class FlightRecorder:
    """Fixed-capacity ring buffer of structured events for one rank.

    Thread-safe; the critical section is a sequence-number increment and a
    deque append.  Events are plain dicts (JSON-ready).  Core keys:
    ``seq`` (per-rank event index), ``kind`` (collective | p2p | store |
    transport | beat | serve | channel | plan | user), ``op``,
    ``t0``/``t1`` (monotonic ns; ``t1`` None while in flight),
    ``outcome`` (pending | ok | error:Type).
    Collective events additionally carry ``coll`` — the process-local
    collective sequence number every rank of an SPMD program increments in
    lockstep, which is what the cross-rank merge aligns on — plus
    ``reduce``, ``digest``, ``bytes``, ``path`` and ``site``.
    """

    def __init__(self, capacity: Optional[int] = None,
                 rank: Optional[int] = None, world: Optional[int] = None,
                 generation: Optional[int] = None):
        self.capacity = capacity if capacity is not None else _capacity()
        self.rank = (rank if rank is not None
                     else int(os.environ.get("RANK", "0") or 0))
        self.world = (world if world is not None
                      else int(os.environ.get("WORLD_SIZE", "1") or 1))
        self.generation = (generation if generation is not None
                           else _generation())
        # role-graph identity (tpu_dist.roles): set from the launcher env
        # here, corrected by init_role_graph — dumps, tails and the
        # supervisor's positions table key on (role, role_rank) alongside
        # the flat rank
        self.role = os.environ.get("TPU_DIST_ROLE") or None
        try:
            self.role_rank = (int(os.environ["TPU_DIST_ROLE_RANK"])
                              if self.role else None)
        except (KeyError, ValueError):
            self.role_rank = None
        self._buf: collections.deque = collections.deque(maxlen=self.capacity)
        self._open: Dict[int, dict] = {}
        # RLock, not Lock: the crash-dump signal handlers run ON the main
        # thread and may interrupt a frame that already holds this lock
        # mid-record — snapshot() must be able to re-enter, not deadlock
        self._mu = threading.RLock()
        self._seq = 0
        self._coll = 0
        self._last: Optional[dict] = None       # newest event
        self._last_coll: Optional[dict] = None  # newest collective event
        self._dumped = False
        # wall/mono anchor pair: lets the merge place each rank's monotonic
        # timestamps on a shared (approximate) wall-clock axis
        self.wall_anchor_ns = time.time_ns()
        self.mono_anchor_ns = time.monotonic_ns()

    # -- ingestion -----------------------------------------------------------

    def next_coll(self) -> int:
        with self._mu:
            c = self._coll
            self._coll += 1
            return c

    def begin(self, kind: str, op: str, **fields) -> dict:
        """Open an in-flight span (outcome ``pending``); finish it with
        :meth:`end`.  The span is pinned in the open-span table so ring
        eviction cannot lose it while it is still pending."""
        now = time.monotonic_ns()
        ev = {"kind": kind, "op": op, "t0": now, "t1": None,
              "outcome": "pending", **fields}
        with self._mu:
            ev["seq"] = self._seq
            self._seq += 1
            self._buf.append(ev)
            self._open[ev["seq"]] = ev
            self._note_last(ev)
        return ev

    def end(self, ev: dict, outcome: str = "ok", **fields) -> None:
        # mutate under the lock: snapshot()/last_position() copy these
        # dicts from other threads (heartbeat tail posts, crash dumps)
        with self._mu:
            ev.update(fields)
            ev["t1"] = time.monotonic_ns()
            ev["outcome"] = outcome
            self._open.pop(ev["seq"], None)

    def update_event(self, ev: dict, **fields) -> None:
        """Stamp extra fields onto an event (e.g. the transport path onto a
        pending span) — under the lock, for the same reason as :meth:`end`."""
        with self._mu:
            ev.update(fields)

    def record(self, kind: str, op: str, t0: Optional[int] = None,
               **fields) -> dict:
        """Append one already-completed event (``t0`` monotonic ns, default
        now)."""
        now = time.monotonic_ns()
        ev = {"kind": kind, "op": op,
              "t0": t0 if t0 is not None else now, "t1": now,
              "outcome": fields.pop("outcome", "ok"), **fields}
        with self._mu:
            ev["seq"] = self._seq
            self._seq += 1
            self._buf.append(ev)
            self._note_last(ev)
        return ev

    def _note_last(self, ev: dict) -> None:
        self._last = ev
        if ev["kind"] == "collective":
            self._last_coll = ev

    # -- reads ---------------------------------------------------------------

    def snapshot(self) -> List[dict]:
        """Events in sequence order: the ring contents plus any in-flight
        spans the ring already evicted (copied — safe to serialize while
        other threads keep recording)."""
        with self._mu:
            merged = {e["seq"]: e for e in self._buf}
            merged.update(self._open)
            return [dict(merged[s]) for s in sorted(merged)]

    def tail(self, n: int = 1) -> List[dict]:
        return self.snapshot()[-n:]

    def last_position(self) -> Optional[dict]:
        """Compact "where was this rank" record: the newest *collective*
        event (falling back to the newest event of any kind) — what gets
        posted to the store and printed in the supervisor's table.  O(1):
        this runs on every heartbeat beat, so it must not walk the ring."""
        with self._mu:
            last = self._last_coll or self._last
            if last is None:
                return None
            pos = {"rank": self.rank, "generation": self.generation,
                   "seq": last["seq"], "kind": last["kind"],
                   "op": last["op"], "coll": last.get("coll"),
                   "site": last.get("site"), "outcome": last["outcome"],
                   "events": self._seq}
            if self.role is not None:
                pos["role"] = f"{self.role}[{self.role_rank}]"
            return pos

    # -- dumps ---------------------------------------------------------------

    def dump(self, reason: str, dir: Optional[str] = None) -> str:
        """Flush the buffer to ``{dir}/obs_g{generation}_r{rank}.json``
        (atomic tmp+rename); returns the path."""
        out_dir = dir or default_dump_dir()
        os.makedirs(out_dir, exist_ok=True)
        path = dump_path(out_dir, self.generation, self.rank)
        doc = {"version": 1, "rank": self.rank, "world": self.world,
               "role": self.role, "role_rank": self.role_rank,
               "generation": self.generation, "pid": os.getpid(),
               "reason": reason, "capacity": self.capacity,
               "wall_anchor_ns": self.wall_anchor_ns,
               "mono_anchor_ns": self.mono_anchor_ns,
               "mono_dump_ns": time.monotonic_ns(),
               "events": self.snapshot()}
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        self._dumped = True
        return path


# -- process-wide singleton ---------------------------------------------------

_rec: Optional[FlightRecorder] = None
_rec_mu = threading.Lock()


def get_recorder() -> Optional[FlightRecorder]:
    """The process's recorder, or None when disarmed — the single gate every
    hook checks (one env lookup on the disarmed path)."""
    if not enabled():
        return None
    global _rec
    if _rec is None:
        with _rec_mu:
            if _rec is None:
                _rec = FlightRecorder()
    return _rec


def safe_record(kind: str, op: str, t0: Optional[int] = None,
                **fields) -> None:
    """Armed-gated, never-raises event record — THE shim instrumentation
    choke points (transport reader threads, store client wrapper) share,
    so the "diagnostics must never break the data path" guarantee lives
    in exactly one place."""
    try:
        rec = get_recorder()
        if rec is not None:
            rec.record(kind, op, t0=t0, **fields)
    except Exception:
        pass


def dump_now(reason: str, force: bool = True) -> Optional[str]:
    """Best-effort dump of the armed recorder (None when disarmed or the
    write fails — crash paths must never raise).  ``force=False`` skips the
    write when a dump already happened (the atexit catch-all must not
    overwrite a crash dump's reason)."""
    rec = get_recorder()
    if rec is None or (not force and rec._dumped):
        return None
    try:
        return rec.dump(reason)
    except Exception:
        return None


def reset() -> None:
    """Drop the singleton recorder and the transport counters (tests)."""
    global _rec
    with _rec_mu:
        _rec = None
    reset_transport_counters()


# -- per-(op, transport) counters ---------------------------------------------
#
# Moved here from tpu_dist.utils.metrics (which now shims to these): the
# counters and the flight recorder ingest the SAME record_transport call,
# so bytes/latency totals and the event stream cannot disagree.

_agg_mu = threading.Lock()
_agg: Dict[str, Dict[str, float]] = {}


def record_transport(op: str, path: str, nbytes: int, seconds: float,
                     wire_bytes: Optional[int] = None,
                     raw_wire_bytes: Optional[int] = None) -> None:
    """Account one transport leg: ``op`` over ``path`` ('dataplane' |
    'store' | 'mesh') moving ``nbytes`` *logical* bytes in ``seconds``.
    ``wire_bytes`` is what actually crossed the wire (compressed when a
    wire format was in play); ``raw_wire_bytes`` is what the SAME traffic
    would have cost uncompressed — their ratio is the wire-format
    compression factor, independent of the ring's 2(N-1)/N wire
    amplification over the logical payload.  Both default to ``nbytes``
    (store/mesh legs move logical bytes, uncompressed).  Always feeds the
    aggregate counters; when armed it additionally annotates the
    enclosing collective span (or records a standalone ``transport``
    event)."""
    key = f"{op}/{path}"
    with _agg_mu:
        c = _agg.get(key)
        if c is None:
            c = _agg[key] = {"calls": 0, "bytes": 0, "wire_bytes": 0,
                             "raw_wire_bytes": 0, "seconds": 0.0}
        c["calls"] += 1
        c["bytes"] += int(nbytes)
        c["wire_bytes"] += int(nbytes if wire_bytes is None else wire_bytes)
        c["raw_wire_bytes"] += int(
            nbytes if raw_wire_bytes is None
            else raw_wire_bytes)
        c["seconds"] += float(seconds)
    rec = get_recorder()
    if rec is not None:
        from . import hooks
        hooks.annotate_transport(rec, op, path, nbytes, seconds)


def transport_counters(reset: bool = False) -> Dict[str, Dict[str, float]]:
    """Snapshot of the per-``op/transport`` counters, each entry
    ``{calls, bytes, wire_bytes, raw_wire_bytes, seconds, mb_per_s,
    compression}`` — ``mb_per_s`` is *effective* (logical bytes over wall
    time, the quantity benchmarks compare) and ``compression`` is
    raw ÷ compressed wire bytes (1.0 uncompressed, at every world size);
    ``reset=True`` atomically clears after reading."""
    with _agg_mu:
        out = {k: dict(v) for k, v in _agg.items()}
        if reset:
            _agg.clear()
    for v in out.values():
        v.setdefault("wire_bytes", v["bytes"])  # pre-quant recordings
        v.setdefault("raw_wire_bytes", v["wire_bytes"])
        v["mb_per_s"] = (v["bytes"] / v["seconds"] / 1e6
                         if v["seconds"] > 0 else 0.0)
        v["compression"] = (v["raw_wire_bytes"] / v["wire_bytes"]
                            if v["wire_bytes"] > 0 else 1.0)
    return out


def reset_transport_counters() -> None:
    with _agg_mu:
        _agg.clear()
