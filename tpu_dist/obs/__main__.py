"""``python -m tpu_dist.obs`` — merge flight-recorder dumps, diagnose hangs.

Subcommands (all read the dump directory, default ``TPU_DIST_OBS_DIR``):

- ``merge``     per-rank dumps → one Chrome ``trace_event`` JSON timeline
  (open in chrome://tracing or ui.perfetto.dev); one track per rank,
  collectives aligned by their lockstep sequence numbers.
- ``diagnose``  print which rank is behind, at which collective sequence
  number and call-site, and which ranks were already waiting on it.
  Exit code: 0 healthy, 1 no dumps, 3 hang found (scriptable).
- ``show``      print one rank's recent events (quick look without a UI).

See docs/observability.md for the event schema and a worked example.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import trace as _trace
from .recorder import default_dump_dir


def _add_common(p):
    p.add_argument("--dir", default=None,
                   help="dump directory (default: TPU_DIST_OBS_DIR)")
    p.add_argument("--generation", type=int, default=None,
                   help="gang generation to read (default: newest present)")


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_dist.obs", description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)
    mp = sub.add_parser("merge", help="merge per-rank dumps into a Chrome "
                                      "trace_event JSON timeline")
    _add_common(mp)
    mp.add_argument("--out", default="-",
                    help="output path ('-' = stdout, the default)")
    dp = sub.add_parser("diagnose", help="name the straggler rank, its "
                                         "collective seq and call-site")
    _add_common(dp)
    dp.add_argument("--json", action="store_true",
                    help="machine-readable diagnosis")
    sp = sub.add_parser("show", help="print one rank's recent events")
    _add_common(sp)
    sp.add_argument("--rank", type=int, default=None,
                    help="rank to show (default: every rank)")
    sp.add_argument("-n", type=int, default=20,
                    help="events per rank (default 20)")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    where = args.dir or default_dump_dir()
    dumps = _trace.read_dumps(where, generation=args.generation)
    if not dumps:
        sys.stderr.write(f"no flight-recorder dumps found in {where}\n")
        return 1

    if args.cmd == "merge":
        obj = _trace.merge_trace(dumps)
        if args.out == "-":
            json.dump(obj, sys.stdout)
            sys.stdout.write("\n")
        else:
            with open(args.out, "w") as f:
                json.dump(obj, f)
        n_ev = sum(len(d["events"]) for d in dumps)
        sys.stderr.write(
            f"merged {len(dumps)} rank(s), {n_ev} events "
            f"(generation {dumps[0].get('generation', 0)})"
            + (f" -> {args.out}" if args.out != "-" else "") + "\n")
        return 0

    if args.cmd == "diagnose":
        diag = _trace.diagnose(dumps)
        if args.json:
            # versioned envelope shared with `tpu_dist.analysis replay
            # --format json` (docs/observability.md): the replay document
            # is this one plus findings/counts, so scripts can read
            # .diagnosis from either tool
            doc = {"version": 1, "tool": "diagnose", "path": where,
                   "generation": dumps[0].get("generation", 0),
                   "ranks": sorted(d.get("rank", -1) for d in dumps),
                   "diagnosis": diag}
            print(json.dumps(doc, indent=2, sort_keys=True))
        else:
            print(_trace.render_diagnosis(diag))
        ok = diag.get("verdict") == "healthy" or (
            # no collectives recorded is only benign when every rank
            # dumped through a clean exit, not a crash/signal path
            diag.get("verdict") == "no-collectives"
            and diag.get("clean_exit"))
        return 0 if ok else 3

    # show
    for d in dumps:
        if args.rank is not None and d.get("rank") != args.rank:
            continue
        print(f"== rank {d.get('rank')} (generation "
              f"{d.get('generation', 0)}, reason {d.get('reason')!r}, "
              f"{len(d['events'])} events) ==")
        for e in d["events"][-args.n:]:
            coll = f" coll#{e['coll']}" if e.get("coll") is not None else ""
            site = f" at {e['site']}" if e.get("site") else ""
            print(f"  #{e.get('seq')} [{e.get('kind')}] {e.get('op')}"
                  f"{coll} {e.get('outcome')}{site}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
