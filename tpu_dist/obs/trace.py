"""Cross-rank merge: per-rank dumps → Chrome trace timeline + hang diagnosis.

:func:`read_dumps` loads the ``obs_g{gen}_r{rank}.json`` files a gang's
ranks flushed (keeping one generation — by default the newest present);
:func:`merge_trace` lays them out as a Chrome ``trace_event`` JSON object
(one *process* track per rank, one *thread* lane per event kind, collectives
named ``op #seq`` so the lockstep sequence numbers line up visually); and
:func:`diagnose` answers the on-call question directly: which rank is
behind, at which collective sequence number and call-site, and which ranks
were already waiting on it.

Time alignment: each dump carries a (wall, monotonic) anchor pair taken at
recorder construction; the merge maps every rank's monotonic timestamps
onto the shared wall axis through its own anchors.  That is exact on one
host (one monotonic clock) and approximate across hosts — which is why the
*diagnosis* never uses time at all: it compares the collective sequence
numbers every rank increments in lockstep.

Load the merged file in ``chrome://tracing`` or https://ui.perfetto.dev.
"""

from __future__ import annotations

import glob
import json
import os
from typing import List, Optional

__all__ = ["read_dumps", "merge_trace", "diagnose", "render_diagnosis"]

# trace lane per event kind (tid within each rank's track)
_TID = {"collective": 0, "p2p": 1, "transport": 2, "store": 3, "beat": 4,
        "channel": 5, "plan": 6, "pipeline": 7}
_TID_NAMES = {0: "collectives", 1: "p2p", 2: "transport", 3: "store",
              4: "beats", 5: "channels", 6: "plans", 7: "pipeline",
              8: "other"}
_OTHER_TID = 8
_ARG_KEYS = ("seq", "coll", "outcome", "site", "path", "bytes",
             "wire_bytes", "raw_wire_bytes", "comm", "digest", "reduce",
             "src", "dst", "peer", "key", "step", "detail",
             "channel", "slot", "plan", "plan_seq", "req", "group",
             "stage", "mb", "phase", "stash_bytes")


def read_dumps(path, generation: Optional[int] = None) -> List[dict]:
    """Load flight-recorder dumps from a directory (all ``obs_g*_r*.json``
    inside), a single file path, or an iterable of file paths; returns the
    dumps of one generation (``generation`` or the newest found), sorted by
    rank.  Unreadable or alien JSON files are skipped."""
    if isinstance(path, (str, os.PathLike)):
        path = os.fspath(path)
        files = (sorted(glob.glob(os.path.join(path, "obs_g*_r*.json")))
                 if os.path.isdir(path) else [path])
    else:
        files = [os.fspath(p) for p in path]
    dumps = []
    for fname in files:
        try:
            with open(fname) as f:
                d = json.load(f)
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            continue
        if not isinstance(d, dict) or d.get("version") != 1 \
                or not isinstance(d.get("events"), list):
            continue
        dumps.append(d)
    if not dumps:
        return []
    gen = (generation if generation is not None
           else max(d.get("generation", 0) for d in dumps))
    return sorted((d for d in dumps if d.get("generation", 0) == gen),
                  key=lambda d: d.get("rank", 0))


def merge_trace(dumps: List[dict]) -> dict:
    """Chrome ``trace_event`` object over the given dumps.  Complete ("X")
    events, microsecond timestamps; an event still pending at dump time
    spans up to the dump instant with ``args.outcome == "pending"``."""
    events: List[dict] = []
    if not dumps:
        return {"traceEvents": events, "displayTimeUnit": "ms"}
    wall0 = min(d.get("wall_anchor_ns", 0) for d in dumps)
    for d in dumps:
        rank = d.get("rank", 0)
        # monotonic -> shared wall axis through this rank's anchor pair
        off = (d.get("wall_anchor_ns", 0) - wall0
               - d.get("mono_anchor_ns", 0))
        dump_mono = d.get("mono_dump_ns", d.get("mono_anchor_ns", 0))
        events.append({"ph": "M", "name": "process_name", "pid": rank,
                       "args": {"name": f"rank {rank}"}})
        for tid, name in sorted(_TID_NAMES.items()):
            events.append({"ph": "M", "name": "thread_name", "pid": rank,
                           "tid": tid, "args": {"name": name}})
        for e in d["events"]:
            t0 = e.get("t0")
            if t0 is None:
                continue
            t1 = e.get("t1")
            if t1 is None:
                t1 = max(dump_mono, t0)
            name = str(e.get("op", "?"))
            if e.get("coll") is not None:
                name = f"{name} #{e['coll']}"
            events.append({
                "name": name,
                "cat": str(e.get("kind", "event")),
                "ph": "X",
                "pid": rank,
                "tid": _TID.get(e.get("kind"), _OTHER_TID),
                "ts": (t0 + off) / 1e3,
                "dur": max((t1 - t0) / 1e3, 0.001),
                "args": {k: e[k] for k in _ARG_KEYS if e.get(k) is not None},
            })
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"tool": "tpu_dist.obs", "version": 1}}


def _last_collective(dump: dict) -> Optional[dict]:
    for e in reversed(dump["events"]):
        if e.get("kind") == "collective" and e.get("coll") is not None:
            return e
    return None


def diagnose(dumps: List[dict]) -> dict:
    """Hang diagnosis over one generation's dumps.

    Verdicts: ``no-dumps``; ``no-collectives`` (nothing to compare —
    healthy only if every dump was a clean exit, see ``clean_exit``);
    ``healthy`` (every rank's last collective completed);
    ``missing-ranks`` (the dumped ranks look fine but some ranks left no
    dump at all — SIGKILL/OOM — see ``missing_ranks``); ``straggler``
    (some rank's collective sequence number is behind the front — THE
    silent-stall shape: the others sit ``pending`` in a collective the
    straggler never reached); ``stuck`` (all ranks at the same sequence
    number but some still pending — a dead peer or in-collective wedge
    rather than a straggler).
    """
    if not dumps:
        return {"version": 1, "verdict": "no-dumps", "ranks": {}}
    ranks: dict = {}
    for d in dumps:
        last = _last_collective(d)
        ranks[d.get("rank", 0)] = (None if last is None else {
            "coll": last["coll"], "op": last.get("op"),
            "site": last.get("site"), "outcome": last.get("outcome"),
            "reduce": last.get("reduce"), "path": last.get("path")})
    reached = {r: (info["coll"] if info else -1) for r, info in ranks.items()}
    front = max(reached.values())
    stragglers = sorted(r for r, c in reached.items() if c < front)
    waiting = sorted(r for r, info in ranks.items()
                     if info and info["outcome"] == "pending"
                     and reached[r] == front)
    world = max(dumps[0].get("world", len(dumps)), len(ranks))
    out = {"version": 1,
           "generation": dumps[0].get("generation", 0),
           "world": world,
           "ranks": ranks, "stragglers": stragglers,
           "waiting_ranks": waiting,
           # a SIGKILLed/OOMed rank leaves no dump at all — a "healthy"
           # verdict over a partial world would mislead the operator
           "missing_ranks": sorted(set(range(world)) - set(ranks)),
           # a crash/signal dump with no collectives is NOT a healthy run
           "clean_exit": all(d.get("reason") == "exit" for d in dumps)}
    # serving ranks: a request's decode is not a lockstep collective, but a
    # PENDING serve span in a dump is exactly "the request this rank was
    # working on when it died/hung" — name it (tpu_dist.serve opens one
    # span per request with its queue/prefill/decode split)
    stuck_requests = []
    for dmp in dumps:
        role = (f"{dmp['role']}[{dmp.get('role_rank')}]"
                if dmp.get("role") else None)
        for e in dmp.get("events", []):
            if e.get("kind") == "serve" and e.get("outcome") == "pending":
                stuck_requests.append({
                    "rank": dmp.get("rank", 0), "role": role,
                    "req": e.get("req"),
                    "phase": ("decode" if e.get("slot") is not None
                              else "queued"),
                    "slot": e.get("slot"),
                    "prompt_len": e.get("prompt_len"),
                    "site": e.get("site")})
    out["stuck_requests"] = stuck_requests
    # control-plane leader changes: a client that rode a store failover
    # records kind="store" op="failover" naming the promoted leader —
    # surface them so an operator reading a hang/restart diagnosis can see
    # the control plane moved under the job (and where it moved TO)
    store_failovers = []
    for dmp in dumps:
        for e in dmp.get("events", []):
            if e.get("kind") == "store" and e.get("op") == "failover":
                store_failovers.append({
                    "rank": dmp.get("rank", 0),
                    "leader": e.get("key"),
                    "old": e.get("old"),
                    "epoch": e.get("epoch")})
    out["store_failovers"] = store_failovers
    # pipeline stages: a PENDING kind="pipeline" span is a stage blocked
    # claiming a microbatch (op "claim-act"/"claim-grad") — the starved
    # stage a dead neighbor leaves behind.  A SIGKILLed stage rank leaves
    # no dump; its survivors' pending claims name it by adjacency.
    pipeline_stalls = []
    for dmp in dumps:
        role = (f"{dmp['role']}[{dmp.get('role_rank')}]"
                if dmp.get("role") else None)
        stall = None
        for e in dmp.get("events", []):
            if e.get("kind") == "pipeline" and e.get("outcome") == "pending":
                stall = e
        if stall is not None:
            pipeline_stalls.append({
                "rank": dmp.get("rank", 0), "role": role,
                "stage": stall.get("stage"), "mb": stall.get("mb"),
                "phase": stall.get("phase"), "op": stall.get("op")})
    out["pipeline_stalls"] = pipeline_stalls
    stuck_ref = ranks[waiting[0]] if waiting else None
    if front < 0:
        out.update({"verdict": "no-collectives", "straggler": None})
        return out
    if stragglers:
        s = stragglers[0]
        info = ranks[s]
        out.update({
            "verdict": "straggler",
            "straggler": s,
            "straggler_last_coll": info["coll"] if info else None,
            "straggler_last_op": info["op"] if info else None,
            "straggler_last_site": info["site"] if info else None,
            "stuck_coll": stuck_ref["coll"] if stuck_ref else front,
            "stuck_op": stuck_ref["op"] if stuck_ref else None,
            "stuck_site": stuck_ref["site"] if stuck_ref else None,
        })
    elif waiting:
        out.update({"verdict": "stuck", "straggler": None,
                    "stuck_coll": front,
                    "stuck_op": stuck_ref["op"],
                    "stuck_site": stuck_ref["site"]})
    elif out["missing_ranks"]:
        out.update({"verdict": "missing-ranks", "straggler": None})
    else:
        out.update({"verdict": "healthy", "straggler": None})
    return out


def _rank_line(r: int, info: Optional[dict]) -> str:
    if info is None:
        return f"  rank {r}: no collective recorded"
    return (f"  rank {r}: collective #{info['coll']} {info['op']} "
            f"{info['outcome']}"
            + (f" at {info['site']}" if info.get("site") else ""))


def render_diagnosis(d: dict) -> str:
    """Human rendering of a :func:`diagnose` result."""
    v = d.get("verdict")
    if v == "no-dumps":
        return "no flight-recorder dumps found"
    lines = []
    if v == "no-collectives":
        lines.append(
            "no collective events recorded"
            + (": nothing to diagnose" if d.get("clean_exit") else
               " but the dump was NOT a clean exit — if the job hung, it "
               "stalled before its first collective (check rendezvous / "
               "the launcher's liveness warning)"))
    elif v == "healthy":
        lines.append("no hang detected: every rank's last recorded "
                     "collective completed")
    elif v == "straggler":
        s = d["straggler"]
        last = ("never reached a collective"
                if d.get("straggler_last_coll") is None else
                f"last at collective #{d['straggler_last_coll']} "
                f"({d['straggler_last_op']}"
                + (f" at {d['straggler_last_site']}"
                   if d.get("straggler_last_site") else "") + ")")
        stuck = f"collective #{d['stuck_coll']}"
        if d.get("stuck_op"):
            stuck += (f" ({d['stuck_op']}"
                      + (f" at {d['stuck_site']}" if d.get("stuck_site")
                         else "") + ")")
        lines.append(f"hang diagnosis: rank {s} is behind — {last}; "
                     f"rank(s) {d['waiting_ranks']} already waiting in "
                     f"{stuck}")
    elif v == "stuck":
        lines.append(f"hang diagnosis: all ranks reached collective "
                     f"#{d['stuck_coll']} ({d.get('stuck_op')}) but rank(s) "
                     f"{d['waiting_ranks']} never completed it — dead peer "
                     f"or wedged transport rather than a straggler")
    elif v == "missing-ranks":
        lines.append("every dumped rank's collectives completed, but some "
                     "ranks left no dump at all (see below) — a "
                     "SIGKILLed/OOMed rank cannot dump; check its store "
                     "tail in the supervisor's positions table")
    if d.get("missing_ranks"):
        lines.append(f"  WARNING: no dump from rank(s) {d['missing_ranks']} "
                     f"(world {d.get('world')})")
    for sr in d.get("stuck_requests", []):
        who = (f"rank {sr['rank']} ({sr['role']})" if sr.get("role")
               else f"rank {sr['rank']}")
        lines.append(
            f"  stuck request: {who} req {sr['req']} "
            f"({sr['phase']}"
            + (f", slot {sr['slot']}" if sr.get("slot") is not None else "")
            + (f", prompt {sr['prompt_len']} tokens"
               if sr.get("prompt_len") is not None else "")
            + ") never completed"
            + (f" — submitted at {sr['site']}" if sr.get("site") else ""))
    for ps in d.get("pipeline_stalls", []):
        who = (f"rank {ps['rank']} ({ps['role']})" if ps.get("role")
               else f"rank {ps['rank']}")
        what = ("activations" if ps.get("op") == "claim-act"
                else "gradients" if ps.get("op") == "claim-grad"
                else ps.get("op"))
        neighbor = (f"stage{ps['stage'] - 1}" if ps.get("op") == "claim-act"
                    and ps.get("stage") is not None
                    else f"stage{ps['stage'] + 1}"
                    if ps.get("op") == "claim-grad"
                    and ps.get("stage") is not None else "its neighbor")
        lines.append(
            f"  stalled pipeline stage: {who} starved at stage "
            f"{ps.get('stage')} {ps.get('phase')} mb {ps.get('mb')} — "
            f"blocked claiming {what} that {neighbor} never produced")
    failovers = d.get("store_failovers") or []
    if failovers:
        latest = max(failovers, key=lambda f: f.get("epoch") or 0)
        seen = sorted({f["rank"] for f in failovers})
        lines.append(
            f"  store failover: leader {latest.get('old')} lost; clients "
            f"re-resolved to promoted leader {latest.get('leader')} "
            f"(epoch {latest.get('epoch')}) — observed by rank(s) {seen}")
    for r in sorted(d.get("ranks", {})):
        lines.append(_rank_line(r, d["ranks"][r]))
    return "\n".join(lines)
