"""Instrumentation hooks: collective spans, store tails, crash dumps.

This is the thin layer the rest of the framework calls into; everything is
a no-op (one env lookup) while the recorder is disarmed.

- :func:`collective_span` — context manager the eager collectives
  (tpu_dist/collectives/eager.py) and host ring collectives
  (tpu_dist/collectives/ring.py) wrap themselves in.  The span opens a
  ``pending`` event before any payload moves, so a hung collective is
  visible in the crash dump, and closes it with ``ok`` / ``error:Type``.
- :func:`annotate_transport` — called from the single counter-ingestion
  point (:func:`tpu_dist.obs.recorder.record_transport`) to stamp the
  enclosing span with the transport path it actually took.
- :func:`post_tail` / :func:`fetch_tail` — each rank's compact "last known
  position" rides the control-plane store under
  ``tpu_dist/g{gen}/obs/{rank}`` (posted on every heartbeat beat), so even
  a SIGKILLed rank leaves its position behind for the supervisor's table
  and for :class:`~tpu_dist.resilience.heartbeat.RankLostError` /
  :class:`~tpu_dist.collectives.transport.PeerGoneError` messages.
- :func:`install_from_env` — arms the crash-dump paths: ``sys.excepthook``
  (any unhandled exception, which covers ``RankLostError``,
  ``CollectiveMismatchError`` and ``PeerGoneError``), a chained SIGTERM
  handler (the supervisor's kill path), and an atexit catch-all so clean
  runs leave dumps for timeline merging too.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import sys
import threading
import time
from typing import Optional

from . import recorder

__all__ = ["collective_span", "current_span", "note_path", "note_algo",
           "annotate_transport", "heartbeat_tick", "post_tail", "fetch_tail",
           "render_tail", "install_from_env", "install_signal_handlers",
           "request_dumps"]

_tls = threading.local()


def current_span() -> Optional[dict]:
    """The innermost in-flight span opened on this thread, or None."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


class _NullCtx:
    """Shared disarmed context — no allocation on the hot path."""
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullCtx()


class _Span:
    __slots__ = ("_rec", "ev")

    def __init__(self, rec, ev):
        self._rec, self.ev = rec, ev

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self.ev)
        return self.ev

    def __exit__(self, etype, exc, tb):
        if etype is None:
            self._rec.end(self.ev, outcome="ok")
        else:
            self._rec.end(self.ev, outcome=f"error:{etype.__name__}")
        stack = getattr(_tls, "stack", None)
        if stack:
            stack.pop()
        return False


def collective_span(op: str, value=None, reduce_op=None, src=None, dst=None,
                    peer=None, kind: str = "collective", path=None,
                    group=None):
    """Span context for one collective (or p2p) call.  ``kind='collective'``
    consumes the cross-rank collective sequence counter (every rank of an
    SPMD program opens span #N together — the merge key); ``kind='p2p'``
    deliberately does not, because send/recv are rank-asymmetric."""
    rec = recorder.get_recorder()
    if rec is None:
        return _NULL
    fields = {}
    try:
        # async collectives: spans opened on the ordered engine attribute
        # to the ISSUE call-site (the engine thread's own stack holds no
        # user frames), and the first span additionally carries queue_ns —
        # how long the work sat behind earlier collectives — split from
        # wire time (both slots set by the engine around the body)
        from ..collectives.work import pending_site, take_pending_queue_ns
        qns = take_pending_queue_ns()
        if qns is not None:
            fields["queue_ns"] = qns
        fields["site"] = pending_site()
    except Exception:
        pass
    if not fields.get("site"):
        fields["site"] = recorder.call_site()
    if group is not None and kind == "collective":
        # sub-group collectives run on MEMBER ranks only: consuming the
        # world's lockstep `coll` counter would permanently skew the
        # cross-rank merge/diagnose key for every later flat collective
        # (members at #N+1, non-members at #N).  Like p2p spans, they are
        # rank-asymmetric from the world's perspective — attributed by
        # the `group` field instead of the lockstep sequence.
        kind = "group-collective"
    if kind == "collective":
        fields["coll"] = rec.next_coll()
    if reduce_op is not None:
        fields["reduce"] = str(reduce_op).lower()
    if src is not None:
        fields["src"] = int(src)
    if dst is not None:
        fields["dst"] = int(dst)
    if peer is not None:
        fields["peer"] = int(peer)
    if path is not None:
        fields["path"] = path
    if group is not None:
        fields["group"] = str(group)  # SubGroup id (scoped collectives)
    if value is not None:
        dg, nbytes = recorder.digest(value)
        fields["digest"] = dg
        fields["bytes"] = nbytes
    return _Span(rec, rec.begin(kind, op, **fields))


def note_algo(algo: str) -> None:
    """Stamp the enclosing span with the selected collective algorithm
    (``flat`` | ``hier`` | ``store`` — tpu_dist/collectives/topology.py's
    autoselector), so traces show WHICH ring shape a payload took."""
    span = current_span()
    if span is None:
        return
    rec = recorder.get_recorder()
    if rec is not None:
        rec.update_event(span, algo=algo)


def note_path(path: str) -> None:
    """Stamp the enclosing span's transport path (the mesh-collective
    branches, which never reach record_transport)."""
    span = current_span()
    if span is not None and span.get("path") is None:
        rec = recorder.get_recorder()
        if rec is not None:
            # through the recorder lock: snapshot()/last_position() copy
            # this dict from other threads, and inserting a new key during
            # that copy raises "dictionary changed size during iteration"
            rec.update_event(span, path=path)


def note_wire(wire_bytes: int, comm: Optional[str] = None,
              raw_bytes: Optional[int] = None) -> None:
    """Stamp the enclosing span with the wire quantities: ``wire_bytes``
    (compressed bytes that actually crossed the wire), ``raw_wire_bytes``
    (what the same traffic would have cost uncompressed — their ratio is
    the wire-format compression factor, independent of the ring's
    2(N-1)/N amplification), and the wire format (``comm``, e.g.
    ``"int8_block256"`` / ``"bfloat16"`` / None for raw).  The span's
    ``bytes`` field stays the logical payload.  Called by the host ring
    collectives (tpu_dist/collectives/ring.py) at span close."""
    span = current_span()
    if span is None:
        return
    rec = recorder.get_recorder()
    if rec is None:
        return
    fields = {"wire_bytes": int(span.get("wire_bytes", 0)) + int(wire_bytes)}
    if raw_bytes is not None:
        fields["raw_wire_bytes"] = (int(span.get("raw_wire_bytes", 0))
                                    + int(raw_bytes))
    if comm is not None:
        fields["comm"] = comm
    rec.update_event(span, **fields)


def annotate_transport(rec, op: str, path: str, nbytes: int,
                       seconds: float) -> None:
    """Fold one transport leg into the enclosing span, or record it as a
    standalone ``transport`` event when no span is open (direct
    metrics-shim callers, ring helpers used standalone)."""
    span = current_span()
    if span is not None and span.get("outcome") == "pending":
        cur = span.get("path")
        rec.update_event(span,
                         path=path if cur in (None, path) else "mixed")
        return
    rec.record("transport", op, t0=time.monotonic_ns() - int(seconds * 1e9),
               path=path, bytes=int(nbytes))


# -- store tails --------------------------------------------------------------


def post_tail(store, rec: Optional["recorder.FlightRecorder"] = None) -> None:
    """Best-effort post of this rank's compact tail to the generation-scoped
    store key (one small SET; a flaky store degrades diagnostics, never the
    job)."""
    rec = rec if rec is not None else recorder.get_recorder()
    if rec is None or store is None:
        return
    pos = rec.last_position()
    if pos is None:
        return
    try:
        store.set(recorder.obs_key(rec.generation, rec.rank),
                  json.dumps(pos).encode())
    except Exception:
        pass


def fetch_tail(store, generation: int, rank: int) -> Optional[dict]:
    """The tail rank ``rank`` last posted, or None.  Works from disarmed
    processes too (the launcher's supervisor is never armed itself)."""
    if store is None:
        return None
    try:
        key = recorder.obs_key(generation, rank)
        # check-then-get: get() would block forever on a never-posted key.
        # The tiny check->get race only loses to the DELETE_PREFIX reaper,
        # which runs strictly after the generation is torn down.
        if not store.check(key):
            return None
        return json.loads(store.get(key).decode())
    except Exception:
        return None


def render_tail(tail: dict) -> str:
    """One-line human rendering of a posted tail."""
    op = tail.get("op", "?")
    what = (f"collective #{tail['coll']} {op}"
            if tail.get("coll") is not None else f"{tail.get('kind', '?')} {op}")
    site = f" at {tail['site']}" if tail.get("site") else ""
    role = f" role={tail['role']}" if tail.get("role") else ""
    return (f"{what} {tail.get('outcome', '?')}{site} "
            f"(event #{tail.get('seq', '?')} of {tail.get('events', '?')})"
            f"{role}")


def heartbeat_tick(store, step=None) -> None:
    """Per-beat hook from :class:`~tpu_dist.resilience.heartbeat.Heartbeat`:
    record the beat and re-post this rank's tail so the store always holds
    a position at most one beat old."""
    rec = recorder.get_recorder()
    if rec is None:
        return
    rec.record("beat", "beat", step=step)
    post_tail(store, rec)


# -- crash-dump installation --------------------------------------------------

_prev_signal = {}
_prev_excepthook = None
_installed = False


def _on_signal(signum, frame):
    recorder.dump_now(f"signal:{signum}")
    prev = _prev_signal.get(signum)
    if callable(prev):
        prev(signum, frame)  # e.g. a Python-level preemption hook
    elif prev != signal.SIG_IGN:
        # SIG_DFL — or None (handler we could not introspect): a TERM must
        # terminate; swallowing it would leave a worker the supervisor
        # believes it killed
        os._exit(128 + signum)


def _on_exception(etype, exc, tb):
    recorder.dump_now(f"exception:{etype.__name__}")
    (_prev_excepthook or sys.__excepthook__)(etype, exc, tb)


def _on_dump_signal(signum, frame):
    # SIGUSR1 = "flush your flight recorder": the launcher sends it to
    # every still-alive worker on a failed round right before TERM, so
    # dumps land even where SIGTERM is owned at the C++ level (XLA's
    # preemption notifier registers a raw sigaction Python cannot chain)
    recorder.dump_now(f"signal:{signum}")


def request_dumps(targets, settle: Optional[float] = None) -> None:
    """Supervisor-side dump flush: SIGUSR1 each still-running worker, then
    wait (bounded) for its dump file to land before TERM goes out.

    The settle wait exists because the TERM that follows can be consumed
    at the C++ layer (jax's preemption notifier owns SIGTERM) and kill the
    process before the Python-level USR1 handler ever ran — the race
    behind intermittently missing per-rank dumps.  Bounded by ``settle`` /
    ``TPU_DIST_OBS_DUMP_SETTLE`` (default 2 s) and skipped for ranks that
    already exited; the dump write is atomic (tmp+rename), so a file that
    exists is complete.

    ``targets``: iterable of ``(proc, dump_path)`` pairs, ``proc`` a
    ``subprocess.Popen`` (``poll()``/``send_signal()``).
    """
    def _mtime(path):
        try:
            return os.stat(path).st_mtime_ns
        except OSError:
            return None

    signaled = []
    for proc, path in targets:
        if proc.poll() is None:
            # snapshot BEFORE signaling: a previous incarnation's dump at
            # the same path (solo respawns share generation + rank) must
            # not satisfy the wait — we need a FRESH write, or the TERM
            # that follows re-opens the very race this settle closes
            signaled.append((proc, path, _mtime(path)))
            try:
                proc.send_signal(signal.SIGUSR1)
            except OSError:
                pass
    if not signaled:
        return
    if settle is None:
        try:
            settle = float(
                os.environ.get("TPU_DIST_OBS_DUMP_SETTLE", "2.0"))
        except ValueError:
            settle = 2.0
    deadline = time.monotonic() + settle
    while time.monotonic() < deadline:
        if all(proc.poll() is not None
               or (_mtime(path) is not None and _mtime(path) != before)
               for proc, path, before in signaled):
            return
        time.sleep(0.05)


def install_signal_handlers() -> None:
    """Install the dump signal handlers: SIGUSR1 (dump and continue — the
    launcher's pre-teardown flush request) and a chained SIGTERM handler
    (dump, then the previous disposition) for plain workers whose TERM is
    not claimed at the C level.  Called at rendezvous start and again after
    ``jax.distributed.initialize``.  Safe to call repeatedly; no-op when
    disarmed or off the main thread."""
    if not recorder.enabled():
        return
    try:
        signal.signal(signal.SIGUSR1, _on_dump_signal)
        cur = signal.getsignal(signal.SIGTERM)
        if cur is None:
            # a C-level sigaction Python cannot introspect or chain (XLA's
            # preemption notifier): leave SIGTERM alone — replacing it
            # would break preemption handling, and the launcher's SIGUSR1
            # flush covers the dump
            return
        if cur is not _on_signal:
            _prev_signal[signal.SIGTERM] = cur
            signal.signal(signal.SIGTERM, _on_signal)
    except (ValueError, OSError):
        pass  # not the main thread / restricted environment


def install_from_env() -> Optional["recorder.FlightRecorder"]:
    """Arm the crash-dump paths if ``TPU_DIST_OBS`` is set (idempotent);
    returns the recorder or None.  Rendezvous calls this for every worker;
    standalone scripts may call it directly."""
    global _installed, _prev_excepthook
    rec = recorder.get_recorder()
    if rec is None:
        return None
    if not _installed:
        _installed = True
        if sys.excepthook is not _on_exception:
            _prev_excepthook = sys.excepthook
            sys.excepthook = _on_exception
        # clean runs dump too (force=False: never clobber a crash dump's
        # reason) so healthy timelines can be merged
        atexit.register(recorder.dump_now, "exit", False)
    install_signal_handlers()
    return rec
