"""tpu_dist.obs — collective flight recorder, cross-rank trace timeline,
and hang diagnosis.

The standing observability surface for the eager/distributed stack
(docs/observability.md).  Three pieces:

1. **Flight recorder** (:mod:`.recorder`): a lock-cheap per-rank ring
   buffer of structured events — every eager host collective (with its
   lockstep sequence number, reduce op, payload digest, transport path,
   start/end monotonic ns, user call-site and outcome), p2p send/recv,
   store client op, and heartbeat beat.  Armed with ``TPU_DIST_OBS=1``
   (launcher ``--flight-recorder``); disarmed cost is one env lookup per
   hook.  The per-(op, transport) byte/latency counters that
   ``tpu_dist.utils.metrics`` exposes are fed by the same ingestion point.
2. **Crash/hang dump + store tails** (:mod:`.hooks`): unhandled
   exceptions (``RankLostError``, ``CollectiveMismatchError``,
   ``PeerGoneError``, ...), SIGTERM and process exit flush the buffer to
   ``TPU_DIST_OBS_DIR``; each heartbeat re-posts a compact tail under the
   generation-scoped store key ``tpu_dist/g{gen}/obs/{rank}`` so even a
   SIGKILLed rank leaves its last known position behind — the supervisor
   prints the per-rank table before restarting, and the resilience /
   transport errors attach the lost peer's tail to their messages.
3. **Timeline + diagnosis** (:mod:`.trace`, CLI ``python -m
   tpu_dist.obs``): merge the per-rank dumps into one Chrome
   ``trace_event`` timeline (a track per rank, collectives aligned by
   sequence number) and name the hang: which rank is behind, at which
   collective seq and call-site, and which ranks were already waiting.
"""

from . import hooks, recorder, trace
from .hooks import (collective_span, fetch_tail, install_from_env, note_path,
                    post_tail, render_tail)
from .recorder import (FlightRecorder, default_dump_dir, dump_now, dump_path,
                       enabled, get_recorder, obs_key, record_transport,
                       reset, reset_transport_counters, transport_counters)
from .trace import diagnose, merge_trace, read_dumps, render_diagnosis

__all__ = [
    "recorder", "hooks", "trace",
    "FlightRecorder", "enabled", "get_recorder", "reset", "dump_now",
    "record_transport", "transport_counters", "reset_transport_counters",
    "obs_key", "default_dump_dir", "dump_path",
    "collective_span", "note_path", "install_from_env", "post_tail",
    "fetch_tail", "render_tail",
    "read_dumps", "merge_trace", "diagnose", "render_diagnosis",
]
