"""torch checkpoint interop — load/export ``state_dict`` weights.

The reference user's checkpoints are torch ``state_dict``s (torchvision
``resnet18`` at /root/reference/example_mp.py:50, the tutorial ConvNet at
/root/reference/mpspawn_dist.py:11-43).  tpu_dist's module paths
deliberately mirror torch naming (``layer1.0.conv1``, ``fc``, ...), so a
torch checkpoint loads by aligning paths and re-laying-out each leaf:

====================  ==========================  =======================
module                torch layout                tpu_dist layout
====================  ==========================  =======================
Conv2d weight         (O, I/g, kh, kw)            (kh, kw, I/g, O)
Linear weight         (out, in)                   (in, out)
MultiheadSelfAttn     in_proj_weight (3d, d)      qkv_weight (d, 3d)
                      out_proj.weight (d, d)      out_weight (d, d), .T
BatchNorm running_*   buffers in state_dict       mutable-state ``mean`` /
                                                  ``var`` pytree
everything else       identical                   identical
====================  ==========================  =======================

``load_torch_state_dict`` returns ``(params, model_state)`` ready for
``apply()``/DDP; ``to_torch_state_dict`` is the exact inverse, so a model
trained here can resume in torch.  Transforms are selected by MODULE
CLASS (not by shape heuristics — a square Linear weight would otherwise
be ambiguous).  ``torch.Tensor`` leaves and plain numpy arrays are both
accepted; nothing here imports torch.

For architectures whose torch naming differs structurally, pass
``key_map`` (our-key → torch-key); :func:`vit_torchvision_key_map`
generates it for torchvision ``VisionTransformer`` checkpoints.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple, Union

import jax
import numpy as np

__all__ = ["load_torch_state_dict", "to_torch_state_dict",
           "vit_torchvision_key_map", "flatten_linear_from_torch",
           "flatten_linear_to_torch"]

# torch buffers with no tpu_dist counterpart, silently ignored
_IGNORED_SUFFIXES = ("num_batches_tracked",)

# our attention leaf name -> torch nn.MultiheadAttention sub-key
_ATTN_LEAF_TO_TORCH = {"qkv_weight": "in_proj_weight",
                       "qkv_bias": "in_proj_bias",
                       "out_weight": "out_proj.weight",
                       "out_bias": "out_proj.bias"}
_STATE_LEAF_TO_TORCH = {"mean": "running_mean", "var": "running_var"}


def _np(x) -> np.ndarray:
    """Accept torch tensors, jax arrays, and numpy arrays."""
    if hasattr(x, "detach"):                      # torch.Tensor
        x = x.detach().cpu()
        try:
            x = x.numpy()
        except TypeError:
            # dtypes numpy can't hold (bf16 checkpoints): upcast; the
            # caller casts to the target leaf dtype afterwards anyway
            x = x.float().numpy()
    return np.asarray(x)


def _join(path: str, leaf: str) -> str:
    return f"{path}.{leaf}" if path else leaf


def _module_kinds(model) -> Dict[str, str]:
    """Map each param path to a transform kind by module class."""
    from . import nn

    kinds = {}
    model._assign_paths()
    for path, mod in model.named_modules():
        if isinstance(mod, nn.Conv2d):
            kinds[path] = "conv"
        elif isinstance(mod, nn.Linear):
            kinds[path] = "linear"
        elif isinstance(mod, nn.MultiheadSelfAttention):
            kinds[path] = "attn"
        else:
            kinds[path] = "direct"
    return kinds


def _torch_key(path: str, leaf: str, kind: str) -> str:
    if kind == "attn":
        return _join(path, _ATTN_LEAF_TO_TORCH.get(leaf, leaf))
    return _join(path, leaf)


def _to_ours(kind: str, leaf: str, t: np.ndarray) -> np.ndarray:
    if kind == "conv" and leaf == "weight":
        return np.transpose(t, (2, 3, 1, 0))
    if kind == "linear" and leaf == "weight":
        return np.transpose(t)
    if kind == "attn" and leaf in ("qkv_weight", "out_weight"):
        return np.transpose(t)
    return t


def _to_torch(kind: str, leaf: str, a: np.ndarray) -> np.ndarray:
    if kind == "conv" and leaf == "weight":
        return np.transpose(a, (3, 2, 0, 1))
    if kind == "linear" and leaf == "weight":
        return np.transpose(a)
    if kind == "attn" and leaf in ("qkv_weight", "out_weight"):
        return np.transpose(a)
    return a


def flatten_linear_from_torch(c: int, h: int, w: int) -> Callable:
    """Transform for a Linear whose input is a FLATTENED conv feature map.

    torch flattens NCHW — the weight's input dim is ordered (C, H, W);
    tpu_dist flattens NHWC — (H, W, C).  A plain transpose would silently
    scramble those columns (outputs wrong, shapes fine), so such leaves
    need this as a per-key ``transforms`` entry, e.g.::

        interop.load_torch_state_dict(model, sd, transforms={
            "fc1.weight": interop.flatten_linear_from_torch(128, 4, 4)})

    Not needed when the flatten is preceded by global pooling to 1x1
    (ResNet's avgpool) — the input dim is then pure channels.
    """
    def f(t: np.ndarray) -> np.ndarray:
        out = t.shape[0]
        return (t.reshape(out, c, h, w).transpose(2, 3, 1, 0)
                .reshape(h * w * c, out))
    return f


def flatten_linear_to_torch(c: int, h: int, w: int) -> Callable:
    """Inverse of :func:`flatten_linear_from_torch` (for export)."""
    def f(a: np.ndarray) -> np.ndarray:
        out = a.shape[1]
        return (a.reshape(h, w, c, out).transpose(3, 2, 0, 1)
                .reshape(out, c * h * w))
    return f


KeyMap = Union[Dict[str, str], Callable[[str], str]]


def _map_key(key: str, key_map: Optional[KeyMap]) -> str:
    if key_map is None:
        return key
    if callable(key_map):
        return key_map(key)
    return key_map.get(key, key)


def load_torch_state_dict(model, state_dict, key_map: Optional[KeyMap] = None,
                          strict: bool = True, seed: int = 0, dtype=None,
                          transforms: Optional[Dict[str, Callable]] = None,
                          ) -> Tuple[dict, dict]:
    """Build ``(params, model_state)`` for ``model`` from a torch
    ``state_dict`` (a mapping of dotted names to tensors/arrays).

    ``key_map``: optional our-key → torch-key translation (dict or
    callable), applied AFTER the built-in attention-name mapping.
    ``strict=True`` (torch semantics) raises ``KeyError`` listing missing
    and unexpected keys; ``strict=False`` leaves missing leaves at their
    seeded init values and ignores extras.  ``dtype``: optional cast for
    the imported param leaves (e.g. ``jnp.bfloat16``).  ``transforms``:
    per-our-key layout overrides replacing the class-based default — see
    :func:`flatten_linear_from_torch` for the case that needs one.
    """
    import jax.numpy as jnp

    params = model.init(jax.random.key(seed))
    state = model.init_state()
    kinds = _module_kinds(model)
    sd = dict(state_dict)
    transforms = transforms or {}
    missing = []

    def fill(tree, leaf_map, is_state):
        for path, leaves in tree.items():
            kind = kinds.get(path, "direct")
            for leaf in leaves:
                if is_state and leaf not in leaf_map:
                    continue  # no torch analogue (e.g. MoE aux_loss)
                name = leaf_map.get(leaf, leaf) if is_state else leaf
                key = _map_key(_torch_key(path, name, kind), key_map)
                if key not in sd:
                    missing.append(key)
                    continue
                t = _np(sd.pop(key))
                ours_key = _join(path, leaf)
                if ours_key in transforms:
                    a = transforms[ours_key](t)
                else:
                    a = t if is_state else _to_ours(kind, leaf, t)
                want = tuple(leaves[leaf].shape)
                if tuple(a.shape) != want:
                    raise ValueError(
                        f"{key}: torch shape {tuple(t.shape)} does not "
                        f"map to {_join(path, leaf)} {want}")
                cast = leaves[leaf].dtype if (is_state or dtype is None) \
                    else dtype
                # jnp.array, NOT asarray: `a` may be a zero-copy VIEW of the
                # torch tensor's storage (_np does .numpy()), and jax's CPU
                # backend zero-copies aligned same-dtype numpy arrays — an
                # asarray here aliases live torch parameters, so a later
                # in-place torch `optimizer.step()` would silently mutate
                # this "immutable" tree (caught by the e2e parity test).
                leaves[leaf] = jnp.array(a, cast)

    fill(params, {}, is_state=False)
    fill(state, _STATE_LEAF_TO_TORCH, is_state=True)

    if dtype is not None:
        # Uniform-dtype guarantee: with strict=False, leaves missing from the
        # state_dict kept their f32 seeded-init values — cast them too, so the
        # returned params tree never mixes dtypes (mixed trees surprise jit
        # donation and checkpoint round-trips).  No-op for leaves fill() cast.
        for leaves in params.values():
            for leaf in leaves:
                leaves[leaf] = jnp.asarray(leaves[leaf], dtype)

    unexpected = [k for k in sd
                  if not k.endswith(_IGNORED_SUFFIXES)]
    if strict and (missing or unexpected):
        raise KeyError(
            f"state_dict does not match model: missing keys {missing}, "
            f"unexpected keys {unexpected}")
    return params, state


def to_torch_state_dict(model, params, model_state=None,
                        key_map: Optional[KeyMap] = None,
                        transforms: Optional[Dict[str, Callable]] = None,
                        ) -> Dict[str, np.ndarray]:
    """Inverse of :func:`load_torch_state_dict`: export ``params`` (+
    optional BN ``model_state``) as a torch-layout ``state_dict`` of numpy
    arrays (``torch.load``-compatible after ``torch.as_tensor``).
    ``transforms`` overrides are keyed by OUR key like on load — use the
    ``*_to_torch`` direction of each helper."""
    kinds = _module_kinds(model)
    transforms = transforms or {}
    out: Dict[str, np.ndarray] = {}
    for path, leaves in params.items():
        kind = kinds.get(path, "direct")
        for leaf, a in leaves.items():
            key = _map_key(_torch_key(path, leaf, kind), key_map)
            ours_key = _join(path, leaf)
            if ours_key in transforms:
                t = transforms[ours_key](_np(a))
            else:
                t = _to_torch(kind, leaf, _np(a))
            # copy=True: _np of a jax array is a zero-copy VIEW of the XLA
            # buffer (so are no-transpose leaves like biases after _to_torch);
            # handing that to torch.as_tensor + an in-place optimizer step
            # would mutate the live jax array.  Mirror of the load-side copy.
            out[key] = np.array(t)
    for path, leaves in (model_state or {}).items():
        for leaf, a in leaves.items():
            if leaf not in _STATE_LEAF_TO_TORCH:
                continue  # no torch analogue (e.g. MoE aux_loss)
            out[_map_key(_join(path, _STATE_LEAF_TO_TORCH[leaf]),
                         key_map)] = np.array(_np(a))
    return out


def vit_torchvision_key_map(num_layers: int) -> Dict[str, str]:
    """our-key → torchvision ``VisionTransformer`` state_dict key, for
    :class:`tpu_dist.models.VisionTransformer` (models/vit.py).

    torchvision structure: encoder blocks live under
    ``encoder.layers.encoder_layer_{i}`` with ``ln_1``/``self_attention``/
    ``ln_2``/``mlp`` (MLPBlock indexes its Linears 0 and 3), the final norm
    is ``encoder.ln``, the head ``heads.head``.
    """
    m = {"tokens.class_token": "class_token",
         "tokens.pos_embedding": "encoder.pos_embedding",
         "ln.weight": "encoder.ln.weight",
         "ln.bias": "encoder.ln.bias",
         "head.weight": "heads.head.weight",
         "head.bias": "heads.head.bias"}
    for i in range(num_layers):
        src = f"block{i}"
        dst = f"encoder.layers.encoder_layer_{i}"
        for ours, theirs in (("ln1", "ln_1"), ("ln2", "ln_2")):
            for w in ("weight", "bias"):
                m[f"{src}.{ours}.{w}"] = f"{dst}.{theirs}.{w}"
        for sub in ("in_proj_weight", "in_proj_bias", "out_proj.weight",
                    "out_proj.bias"):
            m[f"{src}.attn.{sub}"] = f"{dst}.self_attention.{sub}"
        for ours, theirs in (("0", "0"), ("2", "3")):
            for w in ("weight", "bias"):
                m[f"{src}.mlp.{ours}.{w}"] = f"{dst}.mlp.{theirs}.{w}"
    return m
