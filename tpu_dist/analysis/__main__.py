"""``python -m tpu_dist.analysis [lint|graph|replay]`` — the analysis CLI.

Three tools share one findings/JSON/exit-code machinery
(tpu_dist/analysis/findings.py):

- ``lint`` (default — a bare ``python -m tpu_dist.analysis <paths>``
  still lints, unchanged): the tpudlint AST linter, TD001–TD010.
- ``graph``: the static whole-graph protocol verifier (protocol.py),
  TD101–TD105 — deadlock cycles with a printed witness schedule,
  claim-safety, restart-policy soundness, dp-path feasibility.
- ``replay``: the offline trace-replay sanitizer (replay.py),
  TD110–TD115 — re-verifies a flight-recorder dump directory post-hoc
  and embeds the ``obs diagnose`` dict in its JSON report.

Exit codes (all three): 0 = clean (no unsuppressed finding at/above
``--fail-on``), 1 = findings, 2 = usage error.  ``--format json`` emits
the findings schema; ``replay --format json`` adds ``diagnosis`` (the
same schema ``python -m tpu_dist.obs diagnose --json`` prints).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from .findings import SEVERITY_ORDER, render_json, render_text
from .linter import lint_paths
from .rules import RULE_DOCS


def _default_paths() -> List[str]:
    """``tpu_dist`` + ``examples``, resolved against the CWD first and the
    repo/package root second — so the documented bare invocation works
    from any directory instead of emitting TD000 read errors."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    out = []
    for name in ("tpu_dist", "examples"):
        if os.path.exists(name):
            out.append(name)
        elif os.path.exists(os.path.join(root, name)):
            out.append(os.path.join(root, name))
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpu_dist.analysis",
        description="tpudlint: distributed-correctness linter for tpu_dist "
                    "programs (rank-divergent collectives, un-namespaced "
                    "store keys, deadline-less waits, host effects under "
                    "jit, lock-order cycles).  Subcommands: `graph` "
                    "(static role-graph protocol verifier) and `replay` "
                    "(offline flight-recorder replay sanitizer).")
    p.add_argument("paths", nargs="*", default=None,
                   help="files or directories to lint (default: the "
                        "repo's tpu_dist + examples dirs, resolved "
                        "against the CWD and then the installed package "
                        "root)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--rules", type=str, default=None,
                   help="comma-separated rule codes to run (default: all)")
    p.add_argument("--fail-on", choices=("warning", "error", "never"),
                   default="warning",
                   help="minimum unsuppressed severity that makes the exit "
                        "code non-zero (default: warning, i.e. any finding)")
    p.add_argument("--show-suppressed", action="store_true",
                   help="include suppressed findings in text output")
    p.add_argument("--list-rules", action="store_true")
    return p


def _finish(findings, fmt: str, fail_on: str,
            show_suppressed: bool = False,
            extra_json: Optional[dict] = None) -> int:
    """Shared rendering + exit-code tail for all three subcommands."""
    if fmt == "json":
        doc = render_json(findings)
        if extra_json:
            doc.update(extra_json)
        print(json.dumps(doc, indent=2))
    else:
        print(render_text(findings, show_suppressed=show_suppressed))
    if fail_on == "never":
        return 0
    threshold = SEVERITY_ORDER[fail_on]
    worst = max((SEVERITY_ORDER[f.severity] for f in findings
                 if not f.suppressed), default=0)
    return 1 if worst >= threshold else 0


# -- graph subcommand ---------------------------------------------------------


def build_graph_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpu_dist.analysis graph",
        description="Static whole-graph protocol verifier (TD101-TD105): "
                    "model-checks a RoleGraph + ChannelSpec topology for "
                    "bounded-channel deadlock cycles (witness schedule "
                    "printed), claim-safety under solo restarts, "
                    "restart-policy soundness and dp-path feasibility — "
                    "before a single process is spawned.")
    p.add_argument("script", nargs="?", default=None,
                   help="Python file to AST-extract literal "
                        "ChannelSpec(...) calls from (combined with "
                        "--roles)")
    p.add_argument("--roles", type=str, default=None,
                   help="role spec, launcher grammar: "
                        "name:world[:policy][@node],...")
    p.add_argument("--channels", type=str, default=None,
                   help="channel spec: "
                        "name:src>dst[:depth][:queue|latest]"
                        "[:payload=BYTES],...")
    p.add_argument("--graph", type=str, default=None, dest="graph",
                   help="import a graph builder instead: file.py:func or "
                        "pkg.mod:func (called with --graph-args)")
    p.add_argument("--graph-args", type=str, default=None,
                   help="JSON list of positional args for --graph "
                        "(e.g. '[4]')")
    p.add_argument("--nnodes", type=int, default=None,
                   help="cluster size for @node pin validation")
    p.add_argument("--dp-threshold", type=int, default=None,
                   help="payload bytes for TD104 (default: "
                        "TPU_DIST_DP_THRESHOLD or 65536)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--fail-on", choices=("warning", "error", "never"),
                   default="warning")
    p.add_argument("--list-rules", action="store_true")
    return p


def graph_main(argv: List[str]) -> int:
    from .protocol import GRAPH_RULE_DOCS, build_graph, verify_graph

    args = build_graph_parser().parse_args(argv)
    if args.list_rules:
        for code in sorted(GRAPH_RULE_DOCS):
            print(f"{code}  {GRAPH_RULE_DOCS[code]}")
        return 0
    label = (args.graph or args.script
             or (f"--roles {args.roles}" if args.roles else "<graph>"))
    try:
        graph, findings, notes = build_graph(
            roles_spec=args.roles, script=args.script,
            channels_spec=args.channels, graph_target=args.graph,
            graph_args=args.graph_args, path=label)
    except Exception as e:
        sys.stderr.write(f"graph: {e}\n")
        return 2
    for note in notes:
        sys.stderr.write(f"note: {note}\n")
    if graph is not None:
        findings = findings + verify_graph(
            graph, nnodes=args.nnodes, dp_threshold=args.dp_threshold,
            path=label)
        extra = {"graph": json.loads(graph.to_json()), "tool": "graph"}
    else:
        extra = {"graph": None, "tool": "graph"}
    return _finish(findings, args.format, args.fail_on, extra_json=extra)


# -- replay subcommand --------------------------------------------------------


def build_replay_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpu_dist.analysis replay",
        description="Offline trace-replay sanitizer (TD110-TD115): "
                    "re-verifies a flight-recorder dump directory — "
                    "lockstep collective linearization, store-key "
                    "lifecycle, channel cursor invariants, serve "
                    "plan/ack pairing — and embeds the obs diagnose "
                    "verdict in its JSON report.")
    p.add_argument("path",
                   help="dump directory (obs_g*_r*.json) or one dump file")
    p.add_argument("--generation", type=int, default=None,
                   help="replay this generation (default: newest found)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--fail-on", choices=("warning", "error", "never"),
                   default="warning")
    p.add_argument("--list-rules", action="store_true")
    return p


def replay_main(argv: List[str]) -> int:
    from .replay import REPLAY_RULE_DOCS, replay_dir

    if "--list-rules" in argv:
        for code in sorted(REPLAY_RULE_DOCS):
            print(f"{code}  {REPLAY_RULE_DOCS[code]}")
        return 0
    args = build_replay_parser().parse_args(argv)
    report = replay_dir(args.path, generation=args.generation)
    if not report.ranks:
        sys.stderr.write(f"replay: no flight-recorder dumps under "
                         f"{args.path!r}\n")
        return 2
    doc = report.to_json()
    extra = {k: doc[k] for k in ("tool", "generation", "ranks",
                                 "diagnosis")}
    if args.format == "text":
        from ..obs.trace import render_diagnosis
        print(f"replay: generation {report.generation}, "
              f"ranks {report.ranks}")
        print(render_diagnosis(report.diagnosis))
    return _finish(report.findings, args.format, args.fail_on,
                   extra_json=extra)


# -- dispatch -----------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "graph":
        return graph_main(argv[1:])
    if argv and argv[0] == "replay":
        return replay_main(argv[1:])
    if argv and argv[0] == "lint":
        argv = argv[1:]
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for code in sorted(RULE_DOCS):
            print(f"{code}  {RULE_DOCS[code]}")
        return 0
    paths = args.paths or _default_paths()
    if not paths:
        sys.stderr.write("no paths given and no tpu_dist/examples dirs "
                         "found near the CWD or package root\n")
        return 2
    rules = ([r.strip().upper() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    if rules:
        unknown = [r for r in rules if r not in RULE_DOCS and r != "TD000"]
        if unknown:
            sys.stderr.write(f"unknown rule(s): {', '.join(unknown)} "
                             f"(see --list-rules)\n")
            return 2
    findings = lint_paths(paths, rules=rules)
    return _finish(findings, args.format, args.fail_on,
                   show_suppressed=args.show_suppressed)


if __name__ == "__main__":
    sys.exit(main())
