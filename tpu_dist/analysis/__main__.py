"""``python -m tpu_dist.analysis <paths>`` — the tpudlint CLI.

Exit codes: 0 = clean (no unsuppressed finding at/above ``--fail-on``),
1 = findings, 2 = usage error.  ``--format json`` emits the schema in
tpu_dist/analysis/findings.py; text is ``path:line:col: TDnnn [sev] msg``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from .findings import SEVERITY_ORDER, render_json, render_text
from .linter import lint_paths
from .rules import RULE_DOCS


def _default_paths() -> List[str]:
    """``tpu_dist`` + ``examples``, resolved against the CWD first and the
    repo/package root second — so the documented bare invocation works
    from any directory instead of emitting TD000 read errors."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    out = []
    for name in ("tpu_dist", "examples"):
        if os.path.exists(name):
            out.append(name)
        elif os.path.exists(os.path.join(root, name)):
            out.append(os.path.join(root, name))
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpu_dist.analysis",
        description="tpudlint: distributed-correctness linter for tpu_dist "
                    "programs (rank-divergent collectives, un-namespaced "
                    "store keys, deadline-less waits, host effects under "
                    "jit, lock-order cycles).")
    p.add_argument("paths", nargs="*", default=None,
                   help="files or directories to lint (default: the "
                        "repo's tpu_dist + examples dirs, resolved "
                        "against the CWD and then the installed package "
                        "root)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--rules", type=str, default=None,
                   help="comma-separated rule codes to run (default: all)")
    p.add_argument("--fail-on", choices=("warning", "error", "never"),
                   default="warning",
                   help="minimum unsuppressed severity that makes the exit "
                        "code non-zero (default: warning, i.e. any finding)")
    p.add_argument("--show-suppressed", action="store_true",
                   help="include suppressed findings in text output")
    p.add_argument("--list-rules", action="store_true")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for code in sorted(RULE_DOCS):
            print(f"{code}  {RULE_DOCS[code]}")
        return 0
    paths = args.paths or _default_paths()
    if not paths:
        sys.stderr.write("no paths given and no tpu_dist/examples dirs "
                         "found near the CWD or package root\n")
        return 2
    rules = ([r.strip().upper() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    if rules:
        unknown = [r for r in rules if r not in RULE_DOCS and r != "TD000"]
        if unknown:
            sys.stderr.write(f"unknown rule(s): {', '.join(unknown)} "
                             f"(see --list-rules)\n")
            return 2
    findings = lint_paths(paths, rules=rules)
    if args.format == "json":
        print(json.dumps(render_json(findings), indent=2))
    else:
        print(render_text(findings, show_suppressed=args.show_suppressed))
    if args.fail_on == "never":
        return 0
    threshold = SEVERITY_ORDER[args.fail_on]
    worst = max((SEVERITY_ORDER[f.severity] for f in findings
                 if not f.suppressed), default=0)
    return 1 if worst >= threshold else 0


if __name__ == "__main__":
    sys.exit(main())
