"""tpu_dist.analysis — distributed-correctness tooling.

Two halves (docs/analysis.md):

- **tpudlint**, a static AST pass over tpu_dist programs
  (``python -m tpu_dist.analysis <paths>``): six rule classes (TD001–TD006)
  for the hazards that silently deadlock an eager-SPMD world — collectives
  under rank conditionals, divergent collective sequences, un-namespaced
  store keys, deadline-less blocking waits, host side effects under
  ``jax.jit``, inconsistent lock order.  ``# tpudlint: disable=TDnnn``
  suppressions, text/JSON output, CI-friendly exit codes.
- a **runtime sanitizer** (``TPU_DIST_SANITIZE=1`` or ``tpu_dist.launch
  --sanitize``): every eager host collective cross-checks a per-call
  signature (op, tree structure, dtypes/shapes, call-site) across ranks
  through the generation-scoped store before executing, raising
  :class:`CollectiveMismatchError` naming the divergent rank and call-site
  within a bounded deadline instead of hanging.

veScale's argument (PAPERS.md) is that eager-mode SPMD needs consistency
*checking*, not just consistent primitives; Launchpad's is that a
program-level representation enables tooling.  tpudlint is the
program-level half, the sanitizer the runtime half.
"""

from .findings import Finding, render_json, render_text
from .linter import lint_file, lint_paths, lint_source
from .rules import RULE_DOCS, RULES
from .sanitizer import CollectiveMismatchError, check_collective, enabled

__all__ = ["Finding", "lint_source", "lint_file", "lint_paths",
           "render_text", "render_json", "RULES", "RULE_DOCS",
           "CollectiveMismatchError", "check_collective", "enabled"]
