"""tpu_dist.analysis — distributed-correctness tooling.

Four tools (docs/analysis.md):

- **tpudlint**, a static AST pass over tpu_dist programs
  (``python -m tpu_dist.analysis <paths>``): rule classes TD001–TD010
  for the hazards that silently deadlock an eager-SPMD world — collectives
  under rank conditionals, divergent collective sequences, un-namespaced
  store keys, deadline-less blocking waits, host side effects under
  ``jax.jit``, inconsistent lock order.  ``# tpudlint: disable=TDnnn``
  suppressions, text/JSON output, CI-friendly exit codes.
- a **runtime sanitizer** (``TPU_DIST_SANITIZE=1`` or ``tpu_dist.launch
  --sanitize``): every eager host collective cross-checks a per-call
  signature (op, tree structure, dtypes/shapes, call-site) across ranks
  through the generation-scoped store before executing, raising
  :class:`CollectiveMismatchError` naming the divergent rank and call-site
  within a bounded deadline instead of hanging.
- the **static whole-graph protocol verifier** (protocol.py,
  ``python -m tpu_dist.analysis graph``, launcher ``--verify-graph``):
  model-checks a RoleGraph + ChannelSpec topology — bounded-channel
  deadlock cycles with a printed witness schedule, claim-safety under
  solo restarts, restart-policy soundness, dp-path feasibility
  (TD101–TD105).
- the **offline trace-replay sanitizer** (replay.py,
  ``python -m tpu_dist.analysis replay <dump-dir>``): re-verifies a
  flight-recorder dump post-hoc — lockstep collective linearization,
  store-key lifecycle, channel cursor invariants (orphaned claims,
  double-acks, hole-skip/late-write conflicts), serve plan/ack pairing
  (TD110–TD115) — sharing one JSON schema with ``obs diagnose``.

veScale's argument (PAPERS.md) is that eager-mode SPMD needs consistency
*checking*, not just consistent primitives; Launchpad's is that a
program-level representation enables tooling.  tpudlint and the graph
verifier are the program-level half, the sanitizers the runtime half —
and replay closes the loop by re-running the runtime checks over what a
crashed job actually did.
"""

from .findings import Finding, render_json, render_text
from .linter import lint_file, lint_paths, lint_source
from .protocol import (GRAPH_RULE_DOCS, extract_channel_specs,
                       parse_channels_spec, verify_graph)
from .replay import (REPLAY_RULE_DOCS, ReplayReport, replay_dir,
                     replay_dumps)
from .rules import RULE_DOCS, RULES
from .sanitizer import CollectiveMismatchError, check_collective, enabled

__all__ = ["Finding", "lint_source", "lint_file", "lint_paths",
           "render_text", "render_json", "RULES", "RULE_DOCS",
           "CollectiveMismatchError", "check_collective", "enabled",
           "GRAPH_RULE_DOCS", "verify_graph", "extract_channel_specs",
           "parse_channels_spec",
           "REPLAY_RULE_DOCS", "ReplayReport", "replay_dumps",
           "replay_dir"]
