"""Finding model + suppression handling for ``tpudlint``.

A :class:`Finding` is one rule violation at one source location.  Findings
carry a severity (``error`` > ``warning``) and render to the stable text
format ``path:line:col: TDnnn [severity] message`` or to the JSON schema::

    {"version": 1,
     "findings": [{"rule": "TD001", "severity": "error", "path": "...",
                   "line": 3, "col": 4, "message": "..."}],
     "counts": {"error": 1, "warning": 0, "suppressed": 2}}

Suppressions (``# tpudlint: disable=TD001`` or ``disable=TD001,TD004``):

- on the same physical line as the finding — suppresses those rules for
  that line;
- on a standalone comment line — suppresses those rules for the next
  non-blank line (so long flagged lines can carry a justification above);
- ``disable=all`` suppresses every rule for the covered line.

Suppressed findings are kept (marked) rather than dropped, so the JSON
output can audit what was silenced and the self-lint gate can distinguish
"clean" from "suppressed with a justification".
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional

__all__ = ["Finding", "SEVERITY_ORDER", "suppressed_rules_by_line",
           "apply_suppressions", "render_text", "render_json"]

# higher = more severe; CLI --fail-on thresholds compare through this
SEVERITY_ORDER = {"warning": 1, "error": 2}

_SUPPRESS_RE = re.compile(
    r"#\s*tpudlint:\s*disable=([A-Za-z0-9_,\s]+?)\s*(?:#|$)")


@dataclasses.dataclass
class Finding:
    rule: str          # "TD001" .. "TD006" ("TD000" = file failed to parse)
    severity: str      # "error" | "warning"
    path: str
    line: int          # 1-based
    col: int           # 0-based (ast convention)
    message: str
    suppressed: bool = False

    def to_dict(self) -> Dict:
        return {"rule": self.rule, "severity": self.severity,
                "path": self.path, "line": self.line, "col": self.col,
                "message": self.message, "suppressed": self.suppressed}

    def render(self) -> str:
        sup = " (suppressed)" if self.suppressed else ""
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.severity}]{sup} {self.message}")


def suppressed_rules_by_line(source: str) -> Dict[int, set]:
    """Map 1-based line number -> set of rule codes suppressed there.

    The set may contain ``"all"``.  A standalone suppression comment covers
    the next non-blank line as well as its own.
    """
    out: Dict[int, set] = {}
    lines = source.splitlines()
    for i, raw in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(raw)
        if not m:
            continue
        rules = {r.strip().upper() if r.strip().lower() != "all" else "all"
                 for r in m.group(1).split(",") if r.strip()}
        out.setdefault(i, set()).update(rules)
        if raw.lstrip().startswith("#"):
            # standalone comment: also covers the next code line (blank
            # lines and further comment lines — e.g. a stacked suppression
            # — are skipped, so stacked standalone suppressions all land
            # on the same code line)
            for j in range(i, len(lines)):
                stripped = lines[j].strip()
                if stripped and not stripped.startswith("#"):
                    out.setdefault(j + 1, set()).update(rules)
                    break
    return out


def apply_suppressions(findings: List[Finding], source: str) -> None:
    """Mark findings whose line carries a matching suppression comment."""
    by_line = suppressed_rules_by_line(source)
    for f in findings:
        rules = by_line.get(f.line)
        if rules and ("all" in rules or f.rule.upper() in rules):
            f.suppressed = True


def counts(findings: List[Finding]) -> Dict[str, int]:
    out = {"error": 0, "warning": 0, "suppressed": 0}
    for f in findings:
        if f.suppressed:
            out["suppressed"] += 1
        else:
            out[f.severity] = out.get(f.severity, 0) + 1
    return out


def render_text(findings: List[Finding],
                show_suppressed: bool = False) -> str:
    lines = [f.render() for f in findings
             if show_suppressed or not f.suppressed]
    c = counts(findings)
    lines.append(f"tpudlint: {c['error']} error(s), {c['warning']} "
                 f"warning(s), {c['suppressed']} suppressed")
    return "\n".join(lines)


def render_json(findings: List[Finding],
                show_suppressed: bool = True) -> Dict:
    return {"version": 1,
            "findings": [f.to_dict() for f in findings
                         if show_suppressed or not f.suppressed],
            "counts": counts(findings)}


def worst_unsuppressed(findings: List[Finding]) -> Optional[str]:
    """The highest severity among unsuppressed findings, or None."""
    worst = None
    for f in findings:
        if f.suppressed:
            continue
        if worst is None or SEVERITY_ORDER[f.severity] > SEVERITY_ORDER[worst]:
            worst = f.severity
    return worst
