"""Static whole-graph protocol verifier — the TD100 rule family.

tpudlint (rules.py) checks single call sites; this module model-checks the
*graph*: a :class:`~tpu_dist.roles.graph.RoleGraph` plus its
:class:`~tpu_dist.roles.graph.ChannelSpec` topology, before a single
process is spawned.  Surfaced as ``python -m tpu_dist.analysis graph`` and
as the launcher's ``--verify-graph`` pre-flight, which refuses to spawn a
provably-deadlocking graph.

The model: roles are processes, ``queue`` channels are bounded FIFO
buffers whose ``put`` blocks once ``depth`` messages are unacknowledged,
``latest`` channels are registers whose writes never block.  That is
exactly the Kahn-network boundedness setting, so:

- **TD101** (error) — a directed cycle of ``queue`` edges is a
  may-deadlock: there exists a schedule in which every role on the cycle
  fills its outgoing queue and then blocks in ``put`` waiting for the next
  role — which is itself blocked.  The finding carries the witness
  schedule, step by step.  ``latest`` edges never block a writer and
  therefore break cycles.  A cycle whose every edge carries a
  ``ChannelSpec.credits`` annotation (the producer's claim discipline
  bounds its unacknowledged in-flight messages) is admitted when every
  edge has ``depth >= credits`` — in-flight never reaches the
  backpressure wall, so no put on the cycle can block (the 1F1B
  pipeline's fwd/grad loop); an annotated edge with ``depth < credits``
  keeps the error, with a credit-overflow witness naming the edge.
- **TD102** (warning) — claim-safety under restarts: a solo-restarting
  producer can die inside the head-claim/write kill window (holes the
  consumers must settle-ack, losing the message), and a solo-restarting
  rank of a *multi*-consumer role dies holding claims that no sibling can
  return (the orphaned-claim ledger reconciles them only at respawn).
- **TD103** — restart-policy soundness: an ``@node`` pin beyond the
  cluster (error: ``validate_placement`` would refuse at spawn), an
  all-solo graph (warning: no gang anchor means the generation fence
  never advances), and a solo producer pool wider than the channel depth
  (warning: simultaneous kill windows can wedge every slot until the
  hole-settle deadline).
- **TD104** (warning) — dp-path feasibility: a channel whose consumer
  role spans multiple ranks keeps array payloads on the store funnel
  (~96x slower than the p2p lane at 8 MiB); a ``payload_bytes`` hint at
  or above ``TPU_DIST_DP_THRESHOLD`` makes that a named warning instead
  of a production surprise.
- **TD105** (error) — graph/spec mismatch: a channel endpoint naming a
  role absent from the ``--roles`` spec (``RoleGraphError`` at spawn).

Graph sources (``build_graph`` orchestrates; the CLI and the
``--verify-graph`` pre-flight both go through it):

- ``--graph file.py:builder`` / ``--graph pkg.mod:builder`` — import and
  call the graph builder (``load_graph_builder``), the precise path.
- ``--roles`` spec (roles/graph.py grammar) + ``ChannelSpec`` literals
  AST-extracted from the target script (``extract_channel_specs``) and/or
  a ``--channels`` spec (``parse_channels_spec``).
"""

from __future__ import annotations

import ast
import importlib
import importlib.util
import os
from typing import Dict, List, Optional, Sequence, Tuple

from .findings import Finding

__all__ = ["GRAPH_RULE_DOCS", "verify_graph", "extract_channel_specs",
           "parse_channels_spec", "load_graph_builder", "build_graph",
           "render_witness", "render_credit_witness"]

GRAPH_RULE_DOCS = {
    "TD101": "bounded-channel wait-for cycle: every role on the cycle can "
             "fill its outgoing queue and block in put() waiting for the "
             "next blocked role — deadlock, witness schedule printed; a "
             "cycle fully annotated with credits <= depth on every edge "
             "is admitted (credit-disciplined, puts never block)",
    "TD102": "claim-safety under solo restarts: producer kill-window holes "
             "are settle-acked (message loss), and a killed rank of a "
             "multi-consumer role strands claims until respawn "
             "reconciliation",
    "TD103": "restart-policy soundness: @node pin beyond the cluster, "
             "all-solo graph without a gang anchor, or a solo producer "
             "pool wider than the channel depth",
    "TD104": "dp-path feasibility: multi-rank consumer role with a payload "
             "hint at/above TPU_DIST_DP_THRESHOLD rides the store funnel "
             "instead of the p2p lane",
    "TD105": "graph/spec mismatch: channel endpoint names a role absent "
             "from the role spec (RoleGraphError at spawn)",
}


def _default_dp_threshold() -> int:
    try:
        return int(os.environ.get("TPU_DIST_DP_THRESHOLD",
                                  str(64 * 1024)))
    except ValueError:
        return 64 * 1024


# -- witness rendering --------------------------------------------------------


def render_witness(cycle: Sequence[Tuple[str, "object"]]) -> str:
    """The step-by-step schedule that realizes a TD101 cycle.

    ``cycle`` is ``[(role, outgoing ChannelSpec), ...]`` with each
    channel's ``dst`` equal to the next entry's role (wrapping)."""
    lines = ["witness schedule (from the initial empty-channel state):"]
    step = 1
    for role, ch in cycle:
        lines.append(
            f"  {step}. {role} puts {ch.depth} message(s) on "
            f"{ch.name!r} (depth {ch.depth}) before {ch.dst} drains any "
            f"-> {ch.name!r} is full")
        step += 1
    for role, ch in cycle:
        lines.append(
            f"  {step}. {role} blocks in put #{ch.depth + 1} on "
            f"{ch.name!r}: needs {ch.dst} to ack a slot")
        step += 1
    ring = " -> ".join([role for role, _ in cycle] + [cycle[0][0]])
    lines.append(
        f"  wait-for cycle: {ring}; no role can ack while blocked in "
        f"put, so every put times out and no schedule drains the graph")
    return "\n".join(lines)


def render_credit_witness(cycle: Sequence[Tuple[str, "object"]],
                          over: Sequence[Tuple[str, "object"]]) -> str:
    """The witness schedule for a credit-annotated cycle with an
    under-depth edge: the producer's declared in-flight window
    (``credits``) overflows the channel's ``depth``, so the claim
    discipline that was supposed to keep the cycle live blocks instead."""
    lines = ["witness schedule (from the initial empty-channel state):"]
    step = 1
    for role, ch in over:
        lines.append(
            f"  {step}. {role} opens its declared window: puts "
            f"{ch.depth} message(s) on {ch.name!r} (depth {ch.depth}) "
            f"before claiming any inbound ack")
        step += 1
        lines.append(
            f"  {step}. {role} blocks in put #{ch.depth + 1} of its "
            f"{ch.credits}-credit window on {ch.name!r}: the window "
            f"does not fit the depth, and its claim discipline only "
            f"acks inbound edges *between* window puts")
        step += 1
    ring = " -> ".join([role for role, _ in cycle] + [cycle[0][0]])
    lines.append(
        f"  wait-for cycle: {ring}; the blocked producer never reaches "
        f"the claim that would ack its inbound edge, so the cycle "
        f"wedges — raise depth to at least credits on the edge(s) above")
    return "\n".join(lines)


# -- the verifier -------------------------------------------------------------


def _queue_edges(graph) -> List[Tuple[str, str, "object"]]:
    # latest registers never block a writer; a dedicated-drain consumer
    # (ChannelSpec.drain) acks from its own thread even while the role's
    # main loop is blocked in put — neither can close a wait-for cycle
    return [(c.src, c.dst, c) for c in graph.channels
            if c.kind == "queue"
            and getattr(c, "drain", "inline") != "dedicated"]


def _find_cycles(graph) -> List[List[Tuple[str, "object"]]]:
    """One elementary cycle per strongly-connected component of the
    queue-edge graph (Tarjan SCC + a DFS walk inside the component)."""
    edges = _queue_edges(graph)
    adj: Dict[str, List[Tuple[str, "object"]]] = {}
    for src, dst, ch in edges:
        adj.setdefault(src, []).append((dst, ch))

    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Dict[str, bool] = {}
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        # iterative Tarjan: (node, iterator-position) frames
        work = [(v, 0)]
        while work:
            node, pi = work.pop()
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack[node] = True
            recurse = False
            succs = adj.get(node, [])
            for i in range(pi, len(succs)):
                w = succs[i][0]
                if w not in index:
                    work.append((node, i + 1))
                    work.append((w, 0))
                    recurse = True
                    break
                if on_stack.get(w):
                    low[node] = min(low[node], index[w])
            if recurse:
                continue
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])

    for src, _, _ in edges:
        if src not in index:
            strongconnect(src)

    cycles: List[List[Tuple[str, "object"]]] = []
    for comp in sccs:
        comp_set = set(comp)
        self_loops = [ch for src, dst, ch in edges
                      if src == dst and src in comp_set]
        if self_loops:
            cycles.append([(self_loops[0].src, self_loops[0])])
            continue
        if len(comp) < 2:
            continue
        # walk a simple cycle inside the component
        start = comp[0]
        path: List[Tuple[str, "object"]] = []
        seen = {start}
        node = start
        while True:
            nxt = next(((dst, ch) for dst, ch in adj.get(node, [])
                        if dst in comp_set), None)
            if nxt is None:  # pragma: no cover - SCC guarantees an edge
                break
            dst, ch = nxt
            path.append((node, ch))
            if dst == start:
                cycles.append(path)
                break
            if dst in seen:
                # trim the tail before the repeated node
                i = next(i for i, (r, _) in enumerate(path) if r == dst)
                cycles.append(path[i:])
                break
            seen.add(dst)
            node = dst
    return cycles


def verify_graph(graph, nnodes: Optional[int] = None,
                 dp_threshold: Optional[int] = None,
                 path: str = "<graph>") -> List[Finding]:
    """Model-check ``graph`` (a :class:`RoleGraph`); returns TD100-family
    :class:`Finding` objects (line/col 0 — findings are about the graph,
    not a source location)."""
    out: List[Finding] = []
    thr = dp_threshold if dp_threshold is not None \
        else _default_dp_threshold()
    roles = {r.name: r for r in graph.roles}

    # TD101: bounded-queue wait-for cycles.  A cycle in which EVERY edge
    # is credit-annotated is deadlock-free iff every edge has depth >=
    # credits: the producer's claim discipline keeps in-flight <= credits
    # <= depth, so no put on the cycle ever reaches the backpressure wall
    # and no wait-for edge can form (the 1F1B fwd/grad loop).  A single
    # unannotated edge voids the argument — the classic witness stands.
    for cycle in _find_cycles(graph):
        ring = " -> ".join([r for r, _ in cycle] + [cycle[0][0]])
        credited = all(getattr(ch, "credits", None) is not None
                       for _, ch in cycle)
        if credited:
            over = [(r, ch) for r, ch in cycle if ch.depth < ch.credits]
            if not over:
                continue  # credit-disciplined cycle: puts never block
            chans = ", ".join(
                f"{ch.name!r}(depth {ch.depth} < credits {ch.credits})"
                for _, ch in over)
            out.append(Finding(
                "TD101", "error", path, 0, 0,
                f"bounded-channel deadlock: credit-annotated queue cycle "
                f"{ring} has under-depth edge(s) {chans} — the producer's "
                f"declared in-flight window does not fit the channel, so "
                f"its put blocks mid-window and the cycle's claim "
                f"discipline wedges\n{render_credit_witness(cycle, over)}"))
            continue
        chans = ", ".join(f"{ch.name!r}(depth {ch.depth})"
                          for _, ch in cycle)
        out.append(Finding(
            "TD101", "error", path, 0, 0,
            f"bounded-channel deadlock: queue cycle {ring} over {chans} "
            f"— a schedule exists where every role is blocked in put() "
            f"on a full queue only the next blocked role could drain\n"
            f"{render_witness(cycle)}"))

    # TD102: claim-safety under solo restarts.  The healed cases stay
    # silent: a single consumer rewinds orphans at attach, a gang
    # restart re-fences the generation, and a solo respawn inherits the
    # dead rank's persisted claims.  What cannot be healed in place is a
    # tight window: multi-consumer claims are unreturnable (a sibling
    # may have claimed past the dead rank), so with depth <= consumer
    # world a simultaneous kill can strand EVERY slot in orphaned
    # claims until the respawns attach — puts wedge meanwhile.
    for ch in graph.channels:
        if ch.kind != "queue":
            continue
        dst = roles.get(ch.dst)
        if (dst is not None and dst.restart == "solo"
                and dst.world > 1 and ch.depth <= dst.world):
            out.append(Finding(
                "TD102", "warning", path, 0, 0,
                f"channel {ch.name!r}: depth {ch.depth} <= "
                f"{dst.world} solo-restarting consumers — ranks killed "
                f"holding multi-consumer claims (unreturnable: a "
                f"sibling may have claimed past them) can strand the "
                f"entire backpressure window in orphaned claims until "
                f"their respawns inherit them; raise depth above the "
                f"consumer world or restart {ch.dst!r} as a gang "
                f"(replay names the orphans, TD112)"))

    # TD103: restart-policy soundness
    for r in graph.roles:
        if r.node is not None and nnodes is not None and r.node >= nnodes:
            out.append(Finding(
                "TD103", "error", path, 0, 0,
                f"role {r.name!r} pins @node{r.node} but the cluster has "
                f"{nnodes} node(s) (node indices 0..{nnodes - 1}) — "
                f"validate_placement refuses this at spawn"))
    if graph.roles and all(r.restart == "solo" for r in graph.roles):
        out.append(Finding(
            "TD103", "warning", path, 0, 0,
            f"all {len(graph.roles)} role(s) restart solo: the graph has "
            f"no gang anchor, so the generation fence never advances and "
            f"an exhausted solo-restart budget halts the graph with no "
            f"collective restart path"))
    for ch in graph.channels:
        if ch.kind != "queue":
            continue
        src = roles.get(ch.src)
        if (src is not None and src.restart == "solo"
                and src.world > ch.depth):
            out.append(Finding(
                "TD103", "warning", path, 0, 0,
                f"channel {ch.name!r}: depth {ch.depth} < {src.world} "
                f"solo producers — simultaneous kill windows can hole "
                f"every slot, wedging the queue for the full hole-settle "
                f"deadline; raise depth to at least the producer world"))

    # TD104: dp-path feasibility
    for ch in graph.channels:
        dst = roles.get(ch.dst)
        hint = getattr(ch, "payload_bytes", None)
        if (dst is not None and dst.world > 1 and hint is not None
                and hint >= thr):
            out.append(Finding(
                "TD104", "warning", path, 0, 0,
                f"channel {ch.name!r}: consumer role {ch.dst!r} spans "
                f"{dst.world} ranks, so {hint} B payloads stay on the "
                f"store funnel (p2p lane needs a single-rank consumer; "
                f"threshold TPU_DIST_DP_THRESHOLD={thr}) — expect ~96x "
                f"the latency of the data plane at 8 MiB"))

    out.sort(key=lambda f: (f.rule, f.message))
    return out


# -- graph sources ------------------------------------------------------------


def extract_channel_specs(path: str) -> Tuple[List["object"], List[str]]:
    """AST-extract literal ``ChannelSpec(...)`` calls from a Python file.

    Returns ``(specs, notes)`` — notes name calls that were skipped
    because an argument was not a literal (those channels cannot be
    checked statically; point ``--graph`` at the builder instead)."""
    from ..roles.graph import ChannelSpec

    with open(path, "r", encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)
    fields = ("name", "src", "dst", "depth", "kind", "payload_bytes",
              "drain", "credits")
    specs: List[object] = []
    notes: List[str] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else None)
        if name != "ChannelSpec":
            continue
        kw: Dict[str, object] = {}
        ok = True
        for i, arg in enumerate(node.args):
            try:
                kw[fields[i]] = ast.literal_eval(arg)
            except (ValueError, IndexError):
                ok = False
        for k in node.keywords:
            if k.arg is None:
                ok = False
                continue
            try:
                kw[k.arg] = ast.literal_eval(k.value)
            except ValueError:
                ok = False
        if not ok or not {"name", "src", "dst"} <= set(kw):
            notes.append(
                f"{path}:{node.lineno}: ChannelSpec call with non-literal "
                f"arguments skipped — use --graph to import the builder")
            continue
        try:
            specs.append(ChannelSpec(**kw))
        except Exception as e:
            notes.append(f"{path}:{node.lineno}: invalid ChannelSpec "
                         f"literal skipped ({e})")
    return specs, notes


def parse_channels_spec(text: str) -> List["object"]:
    """Parse a ``--channels`` spec: comma-separated
    ``name:src>dst[:N][:queue|latest][:payload=BYTES]`` entries (a bare
    integer token is the depth, ``queue``/``latest`` the kind)."""
    from ..roles.graph import ChannelSpec, RoleGraphError

    out: List[object] = []
    for entry in [e.strip() for e in text.split(",") if e.strip()]:
        parts = entry.split(":")
        if len(parts) < 2 or ">" not in parts[1]:
            raise RoleGraphError(
                f"bad channel spec {entry!r}: want "
                f"name:src>dst[:depth][:kind][:payload=BYTES]")
        name = parts[0]
        src, _, dst = parts[1].partition(">")
        kw: Dict[str, object] = {"name": name, "src": src.strip(),
                                 "dst": dst.strip()}
        for tok in parts[2:]:
            tok = tok.strip()
            if tok in ("queue", "latest"):
                kw["kind"] = tok
            elif tok.startswith("payload="):
                kw["payload_bytes"] = int(tok[len("payload="):])
            elif tok.isdigit():
                kw["depth"] = int(tok)
            else:
                raise RoleGraphError(
                    f"bad channel spec token {tok!r} in {entry!r}")
        out.append(ChannelSpec(**kw))
    return out


def load_graph_builder(target: str, args_json: Optional[str] = None):
    """Import ``file.py:func`` or ``pkg.mod:func`` and call it with the
    JSON-decoded positional args (``--graph-args '[4]'``); returns the
    RoleGraph the builder returns."""
    import json as _json

    mod_part, _, fn_name = target.rpartition(":")
    if not mod_part:
        raise ValueError(f"--graph wants file.py:func or pkg.mod:func, "
                         f"got {target!r}")
    if mod_part.endswith(".py") or os.path.sep in mod_part:
        spec = importlib.util.spec_from_file_location(
            "_tpu_dist_graph_target", mod_part)
        if spec is None or spec.loader is None:
            raise ValueError(f"cannot import {mod_part!r}")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    else:
        mod = importlib.import_module(mod_part)
    fn = getattr(mod, fn_name)
    call_args = _json.loads(args_json) if args_json else []
    if not isinstance(call_args, list):
        call_args = [call_args]
    return fn(*call_args)


def build_graph(roles_spec: Optional[str] = None,
                script: Optional[str] = None,
                channels_spec: Optional[str] = None,
                graph_target: Optional[str] = None,
                graph_args: Optional[str] = None,
                path: str = "<graph>"):
    """Assemble the graph to verify from the CLI/pre-flight inputs.

    Returns ``(graph_or_None, findings, notes)`` — endpoint mismatches
    become TD105 error findings instead of raising, so the pre-flight can
    refuse with the normal findings machinery."""
    from ..roles.graph import RoleGraph, RoleGraphError, parse_roles_spec

    notes: List[str] = []
    findings: List[Finding] = []
    if graph_target:
        graph = load_graph_builder(graph_target, graph_args)
        return graph, findings, notes
    if not roles_spec:
        raise RoleGraphError("no graph source: give --graph, or --roles "
                             "(with an optional script / --channels)")
    base = parse_roles_spec(roles_spec)
    channels = list(base.channels)
    if script and os.path.exists(script) and script.endswith(".py"):
        specs, ex_notes = extract_channel_specs(script)
        channels.extend(specs)
        notes.extend(ex_notes)
    if channels_spec:
        channels.extend(parse_channels_spec(channels_spec))
    role_names = {r.name for r in base.roles}
    kept = []
    seen = set()
    for ch in channels:
        if ch.name in seen:
            continue  # first declaration wins (script + --channels overlap)
        seen.add(ch.name)
        missing = [e for e in (ch.src, ch.dst) if e not in role_names]
        if missing:
            findings.append(Finding(
                "TD105", "error", path, 0, 0,
                f"channel {ch.name!r} endpoint(s) "
                f"{', '.join(repr(m) for m in missing)} not in the role "
                f"spec ({', '.join(sorted(role_names))}) — "
                f"RoleGraphError at spawn"))
            continue
        kept.append(ch)
    try:
        graph = RoleGraph(list(base.roles), kept)
    except RoleGraphError as e:
        findings.append(Finding("TD105", "error", path, 0, 0, str(e)))
        return None, findings, notes
    return graph, findings, notes
