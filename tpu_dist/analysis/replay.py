"""Offline trace-replay sanitizer — the TD110 rule family.

Consumes obs flight-recorder dumps (the ``obs_g{gen}_r{rank}.json``
merge format from obs/trace.py) and re-verifies the run's *protocol*
post-hoc, so any chaos e2e or production incident dump replays into a
named verdict instead of a folder of JSON.  Surfaced as
``python -m tpu_dist.analysis replay <dump-dir>``.

What it checks (each emitted through the tpudlint findings machinery):

- **TD110** — lockstep ``coll`` linearization: every rank of an SPMD
  program increments the collective sequence number in lockstep, so at
  each seq the ranks must agree on the op (and reduce/digest for
  symmetric ops).  Divergence is named like the live sanitizer's
  ``CollectiveMismatchError`` — but from a crash dump.
- **TD111** — store-key lifecycle: access to another generation's
  ``tpu_dist/g{N}/…`` namespace, a write under a prefix this rank
  already reaped with ``delete_prefix``, and sub-group
  (``…/grp{id}/…``) keys touched by a rank that the recorded
  group-collective membership says is not a member.
- **TD112** — channel cursor invariants over the ``channel`` event kind
  (roles/channel.py emits one event per cursor transition): a claim that
  is never resolved by an ack/consume/hole-skip and never returned is an
  **orphaned claim** (the PR 12 documented limit — a rank killed holding
  multi-consumer claims), and a slot resolved more than once is a
  double-ack accounting error.
- **TD113** — hole-skip vs late-write conflict: a slot that was
  settle-acked as a hole *and* has a recorded write — the message was
  lost and its ``m/{idx}`` key leaks until the generation reap.
- **TD114** — serve plan/ack pairing (``plan`` event kind): a sharded
  follower with a gap in its applied plan-seq stream, and a disagg
  descriptor dispatched to prefill whose KV arrival was never recorded.
- **TD115** — the post-hoc hang verdict: obs/trace.py's
  :func:`~tpu_dist.obs.trace.diagnose` runs over the same dumps and its
  straggler/stuck verdict becomes an error finding naming the rank,
  collective seq and call-site; the full diagnosis dict is embedded in
  the JSON report (one schema with ``obs diagnose --json``).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Tuple

from .findings import Finding, counts as _counts

__all__ = ["REPLAY_RULE_DOCS", "ReplayReport", "replay_dumps",
           "replay_dir"]

REPLAY_RULE_DOCS = {
    "TD110": "lockstep collective divergence: ranks disagree on "
             "op/reduce/digest at one collective seq",
    "TD111": "store-key lifecycle violation: cross-generation access, "
             "write after a prefix reap, or sub-group namespace touched "
             "by a non-member rank",
    "TD112": "channel cursor invariant: orphaned claim (claimed, never "
             "resolved or returned) or double-acked slot",
    "TD113": "hole-skip vs late-write conflict: a settle-acked hole was "
             "actually written — message lost, slot key leaked",
    "TD114": "serve plan/ack pairing: follower plan-seq gap, or a "
             "dispatched disagg descriptor with no recorded KV arrival",
    "TD115": "post-hoc hang verdict: straggler/stuck rank named with its "
             "collective seq and site (same schema as obs diagnose)",
}

# key-namespace shapes (built from a root constant so these regex
# sources are not themselves raw store-key literals)
_ROOT = "tpu_dist"
_GEN_RE = re.compile(rf"^{_ROOT}/g(\d+)/")
_GRP_RE = re.compile(r"/grp(\d+)/")
_GROUP_LABEL_RE = re.compile(r"grp(\d+)\[([0-9,\s]*)\]")

# channel cursor transitions (roles/channel.py): ops that resolve a
# slot's accounting vs ops that open a claim on it
_RESOLVE_OPS = frozenset({"ack", "consume", "hole-skip"})
_CLAIM_OPS = frozenset({"claim", "inherit"})


@dataclasses.dataclass
class ReplayReport:
    """One replay verdict: findings + the embedded live-diagnosis dict."""
    path: str
    generation: int
    ranks: List[int]
    findings: List[Finding]
    diagnosis: dict

    def to_json(self) -> dict:
        return {"version": 1, "tool": "replay", "path": self.path,
                "generation": self.generation, "ranks": self.ranks,
                "diagnosis": self.diagnosis,
                "findings": [f.to_dict() for f in self.findings],
                "counts": _counts(self.findings)}


def _check_diagnosis(diag: dict, path: str) -> List[Finding]:
    out: List[Finding] = []
    v = diag.get("verdict")
    if v == "straggler":
        s = diag.get("straggler")
        last = diag.get("straggler_last_coll")
        out.append(Finding(
            "TD115", "error", path, 0, 0,
            f"straggler: rank {s} is behind — "
            + ("never reached a collective"
               if last is None else
               f"last at collective #{last} "
               f"({diag.get('straggler_last_op')})")
            + f"; rank(s) {diag.get('waiting_ranks')} waiting in "
              f"collective #{diag.get('stuck_coll')} "
              f"({diag.get('stuck_op')}"
            + (f" at {diag.get('stuck_site')}"
               if diag.get("stuck_site") else "") + ")"))
    elif v == "stuck":
        out.append(Finding(
            "TD115", "error", path, 0, 0,
            f"stuck: all ranks reached collective "
            f"#{diag.get('stuck_coll')} ({diag.get('stuck_op')}) but "
            f"rank(s) {diag.get('waiting_ranks')} never completed it — "
            f"dead peer or wedged transport"))
    elif v == "missing-ranks":
        out.append(Finding(
            "TD115", "warning", path, 0, 0,
            f"missing ranks: no dump from rank(s) "
            f"{diag.get('missing_ranks')} (world {diag.get('world')}) — "
            f"SIGKILL/OOM leaves no dump"))
    return out


def _check_collectives(dumps: List[dict], path: str) -> List[Finding]:
    out: List[Finding] = []
    by_coll: Dict[int, Dict[int, dict]] = {}
    for d in dumps:
        rank = d.get("rank", 0)
        for e in d.get("events", []):
            if e.get("kind") != "collective" or e.get("coll") is None:
                continue
            by_coll.setdefault(e["coll"], {}).setdefault(rank, e)
    for coll in sorted(by_coll):
        ranks = by_coll[coll]
        if len(ranks) < 2:
            continue  # ring eviction / stragglers: nothing to compare
        ops = {r: e.get("op") for r, e in ranks.items()}
        if len(set(ops.values())) > 1:
            pairing = ", ".join(f"rank {r}: {op}"
                                for r, op in sorted(ops.items()))
            out.append(Finding(
                "TD110", "error", path, 0, 0,
                f"collective #{coll}: ranks paired different ops "
                f"({pairing}) — the lockstep sequence diverged"))
            continue
        reduces = {r: e.get("reduce") for r, e in ranks.items()
                   if e.get("reduce") is not None}
        if len(set(reduces.values())) > 1:
            pairing = ", ".join(f"rank {r}: {red}"
                                for r, red in sorted(reduces.items()))
            out.append(Finding(
                "TD110", "error", path, 0, 0,
                f"collective #{coll} ({next(iter(ops.values()))}): ranks "
                f"disagree on the reduce op ({pairing})"))
        if set(ops.values()) == {"all_reduce"}:
            digests = {r: e.get("digest") for r, e in ranks.items()
                       if e.get("digest") is not None}
            if len(set(digests.values())) > 1:
                pairing = ", ".join(f"rank {r}: {dg}"
                                    for r, dg in sorted(digests.items()))
                out.append(Finding(
                    "TD110", "error", path, 0, 0,
                    f"collective #{coll} (all_reduce): payload digests "
                    f"diverge across ranks ({pairing}) — shape/dtype "
                    f"mismatch the live sanitizer would name"))
    return out


def _group_membership(dumps: List[dict]) -> Dict[int, set]:
    """``grp id -> member ranks`` recovered from group-collective events'
    ``group`` labels (``grp{id}[r0, r1, ...]``)."""
    members: Dict[int, set] = {}
    for d in dumps:
        for e in d.get("events", []):
            label = e.get("group")
            if not label:
                continue
            m = _GROUP_LABEL_RE.search(str(label))
            if not m:
                continue
            gid = int(m.group(1))
            ranks = {int(tok) for tok in m.group(2).split(",")
                     if tok.strip()}
            members.setdefault(gid, set()).update(ranks)
    return members


def _check_store(dumps: List[dict], path: str) -> List[Finding]:
    out: List[Finding] = []
    membership = _group_membership(dumps)
    for d in dumps:
        rank = d.get("rank", 0)
        gen = d.get("generation", 0)
        reaped: List[str] = []
        for e in d.get("events", []):
            if e.get("kind") != "store":
                continue
            op = e.get("op")
            key = e.get("key")
            if op == "failover" or not isinstance(key, str):
                continue  # failover's "key" is the promoted leader addr
            m = _GEN_RE.match(key)
            if m and int(m.group(1)) != gen:
                out.append(Finding(
                    "TD111", "error", path, 0, 0,
                    f"rank {rank} (generation {gen}) {op} on another "
                    f"generation's key {key!r} — stale-incarnation "
                    f"cross-talk the generation fence exists to prevent"))
            if op == "delete_prefix":
                reaped.append(key)
                continue
            if op in ("set", "add"):
                hit = next((p for p in reaped
                            if key == p or key.startswith(p)), None)
                if hit is not None:
                    out.append(Finding(
                        "TD111", "warning", path, 0, 0,
                        f"rank {rank} wrote {key!r} after reaping prefix "
                        f"{hit!r} — the write outlives the reap and "
                        f"leaks until the next generation sweep"))
            g = _GRP_RE.search(key)
            if g:
                gid = int(g.group(1))
                known = membership.get(gid)
                if known and rank not in known:
                    out.append(Finding(
                        "TD111", "warning", path, 0, 0,
                        f"rank {rank} touched sub-group namespace key "
                        f"{key!r} but recorded grp{gid} membership is "
                        f"{sorted(known)} — non-member access breaks "
                        f"the group's scoped counters"))
    return out


def _check_channels(dumps: List[dict], path: str) -> List[Finding]:
    out: List[Finding] = []
    # (channel, slot) -> op -> [ranks]
    slots: Dict[Tuple[str, int], Dict[str, List[int]]] = {}
    for d in dumps:
        rank = d.get("rank", 0)
        for e in d.get("events", []):
            if e.get("kind") != "channel":
                continue
            ch = e.get("channel")
            slot = e.get("slot")
            if ch is None or slot is None:
                continue
            ops = slots.setdefault((str(ch), int(slot)), {})
            ops.setdefault(str(e.get("op")), []).append(rank)
    for (ch, slot) in sorted(slots):
        ops = slots[(ch, slot)]
        resolutions = [(op, r) for op in _RESOLVE_OPS
                       for r in ops.get(op, [])]
        if len(resolutions) > 1:
            pairing = ", ".join(f"{op} by rank {r}"
                                for op, r in sorted(resolutions))
            out.append(Finding(
                "TD112", "error", path, 0, 0,
                f"channel {ch!r} slot {slot}: resolved "
                f"{len(resolutions)} times ({pairing}) — a double-ack "
                f"inflates the backpressure window"))
        claimants = [r for op in _CLAIM_OPS for r in ops.get(op, [])]
        returned = bool(ops.get("claim-return"))
        abandoned = bool(ops.get("abandon"))
        if ((claimants or abandoned) and not resolutions
                and not returned):
            who = sorted(set(claimants)) or sorted(
                set(ops.get("abandon", [])))
            out.append(Finding(
                "TD112", "warning", path, 0, 0,
                f"channel {ch!r} slot {slot}: orphaned claim — rank(s) "
                f"{who} claimed the slot but no ack/consume/hole-skip "
                f"or claim-return followed (a rank killed holding a "
                f"multi-consumer claim strands the slot until its "
                f"respawn inherits it)"))
        if ops.get("hole-skip") and ops.get("put"):
            out.append(Finding(
                "TD113", "warning", path, 0, 0,
                f"channel {ch!r} slot {slot}: settle-acked as a hole by "
                f"rank(s) {sorted(set(ops['hole-skip']))} but rank(s) "
                f"{sorted(set(ops['put']))} recorded a write — the "
                f"message was lost and its slot key leaks until the "
                f"generation reap"))
    return out


def _check_plans(dumps: List[dict], path: str) -> List[Finding]:
    out: List[Finding] = []
    applied: Dict[int, List[int]] = {}
    dispatched: Dict[str, int] = {}
    arrived: set = set()
    for d in dumps:
        rank = d.get("rank", 0)
        for e in d.get("events", []):
            if e.get("kind") != "plan":
                continue
            op = e.get("op")
            if op == "apply" and e.get("plan_seq") is not None:
                applied.setdefault(rank, []).append(int(e["plan_seq"]))
            elif op == "dispatch" and e.get("req") is not None:
                dispatched[str(e["req"])] = rank
            elif op == "arrive" and e.get("req") is not None:
                arrived.add(str(e["req"]))
    for rank in sorted(applied):
        seqs = sorted(set(applied[rank]))
        missing = sorted(set(range(seqs[0], seqs[-1] + 1)) - set(seqs))
        if missing:
            out.append(Finding(
                "TD114", "warning", path, 0, 0,
                f"sharded follower rank {rank} applied plan seqs "
                f"{seqs[0]}..{seqs[-1]} but skipped {missing} — a "
                f"missed plan frame desyncs the follower's slot state"))
    for rid, rank in sorted(dispatched.items()):
        if rid not in arrived:
            out.append(Finding(
                "TD114", "warning", path, 0, 0,
                f"disagg descriptor req={rid!r} was dispatched to "
                f"prefill (rank {rank}) but no KV arrival was recorded "
                f"— the request was in flight when the run ended "
                f"(re-dispatch territory)"))
    return out


def replay_dumps(dumps: List[dict], path: str = "<dumps>") -> ReplayReport:
    """Re-verify one generation's dumps; returns the full report (the
    findings list is empty for a protocol-clean run)."""
    from ..obs.trace import diagnose

    diag = diagnose(dumps)
    findings: List[Finding] = []
    findings += _check_diagnosis(diag, path)
    findings += _check_collectives(dumps, path)
    findings += _check_store(dumps, path)
    findings += _check_channels(dumps, path)
    findings += _check_plans(dumps, path)
    findings.sort(key=lambda f: (f.rule, f.message))
    return ReplayReport(
        path=path,
        generation=dumps[0].get("generation", 0) if dumps else 0,
        ranks=sorted(d.get("rank", 0) for d in dumps),
        findings=findings, diagnosis=diag)


def replay_dir(path: str,
               generation: Optional[int] = None) -> ReplayReport:
    """Load ``obs_g*_r*.json`` dumps from ``path`` (newest generation
    unless pinned) and replay them."""
    from ..obs.trace import read_dumps

    return replay_dumps(read_dumps(path, generation=generation),
                        path=str(path))
