"""``tpudlint`` driver: walk paths, parse, run rules, apply suppressions.

Programmatic entry points (the CLI in ``__main__.py`` is a thin wrapper,
and tests/test_lint_self.py gates the repo on :func:`lint_paths`):

    from tpu_dist.analysis import lint_paths
    findings = lint_paths(["tpu_dist", "examples"])
    errors = [f for f in findings if not f.suppressed]
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, List, Optional

from .findings import Finding, apply_suppressions
from .rules import RULES, run_rules

__all__ = ["lint_source", "lint_file", "lint_paths", "iter_python_files"]

_SKIP_DIRS = {"__pycache__", ".git", ".eggs", "build", "dist",
              ".pytest_cache"}


def lint_source(source: str, path: str = "<string>",
                rules: Optional[Iterable[str]] = None) -> List[Finding]:
    """Lint one source string; returns findings with suppressions applied
    (suppressed findings are kept, marked ``suppressed=True``)."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding("TD000", "error", path, e.lineno or 1,
                        (e.offset or 1) - 1,
                        f"file does not parse: {e.msg}")]
    if rules is None:
        findings = run_rules(tree, path)
    else:
        wanted = {r.upper() for r in rules}
        findings = []
        for code, fn in RULES.items():
            out = fn(tree, path)
            # one rule function may emit several codes (TD001/TD002)
            findings.extend(f for f in out if f.rule in wanted)
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    apply_suppressions(findings, source)
    return findings


def lint_file(path: str,
              rules: Optional[Iterable[str]] = None) -> List[Finding]:
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            source = f.read()
    except OSError as e:
        return [Finding("TD000", "error", path, 1, 0,
                        f"cannot read file: {e}")]
    return lint_source(source, path, rules=rules)


def iter_python_files(paths: Iterable[str]) -> List[str]:
    """Expand files/directories into a sorted list of ``.py`` files."""
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        else:
            out.append(p)  # surfaces as a TD000 read error
    return sorted(dict.fromkeys(out))


def lint_paths(paths: Iterable[str],
               rules: Optional[Iterable[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        findings.extend(lint_file(path, rules=rules))
    return findings
