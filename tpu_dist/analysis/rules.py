"""``tpudlint`` rule implementations — AST passes over one module.

Each rule is a function ``(tree, path) -> [Finding]`` registered in
:data:`RULES`.  The rules encode the distributed-correctness hazards this
framework's own layers are exposed to (see docs/analysis.md for a
deadlocking example per rule):

- **TD001** — collective call inside a rank-conditional branch (classic
  ``if rank == 0: all_reduce(...)``): ranks taking the other branch never
  enter the collective, the participating ranks block forever.  Also fires
  on collectives *after* a rank-conditional early return.
- **TD002** — both branches of a rank-conditional call collectives, but
  different *sequences* of them: ranks pair a ring all-reduce against a
  broadcast and both sides hang (or worse, mis-match payloads).
- **TD003** — raw ``tpu_dist/...`` control-plane store key that is not
  namespaced by gang generation (``tpu_dist/g{gen}/...``) and is not one
  of the known cross-generation infrastructure prefixes.  Stale keys a
  crashed incarnation left behind would collide with a restarted
  incarnation's fresh sequence counters.
- **TD004** — blocking store/queue/socket wait without a deadline: a dead
  peer turns the call into an infinite hang the supervisor cannot name.
- **TD005** — host side effects (store ops, host collectives, ``time``,
  ``random``) inside ``jax.jit``/``pjit``-traced functions: they run at
  trace time, once, not per step — silently wrong, and rank-divergent
  tracing deadlocks the compile barrier.
- **TD006** — inconsistent lock-acquisition order inside one module (lock
  A taken under B in one place, B under A in another): the ABBA deadlock
  pattern for transport-style modules full of fine-grained locks.
- **TD008** — sub-group hazards (ROADMAP item 5's sub-group collectives
  rule): a ``new_group(...)`` member list computed from this rank's
  identity (every rank builds a DIFFERENT group — ids, store scopes and
  wire tags can never match), or a collective issued on a literal
  sub-group with no rank/membership guard (non-member ranks reach the
  call and die on ``GroupMembershipError`` — or deadlock the members if
  only some ranks guard).
- **TD009** — broad (``except Exception`` / bare) or explicit handler
  swallowing a *named* tpu_dist error class (``PeerGoneError``,
  ``RankLostError``, ``CollectiveMismatchError``, ``FrameCorruptError``,
  ``CollectiveTimeoutError``) without re-raising or logging: the
  anti-pattern that turns the resilience layer's named diagnoses — and
  every injected netchaos fault — back into silent hangs.
- **TD010** — role-graph channel hazards (tpu_dist.roles): a channel
  ``put``/``get``/``get_latest`` on a channel-named receiver without a
  timeout argument (warning — the TD004 family; channels do have an
  internal default deadline, but loops should state their budget), or a
  ``Channel``/``ChannelSpec`` whose literal ``src``/``dst`` names a role
  absent from the module's ``RoleGraph`` literal (error — a dangling
  endpoint raises ``RoleGraphError`` at runtime and can never carry a
  message).
- **TD011** — hand-rolled ``PartitionSpec`` naming a rule-plane layout
  axis (``model``/``shard``/``expert``) outside ``parallel/rules.py``
  and its spec builders (``gspmd.py``, ``fsdp.py``): parameter
  placements derive from the unified logical-axis table
  (``rules.spec_for``/``partition_pairs``/``spans_for``) — duplicated
  layout literals are exactly how the pjit, ZeRO, reshard and serving
  layouts drifted before the rule plane existed.

- **TD007** — async collective ``Work`` handle dropped without ``wait()``:
  a bare-expression call with ``async_op=True`` (the handle is discarded
  on the spot), or a handle assigned to a name that is never used again.
  The collective's *errors* travel on the handle (``PeerGoneError``,
  ``CollectiveMismatchError`` re-raise at ``wait()``) — dropping it
  swallows the diagnosis, and gradients synced this way are silently
  unordered against the consumer.

Heuristics are deliberately name-based (``rank``-ish identifiers,
``*_host`` collectives, ``_mu``/``_lock``/``_cv`` locks): this linter
checks *this* codebase's conventions, the same way PR 2 fixed the key
namespace by convention.  False positives are expected to be silenced with
a justified ``# tpudlint: disable=TDnnn`` (findings.py).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple

from .findings import Finding

__all__ = ["RULES", "RULE_DOCS", "run_rules",
           "COLLECTIVE_CALLS", "RANK_NAMES"]

# -- shared vocabulary --------------------------------------------------------

# identifiers whose value is (a function of) this process's rank
RANK_NAMES = frozenset({
    "rank", "local_rank", "node_rank", "global_rank", "process_id",
    "process_index", "proc_id", "worker_rank",
})
RANK_CALLS = frozenset({
    "get_rank", "get_local_rank", "process_index", "get_process_index",
})

# blocking cross-rank collectives: every rank of the group must call these
# the same number of times in the same order.  Point-to-point send/recv are
# rank-asymmetric BY DESIGN and deliberately absent.
COLLECTIVE_CALLS = frozenset({
    "all_reduce_host", "all_gather_host", "broadcast_host", "reduce_host",
    "gather_host", "scatter_host", "all_to_all_host",
    "all_gather_object", "gather_object", "broadcast_object_list",
    "scatter_object_list",
    "barrier", "monitored_barrier",
    "ring_all_reduce", "ring_all_gather", "ring_reduce_scatter",
    "tree_broadcast",
})

# blocking waits that need a deadline (TD004); per-method positional index
# (0-based) at which a timeout may legally arrive positionally
_WAIT_METHODS: Dict[str, int] = {
    "wait": 1,             # store.wait(keys, timeout)
    "wait_value_ge": 2,    # store.wait_value_ge(key, target, timeout)
    "wait_ge": 2,
    "barrier": 2,          # store.barrier(world, tag, timeout)
    "monitored_barrier": 2,
    "recv_array": 2,       # dp.recv_array(src, tag, timeout)
    "wait_done": 0,        # serve RequestHandle.wait_done(timeout)
    "drain": 0,            # serve Scheduler.drain(timeout)
    "recv_plan": 0,        # serve ShardFollower.recv_plan(timeout): a
                           # dead shard leader must surface as a named
                           # PeerGoneError/TimeoutError, never a hang
    "fetch": 2,            # disagg KVTransfer.fetch(src, rid, timeout):
                           # receiver-gated on kv/xfer names in the rule
                           # body — `fetch` is too common a verb to flag
                           # on arbitrary receivers
}
_TIMEOUT_KWARGS = frozenset({"timeout", "deadline", "timeout_s"})

# cross-generation infrastructure keys that legitimately live OUTSIDE the
# g{gen} namespace (bootstrap/liveness/supervisor agreement — written and
# reaped by the launcher itself, see docs/analysis.md#td003)
TD003_ALLOWED_PREFIXES = (
    "tpu_dist/alive",       # pre-rendezvous liveness (reset every round)
    "tpu_dist/generation",  # THE generation fence key itself
    "tpu_dist/master_port", # coordinator port negotiation (pre-generation)
    "tpu_dist/elastic",     # launcher restart agreement (round-scoped keys)
    "tpu_dist/hb",          # heartbeats (generation-scoped by path segment)
    "tpu_dist/serve",       # serving-role service discovery (backend/gateway
                            # addresses): overwritten by each incarnation and
                            # read ACROSS restarts by design — the gateway
                            # re-resolves a restarted backend through it
    "tpu_dist/cluster",     # cluster control plane (node registry, leases,
                            # replica liveness, cross-launcher elastic
                            # counts and roles-gang agreement): written by
                            # node agents/launchers, read ACROSS
                            # generations and leader failovers by design —
                            # the election and the cluster re-form both
                            # outlive any single generation
    "tpu_dist/g",           # already in the generation namespace
)

_LOCK_SUFFIXES = ("_mu", "_lock", "_cv", "_cond", "_mutex")
_LOCK_EXACT = frozenset({"mu", "lock", "cv", "cond", "mutex", "lk"})


def _terminal_name(func: ast.AST) -> Optional[str]:
    """The final identifier of a call target: ``C.all_reduce_host`` ->
    ``all_reduce_host``; ``barrier`` -> ``barrier``."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _dotted(node: ast.AST) -> Optional[str]:
    """Dotted path of a Name/Attribute chain (``self._out_mu`` ->
    ``self._out_mu``), or None for non-trivial expressions."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _mentions_rank(expr: ast.AST) -> bool:
    """True when the expression reads a rank-ish identifier or calls a
    rank accessor — the test of a rank-conditional branch."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in RANK_NAMES:
            return True
        if isinstance(node, ast.Attribute) and node.attr in RANK_NAMES:
            return True
        if isinstance(node, ast.Call):
            name = _terminal_name(node.func)
            if name in RANK_CALLS:
                return True
    return False


def _subgroup_names(tree: ast.AST) -> frozenset:
    """Names bound from ``new_group(...)`` anywhere in the module.
    Collectives scoped ``group=<one of these>`` are *expected* to sit
    under rank/membership guards (only members call them), so TD001/TD002
    leave them to TD008's membership analysis."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                and _terminal_name(node.value.func) == "new_group":
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return frozenset(names)


def _subgroup_scoped(call: ast.Call, skip: frozenset) -> bool:
    for kw in call.keywords:
        if kw.arg == "group" and isinstance(kw.value, ast.Name) \
                and kw.value.id in skip:
            return True
    return False


def _collective_sequence(stmts: Sequence[ast.stmt],
                         skip: frozenset = frozenset()) -> List[ast.Call]:
    """All collective Call nodes in the statements' subtrees, in source
    order (the *sequence* every rank must agree on).  Sub-group-scoped
    calls (``skip``) are excluded — their agreement set is the group's
    members, not every rank reaching this code."""
    calls = []
    for stmt in stmts:
        for node in ast.walk(stmt):
            if (isinstance(node, ast.Call)
                    and _terminal_name(node.func) in COLLECTIVE_CALLS
                    and not _subgroup_scoped(node, skip)):
                calls.append(node)
    calls.sort(key=lambda c: (c.lineno, c.col_offset))
    return calls


def _canonical_names(stmts: Sequence[ast.stmt],
                     skip: frozenset = frozenset()) -> List[str]:
    """Collective-call name sequence a rank EXECUTES through these
    statements: a nested conditional whose branches contribute identical
    sequences counts once (either path makes the same calls), so
    `if fast: all_reduce(...) else: all_reduce(...)` is one call, not
    two.  Divergent nested branches are flattened — a nested *rank*
    conditional gets its own TD001/TD002 visit anyway."""
    out: List[str] = []
    for stmt in stmts:
        out.extend(_canonical_names_node(stmt, skip))
    return out


def _canonical_names_node(node: ast.AST,
                          skip: frozenset = frozenset()) -> List[str]:
    if isinstance(node, ast.If):
        test = _canonical_names_node(node.test, skip)
        body = _canonical_names(node.body, skip)
        orelse = _canonical_names(node.orelse, skip)
        return test + (body if body == orelse else body + orelse)
    out: List[str] = []
    for child in ast.iter_child_nodes(node):
        out.extend(_canonical_names_node(child, skip))
    if isinstance(node, ast.Call):
        name = _terminal_name(node.func)
        if name in COLLECTIVE_CALLS and not _subgroup_scoped(node, skip):
            out.append(name)  # after children: argument-evaluation order
    return out


def _src(node: ast.AST, limit: int = 60) -> str:
    try:
        text = ast.unparse(node)
    except Exception:
        return "<expr>"
    return text if len(text) <= limit else text[:limit - 3] + "..."


def _branch_terminates(stmts: Sequence[ast.stmt]) -> bool:
    """True when the branch unconditionally leaves the enclosing block
    (return/raise/continue/break as a top-level statement)."""
    return any(isinstance(s, (ast.Return, ast.Raise, ast.Continue,
                              ast.Break)) for s in stmts)


# -- TD001 / TD002: rank-divergent collectives --------------------------------


def _check_rank_if(test: ast.expr, body: Sequence[ast.stmt],
                   orelse: Sequence[ast.stmt], path: str,
                   out: List[Finding],
                   skip: frozenset = frozenset()) -> None:
    # canonical sequences decide consistency (nested same-on-both-sides
    # conditionals count once); raw Call nodes locate the TD001 findings
    names_body = _canonical_names(body, skip)
    names_else = _canonical_names(orelse, skip)
    if names_body == names_else:
        return  # both sides run the same collective sequence: consistent
    seq_body = _collective_sequence(body, skip)
    seq_else = _collective_sequence(orelse, skip)
    if names_body and names_else:
        out.append(Finding(
            "TD002", "error", path, test.lineno, test.col_offset,
            f"branches of rank-conditional `if {_src(test)}` call divergent "
            f"collective sequences ({names_body} vs {names_else}); ranks "
            f"taking different branches enter mismatched collectives and "
            f"deadlock"))
        return
    for call in (seq_body or seq_else):
        out.append(Finding(
            "TD001", "error", path, call.lineno, call.col_offset,
            f"collective {_terminal_name(call.func)}() inside "
            f"rank-conditional branch (`if {_src(test)}`): ranks taking "
            f"the other branch never enter it — the group deadlocks"))


def rule_td001_td002(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    skip = _subgroup_names(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.If) and _mentions_rank(node.test):
            _check_rank_if(node.test, node.body, node.orelse, path, out,
                           skip)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.For, ast.While, ast.With)):
            # rank-conditional EARLY RETURN: `if rank != 0: return` followed
            # by collectives — the remaining ranks block in them forever
            _check_early_exit(node.body, path, out, skip)
    return out


def _check_early_exit(stmts: Sequence[ast.stmt], path: str,
                      out: List[Finding],
                      skip: frozenset = frozenset()) -> None:
    for i, stmt in enumerate(stmts):
        if (isinstance(stmt, ast.If) and _mentions_rank(stmt.test)
                and not stmt.orelse and _branch_terminates(stmt.body)
                and not _collective_sequence(stmt.body, skip)):
            for call in _collective_sequence(stmts[i + 1:], skip):
                out.append(Finding(
                    "TD001", "error", path, call.lineno, call.col_offset,
                    f"collective {_terminal_name(call.func)}() is only "
                    f"reached by ranks that pass the rank-conditional "
                    f"early exit at line {stmt.lineno} "
                    f"(`if {_src(stmt.test)}`): the exiting ranks never "
                    f"join it — the group deadlocks"))
            return  # one diagnosis per block; nested blocks walk separately


# -- TD003: un-namespaced store keys ------------------------------------------


def _key_literal_prefix(node: ast.AST) -> Optional[Tuple[str, bool]]:
    """``(literal_prefix, generation_namespaced)`` for a string constant or
    f-string, or None for other expressions.  ``generation_namespaced`` is
    True when the first path segment after ``tpu_dist/`` is ``g`` + an
    interpolated value or digits (the ``tpu_dist/g{gen}/...`` shape)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        text = node.value
    elif isinstance(node, ast.JoinedStr):
        first = node.values[0] if node.values else None
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            return None
        text = first.value
        # "tpu_dist/g{rdzv.generation()}/..." — the literal head ends right
        # at "g" and the interpolation supplies the generation number
        if text.startswith("tpu_dist/g") and len(node.values) > 1:
            rest = text[len("tpu_dist/g"):]
            if rest == "" or rest.isdigit():
                return text, True
    else:
        return None
    # tpudlint: disable=TD003  # prefix literals of the rule itself
    if not text.startswith("tpu_dist/"):
        return None
    seg = text[len("tpu_dist/"):].split("/", 1)[0]  # tpudlint: disable=TD003  # ditto
    namespaced = seg.startswith("g") and seg[1:].isdigit() and len(seg) > 1
    return text, namespaced


def _is_docstring_position(parents: Dict[ast.AST, ast.AST],
                           node: ast.AST) -> bool:
    parent = parents.get(node)
    return isinstance(parent, ast.Expr)


def rule_td003(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    seen_joined: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                seen_joined.add(id(v))
        lit = _key_literal_prefix(node)
        if lit is None or id(node) in seen_joined:
            continue
        if _is_docstring_position(parents, node):
            continue  # docstrings routinely NAME keys; they don't mint them
        text, namespaced = lit
        if namespaced:
            continue
        if any(text == p or text.startswith(p + "/")
               for p in TD003_ALLOWED_PREFIXES):
            continue
        out.append(Finding(
            "TD003", "error", path, node.lineno, node.col_offset,
            f"raw store key {text!r} is not namespaced by gang generation: "
            f"route it through the generation helper "
            f"(tpu_dist/g{{gen}}/..., see "
            f"tpu_dist.collectives.eager._ns) or a documented "
            f"cross-generation prefix — stale keys from a crashed "
            f"incarnation otherwise collide with the restarted one"))
    return out


# -- TD004: deadline-less blocking waits --------------------------------------


def _has_timeout(call: ast.Call, method: str) -> bool:
    for kw in call.keywords:
        if kw.arg in _TIMEOUT_KWARGS:
            return True
    pos_idx = _WAIT_METHODS[method]
    return len(call.args) > pos_idx


def rule_td004(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _terminal_name(node.func)
        if name not in _WAIT_METHODS or not isinstance(node.func,
                                                       ast.Attribute):
            continue
        if _has_timeout(node, name):
            continue
        recv = _dotted(node.func.value) or "<expr>"
        if name == "fetch" and "kv" not in recv.lower() \
                and "xfer" not in recv.lower():
            # only the disagg KV-transfer fetch blocks on a dead peer;
            # any other receiver's fetch is ordinary vocabulary
            continue
        if name == "wait" and len(node.args) == 1 \
                and "store" not in recv.lower():
            # cv.wait(t) / event.wait(t): the single positional IS the
            # timeout; only store.wait(keys) takes keys first
            continue
        out.append(Finding(
            "TD004", "warning", path, node.lineno, node.col_offset,
            f"blocking {recv}.{name}(...) without a timeout/deadline "
            f"argument: a dead peer turns this into an unbounded hang the "
            f"supervisor cannot diagnose — pass timeout= (or suppress with "
            f"a justification if an internal default deadline applies)"))
    return out


# -- TD005: host side effects under jit ---------------------------------------


_JIT_NAMES = frozenset({"jit", "pjit", "pmap"})


def _is_jit_expr(node: ast.AST) -> bool:
    """True for ``jax.jit`` / ``pjit`` / ``partial(jax.jit, ...)`` /
    ``jax.jit(...)`` decorator expressions."""
    name = _terminal_name(node) if isinstance(node, (ast.Name,
                                                     ast.Attribute)) else None
    if name in _JIT_NAMES:
        return True
    if isinstance(node, ast.Call):
        fname = _terminal_name(node.func)
        if fname in _JIT_NAMES:
            return True
        if fname == "partial" and node.args \
                and _terminal_name(node.args[0]) in _JIT_NAMES:
            return True
    return False


_TIME_FUNCS = frozenset({"time", "sleep", "perf_counter", "monotonic",
                         "time_ns", "perf_counter_ns", "monotonic_ns"})
_STORE_OPS = frozenset({"set", "get", "add", "check", "delete_key",
                        "delete_prefix", "wait", "wait_value_ge", "barrier",
                        "num_keys"})


def _td005_offense(call: ast.Call) -> Optional[str]:
    name = _terminal_name(call.func)
    dotted = _dotted(call.func) or name or ""
    root = dotted.split(".", 1)[0]
    if root == "time" and name in _TIME_FUNCS:
        return f"wall-clock call {dotted}()"
    if root == "random" or dotted.startswith(("np.random.", "numpy.random.")):
        return f"host RNG call {dotted}() (use jax.random with a key)"
    if name in COLLECTIVE_CALLS:
        return f"host collective {name}()"
    if name in _STORE_OPS and isinstance(call.func, ast.Attribute):
        recv = (_dotted(call.func.value) or "").lower()
        if "store" in recv:
            return f"control-plane store op {dotted}()"
    return None


def _jitted_functions(tree: ast.AST):
    """FunctionDefs that are jit-traced: decorated with a jit expression,
    or referenced by a ``jax.jit(fn)`` call in the same module."""
    by_name: Dict[str, ast.AST] = {}
    jitted = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, node)
            if any(_is_jit_expr(d) for d in node.decorator_list):
                jitted.append(node)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and _terminal_name(node.func) in _JIT_NAMES:
            for arg in node.args:
                target = _terminal_name(arg) if isinstance(
                    arg, (ast.Name, ast.Attribute)) else None
                fn = by_name.get(target or "")
                if fn is not None and fn not in jitted:
                    jitted.append(fn)
    return jitted


def rule_td005(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    for fn in _jitted_functions(tree):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            offense = _td005_offense(node)
            if offense:
                out.append(Finding(
                    "TD005", "error", path, node.lineno, node.col_offset,
                    f"{offense} inside jit-traced function "
                    f"`{fn.name}`: runs once at trace time (not per step) "
                    f"and may diverge across ranks during compilation"))
    return out


# -- TD006: lock-acquisition order --------------------------------------------


def _lock_name(expr: ast.AST) -> Optional[str]:
    dotted = _dotted(expr)
    if dotted is None:
        return None
    last = dotted.rsplit(".", 1)[-1].lower()
    if last in _LOCK_EXACT or last.endswith(_LOCK_SUFFIXES):
        return dotted
    return None


class _LockOrderVisitor(ast.NodeVisitor):
    """Collects (outer, inner) lock-nesting edges from `with` blocks.

    Per-function lock stacks (a `with` in one function does not cover a
    nested function's body at runtime), aggregated module-wide — two
    functions disagreeing on order is exactly the ABBA hazard."""

    def __init__(self):
        self.edges: Dict[Tuple[str, str], Tuple[int, int]] = {}
        self._stack: List[str] = []

    def _visit_scope(self, node):
        saved, self._stack = self._stack, []
        self.generic_visit(node)
        self._stack = saved

    visit_FunctionDef = _visit_scope
    visit_AsyncFunctionDef = _visit_scope
    visit_Lambda = _visit_scope

    def visit_With(self, node: ast.With):
        names = []
        for item in node.items:
            ctx = item.context_expr
            if isinstance(ctx, ast.Call):
                ctx = ctx.func  # with self._out_lock(dst): -> _out_lock
            name = _lock_name(ctx)
            if name:
                names.append(name)
        for i, name in enumerate(names):
            # `with a, b:` acquires left to right — the earlier items of
            # the same statement are held when the later ones are taken
            for held in self._stack + names[:i]:
                if held != name:
                    self.edges.setdefault(
                        (held, name), (node.lineno, node.col_offset))
        self._stack.extend(names)
        self.generic_visit(node)
        del self._stack[len(self._stack) - len(names):]


def rule_td006(tree: ast.AST, path: str) -> List[Finding]:
    v = _LockOrderVisitor()
    v.visit(tree)
    out: List[Finding] = []
    reported = set()
    for (a, b), (line, col) in sorted(v.edges.items(),
                                      key=lambda kv: kv[1]):
        if (b, a) in v.edges and frozenset((a, b)) not in reported:
            reported.add(frozenset((a, b)))
            other_line = v.edges[(b, a)][0]
            first, second = ((a, b, line), (b, a, other_line))
            if other_line < line:
                first, second = second, first
            out.append(Finding(
                "TD006", "warning", path, second[2], col,
                f"inconsistent lock order: {second[0]} -> {second[1]} "
                f"here, but {first[0]} -> {first[1]} at line {first[2]} — "
                f"two threads taking the locks in opposite order deadlock "
                f"(ABBA)"))
    return out


# -- TD007: dropped async Work handles ----------------------------------------

# calls whose async_op=True form returns a Work future (the eager
# collectives), plus the bucketer issue call which ALWAYS returns a
# BucketWork needing wait_all()
_ASYNC_ISSUERS = COLLECTIVE_CALLS | {"send", "recv"}


def _is_async_call(node: ast.AST) -> bool:
    """A call that returns a Work-like handle: any collective/p2p call with
    a truthy-constant ``async_op=``; ``<bucketer>.all_reduce(...)`` /
    ``<bucketer>.reduce_scatter(...)`` (always return a BucketWork); or a
    ZeRO optimizer's handle-returning calls (``<zero-ish>.update(...)``
    yields the async param-gather handle, ``<zero-ish>.reduce_scatter(...)``
    the in-flight gradient shards).  Handles *held* somewhere — a tuple
    unpack, an attribute, a container — count as used; only a
    bare-expression drop or a never-read name fires, so the lazily-waited
    param gather a train loop keeps in state is not a finding."""
    if not isinstance(node, ast.Call):
        return False
    name = _terminal_name(node.func)
    if name in _ASYNC_ISSUERS:
        for kw in node.keywords:
            if kw.arg == "async_op" and isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
        return False
    if not isinstance(node.func, ast.Attribute):
        return False
    recv_name = (_dotted(node.func.value) or "").lower()
    # disagg KV transfer: <kv/xfer>.fetch(src, rid, timeout,
    # async_op=True) returns a Work-like handle — the captured
    # KVTransferError (dead prefill rank, geometry drift) surfaces only
    # at wait(), so dropping it loses the failure with the result.
    # (kv.send's async form is already covered by _ASYNC_ISSUERS.)
    if name == "fetch" and ("kv" in recv_name or "xfer" in recv_name):
        for kw in node.keywords:
            if kw.arg == "async_op" and isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
        return False
    if name in ("all_reduce", "reduce_scatter") \
            and ("bucketer" in recv_name or "zopt" in recv_name
                 or "zero" in recv_name):
        return True
    # sharded-serving partial combines: <shard/decoder>.all_reduce(part,
    # async_op=True) returns a Work handle on the group's ordered engine
    # (tpu_dist/serve/sharded.py); the SYNC form returns the reduced
    # array, so only the truthy async_op spelling is a handle drop
    if name == "all_reduce" and ("shard" in recv_name
                                 or "decoder" in recv_name):
        for kw in node.keywords:
            if kw.arg == "async_op" and isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
        return False
    # .update() is ubiquitous (dict/set/Counter) — only receivers that
    # unambiguously name a ZeRO optimizer count, not any *zero* substring
    if name == "update" and ("zopt" in recv_name or "zeroopt" in recv_name
                             or "zero_opt" in recv_name):
        return True
    # host-pipeline issuers (tpu_dist/pipeline): a stage's async
    # activation/gradient put — <stage/pipe>.send_async(...) ALWAYS
    # returns a PendingSend whose captured channel error (closed, peer
    # gone, backpressure timeout) surfaces only at wait() — and the
    # trainer's step handle: <trainer/pipe>.step(...) returns the
    # StepHandle that applies the optimizer update at wait(); dropping
    # it silently drops the whole step.  ``.step()`` is common English,
    # so only receivers that name a trainer/pipeline count.
    if name == "send_async" and ("stage" in recv_name
                                 or "pipe" in recv_name):
        return True
    if name == "step" and ("trainer" in recv_name or "pipe" in recv_name):
        return True
    # handle-returning submits: the ordered collective engine
    # (collectives/work.py Engine.submit -> Work) and the serving layer
    # (Scheduler.submit / ServeClient.submit -> RequestHandle, whose
    # captured errors — QueueFullError, BackendGoneError — surface at
    # wait_done()).  ThreadPoolExecutor receivers (pool/executor) are
    # deliberately NOT matched.
    if name == "submit" and ("engine" in recv_name or "sched" in recv_name
                             or "serve" in recv_name
                             or "client" in recv_name) \
            and "pool" not in recv_name and "executor" not in recv_name:
        # the exclusion keeps the carve-out honest for names that hit both
        # vocabularies (client_pool.submit is an executor, not an issuer)
        return True
    return False


def _scopes(tree: ast.AST):
    """Module + every function definition (a handle's liveness is judged
    within its enclosing scope, nested functions included — a closure
    waiting on it counts as a use)."""
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def rule_td007(tree: ast.AST, path: str) -> List[Finding]:
    # bare-expression drops are judged globally; assigned-then-unused is
    # judged per scope (module + each function), where "use" is any
    # load-context read of the name anywhere under the scope — a closure
    # or loop waiting on the handle counts.  A statement nested in a
    # function is seen by both its function's walk and the module walk;
    # the location-keyed dedupe keeps one finding, and the module walk's
    # superset of loads can only suppress, never add, assign findings.
    out: List[Finding] = []
    seen = set()

    def emit(f: Finding) -> None:
        key = (f.line, f.col, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)

    for scope in _scopes(tree):
        loads: Dict[str, int] = {}
        for node in ast.walk(scope):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                loads[node.id] = loads.get(node.id, 0) + 1
        for stmt in ast.walk(scope):
            if not isinstance(stmt, (ast.Expr, ast.Assign)) \
                    or not _is_async_call(stmt.value):
                continue
            call = stmt.value
            name = _terminal_name(call.func)
            if isinstance(stmt, ast.Expr):
                emit(Finding(
                    "TD007", "error", path, call.lineno, call.col_offset,
                    f"async collective {name}(..., async_op=True) discards "
                    f"its Work handle: the result AND any captured error "
                    f"(PeerGoneError, CollectiveMismatchError) are lost — "
                    f"keep the handle and wait()/wait_all() it"))
                continue
            for t in stmt.targets:
                if isinstance(t, ast.Name) and loads.get(t.id, 0) == 0:
                    emit(Finding(
                        "TD007", "warning", path, call.lineno,
                        call.col_offset,
                        f"async collective handle `{t.id}` from "
                        f"{name}(...) is never used: nothing ever wait()s "
                        f"on it, so its result and captured errors are "
                        f"silently dropped"))
    out.sort(key=lambda f: (f.line, f.col))
    return out


# -- TD008: sub-group construction / membership hazards -----------------------
#
# new_group() (tpu_dist/collectives/topology.py, the torch new_group
# analogue) must be called by EVERY rank with the IDENTICAL member list —
# the group id that namespaces store keys and wire tags derives from it, so
# rank-divergent lists mint divergent groups whose collectives can never
# match.  And a collective issued on a literal sub-group without any
# rank/membership guard runs on ranks that may not be members, which the
# runtime rejects (GroupMembershipError) — or worse, desynchronizes the
# members if the guard exists on some ranks only.


def _membership_guarded(parents: Dict[ast.AST, ast.AST], node: ast.AST,
                        group_name: str) -> bool:
    """True when an enclosing ``if`` tests rank-ness or the group object
    itself (``if rank in members:``, ``if g.rank is not None:``,
    ``if me in g.members:``, ...) — the caller is gating on membership."""
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.If):
            test = cur.test
            if _mentions_rank(test):
                return True
            for sub in ast.walk(test):
                if isinstance(sub, ast.Name) and sub.id == group_name:
                    return True
        cur = parents.get(cur)
    return False


def rule_td008(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node

    # (a) member list computed from this rank's identity: every rank gets a
    # DIFFERENT group — keys/tags/sanitizer scopes can never line up
    literal_groups: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and _terminal_name(node.func) == "new_group"):
            continue
        member_args = list(node.args) + [kw.value for kw in node.keywords
                                         if kw.arg == "ranks"]
        for arg in member_args:
            if _mentions_rank(arg):
                out.append(Finding(
                    "TD008", "error", path, node.lineno, node.col_offset,
                    f"new_group member list `{_src(arg)}` depends on this "
                    f"process's rank: every rank must pass the IDENTICAL "
                    f"list (torch new_group semantics) — rank-divergent "
                    f"lists mint divergent group ids whose collectives "
                    f"deadlock instead of matching"))
        # remember names bound to groups with fully-literal member lists
        # for the membership check below
        assign = parents.get(node)
        if isinstance(assign, ast.Assign) and member_args:
            m = member_args[0]
            if isinstance(m, (ast.List, ast.Tuple)) and all(
                    isinstance(e, ast.Constant) for e in m.elts):
                for t in assign.targets:
                    if isinstance(t, ast.Name):
                        literal_groups[t.id] = node.lineno

    # (b) collective on a literal sub-group with no rank/membership guard:
    # non-member ranks reaching this call either die on
    # GroupMembershipError or (guarded on SOME ranks only) desynchronize
    # the members
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and _terminal_name(node.func) in COLLECTIVE_CALLS):
            continue
        for kw in node.keywords:
            if kw.arg != "group" or not isinstance(kw.value, ast.Name):
                continue
            gname = kw.value.id
            if gname not in literal_groups:
                continue
            if _membership_guarded(parents, node, gname):
                continue
            out.append(Finding(
                "TD008", "warning", path, node.lineno, node.col_offset,
                f"collective {_terminal_name(node.func)}(group={gname}) on "
                f"the sub-group built at line {literal_groups[gname]} has "
                f"no rank/membership guard: ranks outside the member list "
                f"reach this call too — gate it (e.g. `if rank in "
                f"members:` / `if {gname}.rank is not None:`) or run it on "
                f"every rank of a group they are all members of"))
    out.sort(key=lambda f: (f.line, f.col))
    return out


# -- TD009: broad except swallowing named tpu_dist error classes --------------
#
# The resilience/netchaos layers spend a lot of machinery converting hangs
# and silent corruption into NAMED errors (PeerGoneError, RankLostError,
# CollectiveMismatchError, FrameCorruptError, CollectiveTimeoutError).  A
# `try: all_reduce_host(...)\nexcept Exception: pass` converts them right
# back into silent wrong-results/hangs — the diagnosis is swallowed, the
# peers keep waiting.  The rule fires on (a) a broad handler (bare,
# Exception, BaseException) whose try body issues calls that raise the
# named classes, and (b) an explicit catch of a named class — in either
# case only when the handler neither re-raises nor records the error
# (log_event / logger methods / a request-failing callback).

_TD009_NAMED_ERRORS = frozenset({
    "PeerGoneError", "RankLostError", "CollectiveMismatchError",
    "FrameCorruptError", "CollectiveTimeoutError",
})
_TD009_BROAD = frozenset({"Exception", "BaseException"})
# calls whose failure modes are exactly the named error classes
_TD009_SOURCES = COLLECTIVE_CALLS | frozenset({
    "send", "recv", "recv_array", "recv_array_dual", "send_array",
    "send_quant", "wait_done", "wait_all",
})
# handler calls that count as propagating/recording the diagnosis
_TD009_SINKS = frozenset({
    "log_event", "warning", "error", "exception", "critical", "warn",
    "fail", "fail_slot", "fail_all", "safe_record",
})


def _handler_caught(htype: ast.AST):
    """Names a handler catches: set of identifiers, or None for bare."""
    if htype is None:
        return None
    nodes = htype.elts if isinstance(htype, ast.Tuple) else [htype]
    names = set()
    for n in nodes:
        name = _terminal_name(n)
        if name:
            names.add(name)
    return names


def _handler_propagates(handler: ast.ExceptHandler) -> bool:
    for n in ast.walk(handler):
        if isinstance(n, ast.Raise):
            return True
        if isinstance(n, ast.Call) and _terminal_name(n.func) in _TD009_SINKS:
            return True
    return False


def rule_td009(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Try):
            continue
        try_calls = set()
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call):
                    name = _terminal_name(sub.func)
                    if name in _TD009_SOURCES:
                        try_calls.add(name)
        for handler in node.handlers:
            caught = _handler_caught(handler.type)
            named = (caught or set()) & _TD009_NAMED_ERRORS
            broad = caught is None or bool(caught & _TD009_BROAD)
            if not (named or (broad and try_calls)):
                continue
            if _handler_propagates(handler):
                continue
            what = (f"named error class(es) {sorted(named)}" if named
                    else f"errors from {sorted(try_calls)} (PeerGoneError, "
                         f"FrameCorruptError, CollectiveTimeoutError, ...)")
            shape = ("bare except" if caught is None
                     else f"except {'/'.join(sorted(caught))}")
            out.append(Finding(
                "TD009", "error", path, handler.lineno, handler.col_offset,
                f"{shape} swallows {what} without re-raising or logging: "
                f"the named diagnosis the resilience layer produced is "
                f"discarded, turning an injected/real network fault back "
                f"into a silent hang or wrong result — re-raise, "
                f"log_event(...), or fail the owning request by name"))
    out.sort(key=lambda f: (f.line, f.col))
    return out


# -- TD010: role-graph channel hazards ----------------------------------------
#
# Two checks for tpu_dist.roles (docs/roles.md):
#
# (a) TD004-family deadline check on CHANNEL ops: `ch.get()` / `ch.put(x)`
#     / `ch.get_latest(v)` without a timeout argument.  Channels do carry
#     an internal default deadline (TPU_DIST_CH_TIMEOUT), so this is a
#     warning, not an error — but a producer/consumer loop should state
#     its budget explicitly, exactly like store waits.  Receiver-gated
#     ("ch"/"chan"/"channel"-named receivers), because bare `get`/`put`
#     are the most overloaded method names in Python (dict.get,
#     queue.put) — same discipline as TD007's receiver gating.
#
# (b) a ChannelSpec whose literal src=/dst= role name — or a direct
#     Channel rig constructor whose literal role= argument — is
#     absent from the module's RoleGraph literal: the graph constructor
#     raises at runtime (dangling endpoint), but only on the rank that
#     builds it — statically it is always a bug.  Only enforced when the
#     module's Role(...) literals are all string constants (a
#     dynamically-built graph disables the check rather than guessing).

_TD010_CHANNEL_EXACT = frozenset({"ch", "chan", "channel"})
_TD010_CHANNEL_SUFFIXES = ("_ch", "_chan", "_channel")
# blocking channel ops -> 0-based positional index at which a timeout may
# legally arrive (put(value, timeout) / get(timeout) /
# get_latest(last_version, timeout)); put_latest never blocks
_TD010_BLOCKING = {"put": 1, "get": 0, "get_latest": 1}
# endpoint-bearing callables -> (positional index, kwarg name) of their
# role-name arguments: ChannelSpec names roles at (name, src, dst, ...);
# Channel (the direct rig constructor) names THIS endpoint's role at
# (spec, store, rank, role, ...)
_TD010_ENDPOINT_CALLS = {
    "ChannelSpec": ((1, "src"), (2, "dst")),
    "Channel": ((3, "role"),),
}


def _channel_receiver(call: ast.Call) -> Optional[str]:
    """The receiver name when it is channel-ish (``ch``/``traj_chan``/
    ``params_channel``), else None."""
    if not isinstance(call.func, ast.Attribute):
        return None
    base = call.func.value
    name = (base.id if isinstance(base, ast.Name)
            else base.attr if isinstance(base, ast.Attribute) else None)
    if name is None:
        return None
    low = name.lower()
    if low in _TD010_CHANNEL_EXACT or low.endswith(_TD010_CHANNEL_SUFFIXES):
        return name
    return None


def _role_literals(tree: ast.AST):
    """``(names, complete)``: role names collected from ``Role(...)``
    literals.  ``complete`` only when a ``RoleGraph(...)`` literal exists
    and every ``Role`` first argument is a string constant — otherwise
    the endpoint check stays off (we cannot prove a name is absent)."""
    names = set()
    any_graph = False
    complete = True
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        t = _terminal_name(node.func)
        if t == "RoleGraph":
            any_graph = True
        elif t == "Role":
            arg = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords if kw.arg == "name"),
                None)
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                names.add(arg.value)
            else:
                complete = False
    return names, (any_graph and complete and bool(names))


def _endpoint_roles(call: ast.Call, layout):
    """``[(end, name_node)]`` for the literal role-name arguments of a
    Channel/ChannelSpec call, per that callable's ``layout`` (positional
    index, kwarg name)."""
    out = []
    for pos, end in layout:
        node = None
        if len(call.args) > pos:
            node = call.args[pos]
        else:
            node = next((kw.value for kw in call.keywords
                         if kw.arg == end), None)
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.append((end, node))
    return out


def rule_td010(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    role_names, complete = _role_literals(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _terminal_name(node.func)
        if name in _TD010_BLOCKING:
            recv = _channel_receiver(node)
            if recv is not None:
                has = any(kw.arg in _TIMEOUT_KWARGS
                          for kw in node.keywords) \
                    or len(node.args) > _TD010_BLOCKING[name]
                if not has:
                    out.append(Finding(
                        "TD010", "warning", path, node.lineno,
                        node.col_offset,
                        f"channel {recv}.{name}(...) without a "
                        f"timeout/deadline argument: the internal "
                        f"TPU_DIST_CH_TIMEOUT default applies, but a "
                        f"role-graph producer/consumer loop should state "
                        f"its budget explicitly (TD004 family) — a dead "
                        f"peer role otherwise waits out the full default "
                        f"before ChannelTimeoutError/"
                        f"ChannelPeerGoneError names it"))
        if name in _TD010_ENDPOINT_CALLS and complete:
            for end, lit in _endpoint_roles(node,
                                            _TD010_ENDPOINT_CALLS[name]):
                if lit.value not in role_names:
                    out.append(Finding(
                        "TD010", "error", path, lit.lineno,
                        lit.col_offset,
                        f"channel endpoint {end}={lit.value!r} names no "
                        f"role of this module's RoleGraph literal "
                        f"(roles: {sorted(role_names)}): the graph "
                        f"constructor raises RoleGraphError at runtime — "
                        f"a dangling endpoint can never carry a message"))
    out.sort(key=lambda f: (f.line, f.col))
    return out


# -- TD011: hand-rolled parameter-layout PartitionSpecs -----------------------

# mesh axes the unified rule plane (tpu_dist/parallel/rules.py) owns:
# parameter placements over these derive from the logical-axis rule +
# layout tables.  'data'/'pipe'/shard_map batch specs are NOT layout
# arithmetic and stay free-form.
_TD011_LAYOUT_AXES = frozenset({"model", "shard", "expert"})

# the rule plane itself plus the spec builders that DEFINE the generated
# tables — the only modules allowed to spell layout axes into
# PartitionSpec literals by hand
_TD011_ALLOWED_SUFFIXES = (
    "parallel/rules.py", "parallel/gspmd.py", "parallel/fsdp.py",
)


def _spec_constructor_names(tree: ast.AST) -> frozenset:
    """Local names bound to ``jax.sharding.PartitionSpec`` by import —
    including the conventional ``as P`` alias."""
    names = {"PartitionSpec"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == "PartitionSpec":
                    names.add(alias.asname or alias.name)
    return frozenset(names)


def _layout_axis_literal(call: ast.Call) -> Optional[str]:
    """The first string-literal argument naming a rule-plane layout axis,
    looking through tuple entries (``P(("data", "model"))``)."""
    def scan(node):
        if isinstance(node, ast.Constant) and node.value in \
                _TD011_LAYOUT_AXES:
            return node.value
        if isinstance(node, ast.Tuple):
            for elt in node.elts:
                hit = scan(elt)
                if hit is not None:
                    return hit
        return None

    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        hit = scan(arg)
        if hit is not None:
            return hit
    return None


def rule_td011(tree: ast.AST, path: str) -> List[Finding]:
    norm = path.replace("\\", "/")
    if norm.endswith(_TD011_ALLOWED_SUFFIXES):
        return []
    spec_names = _spec_constructor_names(tree)
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _terminal_name(node.func) not in spec_names:
            continue
        axis = _layout_axis_literal(node)
        if axis is None:
            continue
        out.append(Finding(
            "TD011", "error", path, node.lineno, node.col_offset,
            f"hand-rolled PartitionSpec places rule-plane mesh axis "
            f"{axis!r} outside tpu_dist/parallel/rules.py: derive the "
            f"placement from the logical-axis table instead "
            f"(rules.spec_for / partition_pairs for pjit specs, "
            f"rules.spans_for for host-path spans) — duplicated layout "
            f"literals are how the pjit, ZeRO, reshard and serving "
            f"layouts drift apart"))
    out.sort(key=lambda f: (f.line, f.col))
    return out


# -- registry -----------------------------------------------------------------

RULES = {
    "TD001": rule_td001_td002,   # emits TD001 and TD002
    "TD003": rule_td003,
    "TD004": rule_td004,
    "TD005": rule_td005,
    "TD006": rule_td006,
    "TD007": rule_td007,
    "TD008": rule_td008,
    "TD009": rule_td009,
    "TD010": rule_td010,
    "TD011": rule_td011,
}

RULE_DOCS = {
    "TD001": "collective call inside a rank-conditional branch",
    "TD002": "divergent collective sequences across rank-conditional "
             "branches",
    "TD003": "raw control-plane store key not namespaced by generation",
    "TD004": "blocking store/socket/queue wait without a deadline",
    "TD005": "host side effects (store/collectives/time/random) inside "
             "jit-traced functions",
    "TD006": "inconsistent lock-acquisition order within a module",
    "TD007": "async collective Work handle dropped without wait()/"
             "wait_all()",
    "TD008": "sub-group built from a rank-divergent member list, or a "
             "collective issued on a group the caller may not be a "
             "member of",
    "TD009": "broad/bare except swallowing a named tpu_dist error class "
             "(PeerGoneError, RankLostError, CollectiveMismatchError, "
             "FrameCorruptError) without re-raising or logging",
    "TD010": "role-graph channel hazards: deadline-less channel "
             "put/get/get_latest (warning, TD004 family), or a "
             "Channel/ChannelSpec endpoint naming a role absent from "
             "the module's RoleGraph literal (error)",
    "TD011": "hand-rolled PartitionSpec over a rule-plane layout axis "
             "('model'/'shard'/'expert') outside parallel/rules.py and "
             "its spec builders (gspmd.py, fsdp.py) — parameter "
             "placements must derive from the logical-axis rule table",
}


def run_rules(tree: ast.AST, path: str) -> List[Finding]:
    findings: List[Finding] = []
    for fn in RULES.values():
        findings.extend(fn(tree, path))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
