"""Runtime cross-rank collective sanitizer (``TPU_DIST_SANITIZE=1``).

The static pass (tpudlint) catches rank-divergent collectives it can *see*;
this is the runtime complement for the ones it cannot (divergence through
data, config skew, library code).  When enabled, every eager host
collective first publishes a per-call **signature** to the
generation-scoped control-plane store and cross-checks agreement across
ranks before any payload moves:

    {ns}/san[/grp{set}]/{seq}/{rank}  ->  {"op": "all_reduce",
                               "reduce": "sum",
                               "tree": "<structure hash>",
                               "leaves": [["float32", [1024]], ...],
                               "src"/"dst": ...,
                               "group": "world[4]" | "grp<id>[0, 1]",
                               "site": "train.py:123", "rank": 2}

Sub-group collectives (tpu_dist/collectives/topology.py) post under a
scope derived from the member *set* and sign the group id + the exact
ordered membership, so mismatched group objects raise naming BOTH
memberships (see ``_group_sig``); each scope counts its own ``seq``.

``seq`` is a process-local counter: in an SPMD program every rank arrives
at sanitized collective #seq together, so the keys line up.  Each rank
waits (bounded by ``TPU_DIST_SANITIZE_TIMEOUT``, default 30 s) for every
peer's signature, then compares the *semantic* fields (op, reduce op,
tree structure, leaf dtypes/shapes, root rank — everything that must be
uniform for the collective to be well-formed).  Divergence raises
:class:`CollectiveMismatchError` on **every** rank, naming the divergent
rank(s), their call-sites, and the first differing field — a named error
at first occurrence instead of a silent hang.  A rank that never announces
(the ``if rank == 0: all_reduce(...)`` bug: the other ranks never reach a
collective at all) surfaces as the same error via the deadline.

Cost model: one store SET + one bounded poll per peer per collective —
strictly control-plane traffic, so it rides the same server the small-leaf
path already uses.  Off (the default), the only cost is one environment
lookup per collective call (measured ≤ 1 µs; the acceptance bound is ≤ 5%
on ``benchmarks/bench_host_collectives.py --smoke``).

Call-site attribution walks the stack to the first frame outside
``tpu_dist/collectives`` and ``tpu_dist/analysis``, so the error names the
user's line, not the framework's.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional

__all__ = ["CollectiveMismatchError", "enabled", "check_collective",
           "reset", "SEMANTIC_FIELDS"]

# fields that must agree across ranks (compared); "site"/"rank" are
# diagnostic only — the same collective may legitimately be reached from
# different lines (e.g. matching calls in both branches of a conditional).
# "comm" is the wire-compression scheme (TPU_DIST_COMM_DTYPE — a dtype
# cast or an int8 block-quant spec): ranks running different schemes would
# exchange frames in different wire formats and corrupt the ring, so a
# skewed compression config fails here naming both schemes instead.
# "group" is the SubGroup identity (group_id + the ordered membership):
# ranks whose group objects diverge — different ring order, different
# members, or a sub-group vs the flat world — would run incompatible rings
# over colliding tags; the signature names BOTH memberships before any
# payload moves.
# "role" is the process's role-graph role (tpu_dist.roles), signed for
# collectives on the FLAT world only: a collective that accidentally spans
# two roles (a learner-side all_reduce reaching actor ranks through the
# default group) then fails naming BOTH role names instead of a bare
# membership deadline.  Deliberately-scoped cross-role SubGroups are
# exempt — their identity is already signed via "group" — and role_rank
# rides along as a diagnostic field (it legitimately differs per rank).
SEMANTIC_FIELDS = ("op", "reduce", "tree", "leaves", "src", "dst", "comm",
                   "group", "role")

# process-local sanitized-collective counters, one per signature scope:
# every group (and the flat world) counts its own collectives, because a
# rank participates in different subsets of each group's traffic
_seqs: Dict[str, int] = {}


class CollectiveMismatchError(RuntimeError):
    """Ranks disagreed on (or never announced) a host collective.

    Attributes: ``rank`` (this process), ``seq`` (sanitized-call index),
    ``op``, ``site`` (this rank's call-site), ``divergent`` (rank ->
    signature dict for the disagreeing ranks, empty on a timeout),
    ``missing`` (ranks that never announced, empty on a mismatch)."""

    def __init__(self, rank: int, seq: int, op: str, site: str,
                 message: str, divergent: Optional[Dict[int, Dict]] = None,
                 missing: Optional[List[int]] = None):
        self.rank, self.seq, self.op, self.site = rank, seq, op, site
        self.divergent = divergent or {}
        self.missing = missing or []
        super().__init__(message)


def enabled() -> bool:
    return os.environ.get("TPU_DIST_SANITIZE", "").strip().lower() in (
        "1", "true", "yes", "on")


def _timeout() -> float:
    try:
        return float(os.environ.get("TPU_DIST_SANITIZE_TIMEOUT", "30"))
    except ValueError:
        return 30.0


def reset() -> None:
    """Restart the sanitized-call counters (tests / re-init)."""
    _seqs.clear()


def _group_sig(group):
    """``(scope_segment, group_field)`` for a collective's group.

    ``scope_segment`` namespaces the signature keys.  It hashes the
    *sorted member set*, NOT the ordered list: two ranks holding groups
    that diverge only in ring order / id still post into the SAME
    keyspace, so the divergence is diagnosed as a named mismatch (the
    ``group`` field below differs) rather than a mute deadline.  Groups
    over different member sets can never rendezvous at all — those fail
    via the deadline, naming the ranks that never announced.

    ``group_field`` is the compared signature value: the group id plus
    the exact ordered membership — the error therefore NAMES both
    memberships."""
    gid = getattr(group, "group_id", None)
    if gid is None:
        return "", f"world[{group.num_processes}]"
    set_scope = getattr(group, "set_scope", gid)
    return f"/grp{set_scope}", f"grp{gid}{list(group.members)}"


def _call_site() -> str:
    """First stack frame outside the collectives/analysis machinery
    (delegates to the shared attribution helper in tpu_dist.obs)."""
    from ..obs.recorder import call_site
    return call_site(skip_parts=("collectives", "analysis"))


def _current_role():
    """This process's ``(role, role_rank)`` under a role graph
    (tpu_dist.roles), or None — consulted only on the armed path."""
    try:
        from ..roles.graph import current_role
        return current_role()
    except Exception:
        return None


def _role_notes(ranks) -> str:
    """``" (roles: 2=actor[1], 3=actor[2])"`` for a rank list when a role
    graph is installed — so a membership deadline names roles, not just
    bare ranks.  Empty outside any graph."""
    try:
        from ..roles.graph import role_label
        labels = [(r, role_label(r)) for r in ranks]
        if any(lbl for _, lbl in labels):
            return (" (roles: "
                    + ", ".join(f"{r}={lbl or '?'}" for r, lbl in labels)
                    + ")")
    except Exception:
        pass
    return ""


def _signature(op: str, rank: int, value: Any = None,
               reduce_op: Optional[str] = None, src: Optional[int] = None,
               dst: Optional[int] = None, comm: Optional[str] = None,
               with_leaves: bool = True) -> Dict:
    sig: Dict[str, Any] = {"op": op, "rank": rank, "site": _call_site()}
    if reduce_op is not None:
        sig["reduce"] = str(reduce_op).lower()
    if src is not None:
        sig["src"] = int(src)
    if dst is not None:
        sig["dst"] = int(dst)
    if comm is not None:
        sig["comm"] = str(comm)
    if value is not None and with_leaves:
        import jax
        import numpy as np
        leaves, treedef = jax.tree.flatten(value)
        sig["tree"] = hashlib.sha256(
            str(treedef).encode()).hexdigest()[:12]
        sig["leaves"] = [[np.asarray(l).dtype.name,
                          list(np.asarray(l).shape)] for l in leaves]
    return sig


def _first_divergence(ref: Dict, other: Dict) -> str:
    for field in SEMANTIC_FIELDS:
        if ref.get(field) != other.get(field):
            return (f"{field}: {json.dumps(ref.get(field))} vs "
                    f"{json.dumps(other.get(field))}")
    return "<consistent>"


def _ns() -> str:
    import importlib
    rdzv = importlib.import_module("tpu_dist.dist.rendezvous")
    return f"tpu_dist/g{rdzv.generation()}/san"


def check_collective(group, store, op: str, value: Any = None,
                     reduce_op: Optional[str] = None,
                     src: Optional[int] = None, dst: Optional[int] = None,
                     comm: Optional[str] = None,
                     with_leaves: bool = True) -> None:
    """Publish this rank's signature for the next sanitized collective and
    verify every peer announced an identical one; raises
    :class:`CollectiveMismatchError` (never hangs: bounded by
    ``TPU_DIST_SANITIZE_TIMEOUT``).

    Called by the eager collectives (tpu_dist/collectives/eager.py) before
    any payload moves; safe to call directly around custom store-based
    synchronization as well."""
    n = group.num_processes
    if store is None or n <= 1:
        return
    scope, group_field = _group_sig(group)
    # signature keys carry GLOBAL rank identity: two ranks holding groups
    # that diverge in ring order would collide on group-local ranks (both
    # think they are local rank 0) and mis-wait — global ids keep the
    # rendezvous honest, so order divergence is compared and NAMED
    members = getattr(group, "members", None)
    me = group.parent_rank if members is not None else group.rank
    peers = ([r for r in members if r != me] if members is not None
             else [r for r in range(n) if r != me])
    seq = _seqs.get(scope, 0)
    _seqs[scope] = seq + 1
    mine = _signature(op, me, value=value, reduce_op=reduce_op, src=src,
                      dst=dst, comm=comm, with_leaves=with_leaves)
    mine["group"] = group_field
    if getattr(group, "group_id", None) is None:
        # flat-world collectives sign the caller's role (see the
        # SEMANTIC_FIELDS note): inside a role graph, the default group
        # spanning two roles is almost always the accident this catches
        role = _current_role()
        if role is not None:
            mine["role"], mine["role_rank"] = role
    base = f"{_ns()}{scope}/{seq}"
    store.set(f"{base}/{me}", json.dumps(mine, sort_keys=True).encode())

    timeout = _timeout()
    deadline = time.monotonic() + timeout
    waiting = set(peers)
    delay = 0.0005
    while waiting:
        waiting = {r for r in waiting if not store.check(f"{base}/{r}")}
        if not waiting:
            break
        if time.monotonic() > deadline:
            missing = sorted(waiting)
            raise CollectiveMismatchError(
                me, seq, op, mine["site"],
                f"collective sanitizer: rank {me} announced collective "
                f"#{seq} ({op} at {mine['site']}) but rank(s) "
                f"{missing}{_role_notes(missing)} "
                f"never announced theirs within {timeout:.0f}s "
                f"(TPU_DIST_SANITIZE_TIMEOUT) — a rank-divergent "
                f"collective: those ranks skipped this call or are blocked "
                f"elsewhere", missing=missing)
        time.sleep(delay)
        delay = min(delay * 2, 0.02)

    sigs = {me: mine}
    for r in peers:
        sigs[r] = json.loads(store.get(f"{base}/{r}"))
        # ack-counter GC (the _store_all_gather_payload discipline): the
        # last reader of a peer's signature deletes it
        if store.add(f"{base}/{r}/ack", 1) >= n - 1:
            store.delete_key(f"{base}/{r}")
            store.delete_key(f"{base}/{r}/ack")

    # reference = the majority signature (ties -> lowest rank holding one)
    by_sem: Dict[str, List[int]] = {}
    for r, sig in sigs.items():
        key = json.dumps([sig.get(f) for f in SEMANTIC_FIELDS],
                         sort_keys=True)
        by_sem.setdefault(key, []).append(r)
    if len(by_sem) == 1:
        return
    ref_ranks = max(by_sem.values(), key=lambda rs: (len(rs), -min(rs)))
    ref = sigs[min(ref_ranks)]
    divergent = {r: sigs[r] for rs in by_sem.values() if rs is not ref_ranks
                 for r in rs}
    detail = "; ".join(
        f"rank {r} called {sigs[r].get('op')} at {sigs[r].get('site')} "
        f"({_first_divergence(ref, sigs[r])})"
        for r in sorted(divergent))
    raise CollectiveMismatchError(
        me, seq, op, mine["site"],
        f"collective sanitizer: ranks diverged on collective #{seq}: "
        f"majority ranks {sorted(ref_ranks)} called {ref.get('op')} at "
        f"{ref.get('site')}, but {detail}", divergent=divergent)
