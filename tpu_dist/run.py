"""``python -m tpu_dist.run`` — torchrun-style alias for the launch CLI.

torch renamed its launcher ``torch.distributed.launch`` →
``torch.distributed.run`` (torchrun); both module names work here too:
``python -m tpu_dist.launch ...`` and ``python -m tpu_dist.run ...`` are
the same CLI (tpu_dist/launch/cli.py).
"""

import sys

from .launch.cli import main

if __name__ == "__main__":
    sys.exit(main())
