"""tpu_dist.collectives — L0/L1 collective communication.

Two API surfaces, reflecting how TPU differs from the reference's NCCL world
(ring-allreduce described at /root/reference/README.md:5-20, invoked
implicitly by DDP in every ``loss.backward()``):

- **In-jit** (:mod:`.ops`): functions used *inside* ``shard_map``/``pjit``
  over a mesh axis — ``all_reduce``→``lax.psum`` etc.  XLA fuses these into
  the surrounding graph and lowers them to ICI collectives; this is where the
  gradient all-reduce of the DDP wrapper lives.
- **Eager** (:mod:`.eager`): host-level collectives on a
  :class:`~tpu_dist.dist.ProcessGroup` for occasional out-of-graph syncs
  (metric averaging, parameter broadcast at init) — the closest analogue of
  torch's ``dist.all_reduce(tensor)`` call style.

:func:`ops.ring_all_reduce` is a ppermute-based reduce-scatter + all-gather
ring — the literal algorithm the reference README teaches, runnable on the
TPU torus; numerically equal to ``psum`` (tested) but kept for teaching and
as a building block for later pipeline/sequence parallelism.

The eager collectives themselves ride two host transports (see
docs/collectives.md): the control-plane TCPStore for small payloads, and a
direct rank↔rank socket **data plane** (:mod:`.transport`) over which large
array payloads run the same ring algorithm between *processes*
(:mod:`.ring`: double-buffered chunk-pipelined ring all-reduce/all-gather,
tree broadcast).  All of them take ``async_op=True`` and return a
:class:`Work` future executed on an ordered engine (:mod:`.work`), and
:class:`Bucketer` (:mod:`.bucketer`) coalesces gradient trees into flat
buckets issued as async ring all-reduces — the torch DDP Reducer
discipline, bit-identical to per-leaf results by construction.
"""

from .ops import (all_gather, all_reduce, all_to_all, broadcast, pmean,
                  ppermute, psum, reduce_scatter, ring_all_reduce)
from .eager import (ReduceOp, all_gather_host, all_gather_object,
                    all_reduce_host, all_to_all_host, broadcast_host,
                    broadcast_object_list, gather_host, gather_object, recv,
                    reduce_host, scatter_host, scatter_object_list, send,
                    send_recv_device)
# host-side data plane: the ring/tree collectives large eager payloads ride
# (module-qualified — ``ring.ring_all_reduce`` is the host-payload twin of
# the in-jit ``ops.ring_all_reduce`` above)
from . import ring, transport
from .transport import (CollectiveTimeoutError, DataPlane,
                        FrameCorruptError, PeerGoneError)
# async engine: Work futures (async_op=True), the ordered executor, and the
# gradient bucketer (DDP Reducer / Horovod tensor-fusion parity)
from . import bucketer, work
from .work import Work, wait_all
from .bucketer import (Bucketer, BucketWork, bucketed_all_reduce,
                       bucketed_reduce_scatter)
# block-quantized int8 wire format (EQuARX-style) + error feedback:
# selectable wherever comm_dtype is accepted (TPU_DIST_COMM_DTYPE=
# int8_block256, Bucketer/ZeroOptimizer comm_dtype=...)
from . import quant
from .quant import ErrorFeedback, QuantScheme
# topology-aware collectives: host detection, scoped sub-groups
# (torch new_group analogue), the two-level hierarchical ring over
# shared-memory intra-host lanes (.shm), and algorithm autoselection
# (TPU_DIST_ALGO: auto | flat | hier | store)
from . import shm, topology
from .topology import (GroupMembershipError, SubGroup, Topology,
                       detect_topology, hier_all_reduce, new_group)

__all__ = [
    "all_reduce", "all_gather", "reduce_scatter", "broadcast", "all_to_all",
    "ppermute", "psum", "pmean", "ring_all_reduce",
    "ReduceOp", "all_reduce_host", "all_gather_host", "broadcast_host",
    "reduce_host", "gather_host", "scatter_host", "send", "recv",
    "send_recv_device",
    "all_gather_object", "gather_object", "broadcast_object_list",
    "scatter_object_list", "all_to_all_host",
    "ring", "transport", "DataPlane", "PeerGoneError",
    "FrameCorruptError", "CollectiveTimeoutError",
    "work", "Work", "wait_all", "bucketer", "Bucketer", "BucketWork",
    "bucketed_all_reduce", "bucketed_reduce_scatter",
    "quant", "QuantScheme", "ErrorFeedback",
    "shm", "topology", "Topology", "SubGroup", "GroupMembershipError",
    "new_group", "detect_topology", "hier_all_reduce",
]
