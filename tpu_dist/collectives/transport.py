"""Peer-to-peer data plane for host collectives — direct rank↔rank sockets.

Why this exists: every ``*_host`` collective used to move its bytes through
the single control-plane TCPStore server (tpu_dist/dist/store.py) — one
pickled blob per key, one blocking request round-trip per transfer, all of
it funnelled through one process.  That is O(world × bytes) at the store
and it serializes what the reference's theory section says should pipeline
(ring all-reduce, /root/reference/README.md §1).  The data plane gives each
rank a listening socket and persistent peer connections; ndarray payloads
move as raw-byte frames (dtype/shape/tag header — never pickle), chunked so
send, recv, and the local reduce overlap.  The store remains the *control*
plane: it only carries each rank's advertised address (a few bytes, once
per incarnation).

Design notes:

- **One connection per direction.**  ``send_array(dst, ...)`` lazily opens
  (and keeps) a connection to ``dst``'s listener; inbound connections are
  identified by a hello frame carrying the peer's rank and generation.
  A stale-generation hello is refused — a rank left over from a failed
  incarnation cannot inject frames into the restarted gang.
- **A receiver thread per inbound connection** drains the socket into
  per-``(src, tag)`` FIFO queues.  Because the receiving side is *always*
  reading, a ring step where every rank sends before it receives cannot
  deadlock on full TCP buffers, and ``recv_array`` overlaps with whatever
  the caller computes between frames — this is what makes the chunked ring
  pipeline (tpu_dist/collectives/ring.py) actually pipeline.
- **Peer death is a named error.**  EOF or a reset on an inbound connection
  marks that rank gone and wakes every blocked ``recv_array`` with
  :class:`PeerGoneError` naming the rank — collectives fail fast with a
  diagnosis instead of hanging until a multi-minute timeout (the same
  philosophy as the resilience layer's ``RankLostError``).

- **Shared-memory lanes for co-located peers.**  Every rank publishes its
  host fingerprint (tpu_dist/collectives/topology.py) next to its address;
  a sender that discovers its destination on the same host sets up an SHM
  payload lane (tpu_dist/collectives/shm.py) and announces it in-band on
  the peer socket.  Frame *headers* — the exact same tag/dtype/shape
  contract, including ``q8b{N}`` quant frames — keep riding TCP (ordering,
  liveness, generation fencing unchanged); payload *bytes* move as two
  memcpys through the shared ring instead of through the loopback TCP
  stack.  TCP remains the fallback: ``TPU_DIST_SHM=0``, setup failure, or
  a frame racing ahead of lane setup all ship inline, and the receiver
  accepts both forms at any time.

- **Frame integrity (CRC).**  Every frame payload carries a 32-bit
  checksum (``TPU_DIST_FRAME_CRC``, armed by default; ``0`` disables) —
  CRC32C where a native implementation is importable, zlib CRC32
  otherwise (same 4-byte integrity contract).  The sender marks the
  frame's dtype name (``!`` prefix) and appends the checksum to the
  header; the receiver verifies after the payload lands (inline TCP or
  SHM lane alike) and raises a named :class:`FrameCorruptError` — src,
  tag, stream offset, both CRCs — instead of folding flipped bits into
  gradients.  The marker travels per frame, so a rank with checksums
  disabled still interoperates: unmarked frames are simply not verified.

Env knobs: ``TPU_DIST_DP_HOST`` (advertised address override),
``TPU_DIST_SHM`` / ``TPU_DIST_SHM_RING`` (shared-memory lanes, shm.py),
``TPU_DIST_DP_TIMEOUT`` (recv deadline, seconds, default 300),
``TPU_DIST_COLL_TIMEOUT`` (end-to-end collective watchdog, seconds,
0/unset = off — ring/eager collectives raise
:class:`CollectiveTimeoutError` naming the stalled hop instead of waiting
out the per-frame deadline), ``TPU_DIST_FRAME_CRC`` (payload checksums,
default on), ``TPU_DIST_NETCHAOS`` (deterministic network fault
injection, tpu_dist/resilience/netchaos.py — partitions, delays, resets,
truncations, bit flips and throttles at this module's frame boundary),
``TPU_DIST_NO_DATAPLANE=1`` (disable; collectives fall back to the store),
``TPU_DIST_SOCK_BUF`` (bytes for ``SO_SNDBUF``/``SO_RCVBUF`` on every
data-plane socket; 0/unset keeps the OS default — the negotiated sizes are
recorded on the peer-connect flight-recorder event).  All sockets run with
``TCP_NODELAY``: ring sub-chunk frames are latency-sensitive and must not
sit in Nagle's buffer, and header+payload leave in ONE vectored ``sendmsg``
call anyway, so there is no small-segment flood for Nagle to fix.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
from collections import deque
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["DataPlane", "PeerGoneError", "FrameCorruptError",
           "CollectiveTimeoutError", "get_data_plane", "close_data_plane",
           "frame_crc_enabled", "frame_checksum", "coll_timeout",
           "dp_addr_key"]


def dp_addr_key(generation: int, rank: int) -> str:
    """The store key under which ``rank`` publishes its data-plane
    listener address — THE definition of the key contract; anything that
    probes for a published address (e.g. roles channels deciding
    store-vs-dataplane routing) must build the key here."""
    return f"tpu_dist/g{generation}/dp/addr/{rank}"

_MAGIC = b"TPDP"
_HELLO = struct.Struct("<4sII")      # magic, rank, generation
# in-band SHM control frame (lane announce) + the dtype-name marker that
# says "payload bytes are in the announced lane, not on this socket".
# User tags are store-key-shaped paths, so the NUL prefix cannot collide.
_SHM_TAG = "\x00shm-lane"
_SHM_MARK = "&"
# dtype-name marker: a 4-byte payload checksum follows the frame header
# (composable with the SHM mark, which stays outermost: "&!float32")
_CRC_MARK = "!"
_CONTROL = object()   # _read_frame sentinel: handled frame, nothing to queue
_U8 = struct.Struct("<B")
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_CONNECT_TIMEOUT = 60.0


def _connect_deadline() -> float:
    """Overall budget for dialing a peer's listener
    (``TPU_DIST_DIAL_TIMEOUT``, default 60 s) — individual dials retry
    under exponential backoff inside it, so a peer mid-restart is a
    transparent retry and a dead one a bounded named error."""
    try:
        return max(0.1, float(os.environ.get("TPU_DIST_DIAL_TIMEOUT",
                                             str(_CONNECT_TIMEOUT))))
    except ValueError:
        return _CONNECT_TIMEOUT


class PeerGoneError(ConnectionError):
    """A data-plane peer died (EOF/reset on its connection, or a send to it
    failed).  Carries the peer's rank so supervisors and tests can name the
    lost rank instead of pattern-matching an errno."""

    def __init__(self, peer: int, detail: str = ""):
        self.peer = int(peer)
        self.detail = detail
        msg = f"data-plane peer rank {peer} is gone"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class FrameCorruptError(ConnectionError):
    """A frame's payload failed its checksum: the bytes that arrived are
    not the bytes that were sent.  Carries the source rank, the frame tag,
    the stream offset (payload bytes previously delivered on this
    connection) and both CRCs — the named alternative to silently folding
    a flipped bit into gradients.  The connection is unusable afterwards
    (stream integrity is lost), so the peer is marked gone and every
    blocked recv re-raises this error."""

    def __init__(self, peer: Optional[int], tag: str, nbytes: int,
                 expected: int, got: int, offset: int):
        self.peer = None if peer is None else int(peer)
        self.tag = tag
        self.nbytes = int(nbytes)
        self.expected = int(expected)
        self.got = int(got)
        self.offset = int(offset)
        src = ("the control-plane store" if peer is None
               else f"rank {peer}")
        super().__init__(
            f"corrupt frame from {src} tag {tag!r}: payload checksum "
            f"mismatch (expected {expected:#010x}, got {got:#010x}) over "
            f"{nbytes} bytes at stream offset {offset} — refusing to "
            f"deliver corrupt payload bytes")


class CollectiveTimeoutError(TimeoutError):
    """A host collective failed to complete within
    ``TPU_DIST_COLL_TIMEOUT``: some hop of the ring/tree never delivered
    (a network partition, a wedged peer).  The message names the stalled
    hop (which peer, which span, which tag) and, when the flight recorder
    is armed, this rank's last recorded position — the diagnosis a silent
    hang never yields."""


def coll_timeout() -> float:
    """End-to-end collective watchdog budget in seconds
    (``TPU_DIST_COLL_TIMEOUT``; 0/unset = disabled — each blocking recv
    then falls back to the per-frame ``TPU_DIST_DP_TIMEOUT``)."""
    try:
        return max(0.0, float(os.environ.get("TPU_DIST_COLL_TIMEOUT",
                                             "0") or 0))
    except ValueError:
        return 0.0


# CRC32C when a native implementation is reachable — hardware (SSE4.2)
# CRC32C runs ~20 GB/s, which is what keeps the armed-overhead gate < 5%
# even on loopback where the "wire" moves at memory speed.  Resolution
# order: (1) the raw C ``crc32c_extend`` from the library google_crc32c
# ships, bound zero-copy through ctypes (the package's own Python entry
# point only accepts ``bytes``, which would force a copy per frame);
# (2) google_crc32c's Python API (bytes copy, still ~5 GB/s); (3) the
# ``crc32c`` package; (4) zlib's CRC32 (~1 GB/s, different polynomial,
# same 4-byte integrity contract).  The marker byte travels per frame, so
# hosts resolving different implementations MUST NOT be mixed in one gang
# — like every wire knob, TPU_DIST_FRAME_CRC is launcher-uniform and the
# resolution is environment-deterministic.


def _resolve_crc_fn():  # pragma: no cover - environment-dependent
    try:
        import ctypes
        import glob

        import google_crc32c
        root = os.path.join(
            os.path.dirname(os.path.dirname(google_crc32c.__file__)),
            "google_crc32c.libs")
        lib = ctypes.CDLL(glob.glob(os.path.join(root,
                                                 "libcrc32c*.so*"))[0])
        lib.crc32c_extend.restype = ctypes.c_uint32
        lib.crc32c_extend.argtypes = [ctypes.c_uint32, ctypes.c_void_p,
                                      ctypes.c_size_t]

        def _crc_hw(data, crc=0):
            a = np.frombuffer(data, np.uint8)  # zero-copy pointer access
            return lib.crc32c_extend(crc, a.ctypes.data, a.size)

        _crc_hw(b"tpu_dist")  # prove the binding before committing to it
        return _crc_hw
    except Exception:
        pass
    try:
        from google_crc32c import extend as _gcrc

        return lambda data, crc=0: _gcrc(crc, bytes(data))
    except Exception:
        pass
    try:
        from crc32c import crc32c

        return crc32c
    except Exception:
        from zlib import crc32

        return crc32


_crc_fn = _resolve_crc_fn()


def frame_checksum(parts, seed: int = 0) -> int:
    """Streaming checksum over payload parts (in wire order)."""
    c = seed
    for p in parts:
        v = memoryview(p).cast("B").toreadonly()
        if len(v):
            c = _crc_fn(v, c)
    return c & 0xFFFFFFFF


def frame_crc_enabled() -> bool:
    """Whether outgoing frames carry payload checksums
    (``TPU_DIST_FRAME_CRC``; armed by default).  Read per send, and
    one-sided-safe: the receiver verifies exactly the frames that arrive
    marked."""
    return os.environ.get("TPU_DIST_FRAME_CRC", "auto").strip().lower() \
        not in ("0", "off", "false", "no")


def _net_chaos():
    """The active network-fault injector, or None.  Guarded by
    sys.modules + the env var, so a process that never arms netchaos
    never imports it — the disarmed per-frame cost is two dict lookups."""
    import sys
    if "tpu_dist.resilience.netchaos" not in sys.modules \
            and not os.environ.get("TPU_DIST_NETCHAOS"):
        return None
    from ..resilience import netchaos
    return netchaos.install_from_env()


def _default_timeout() -> float:
    try:
        return float(os.environ.get("TPU_DIST_DP_TIMEOUT", "300"))
    except ValueError:
        return 300.0


def _sock_buf_bytes() -> int:
    """Requested ``SO_SNDBUF``/``SO_RCVBUF`` size (``TPU_DIST_SOCK_BUF``;
    0 = keep the OS default).  Bigger buffers keep a whole ring sub-chunk
    in flight per direction on high-BDP links."""
    try:
        return max(0, int(os.environ.get("TPU_DIST_SOCK_BUF", "0")))
    except ValueError:
        return 0


def _tune_socket(sock) -> Tuple[int, int]:
    """Apply TCP_NODELAY + requested buffer sizes; returns the negotiated
    ``(sndbuf, rcvbuf)`` the kernel actually granted (it may clamp or, on
    Linux, double the request)."""
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    want = _sock_buf_bytes()
    if want:
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, want)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, want)
        except OSError:
            pass  # a clamped/refused request is diagnostic, not fatal
    try:
        return (sock.getsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF),
                sock.getsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF))
    except OSError:
        return (0, 0)


def store_routed_host(store) -> str:
    """The local interface that routes toward the control-plane store —
    the address peers on OTHER hosts can reach this process on (a UDP
    ``connect`` resolves the route without sending traffic).  Loopback
    when the store is local/absent.  Shared by the data plane's address
    advertisement and the serve gateway's discovery key — one probe, so
    the two can never publish inconsistent interfaces."""
    target = getattr(store, "host", None)
    if not target or target in ("127.0.0.1", "localhost", "0.0.0.0", ""):
        return "127.0.0.1"
    try:
        probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            probe.connect((target, int(getattr(store, "port", 1))))
            return probe.getsockname()[0]
        finally:
            probe.close()
    except OSError:
        return "127.0.0.1"


def _sendv(sock, header: bytes, *payloads) -> None:
    """Vectored send: header + every payload part leave in one ``sendmsg``
    syscall — no concat copy of the payloads, no separate header segment
    on the wire.  Quantized frames pass two parts (scales, int8 payload);
    plain frames one.  Falls back to sequential ``sendall`` where
    ``sendmsg`` is missing."""
    parts = [memoryview(header)]
    parts.extend(memoryview(p).cast("B") for p in payloads if len(p))
    if len(parts) == 1:
        sock.sendall(header)
        return
    if not hasattr(sock, "sendmsg"):
        for p in parts:
            sock.sendall(p)
        return
    total = sum(len(p) for p in parts)
    done = 0
    while done < total:  # partial vectored sends resume across the parts
        n = sock.sendmsg(parts) if len(parts) > 1 else sock.send(parts[0])
        done += n
        while parts and n >= len(parts[0]):
            n -= len(parts[0])
            parts.pop(0)
        if n and parts:
            parts[0] = parts[0][n:]


def _sendv_paced(sock, header: bytes, parts, rate: float) -> None:
    """Throttled send (netchaos ``slow-drip``): the header goes out whole
    (frame parsing must make progress), payload bytes drip at ``rate``
    bytes/sec in ~10 ms slices.  Deterministic degradation, not an error:
    the frame completes, just slowly — bounded by the caller's deadlines."""
    sock.sendall(header)
    rate = max(1.0, float(rate))
    chunk = max(1, int(rate * 0.01))
    for p in parts:
        view = memoryview(p).cast("B")
        for off in range(0, len(view), chunk):
            piece = view[off:off + chunk]
            sock.sendall(piece)
            time.sleep(len(piece) / rate)


def _inject_break(sock, header: bytes, parts, plan) -> None:
    """netchaos ``conn-reset`` / ``truncate``: break the connection
    mid-frame and raise — the sender's error path turns it into a named
    ``PeerGoneError``; the receiver's framing layer sees a reset or a
    truncated frame and marks the peer gone the same way."""
    if plan.kind == "truncate":
        # header promises the full payload; deliver half of the first
        # part, then FIN — the receiver raises "connection closed
        # mid-frame" / "truncated frame" at the exact byte boundary
        sock.sendall(header)
        first = memoryview(parts[0]).cast("B") if parts else b""
        if len(first):
            sock.sendall(first[:max(1, len(first) // 2)])
        try:
            sock.shutdown(socket.SHUT_WR)
        except OSError:
            pass
    else:
        # RST mid-header: SO_LINGER(0) close discards the send queue and
        # resets — the hard variant (ECONNRESET on the peer)
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                            struct.pack("ii", 1, 0))
        except OSError:
            pass
        sock.sendall(header[:max(1, len(header) // 2)])
    sock.close()
    raise ConnectionResetError(
        f"netchaos: injected {plan.kind} mid-frame")


def _recv_exact(conn, n: int) -> Optional[bytearray]:
    """Read exactly ``n`` bytes into a fresh (writable) buffer.

    Returns None on EOF at a frame boundary (peer closed cleanly);
    raises ConnectionError on EOF mid-read (truncated frame)."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = conn.recv_into(view[got:], n - got)
        if r == 0:
            if got == 0:
                return None
            raise ConnectionError(f"truncated frame ({got}/{n} bytes)")
        got += r
    return buf


def _decode_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # jax's low-precision dtypes (bfloat16, float8_*) register with
        # numpy through ml_dtypes; resolve by attribute name
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _encode_frame_header(tag: bytes, dtype_name: bytes, shape,
                         payload_len: int) -> bytes:
    parts = [_U32.pack(len(tag)), tag,
             _U16.pack(len(dtype_name)), dtype_name,
             _U8.pack(len(shape))]
    parts.extend(_U64.pack(int(d)) for d in shape)
    parts.append(_U64.pack(payload_len))
    return b"".join(parts)


class DataPlane:
    """Per-process endpoint of the rank↔rank data plane.

    Opens a listening socket at construction and publishes its address to
    the control-plane store under
    ``tpu_dist/g{generation}/dp/addr/{rank}``; peers resolve each other
    through those keys on first send.  All methods are thread-safe.
    """

    def __init__(self, store, rank: int, num_processes: int,
                 generation: int = 0):
        self.rank = int(rank)
        self.num_processes = int(num_processes)
        self.generation = int(generation)
        self._store = store
        self._closing = False

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("0.0.0.0", 0))
        self._listener.listen(max(8, num_processes * 2))
        self.port = self._listener.getsockname()[1]

        # inbound frame queues + liveness, all under one condition variable
        self._cv = threading.Condition()
        self._in_q: Dict[Tuple[int, str], deque] = {}
        self._dead: Dict[int, str] = {}
        # peer -> the exception that killed its connection, when it is a
        # NAMED diagnosis (FrameCorruptError): blocked recvs re-raise the
        # named class instead of a generic PeerGoneError
        self._dead_errs: Dict[int, BaseException] = {}
        self._in_conn: Dict[int, object] = {}  # peer -> current inbound sock
        self._rx_off: Dict[int, int] = {}      # id(conn) -> payload bytes in

        # outbound connections, one per destination, each with its own lock
        # so concurrent senders to different peers do not serialize
        self._out: Dict[int, socket.socket] = {}
        self._out_locks: Dict[int, threading.Lock] = {}
        self._out_mu = threading.Lock()

        # shared-memory payload lanes (tpu_dist/collectives/shm.py):
        # outbound per co-located destination (we create + own), inbound
        # per announcing CONNECTION (keyed id(conn) — a reconnecting
        # sender announces a fresh lane while the old connection's reader
        # may still be draining the old one).  _shm_tried remembers a
        # definitively failed/declined setup so sends stop re-probing.
        self._shm_out: Dict[int, object] = {}
        self._shm_in: Dict[int, object] = {}
        self._shm_tried: Dict[int, bool] = {}
        self._peer_host: Dict[int, bool] = {}  # dst -> co-located?

        from .topology import host_fingerprint, publish_host_fingerprint
        self.host_id = host_fingerprint(self.rank)
        # host key BEFORE the addr key: peers wait on addr, so by the time
        # an address is visible the fingerprint is too (no second wait)
        publish_host_fingerprint(store, self.rank, self.generation)
        self.addr = f"{self._advertised_host()}:{self.port}"
        store.set(self._addr_key(self.rank), self.addr.encode())

        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"tpu_dist-dp-accept-r{rank}")
        self._accept_thread.start()
        # interpreter-exit close (idempotent; close() unregisters it so a
        # superseded incarnation's DataPlane is not pinned for process
        # lifetime): drops lane mappings and sockets even when the process
        # never reaches rendezvous.shutdown.  The exit-time variant must
        # NOT touch the store: a client round-trip (native libtpudist)
        # during interpreter teardown segfaults, and the addr key is
        # generation-scoped debris the reaper covers.
        import atexit
        atexit.register(self.close, _at_exit=True)

    # -- addressing ----------------------------------------------------------

    def _addr_key(self, rank: int) -> str:
        return dp_addr_key(self.generation, rank)

    def _host_key(self, rank: int) -> str:
        from .topology import host_key
        return host_key(self.generation, rank)

    def _advertised_host(self) -> str:
        host = os.environ.get("TPU_DIST_DP_HOST")
        if host:
            return host
        return store_routed_host(self._store)

    # -- inbound -------------------------------------------------------------

    def _accept_loop(self):
        while not self._closing:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            bufs = _tune_socket(conn)
            threading.Thread(target=self._reader, args=(conn, bufs),
                             daemon=True,
                             name=f"tpu_dist-dp-reader-r{self.rank}").start()

    def _reader(self, conn, bufs=(0, 0)):
        peer = None
        detail = "connection closed"
        named_err = None
        try:
            hello = _recv_exact(conn, _HELLO.size)
            if hello is None:
                return
            magic, peer, gen = _HELLO.unpack(bytes(hello))
            if magic != _MAGIC:
                peer = None
                return
            if gen != self.generation:
                # straggler from a failed incarnation: refuse its frames,
                # but do NOT mark the rank dead in THIS generation
                peer = None
                return
            with self._cv:
                # a valid hello supersedes any earlier death mark: the peer
                # reconnected after a transient drop, so future recvs must
                # wait for its frames again instead of failing spuriously
                self._dead.pop(peer, None)
                self._dead_errs.pop(peer, None)
                self._in_conn[peer] = conn
            self._obs("peer-connect", peer, sndbuf=bufs[0], rcvbuf=bufs[1])
            while True:
                frame = self._read_frame(conn, peer)
                if frame is None:
                    break
                if frame is _CONTROL:
                    continue  # lane announce — handled inside _read_frame
                tag, arr = frame
                with self._cv:
                    self._in_q.setdefault((peer, tag), deque()).append(arr)
                    self._cv.notify_all()
        except OSError as e:
            detail = repr(e)
            if isinstance(e, FrameCorruptError):
                # keep the NAMED diagnosis: blocked recvs re-raise exactly
                # this (src/tag/offset) instead of a generic peer-gone
                named_err = e
        finally:
            try:
                conn.close()
            except OSError:
                pass
            self._rx_off.pop(id(conn), None)
            lane = self._shm_in.pop(id(conn), None)
            if lane is not None:
                lane.close()  # this reader owned the mapping
            if peer is not None and not self._closing:
                died = False
                with self._cv:
                    # only this peer's CURRENT connection may declare it
                    # dead: a stale reader observing its own superseded
                    # socket's reset must not flag a reconnected live peer
                    if self._in_conn.get(peer) is conn:
                        del self._in_conn[peer]
                        self._dead[peer] = detail
                        if named_err is not None:
                            self._dead_errs[peer] = named_err
                        self._cv.notify_all()
                        died = True
                if died:
                    # a dead peer will never attach our announced lane:
                    # reap the name now (no-op if it already attached) so
                    # a crashed pair leaves no /dev/shm debris.  The lane
                    # object stays; a failed send replaces it.
                    lane = self._shm_out.get(peer)
                    if lane is not None:
                        lane.unlink()
                    self._obs("peer-gone", peer, detail=detail,
                              outcome=("error:FrameCorrupt"
                                       if named_err is not None
                                       else "error:PeerGone"))

    def _read_frame(self, conn, peer):
        raw = _recv_exact(conn, _U32.size)
        if raw is None:
            return None
        (tlen,) = _U32.unpack(bytes(raw))
        tag = bytes(_recv_exact_or_raise(conn, tlen)).decode()
        (dlen,) = _U16.unpack(bytes(_recv_exact_or_raise(conn, _U16.size)))
        dtype_name = bytes(_recv_exact_or_raise(conn, dlen)).decode()
        (ndim,) = _U8.unpack(bytes(_recv_exact_or_raise(conn, _U8.size)))
        shape = tuple(
            _U64.unpack(bytes(_recv_exact_or_raise(conn, _U64.size)))[0]
            for _ in range(ndim))
        (plen,) = _U64.unpack(bytes(_recv_exact_or_raise(conn, _U64.size)))
        lane_mode = dtype_name.startswith(_SHM_MARK)
        if lane_mode:
            dtype_name = dtype_name[len(_SHM_MARK):]
        crc_expected = None
        if dtype_name.startswith(_CRC_MARK):
            # a payload checksum follows the header (always on the TCP
            # socket — in lane mode the payload bytes are in shared
            # memory, but the integrity word rides the ordered stream)
            dtype_name = dtype_name[len(_CRC_MARK):]
            (crc_expected,) = _U32.unpack(
                bytes(_recv_exact_or_raise(conn, _U32.size)))
        if lane_mode:
            # payload bytes live in the announced SHM lane, not on the
            # socket — drain them there (same framing contract otherwise)
            payload = self._lane_read(conn, peer, plen)
        else:
            payload = (_recv_exact_or_raise(conn, plen) if plen
                       else bytearray())
        if crc_expected is not None:
            got = frame_checksum((payload,))
            if got != crc_expected:
                raise FrameCorruptError(
                    peer if peer is not None else -1, tag, plen,
                    crc_expected, got, self._rx_off.get(id(conn), 0))
        self._rx_off[id(conn)] = self._rx_off.get(id(conn), 0) + plen
        if tag == _SHM_TAG:
            self._attach_lane(conn, peer, payload)
            return _CONTROL
        if dtype_name.startswith("q8b"):
            return tag, _decode_quant(dtype_name, shape, payload, plen)
        dtype = _decode_dtype(dtype_name)
        # zero-copy: the ndarray wraps the receive buffer (writable, owned
        # by the frame) — no pickle, no second materialization
        arr = np.frombuffer(payload, dtype=dtype)
        if arr.size != int(np.prod(shape, dtype=np.int64)):
            raise ConnectionError(
                f"frame payload {plen}B does not match shape {shape} "
                f"dtype {dtype}")
        return tag, arr.reshape(shape)

    # -- shared-memory lanes (tpu_dist/collectives/shm.py) -------------------

    @staticmethod
    def _peek_dead(sock) -> Optional[str]:
        """Non-blocking liveness probe of a peer socket while parked in a
        lane wait: EOF/reset means the peer died mid-frame (pending data —
        e.g. the next frame header — means it is alive and streaming)."""
        try:
            b = sock.recv(1, socket.MSG_PEEK | socket.MSG_DONTWAIT)
            if b == b"":
                return "peer closed the connection mid-shm-frame"
        except (BlockingIOError, InterruptedError):
            return None
        except OSError as e:
            return f"connection error mid-shm-frame: {e!r}"
        return None

    def _lane_abort(self, sock):
        def check() -> Optional[str]:
            if self._closing:
                return "data plane closed"
            return self._peek_dead(sock)
        return check

    def _lane_read(self, conn, peer, plen: int) -> bytearray:
        # lanes are keyed by CONNECTION, not peer: after a sender
        # reconnect, the old connection's reader may still be draining
        # frames that reference the old lane while the new connection has
        # already announced a fresh one — each reader must keep consuming
        # exactly the lane its own stream announced
        lane = self._shm_in.get(id(conn))
        if lane is None:
            raise ConnectionError(
                f"rank {peer} sent an shm-lane frame but never announced "
                f"a lane on this connection")
        buf = bytearray(plen)
        if plen:
            lane.read_into(buf, timeout=_default_timeout(),
                           abort_check=self._lane_abort(conn))
        return buf

    def _attach_lane(self, conn, peer, payload) -> None:
        info = json.loads(bytes(payload).decode())
        from .shm import ShmLane
        old = self._shm_in.pop(id(conn), None)
        if old is not None:
            old.close()  # re-announce on the SAME connection (shouldn't
            # happen, but must not leak the mapping)
        try:
            self._shm_in[id(conn)] = ShmLane(name=info["name"],
                                             capacity=info.get("capacity",
                                                               0))
        except Exception as e:
            # the sender will stream payloads we cannot reach — this
            # connection is unusable; fail it loudly (fingerprints lying
            # about co-location is the only way here)
            raise ConnectionError(
                f"failed to attach shm lane {info.get('name')!r} announced "
                f"by rank {peer} (host fingerprints claim co-location but "
                f"the segment is unreachable): {e!r}") from e
        self._obs("shm-lane", peer, name=info["name"], role="attached")

    def _maybe_lane(self, dst: int, sock):
        """The outbound SHM lane to ``dst``, set up on first use when the
        peer is co-located and SHM is enabled; None otherwise (inline TCP
        payloads).  Called under the destination's send lock.  Setup
        failure falls back to TCP silently — only this rank's sends are
        affected, so one-sided degradation cannot wedge a ring."""
        from . import shm as _shm
        if not _shm.shm_enabled():
            return None
        lane = self._shm_out.get(dst)
        if lane is not None:
            return lane
        if self._shm_tried.get(dst):
            return None
        if not self.colocated(dst):
            # stop probing only on a DEFINITIVE different-host answer; an
            # unpublished fingerprint / transient store error stays
            # uncached so a later send re-resolves (colocated()'s contract)
            if dst in self._peer_host:
                self._shm_tried[dst] = True
            return None
        try:
            lane = _shm.ShmLane(create=True, generation=self.generation)
        except Exception:
            self._shm_tried[dst] = True  # no /dev/shm etc. — TCP fallback
            return None
        info = json.dumps({"name": lane.name,
                           "capacity": lane.capacity}).encode()
        header = _encode_frame_header(_SHM_TAG.encode(), b"uint8",
                                      (len(info),), len(info))
        try:
            _sendv(sock, header, info)
        except OSError:
            lane.unlink()  # the announce never left: nobody will attach
            lane.close()
            raise  # connection trouble: the caller's send error path owns it
        self._shm_out[dst] = lane
        self._obs("shm-lane", dst, name=lane.name, role="owner",
                  capacity=lane.capacity)
        return lane

    def shm_active(self, dst: int) -> bool:
        """True when an outbound shared-memory lane to ``dst`` is up
        (introspection for tests/benchmarks)."""
        return dst in self._shm_out

    def colocated(self, dst: int) -> bool:
        """Whether ``dst`` shares this rank's host fingerprint (cached;
        False until the peer has published — callers treat that as 'not
        yet known', and the send path re-resolves at lane setup)."""
        got = self._peer_host.get(dst)
        if got is None:
            from .topology import parse_host_record
            try:
                key = self._host_key(dst)
                if not self._store.check(key):
                    return False  # unpublished: do NOT cache the miss
                peer_host, _ = parse_host_record(self._store.get(key))
                got = peer_host == self.host_id
            except Exception:
                return False
            self._peer_host[dst] = got
        return got

    def send_chunk_bytes(self, dst: int, base: int) -> int:
        """Per-destination wire-frame grain for the ring
        (tpu_dist/collectives/ring.py): shared-memory destinations take
        far coarser frames (``TPU_DIST_SHM_CHUNK``, default 4 MiB) — the
        transfer is a memcpy, so fine-grained pipelining only multiplies
        per-frame overhead; TCP destinations keep ``base``."""
        from . import shm as _shm
        if not _shm.shm_enabled() or not self.colocated(dst):
            return base
        try:
            return max(base, int(os.environ.get("TPU_DIST_SHM_CHUNK",
                                                str(4 << 20))))
        except ValueError:
            return max(base, 4 << 20)

    # -- outbound ------------------------------------------------------------

    def _out_lock(self, dst: int) -> threading.Lock:
        with self._out_mu:
            lock = self._out_locks.get(dst)
            if lock is None:
                lock = self._out_locks[dst] = threading.Lock()
            return lock

    def _connect(self, dst: int) -> socket.socket:
        # bounded wait for the peer's address: a blocking store.get here
        # would hang forever (holding this destination's send lock) when
        # the peer died before constructing its DataPlane
        key = self._addr_key(dst)
        timeout = _default_timeout()
        try:
            self._store.wait([key], timeout=timeout if timeout > 0 else None)
        except TimeoutError as e:
            raise PeerGoneError(
                dst, f"never published a data-plane address: {e}") from e
        raw = self._store.get(key)
        host, _, port = raw.decode().rpartition(":")

        def _dial():
            sock = socket.create_connection((host, int(port)), timeout=5.0)
            try:
                _tune_socket(sock)
                sock.settimeout(None)
                sock.sendall(_HELLO.pack(_MAGIC, self.rank,
                                         self.generation))
            except OSError:
                sock.close()
                raise
            return sock

        # bounded exponential backoff instead of a one-shot dial: a peer
        # mid-restart (listener briefly down, address re-published an
        # instant later) retries transparently; a peer that stays
        # unreachable is a named error within _connect_deadline() seconds
        from ..utils.backoff import BackoffDeadlineError, retry_call
        try:
            return retry_call(_dial, timeout=_connect_deadline(),
                              what=f"dial data-plane peer rank {dst}")
        except BackoffDeadlineError as e:
            raise PeerGoneError(
                dst, f"listener at {host}:{port} unreachable after "
                f"{e.timeout:.0f}s of bounded-backoff dials "
                f"(TPU_DIST_DIAL_TIMEOUT): {e.last!r}") from e

    def send_array(self, dst: int, tag: str, arr) -> int:
        """Send one array frame to ``dst``; returns payload bytes sent.

        Blocking, but never deadlocks against a peer doing the same: the
        peer's reader thread is always draining its socket.  Raises
        :class:`PeerGoneError` if the connection to ``dst`` fails."""
        arr = np.asarray(arr)
        shape = arr.shape  # before ascontiguousarray, which flattens 0-d
        arr = np.ascontiguousarray(arr)
        try:
            payload = memoryview(arr).cast("B")
        except (TypeError, ValueError):
            payload = memoryview(arr.tobytes())  # exotic buffer-less dtypes
        return self._send_frame(dst, tag, arr.dtype.name, shape, (payload,))

    def send_quant(self, dst: int, tag: str, chunk) -> int:
        """Send one block-quantized frame (a
        :class:`~tpu_dist.collectives.quant.QuantChunk`): int8 payload +
        per-block float32 scales in ONE vectored ``sendmsg``, under the
        wire dtype name ``q8b{block}``.  Returns wire payload bytes sent
        (q + scales) — the compressed quantity obs counts as
        ``wire_bytes``."""
        scales = np.ascontiguousarray(chunk.scales, np.float32)
        q = np.ascontiguousarray(chunk.q, np.int8)
        return self._send_frame(
            dst, tag, f"q8b{chunk.scheme.block}", (q.size,),
            (memoryview(scales).cast("B"), memoryview(q).cast("B")))

    def _lane_stage(self, lane, parts, plan):
        """Pre-header SHM staging: copy whatever fits into the lane
        without blocking; returns the not-yet-staged remainders (sent
        after the header).  Everything that can fail here fails BEFORE the
        frame header leaves on TCP, which is what makes mid-stream lane
        failure recoverable — the caller degrades the frame (and the
        destination) to inline TCP instead of wedging the ring."""
        if plan is not None and plan.kind in ("conn-reset", "truncate"):
            # injected lane breakage (netchaos shm surface): the recovery
            # contract under test is the TCP fallback, not an error
            raise ConnectionError(
                f"netchaos: injected shm lane {plan.kind}")
        if plan is not None and plan.kind == "slow-drip":
            # the lane transfer is a memcpy — approximate a slow medium
            # with the equivalent stall up front
            time.sleep(sum(len(p) for p in parts) / max(1.0, plan.rate))
        rest = []
        for p in parts:
            if rest:
                rest.append(p)  # keep strict byte order
            elif len(p):
                done = lane.write_some(p)
                if done < len(p):
                    rest.append(p[done:])
        return rest

    def _degrade_lane(self, dst: int, err: BaseException) -> None:
        """Mid-stream SHM lane failure: drop the lane and pin this
        destination to inline TCP for the rest of the incarnation.  The
        established peer socket is untouched, so the frame (and the
        collective it belongs to) completes over TCP with identical
        bytes — degraded transport, bitwise-equal result.

        Deliberately NO ``unlink`` here, unlike the send-failure reap:
        the connection is ALIVE, so the lane announce may still be in
        flight toward the receiver's reader thread — yanking the name
        now would fail its attach (and with it the healthy connection).
        The receiver removes the name at attach as usual; only a
        receiver that dies before ever attaching leaves one named
        segment behind (the same bounded crash debris as ``close``)."""
        stale = self._shm_out.pop(dst, None)
        self._shm_tried[dst] = True
        if stale is not None:
            stale.close()
        self._obs("shm-lane", dst, role="degraded-to-tcp",
                  detail=repr(err))
        try:
            from ..utils.logging import log_event
            log_event("shm-lane-degraded", dst=dst, detail=repr(err))
        except Exception:
            pass

    def _send_frame(self, dst: int, tag: str, dtype_name: str, shape,
                    parts) -> int:
        """Shared outbound path for plain and quantized frames: one
        connection per destination, vectored send (or an SHM-lane payload
        with a TCP header, for co-located peers), optional payload
        checksum, deterministic network-fault injection, peer death
        diagnosed outside the send lock."""
        if dst == self.rank:
            raise ValueError("data plane does not deliver to self")
        parts = [memoryview(p).cast("B") for p in parts]
        plen = sum(len(p) for p in parts)
        send_err = None
        with self._out_lock(dst):
            sock = self._out.get(dst)
            try:
                if sock is None:
                    sock = self._connect(dst)
                    self._out[dst] = sock
                lane = self._maybe_lane(dst, sock) if plen else None
                # checksum BEFORE fault injection: netchaos `corrupt`
                # simulates bit flips ON THE WIRE, which is exactly what
                # the receiver-side verification must catch
                wire_dtype = dtype_name
                trailer = b""
                if frame_crc_enabled():
                    wire_dtype = _CRC_MARK + dtype_name
                    trailer = _U32.pack(frame_checksum(parts))
                plan = None
                nc = _net_chaos()
                if nc is not None:
                    plan = nc.plan("shm" if lane is not None else "tcp",
                                   src=self.rank, dst=dst)
                if plan is not None:
                    if plan.kind == "partition":
                        # rank-pair blackhole: the frame never leaves.
                        # The receiver's watchdog names the stalled hop.
                        return plen
                    if plan.kind == "delay":
                        time.sleep(plan.delay)
                    elif plan.kind == "corrupt":
                        parts = [memoryview(p).cast("B") for p in
                                 nc.corrupt_parts(plan, parts)]
                if lane is not None:
                    try:
                        rest = self._lane_stage(lane, parts, plan)
                    except (OSError, TimeoutError) as lane_err:
                        self._degrade_lane(dst, lane_err)
                        lane = None
                        plan = None  # the fault WAS the lane breakage —
                        # it must not fire again on the TCP fallback
                    else:
                        # payload FIRST (whatever fit without blocking),
                        # then header + checksum: by the time the
                        # receiver's reader parses the header, the bytes
                        # are already in the ring.  Only a frame
                        # overrunning the ring streams the remainder
                        # after the header (the receiver drains
                        # concurrently).
                        header = _encode_frame_header(
                            tag.encode(),
                            (_SHM_MARK + wire_dtype).encode(),
                            shape, plen) + trailer
                        _sendv(sock, header)
                        if rest:
                            timeout = _default_timeout()
                            abort = self._lane_abort(sock)
                            for p in rest:
                                lane.write(p, timeout=timeout,
                                           abort_check=abort)
                if lane is None:
                    header = _encode_frame_header(
                        tag.encode(), wire_dtype.encode(), shape,
                        plen) + trailer
                    if plan is not None and plan.kind in ("conn-reset",
                                                          "truncate"):
                        _inject_break(sock, header, parts, plan)
                    elif plan is not None and plan.kind == "slow-drip":
                        _sendv_paced(sock, header, parts, plan.rate)
                    else:
                        _sendv(sock, header, *parts)
            # tpudlint: disable=TD009  # stored in send_err and re-raised below, outside the send lock
            except PeerGoneError as e:
                send_err = e  # _connect diagnosed the peer; the obs-tail
                # enrichment still happens below, outside the lock
            except (OSError, TimeoutError) as e:
                self._out.pop(dst, None)
                stale = self._shm_out.pop(dst, None)
                self._shm_tried.pop(dst, None)
                if stale is not None:
                    # a reconnect announces a fresh lane — the receiver's
                    # read position in this one is unknowable.  Unlink too:
                    # the peer either attached already (name is gone,
                    # no-op) or is dead/never-attaching (reap the name)
                    stale.unlink()
                    stale.close()
                try:
                    if sock is not None:
                        sock.close()
                except OSError:
                    pass
                send_err = e  # diagnose outside the lock: gone_error's
                # obs-tail lookup is a store round-trip, and senders to
                # this dst must not queue behind a diagnostic
        if send_err is not None:
            detail = (send_err.detail if isinstance(send_err, PeerGoneError)
                      else repr(send_err))
            raise self.gone_error(dst, detail) from send_err
        return plen

    # -- receive -------------------------------------------------------------

    def _obs(self, op: str, peer: int, **fields) -> None:
        """Record a transport lifecycle event on the flight recorder
        (no-op when disarmed; must never raise into the reader threads)."""
        try:
            from ..obs.recorder import safe_record
        except Exception:
            return
        safe_record("transport", op, peer=peer, **fields)

    def gone_error(self, peer: int, detail: str = "") -> PeerGoneError:
        """A :class:`PeerGoneError` for ``peer``, enriched (when the flight
        recorder is armed) with the peer's last posted position from the
        store — the dead rank cannot speak for itself, but its obs tail
        can.  Call OUTSIDE any transport lock: the lookup is a store
        round-trip.  Under a role graph (tpu_dist.roles) the peer is also
        named by role — ``actor[2]`` says much more than ``rank 3``."""
        try:
            from ..roles.graph import role_label
            label = role_label(peer)
            if label:
                detail = (f"{detail}; role {label}" if detail
                          else f"role {label}")
        except Exception:
            pass
        try:
            from ..obs import hooks as _obs_hooks
            from ..obs import recorder as _obs_rec
            if _obs_rec.enabled():
                tail = _obs_hooks.fetch_tail(self._store, self.generation,
                                             peer)
                if tail is not None:
                    extra = f"peer's last obs: {_obs_hooks.render_tail(tail)}"
                    detail = f"{detail}; {extra}" if detail else extra
        except Exception:
            pass
        return PeerGoneError(peer, detail)

    def try_recv_array(self, src: int, tag: str):
        """Non-blocking: the next queued frame from ``(src, tag)`` or None."""
        with self._cv:
            return self._pop_locked(src, tag)

    def _pop_locked(self, src: int, tag: str):
        q = self._in_q.get((src, tag))
        if q:
            arr = q.popleft()
            if not q:
                del self._in_q[(src, tag)]
            return arr
        return None

    def peer_gone(self, src: int) -> Optional[str]:
        """Detail string if ``src``'s inbound connection died, else None."""
        with self._cv:
            return self._dead.get(src)

    def recv_array(self, src: int, tag: str,
                   timeout: Optional[float] = None) -> np.ndarray:
        """Block until a frame from ``(src, tag)`` arrives and return it.

        Frames from one peer arrive in send order (TCP + one connection per
        direction), so repeated calls with the same tag see the sender's
        chunk sequence in order.  Raises :class:`PeerGoneError` when the
        peer's connection died with frames still owed, ``TimeoutError``
        after ``timeout`` seconds (default ``TPU_DIST_DP_TIMEOUT``, 300).

        One wait loop exists — this delegates to :meth:`recv_array_dual`
        (with no alternate transport), so the peer-death / close / deadline
        handling cannot drift between the single- and dual-transport
        paths."""
        _, arr = self.recv_array_dual(src, tag, alt_check=None,
                                      timeout=timeout)
        return arr

    def recv_array_dual(self, src: int, tag: str, alt_check=None,
                        timeout: Optional[float] = None):
        """Wait for a frame from ``(src, tag)`` OR for ``alt_check()`` (a
        cheap poll of a second transport, e.g. a store key) to turn true.

        Returns ``("dataplane", arr)`` or ``("alt", None)``.  Frame
        arrival and peer death wake this *immediately* through the
        transport's condition variable; the alternate transport is polled
        between CV waits at an exponentially-backed-off interval (bounded
        at 50 ms), never while holding the CV — a store round-trip under
        the lock would stall every reader thread's frame delivery.  This
        replaces the old busy-poll loop in ``eager.recv`` (0.2 ms sleeps
        hammering both transports).  Raises :class:`PeerGoneError` /
        ``TimeoutError`` like :meth:`recv_array`."""
        if timeout is None:
            timeout = _default_timeout()
        deadline = (time.monotonic() + timeout) if timeout > 0 else None
        # with no alternate transport there is nothing to poll between CV
        # waits — park in long slices instead of the alt-poll backoff
        poll, poll_cap = (0.002, 0.05) if alt_check is not None \
            else (1.0, 1.0)
        while True:
            dead_detail = None
            with self._cv:
                slice_end = time.monotonic() + poll
                while True:
                    arr = self._pop_locked(src, tag)
                    if arr is not None:
                        return "dataplane", arr
                    if src in self._dead:
                        dead_detail = self._dead[src]
                        break
                    if self._closing:
                        raise RuntimeError("data plane closed during recv")
                    now = time.monotonic()
                    wake = slice_end if deadline is None \
                        else min(slice_end, deadline)
                    if wake - now <= 0:
                        break
                    self._cv.wait(wake - now)
            # outside the CV: consult the alternate transport / diagnose
            if alt_check is not None and alt_check():
                return "alt", None
            if dead_detail is not None:
                # the peer died — one last look at both sources (a frame
                # or key that landed between our check and the death
                # report still counts), then a named diagnosis
                with self._cv:
                    arr = self._pop_locked(src, tag)
                    named = self._dead_errs.get(src)
                if arr is not None:
                    return "dataplane", arr
                if isinstance(named, FrameCorruptError):
                    # the connection died because a frame failed its
                    # checksum: surface THAT diagnosis (src/tag/offset),
                    # not a generic peer-gone
                    raise named
                raise self.gone_error(src, dead_detail)
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(
                    f"data-plane recv from rank {src} tag {tag!r} "
                    f"timed out after {timeout:.0f}s")
            poll = min(poll * 2, poll_cap)

    # -- lifecycle -----------------------------------------------------------

    def close(self, _at_exit: bool = False) -> None:
        if self._closing:
            return
        self._closing = True
        if not _at_exit:
            try:
                self._store.delete_key(self._addr_key(self.rank))
            except Exception:
                pass  # store may be down; the key is generation-scoped
            import atexit
            try:
                # an explicitly-closed (superseded-incarnation) DataPlane
                # must not stay pinned by its exit hook
                atexit.unregister(self.close)
            except Exception:
                pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._out_mu:
            socks = list(self._out.values())
            self._out.clear()
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        for lane in (list(self._shm_out.values())
                     + list(self._shm_in.values())):
            # mappings only — deliberately NO unlink: the receiver removed
            # the name at attach, and unlinking a not-yet-attached lane
            # here would lose frames a clean exit must deliver (shm.py's
            # lifecycle note).  A receiver SIGKILLed before ever attaching
            # leaves one named segment behind — bounded crash debris.
            lane.close()
        self._shm_out.clear()
        self._shm_in.clear()
        with self._cv:
            # undelivered frames die with the incarnation; dropping them
            # here keeps a closed DataPlane from pinning megabytes of
            # queued ndarrays for the rest of the process
            self._in_q.clear()
            self._cv.notify_all()

    def __repr__(self):
        return (f"DataPlane(rank={self.rank}/{self.num_processes}, "
                f"addr={self.addr}, generation={self.generation})")


def _recv_exact_or_raise(conn, n: int) -> bytearray:
    buf = _recv_exact(conn, n)
    if buf is None:
        raise ConnectionError("connection closed mid-frame")
    return buf


def _decode_quant(dtype_name: str, shape, payload, plen: int):
    """Decode one ``q8b{block}`` frame (scales || int8 payload) into a
    :class:`~tpu_dist.collectives.quant.QuantChunk`.  Both arrays wrap the
    receive buffer zero-copy; the ring dequantizes at the fold or forwards
    the chunk verbatim."""
    from .quant import QuantChunk, QuantScheme
    try:
        scheme = QuantScheme(int(dtype_name[3:]))
    except ValueError as e:
        raise ConnectionError(f"bad quant frame dtype {dtype_name!r}") from e
    if len(shape) != 1:
        raise ConnectionError(
            f"quant frame wants flat shape, got {shape}")
    n = int(shape[0])
    sbytes = 4 * scheme.scales_for(n)
    if plen != sbytes + n:
        raise ConnectionError(
            f"quant frame payload {plen}B does not match {n} elements at "
            f"block {scheme.block} ({sbytes}B scales + {n}B q)")
    view = memoryview(payload)
    return QuantChunk(np.frombuffer(view[sbytes:], np.int8),
                      np.frombuffer(view[:sbytes], np.float32), scheme)


# -- process-wide singleton ---------------------------------------------------

_dp: Optional[DataPlane] = None
_dp_mu = threading.Lock()


def get_data_plane(store, rank: int, num_processes: int) -> Optional[DataPlane]:
    """The process's data plane, created on first use (None when disabled,
    single-process, or no store).  One per process per incarnation — the
    generation comes from ``TPU_DIST_RESTART_COUNT`` like every other
    incarnation-scoped key."""
    global _dp
    if store is None or num_processes <= 1:
        return None
    if os.environ.get("TPU_DIST_NO_DATAPLANE"):
        return None
    with _dp_mu:
        if _dp is not None and not _dp._closing:
            return _dp
        import importlib
        gen = importlib.import_module("tpu_dist.dist.rendezvous").generation()
        _dp = DataPlane(store, rank, num_processes, generation=gen)
        return _dp


def close_data_plane() -> None:
    """Tear down the process's data plane (called from
    ``tpu_dist.dist.rendezvous.shutdown``; safe to call twice)."""
    global _dp
    with _dp_mu:
        if _dp is not None:
            _dp.close()
            _dp = None
