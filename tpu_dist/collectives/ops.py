"""In-jit collectives over mesh axes (the NCCL kernel equivalents).

These are meant to be called inside ``jax.shard_map`` / ``pmap`` bodies with
the mesh axis name; XLA lowers them to ICI/DCN collectives and fuses them
with surrounding compute — the property the reference gets from NCCL+DDP
overlap (/root/reference/README.md:9-20) falls out of compilation here.

Reduction ops mirror torch.distributed.ReduceOp: SUM, AVG (mean), MAX, MIN,
PRODUCT.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["all_reduce", "psum", "pmean", "all_gather", "reduce_scatter",
           "broadcast", "all_to_all", "ppermute", "ring_all_reduce"]

_REDUCE_OPS = {
    "sum": lax.psum,
    "avg": lax.pmean,
    "mean": lax.pmean,
    "max": lax.pmax,
    "min": lax.pmin,
}


def all_reduce(x, axis_name: str, op: str = "sum"):
    """All-reduce over a mesh axis (ReduceOp parity).

    ``op='product'`` has no direct lax primitive; computed as
    ``exp(psum(log))`` would lose sign, so it is lowered via all_gather+prod.
    """
    op = op.lower()
    if op in _REDUCE_OPS:
        return jax.tree.map(lambda v: _REDUCE_OPS[op](v, axis_name), x)
    if op in ("prod", "product"):
        return jax.tree.map(
            lambda v: jnp.prod(lax.all_gather(v, axis_name, axis=0), axis=0), x)
    raise ValueError(f"Unknown reduce op {op!r}")


def psum(x, axis_name: str):
    return jax.tree.map(lambda v: lax.psum(v, axis_name), x)


def pmean(x, axis_name: str):
    return jax.tree.map(lambda v: lax.pmean(v, axis_name), x)


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = False):
    """Gather shards from every device along ``axis``.

    ``tiled=False`` stacks (new leading dim of size world); ``tiled=True``
    concatenates along ``axis`` (torch ``all_gather_into_tensor`` style).
    """
    return jax.tree.map(
        lambda v: lax.all_gather(v, axis_name, axis=axis, tiled=tiled), x)


def reduce_scatter(x, axis_name: str, scatter_axis: int = 0, op: str = "sum"):
    """Reduce across the axis, leaving each device its 1/world slice —
    ``lax.psum_scatter``; the building block of ring all-reduce."""
    if op.lower() not in ("sum", "avg", "mean"):
        raise ValueError("reduce_scatter supports sum/avg")
    out = jax.tree.map(
        lambda v: lax.psum_scatter(v, axis_name, scatter_dimension=scatter_axis,
                                   tiled=True), x)
    if op.lower() in ("avg", "mean"):
        n = lax.psum(1, axis_name)
        out = jax.tree.map(lambda v: v / n, out)
    return out


def broadcast(x, axis_name: str, src: int = 0):
    """Broadcast ``src``'s value to every device on the axis.

    DDP does this once at wrap time to align parameters
    (rank-0 broadcast; the reference relies on it at
    /root/reference/example_mp.py:53 in lieu of seeding).  Implemented as
    mask+psum, which XLA lowers to an efficient one-to-all.
    """
    idx = lax.axis_index(axis_name)

    def _bcast(v):
        vv = jnp.asarray(v)
        return lax.psum(jnp.where(idx == src, vv, jnp.zeros_like(vv)),
                        axis_name)

    return jax.tree.map(_bcast, x)


def all_to_all(x, axis_name: str, split_axis: int, concat_axis: int):
    """All-to-all (the Ulysses sequence-parallel primitive); each device
    splits along ``split_axis`` and concatenates received chunks along
    ``concat_axis``."""
    return jax.tree.map(
        lambda v: lax.all_to_all(v, axis_name, split_axis=split_axis,
                                 concat_axis=concat_axis, tiled=True), x)


def ppermute(x, axis_name: str, perm: Sequence[Tuple[int, int]]):
    """Point-to-point permutation over the axis (ring hops)."""
    return jax.tree.map(lambda v: lax.ppermute(v, axis_name, perm=perm), x)


def ring_all_reduce(x, axis_name: str, axis_size: Optional[int] = None):
    """Ring all-reduce, spelled out: the algorithm the reference README
    teaches (/root/reference/README.md:9-20) — N-1 reduce-scatter hops then
    N-1 all-gather hops around a ring, per-step volume constant in world
    size.

    On TPU the ring is physical (ICI torus links between neighbours), so the
    ppermute hops below map 1:1 onto hardware — but note ``lax.psum`` already
    compiles to this (or better); this explicit version exists for teaching
    parity and as the pattern for ring attention.  Numerically equal to
    ``psum`` (tested in tests/test_collectives.py).

    Requires each leaf's leading dimension divisible by the axis size.
    """
    n = axis_size if axis_size is not None else lax.axis_size(axis_name)
    if n == 1:
        return x
    ring_fwd = [(i, (i + 1) % n) for i in range(n)]

    def _ring(v):
        if v.shape[0] % n:
            raise ValueError(
                f"ring_all_reduce needs leading dim divisible by axis size "
                f"{n}; got shape {v.shape}. Pad or use psum.")
        me = lax.axis_index(axis_name)
        chunks = v.reshape((n, v.shape[0] // n) + v.shape[1:])

        # Phase 1 — reduce-scatter: after N-1 hops, device d holds the full
        # sum of chunk (d+1) mod n.
        def rs_step(i, acc):
            # acc: the partial chunk being accumulated, travelling the ring
            acc = lax.ppermute(acc, axis_name, perm=ring_fwd)
            recv_idx = jnp.mod(me - i - 1, n)
            return acc + lax.dynamic_index_in_dim(chunks, recv_idx, 0,
                                                  keepdims=False)

        start = lax.dynamic_index_in_dim(chunks, jnp.mod(me, n), 0,
                                         keepdims=False)
        acc = lax.fori_loop(0, n - 1, rs_step, start)
        # device d now owns the reduced chunk with index (d - (n-1)) mod n
        # = (d+1) mod n.

        # Phase 2 — all-gather: circulate reduced chunks N-1 hops; each
        # device scatters what it receives into its output buffer.
        own_idx = jnp.mod(me + 1, n)
        out = jnp.zeros_like(chunks)
        out = lax.dynamic_update_index_in_dim(out, acc, own_idx, 0)

        def ag_step(i, carry):
            out, piece = carry
            piece = lax.ppermute(piece, axis_name, perm=ring_fwd)
            idx = jnp.mod(me - i, n)  # index of the chunk just received
            out = lax.dynamic_update_index_in_dim(out, piece, idx, 0)
            return out, piece

        out, _ = lax.fori_loop(0, n - 1, ag_step, (out, acc))
        return out.reshape(v.shape)

    return jax.tree.map(_ring, x)
