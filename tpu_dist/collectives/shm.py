"""Shared-memory intra-host payload lane for the p2p data plane.

Co-located ranks (same host fingerprint, tpu_dist/collectives/topology.py)
waste two kernel copies plus the whole TCP stack on every loopback frame —
PR 8 measured the consequence directly: world 4 on a 2-core box *inverts*
int8-vs-f32 because co-located ranks serialize through loopback sockets.
This module gives each directed co-located pair an SHM **byte stream**: a
single-producer/single-consumer ring buffer in a
``multiprocessing.shared_memory`` segment, through which frame *payloads*
move as two memcpys (sender in, receiver out) instead of user→kernel→user.

Deliberately a *payload* lane, not a second transport:

- **Framing, ordering, and liveness stay on the TCP connection.**  The
  sender still writes every frame header (tag/dtype/shape — the exact
  contract of transport.py, including ``q8b{N}`` quant frames) onto the
  established peer socket, with the dtype name marked (``&``-prefixed) to
  say "payload is in the lane"; the receiver's existing reader thread
  parses the header and then drains the payload bytes from the lane.  One
  stream, one consumer thread, so per-``(src, tag)`` FIFO order, the
  generation-fenced hello, and ``PeerGoneError`` semantics are inherited
  unchanged rather than re-implemented.
- **Backpressure by ring occupancy.**  The stream carries two monotonic
  u64 counters (written / read, on separate cache lines).  A sender that
  outruns the receiver parks in a spin-then-sleep wait for space and
  **resumes partially written frames** as the receiver frees bytes, so a
  frame larger than the whole ring still flows.  Both sides poll a
  caller-supplied ``abort_check`` (a non-blocking peek of the TCP socket)
  while waiting, so a peer that dies mid-frame surfaces as a named
  ``ConnectionError`` → ``PeerGoneError``, never a hang.
- **x86 TSO ordering note.**  The producer writes payload bytes, then
  advances the write counter; the consumer reads the counter, then the
  bytes.  Aligned 8-byte counter stores/loads are atomic and stay ordered
  on x86 (total store order); the same discipline every mmap'd SPSC queue
  relies on.

- **Mid-stream failure degrades, pre-header.**  The transport stages a
  frame's payload into the lane *before* committing the frame header to
  TCP, so any lane failure at staging time (mapping gone, injected
  ``TPU_DIST_NETCHAOS`` fault on the ``shm`` surface) lets the sender
  fall back to an inline-TCP payload for that very frame — the
  collective completes bitwise-equal over the degraded transport
  (transport.py ``_lane_stage``/``_degrade_lane``).  Lane payloads are
  covered by the same per-frame checksums as TCP payloads
  (``TPU_DIST_FRAME_CRC``): the integrity word rides the TCP header
  stream while the bytes move through shared memory.

Env knobs: ``TPU_DIST_SHM`` (``auto`` default — lanes come up for
co-located peers; ``0`` disables), ``TPU_DIST_SHM_RING`` (ring capacity
bytes, default 8 MiB).  Lane names carry the gang generation and the
creator's pid, so a restarted incarnation can never attach a stale ring.
"""

from __future__ import annotations

import os
import secrets
import time
from typing import Callable, Optional

import numpy as np

__all__ = ["ShmLane", "shm_enabled", "ring_capacity"]

# counter offsets (separate cache lines) + start of the data ring
_W_OFF = 0
_R_OFF = 64
_DATA_OFF = 128

_DEF_RING = 8 * 1024 * 1024


def shm_enabled() -> bool:
    """Whether SHM lanes may come up for co-located peers
    (``TPU_DIST_SHM``: ``auto``/``1`` on, ``0`` off).  Read per send so
    benchmarks can A/B the transport without rebuilding the DataPlane."""
    return os.environ.get("TPU_DIST_SHM", "auto").strip().lower() not in (
        "0", "off", "false", "no")


def ring_capacity() -> int:
    try:
        cap = int(os.environ.get("TPU_DIST_SHM_RING", str(_DEF_RING)))
    except ValueError:
        cap = _DEF_RING
    return max(4096, cap)


def _np_u64(buf, off: int) -> np.ndarray:
    return np.frombuffer(buf, dtype=np.uint64, count=1, offset=off)


class ShmLane:
    """One directed SPSC byte stream through a shared-memory segment.

    The sender constructs with ``create=True`` (it owns the segment and
    unlinks it at close); the receiver attaches by name.  ``write`` and
    ``read_into`` are blocking with deadline + abort polling; each side
    must be driven by exactly one thread (the data plane guarantees this:
    sends hold the per-destination lock, reads happen on the one reader
    thread of the inbound connection)."""

    def __init__(self, name: Optional[str] = None, capacity: int = 0,
                 create: bool = False, generation: int = 0):
        from multiprocessing import shared_memory
        self.owner = bool(create)
        if create:
            capacity = int(capacity) or ring_capacity()
            name = (f"tpdp_g{generation}_{os.getpid()}_"
                    f"{secrets.token_hex(4)}")
            self._shm = shared_memory.SharedMemory(
                name=name, create=True, size=_DATA_OFF + capacity)
            self._shm.buf[:_DATA_OFF] = b"\x00" * _DATA_OFF
            # the lane owns its own lifecycle (see below): keep the
            # resource tracker out of it, or the creator's exit would
            # unlink a name a not-yet-attached receiver still needs
            self._untrack()
        else:
            self._shm = shared_memory.SharedMemory(name=name)
            # the name's one job — letting this attach find the segment —
            # is done: remove it NOW.  Both mappings stay valid, in-flight
            # frames survive a sender that exits right after sending
            # (the TCP-buffer delivery semantic peers rely on), and a
            # SIGKILLed pair leaves no /dev/shm debris.  CPython 3.8-3.12
            # auto-registers attachments with the resource tracker;
            # unlink() (shm_unlink + unregister) balances that too.
            try:
                self._shm.unlink()
            except Exception:
                pass  # already unlinked (double announce / re-attach race)
            # ring capacity comes from the CREATOR's announce: both sides
            # must wrap at the same modulus, and platforms may page-round
            # the mapped segment size — recomputing from self._shm.size
            # here would silently corrupt payloads at wraparound
            capacity = int(capacity) or (self._shm.size - _DATA_OFF)
        self.name = self._shm.name.lstrip("/")
        self.capacity = int(capacity)
        buf = self._shm.buf
        self._w = _np_u64(buf, _W_OFF)
        self._r = _np_u64(buf, _R_OFF)
        self._data = np.frombuffer(buf, dtype=np.uint8, offset=_DATA_OFF,
                                   count=self.capacity)
        self._closed = False

    # -- ring I/O ------------------------------------------------------------

    def _views(self):
        """Local refs to the mapped views — taken once per call so a
        concurrent close() (which nulls the attributes before unmapping)
        cannot yank them mid-loop; a ref held here keeps the mapping
        alive, and the ``_closed`` flag ends the loop at its next check."""
        data, w, r = self._data, self._w, self._r
        if data is None:
            raise ConnectionError(f"shm lane {self.name} closed")
        return data, w, r

    def _copy_in(self, data, pos: int, src: np.ndarray) -> None:
        lo = pos % self.capacity
        first = min(src.size, self.capacity - lo)
        data[lo:lo + first] = src[:first]
        if first < src.size:  # wrap
            data[:src.size - first] = src[first:]

    def _copy_out(self, data, pos: int, dst: np.ndarray) -> None:
        lo = pos % self.capacity
        first = min(dst.size, self.capacity - lo)
        dst[:first] = data[lo:lo + first]
        if first < dst.size:
            dst[first:] = data[:dst.size - first]

    def _park(self, spun: int, detail: str, deadline: float,
              abort_check: Optional[Callable[[], Optional[str]]]) -> int:
        """One wait iteration while the ring has no room/data: spin a few
        rounds (the common case — the peer is actively streaming), then
        sleep-poll, checking peer death and the deadline."""
        if spun < 200:
            return spun + 1
        if abort_check is not None:
            why = abort_check()
            if why:
                raise ConnectionError(f"shm lane {self.name}: {why}")
        if time.monotonic() >= deadline:
            raise TimeoutError(
                f"shm lane {self.name}: peer made no progress before the "
                f"deadline (TPU_DIST_DP_TIMEOUT)")
        time.sleep(0.0002)
        return spun

    def write_some(self, payload) -> int:
        """Non-blocking write: copy as much of ``payload`` as the ring has
        room for, return the number of bytes written.  The send path uses
        this to stage a frame's payload BEFORE its header goes out on the
        socket — the receiver then finds the bytes already in the ring and
        never parks on a frame the sender is still copying."""
        src = np.frombuffer(memoryview(payload).cast("B"), dtype=np.uint8)
        if self._closed:
            raise ConnectionError(f"shm lane {self.name} closed")
        data, wctr, rctr = self._views()
        w = int(wctr[0])
        space = self.capacity - (w - int(rctr[0]))
        chunk = min(space, src.size)
        if chunk <= 0:
            return 0
        self._copy_in(data, w, src[:chunk])
        wctr[0] = w + chunk  # counter advances AFTER the bytes land
        return chunk

    def write(self, payload, timeout: float,
              abort_check: Optional[Callable[[], Optional[str]]] = None
              ) -> None:
        """Stream ``payload`` (a bytes-like) into the ring, blocking for
        space; partially written frames resume as the reader frees bytes."""
        src = np.frombuffer(memoryview(payload).cast("B"), dtype=np.uint8)
        deadline = time.monotonic() + timeout
        done, n, spun = 0, src.size, 0
        while done < n:
            wrote = self.write_some(src[done:])
            if wrote == 0:
                spun = self._park(spun, "peer stopped draining the ring",
                                  deadline, abort_check)
                continue
            spun = 0
            done += wrote

    def read_into(self, out: bytearray, timeout: float,
                  abort_check: Optional[Callable[[], Optional[str]]] = None
                  ) -> None:
        """Fill ``out`` from the ring, blocking until the writer has
        produced enough bytes; frees space as it consumes."""
        dst = np.frombuffer(out, dtype=np.uint8)
        deadline = time.monotonic() + timeout
        done, n, spun = 0, dst.size, 0
        while done < n:
            if self._closed:
                raise ConnectionError(f"shm lane {self.name} closed")
            data, wctr, rctr = self._views()
            r = int(rctr[0])
            avail = int(wctr[0]) - r
            if avail <= 0:
                spun = self._park(spun, "peer died mid-frame", deadline,
                                  abort_check)
                continue
            spun = 0
            chunk = min(avail, n - done)
            self._copy_out(data, r, dst[done:done + chunk])
            rctr[0] = r + chunk  # free the span only after the copy
            done += chunk

    # -- lifecycle -----------------------------------------------------------

    def _untrack(self) -> None:
        try:
            from multiprocessing import resource_tracker
            resource_tracker.unregister(
                getattr(self._shm, "_name", None)
                or "/" + self._shm.name.lstrip("/"), "shared_memory")
        except Exception:
            pass

    def unlink(self) -> None:
        """Remove the segment's name (creator-side, for lanes whose
        announce never reached the peer — the receiver otherwise unlinks
        at attach)."""
        try:
            self._shm.unlink()
        except Exception:
            pass

    def close(self) -> None:
        """Drop this side's mapping.  Deliberately NO unlink here: the
        receiver removed the name at attach; unlinking on the creator's
        close would race a receiver that has the announce in flight but
        has not attached yet (losing frames a clean sender exit must
        deliver)."""
        if self._closed:
            return
        self._closed = True
        # numpy views pin the mmap'd buffer; drop them before close()
        self._w = self._r = self._data = None
        try:
            self._shm.close()
        except Exception:
            pass

    def __repr__(self):
        return (f"ShmLane({self.name!r}, cap={self.capacity}, "
                f"{'owner' if self.owner else 'attached'})")
