"""Gradient bucketing: coalesce pytree leaves into flat buckets and issue
them as async ring all-reduces (torch DDP Reducer / Horovod tensor-fusion
parity for the host data plane).

A gradient tree is dozens-to-hundreds of leaves; synchronously ring-reducing
each one pays the full 2(N-1)-step ring latency per leaf, and the tiny
leaves never amortize per-frame overhead.  The :class:`Bucketer` packs
leaves into fixed-size flat buckets (``TPU_DIST_BUCKET_BYTES``, 25 MiB
default — torch DDP's ``bucket_cap_mb`` default), issues each bucket as ONE
async ring all-reduce on the ordered engine
(:mod:`tpu_dist.collectives.work`), and unflattens on ``wait_all()`` — so
the caller overlaps whatever it computes next with the whole sync, and the
wire sees a few large pipelined collectives instead of many small ones.

Buckets are filled in **reverse leaf order** (DDP's heuristic: backward
produces gradients roughly in reverse parameter order, so the last-produced
gradients — the first ready in a hook-driven flow — sync first).

**Bitwise parity with the per-leaf ring** (the property the chaos e2e's
bit-identical resume check leans on): a naive bucketer concatenates leaves
and ring-chunks the concatenation, which moves elements into *different
ring chunks* than the per-leaf collectives would — a different chunk owner
means a different (deterministic, but different) float fold order, so
bucketed sums come out bit-different from the unbucketed path.  This
bucketer instead lays each bucket out **chunk-major**: bucket chunk *c* is
the concatenation of every member leaf's own per-leaf ring chunk *c* (each
leaf split by the same ``_bounds(leaf.size, world)`` the per-leaf ring
uses), and the ring runs with those custom chunk bounds.  Chunk ownership —
and therefore the accumulation order of every single element — is identical
to the per-leaf ring, making bucketed results bit-identical to unbucketed
ones, per element, including under ``comm_dtype`` wire compression (the
owner re-quantizes the same chunk either way).

Leaves a ring cannot reduce (unsupported dtype/op, zero-size) fall back to
ONE coalesced eager ``all_reduce_host`` call issued as a trailing async
work, so the API contract (every leaf reduced, one ``wait_all``) holds on
every transport; with no data plane at all the whole tree rides that path.
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional

import numpy as np

__all__ = ["Bucketer", "BucketWork", "bucketed_all_reduce",
           "bucketed_reduce_scatter", "DEFAULT_BUCKET_BYTES"]

DEFAULT_BUCKET_BYTES = 25 * 1024 * 1024  # torch DDP bucket_cap_mb parity


def _bucket_bytes_env() -> int:
    try:
        return max(4096, int(os.environ.get("TPU_DIST_BUCKET_BYTES",
                                            str(DEFAULT_BUCKET_BYTES))))
    except ValueError:
        return DEFAULT_BUCKET_BYTES


def _ring_leaf_ok(a: np.ndarray, op: str) -> bool:
    """True iff the ring can reduce this leaf (dtype/op only — bucketing
    exists to aggregate small leaves, so no size threshold).  Must depend
    only on dtype/op so every rank answers identically."""
    from . import ring as _ring
    if op not in _ring.RING_OPS or a.size == 0:
        return False
    dt = a.dtype
    if dt.kind in "iuf":
        return True
    if dt.kind == "V" and dt.fields is None:
        from .transport import _decode_dtype
        try:
            return _decode_dtype(dt.name) == dt
        except Exception:
            return False
    return False


class _Bucket:
    """One dtype-uniform bucket: member leaf indices + flat leaf arrays."""

    __slots__ = ("dtype", "indices", "flats", "nbytes")

    def __init__(self, dtype: np.dtype):
        self.dtype = dtype
        self.indices: List[int] = []
        self.flats: List[np.ndarray] = []
        self.nbytes = 0

    def add(self, idx: int, flat: np.ndarray) -> None:
        self.indices.append(idx)
        self.flats.append(flat)
        self.nbytes += flat.nbytes

    def pack(self, n: int):
        """Chunk-major layout: returns ``(buf, bucket_bounds, leaf_bounds)``
        where ``buf`` is the flat bucket, ``bucket_bounds[c]`` the (lo, hi)
        span of bucket chunk *c*, and ``leaf_bounds[i]`` each member leaf's
        own per-leaf ring bounds.  Bucket chunk *c* holds every member
        leaf's chunk *c*, so chunk ownership matches the per-leaf ring
        exactly (see module docstring)."""
        from .ring import _bounds
        leaf_bounds = [_bounds(f.size, n) for f in self.flats]
        total = sum(f.size for f in self.flats)
        buf = np.empty(total, dtype=self.dtype)
        bucket_bounds = []
        pos = 0
        for c in range(n):
            lo = pos
            for f, b in zip(self.flats, leaf_bounds):
                flo, fhi = b[c]
                if fhi > flo:
                    buf[pos:pos + (fhi - flo)] = f[flo:fhi]
                    pos += fhi - flo
            bucket_bounds.append((lo, pos))
        return buf, bucket_bounds, leaf_bounds

    def unpack(self, reduced: np.ndarray, n: int, leaf_bounds) -> List:
        """Invert :meth:`pack`: per-member flat reduced arrays (in member
        order, ``reduced``'s dtype)."""
        outs = [np.empty(f.size, dtype=reduced.dtype) for f in self.flats]
        pos = 0
        for c in range(n):
            for out, b in zip(outs, leaf_bounds):
                flo, fhi = b[c]
                if fhi > flo:
                    out[flo:fhi] = reduced[pos:pos + (fhi - flo)]
                    pos += fhi - flo
        return outs


class BucketWork:
    """Aggregate handle over one bucketed all-reduce: per-bucket
    :class:`~tpu_dist.collectives.work.Work` futures plus the unflatten.
    ``wait_all(timeout)`` returns the fully-reduced tree."""

    def __init__(self, treedef, assemble, works: List, label: str):
        self._treedef = treedef
        self._assemble = assemble      # (results per work) -> leaves list
        self.works = list(works)
        self._label = label
        self._result = None
        self._done = False

    def wait_all(self, timeout: Optional[float] = None):
        """Wait for every bucket; returns the reduced tree.  The first
        captured error (``PeerGoneError``, ...) re-raises."""
        if self._done:
            return self._result
        from .work import wait_all as _wait_all
        results = _wait_all(self.works, timeout)
        import jax
        leaves = self._assemble(results)
        self._result = jax.tree.unflatten(self._treedef, leaves)
        self._done = True
        return self._result

    # Work-flavored aliases so generic handle code treats the aggregate
    # like a single collective
    def wait(self, timeout: Optional[float] = None):
        return self.wait_all(timeout)

    def is_completed(self) -> bool:
        return self._done or all(w.is_completed() for w in self.works)

    def exception(self) -> Optional[BaseException]:
        for w in self.works:
            exc = w.exception()
            if exc is not None:
                return exc
        return None

    def __repr__(self):
        state = "done" if self._done else f"{len(self.works)} buckets"
        return f"BucketWork({self._label!r}, {state})"


class Bucketer:
    """Coalesces pytree leaves into flat buckets and all-reduces them
    asynchronously; see module docstring.

    Production use (the chaos/elastic grad-sync path)::

        bucketer = C.Bucketer()                  # 25 MiB buckets
        grads = bucketer.all_reduce(grads, op="avg", group=pg).wait_all()

    ``dp`` pins a specific :class:`DataPlane` (tests drive several
    in-process "ranks", each with its own plane and its own ordered
    engine; pinned mode is ring-only).  Production resolves the process's
    plane lazily inside the work body and shares the process-wide engine
    with the eager ``async_op`` path, so every async collective in the
    process rides ONE ordered stream (consistent collective order for the
    sanitizer and the flight recorder's lockstep sequence).
    """

    def __init__(self, bucket_bytes: Optional[int] = None, dp=None,
                 comm_dtype=None):
        self.bucket_bytes = (int(bucket_bytes) if bucket_bytes
                             else _bucket_bytes_env())
        self._dp = dp
        # wire-compression dtype for pinned (test) mode; production reads
        # TPU_DIST_COMM_DTYPE like the eager routed collectives
        self._comm_dtype = comm_dtype
        # per-instance tag counter for pinned (test) mode: the process-
        # global eager counters are shared across the in-process "ranks"
        # and would interleave; allocated at ISSUE time = program order
        self._seq = 0
        self._seq_mu = threading.Lock()

    def all_reduce(self, tree, op: str = "avg", group=None,
                   error_feedback=None) -> BucketWork:
        """Issue bucketed async all-reduces for every leaf of ``tree``;
        returns a :class:`BucketWork` (``wait_all()`` -> reduced tree).
        ``op``: sum/avg/max/min ride the ring; anything else (and
        ring-incompatible leaves) coalesces onto the store path.

        ``error_feedback`` (a
        :class:`~tpu_dist.collectives.quant.ErrorFeedback`) activates the
        residual loop under a lossy wire format (``comm_dtype`` cast or
        int8 block quantization): each leaf's owner folds last step's
        compression loss back into its chunk before compressing, and keeps
        the new loss — pass the same object every step.  A no-op when no
        lossy wire is configured.

        Leaves are **snapshotted at issue** (the pack copy happens on this
        thread, before returning), so the caller may mutate its arrays the
        moment this returns — no torch-style "don't touch until wait"
        hazard."""
        return self._issue(tree, op, group, scatter=False,
                           error_feedback=error_feedback)

    def reduce_scatter(self, tree, op: str = "avg", group=None,
                       error_feedback=None) -> BucketWork:
        """Bucketed all-reduce **stopped at the reduce-scatter phase**:
        ``wait_all()`` returns a tree of the same structure whose leaves are
        this rank's **owned flat chunk** of each reduced leaf (1-D, span
        ``ring.ring_chunk_span(leaf.size, world, rank)``; empty on ranks
        that own no elements of a tiny leaf).

        Because buckets are laid out chunk-major, bucket chunk *c* is
        already the concatenation of every member leaf's own per-leaf ring
        chunk *c* — so the shard this rank keeps is **bitwise-identical**
        to the span a full :meth:`all_reduce` would have folded there (same
        chunk owner ⇒ same accumulation order, same owner-side avg division
        and ``comm_dtype`` re-quantization).  This is the ZeRO entry point:
        update the owned shard only, then redistribute with
        :func:`~tpu_dist.collectives.ring.ring_chunk_all_gather`
        (tpu_dist/parallel/zero.py).

        Leaves the ring cannot reduce coalesce onto one eager store
        all-reduce and are sliced to the owned span locally — same shard
        contract on every transport.  At world 1 the "shard" is the whole
        (flattened) leaf.  Inputs are snapshotted at issue, like
        :meth:`all_reduce`.  ``error_feedback`` as in :meth:`all_reduce`
        (this is how ``ZeroOptimizer`` keeps its shard-shaped residual)."""
        return self._issue(tree, op, group, scatter=True,
                           error_feedback=error_feedback)

    def _issue(self, tree, op: str, group, scatter: bool,
               error_feedback=None) -> BucketWork:
        import jax
        from . import eager as _eager
        from .work import completed_work, engine_for

        op = str(op).lower()
        _eager._reduce_fn(op)  # validate before anything moves
        pinned = self._dp is not None
        if not pinned:
            group = _eager._default_group(group)
        n = self._dp.num_processes if pinned else group.num_processes
        r = self._dp.rank if pinned else group.rank
        kind_name = "bucket_reduce_scatter" if scatter else "bucket_all_reduce"
        leaves, treedef = jax.tree.flatten(tree)
        arrs = [np.asarray(l) for l in leaves]
        label = f"{kind_name}[{op}]x{len(arrs)}"

        if n <= 1:
            # copy, not views: the snapshot-at-issue contract must hold on
            # the single-process fast path too (the caller may clobber its
            # arrays right after issue).  The world-1 "shard" is the whole
            # leaf, flattened — the degenerate bounds(size, 1) span.
            out = [np.array(a).reshape(-1) if scatter else np.array(a)
                   for a in arrs]
            return BucketWork(treedef, lambda results: out,
                              [completed_work(None, label)], label)

        use_ring = pinned or (_eager._dp_enabled()
                              and not _eager._prefer_mesh(group)
                              and _eager._coll_store() is not None)
        ring_set = {i for i, a in enumerate(arrs)
                    if use_ring and _ring_leaf_ok(a, op)}
        rest_idx = [i for i in range(len(arrs)) if i not in ring_set]
        if pinned and rest_idx:
            bad = {arrs[i].dtype for i in rest_idx if arrs[i].size}
            raise ValueError(
                f"Bucketer(dp=...) is a ring-only harness; leaves with "
                f"dtypes {sorted(map(str, bad))} (or empty leaves) cannot "
                f"ride it for op {op!r}")

        # fill dtype-uniform buckets in REVERSE leaf order (DDP heuristic)
        buckets: List[_Bucket] = []
        open_by_dtype = {}
        for i in sorted(ring_set, reverse=True):
            a = arrs[i]
            b = open_by_dtype.get(a.dtype)
            if b is None or b.nbytes + a.nbytes > self.bucket_bytes:
                b = _Bucket(a.dtype)
                buckets.append(b)
                open_by_dtype[a.dtype] = b
            b.add(i, np.ascontiguousarray(a).reshape(-1))

        engine = engine_for(self._dp)
        issue_seq = self._next_issue_seq() if pinned else -1
        # the wire format is resolved AT ISSUE (env is launcher-level and
        # uniform, so issue-time == execute-time for every rank) — the
        # error-feedback residual needs it to decide whether a residual
        # exists at all
        comm_spec = self._comm_dtype if pinned else _eager._comm_dtype()
        works, plans = [], []
        for bi, bucket in enumerate(buckets):
            # pack HERE, on the caller's thread: the flat bucket is a
            # snapshot, so the caller is free to mutate its gradient
            # arrays the moment all_reduce() returns (packing on the
            # engine thread would race such mutations and silently
            # diverge ranks that packed at different times)
            packed = bucket.pack(n)
            residuals = self._bucket_residuals(bucket, bi, packed, n, r,
                                               error_feedback, comm_spec,
                                               scatter)
            works.append(engine.submit(
                self._bucket_body(packed, op, n, group, issue_seq, bi,
                                  scatter, comm_spec, residuals),
                label=f"{label}/bkt{bi}"))
            plans.append(("bucket", bucket))
        if rest_idx:
            # copy, not views: same issue-time snapshot contract as the
            # packed buckets — the caller may mutate after issue
            sub = [np.array(arrs[i]) for i in rest_idx]

            def rest_body(sub=sub, group=group, op=op):
                # one coalesced eager call: small/exotic leaves batch into
                # a single store round exactly as a sync tree call would
                return _eager.all_reduce_host(sub, group=group, op=op)

            works.append(engine.submit(rest_body, label=f"{label}/store"))
            plans.append(("rest", rest_idx))

        def assemble(results):
            from .ring import _bounds
            out: List = [None] * len(arrs)
            for (kind, plan), res in zip(plans, results):
                if kind == "bucket":
                    if scatter:
                        # the owned bucket chunk is the concat of member
                        # leaves' own chunks, in member order — slice it
                        # back into per-leaf shards
                        chunk, leaf_bounds = res
                        pos = 0
                        for idx, b in zip(plan.indices, leaf_bounds):
                            flo, fhi = b[r]
                            out[idx] = np.array(chunk[pos:pos + fhi - flo])
                            pos += fhi - flo
                    else:
                        flats = plan.unpack(res[0], n, res[1])
                        for idx, flat in zip(plan.indices, flats):
                            out[idx] = flat.reshape(arrs[idx].shape)
                else:
                    for idx, val in zip(plan, res):
                        a = np.asarray(val)
                        if scatter:
                            # store path has no scatter: slice the fully-
                            # reduced value to the span this rank owns
                            lo, hi = _bounds(a.size, n)[r]
                            a = np.array(a.reshape(-1)[lo:hi])
                        out[idx] = a
            return out

        return BucketWork(treedef, assemble, works, label)

    # -- internals -----------------------------------------------------------

    def _next_issue_seq(self) -> int:
        with self._seq_mu:
            s = self._seq
            self._seq += 1
            return s

    @staticmethod
    def _bucket_residuals(bucket, bi: int, packed, n: int, r: int,
                          error_feedback, comm_spec, scatter: bool):
        """Error-feedback residual(s) for one bucket, or None when no
        residual loop is active.  The arrays live in the caller's
        :class:`~tpu_dist.collectives.quant.ErrorFeedback` so they persist
        across steps (bucket formation is deterministic per tree
        structure, so bucket index ``bi`` is a stable key).

        - all-reduce: ``("full", buf)`` — ONE full-bucket-layout residual
          covering every per-hop partial-sum compression plus the owner
          compression; the ring updates it in place.
        - reduce-scatter: ``("leaves", [arrays])`` — per-member
          owned-chunk residuals (the ZeRO-shard-resident form; possibly
          views into ``zstate['ef']``), concatenated for the ring's
          owner-compression hook and scattered back after."""
        if error_feedback is None or comm_spec is None or n <= 1:
            return None
        buf, bucket_bounds, leaf_bounds = packed
        dt = np.dtype(bucket.dtype)
        if dt.kind not in "fV":  # lossy wire never applies to exact ints
            return None
        if not scatter:
            return ("full", error_feedback.residual_for(
                ("bucket", bi, dt.str), buf.size, dt))
        return ("leaves",
                [error_feedback.residual_for(idx, b[r][1] - b[r][0], dt)
                 for idx, b in zip(bucket.indices, leaf_bounds)])

    def _bucket_body(self, packed, op: str, n: int, group,
                     issue_seq: int, bi: int, scatter: bool = False,
                     comm_spec=None, residuals=None):
        """The deferred per-bucket collective: ring all-reduce the
        (already-packed, issue-time-snapshotted) flat bucket with its
        per-leaf-aligned bounds, return ``(reduced_flat, leaf_bounds)`` —
        or, with ``scatter=True``, stop at the reduce-scatter phase and
        return ``(owned_chunk, leaf_bounds)``.  Runs on the ordered
        engine."""
        buf, bucket_bounds, leaf_bounds = packed
        op_name = "bucket_reduce_scatter" if scatter else "bucket_all_reduce"

        def body():
            from . import eager as _eager
            from . import ring as _ring
            if self._dp is not None:
                dp = self._dp
                tag = f"bkt/i{issue_seq}/{bi}"
            else:
                store = _eager._coll_store()
                # sequence allocated HERE, in engine order — every rank
                # submits the same buckets in the same order, so the k-th
                # body draws the k-th seq on every rank
                seq = _eager._next_seq("bucket_rs" if scatter
                                       else "bucket_ar", 0)
                tag = f"{_eager._ns()}/coll/bkt/{seq}"
                _eager._sanitize(op_name, group, store,
                                 value=buf, reduce_op=op)
                dp = _eager._maybe_data_plane(group, store)
            residual = leaf_res = None
            if residuals is not None:
                kind, payload = residuals
                if kind == "full":
                    residual = payload  # ring updates it in place
                else:
                    leaf_res = payload
                    residual = (payload[0] if len(payload) == 1
                                else np.concatenate(
                                    [np.asarray(a) for a in payload]))
            with _eager._obs_span(op_name, value=buf, reduce_op=op):
                t0 = time.perf_counter()
                stats: dict = {}
                if scatter:
                    reduced = _ring.ring_reduce_scatter(
                        dp, buf, op=op, tag=tag, comm_dtype=comm_spec,
                        bounds=bucket_bounds, quant_residual=residual,
                        stats=stats)
                else:
                    reduced = _ring.ring_all_reduce(
                        dp, buf, op=op, tag=tag, comm_dtype=comm_spec,
                        bounds=bucket_bounds, quant_residual=residual,
                        stats=stats)
                _eager._record(op_name, "dataplane", buf.nbytes, t0,
                               wire_bytes=stats.get("wire_bytes"),
                               raw_wire_bytes=stats.get("raw_wire_bytes"))
            if leaf_res is not None and len(leaf_res) > 1:
                # scatter the ring-updated concat back into the per-leaf
                # ErrorFeedback arrays (single-member buckets updated the
                # leaf's array in place already)
                pos = 0
                for a in leaf_res:
                    a[...] = residual[pos:pos + a.size]
                    pos += a.size
            return reduced, leaf_bounds

        return body


def bucketed_all_reduce(tree, op: str = "avg", group=None,
                        bucket_bytes: Optional[int] = None):
    """Synchronous convenience: bucketed all-reduce, waited inline (still
    coalesced + pipelined on the wire; the async win needs ``Bucketer``
    plus caller-side overlap)."""
    return Bucketer(bucket_bytes=bucket_bytes).all_reduce(
        tree, op=op, group=group).wait_all()


def bucketed_reduce_scatter(tree, op: str = "avg", group=None,
                            bucket_bytes: Optional[int] = None):
    """Synchronous convenience: bucketed reduce-scatter, waited inline —
    returns this rank's owned flat shard of every leaf (see
    :meth:`Bucketer.reduce_scatter`)."""
    return Bucketer(bucket_bytes=bucket_bytes).reduce_scatter(
        tree, op=op, group=group).wait_all()
