"""Block-quantized int8 wire format for the host ring collectives
(EQuARX-style, arXiv:2506.17615) with 1-bit-Adam-lineage error feedback.

``comm_dtype`` (PR 2) compresses the ring wire by *casting* — fine for
bf16, useless below it.  A :class:`QuantScheme` (spec string
``"int8_block{N}"``, e.g. ``"int8_block256"``) compresses each ring
sub-chunk to **int8 payload + one float32 scale per N-element block**:
~3.9× fewer wire bytes than f32 at block 256, selectable everywhere
``comm_dtype`` is accepted today (``ring_all_reduce`` /
``ring_reduce_scatter`` / ``ring_chunk_all_gather`` / ``ring_all_gather``,
the eager routed collectives via ``TPU_DIST_COMM_DTYPE=int8_block256``,
``Bucketer(comm_dtype=...)``, ``ZeroOptimizer(comm_dtype=...)``).

Quantization is symmetric per block: ``scale = max|x| / 127``,
``q = clip(rint(x / scale), -127, 127)``; dequantization is
``q * scale``.  Numerics policy (tested):

- **zero / underflowing blocks** (``max|x| == 0``, or so subnormal that
  ``1/scale`` overflows): scale 0, payload zeros — the block dequantizes
  to exact zeros and the loss lands in the error-feedback residual;
- **non-finite blocks** (any inf/nan element): scale NaN, payload zeros —
  the whole block dequantizes to NaN.  A poisoned gradient is *loudly*
  poisoned, never silently clipped to ±127·scale;
- subnormal *elements* inside a healthy block quantize to 0 like any
  value below scale/2.

**Cross-rank byte-identity** (the property the chaos e2e's bitwise-resume
check rides): during the all-gather phase the quantized ``(q, scales)``
frames are forwarded **verbatim** hop to hop — never re-quantized — and
the chunk owner replaces its own span with the dequantization of exactly
those frames.  Every rank therefore reconstructs each chunk from
identical bytes, with no reliance on re-quantization being a fixed point
of float rounding.

**Error feedback** (:class:`ErrorFeedback`): quantizing partial sums on
every hop biases training if the dropped mass is discarded.  Every
compression point keeps its residual and re-injects it before quantizing
on the next step (the 1-bit Adam / ScaleCom discipline):

- **hop residual** — each rank quantizes its outgoing reduce-scatter
  partial sum as ``Q(partial + e)`` and keeps ``e' = (partial + e) -
  deq(Q(...))``.  Every element of the payload is sent by each rank
  exactly once per collective (rank *r* sends every chunk except its
  own), so a full-payload residual covers all hops;
- **owner residual** — the chunk owner folds its residual into the fully
  reduced chunk before the final compression the all-gather distributes
  (1-bit Adam's server error).

``Bucketer.all_reduce(..., error_feedback=ef)`` keeps the **full**
(hop + owner) residual per bucket, in bucket layout — dropped compression
mass becomes a convergent series instead of a noise floor.
``Bucketer.reduce_scatter`` / ``ZeroOptimizer(error_feedback=True)`` keep
the **owner** residual only, shard-shaped, so it rides the ZeRO shard
layout, the sharded checkpoint, and the elastic reshard manifest (a
full-size residual per rank would undo ZeRO's memory division).
"""

from __future__ import annotations

import re
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["QuantScheme", "QuantChunk", "ErrorFeedback", "parse_scheme",
           "resolve_wire", "wire_name", "quantize", "dequantize"]

_SPEC_RE = re.compile(r"^int8_block(\d+)$")


class QuantScheme:
    """One block-quantized wire format: int8 payload, float32 scale per
    ``block`` contiguous elements.  Instances are interned per block size
    so scheme comparison is identity-cheap."""

    __slots__ = ("block", "name")
    _interned: Dict[int, "QuantScheme"] = {}

    def __new__(cls, block: int):
        block = int(block)
        if block < 1:
            raise ValueError(f"quant block size must be >= 1, got {block}")
        got = cls._interned.get(block)
        if got is None:
            got = cls._interned[block] = object.__new__(cls)
            got.block = block
            got.name = f"int8_block{block}"
        return got

    def scales_for(self, n: int) -> int:
        """Number of per-block scales covering ``n`` elements."""
        return -(-int(n) // self.block)

    def wire_bytes(self, n: int) -> int:
        """Total wire payload bytes for ``n`` elements (q + scales)."""
        return int(n) + 4 * self.scales_for(n)

    def __repr__(self):
        return f"QuantScheme({self.name!r})"


def parse_scheme(spec) -> Optional[QuantScheme]:
    """``"int8_block256"`` -> :class:`QuantScheme`; None when ``spec`` is
    not a quant-scheme string (a plain dtype name, or None)."""
    if isinstance(spec, QuantScheme):
        return spec
    if not isinstance(spec, str):
        return None
    m = _SPEC_RE.match(spec.strip())
    return QuantScheme(int(m.group(1))) if m else None


def resolve_wire(spec):
    """THE parser for everything ``comm_dtype`` accepts: None (no
    compression), a :class:`QuantScheme` / ``"int8_blockN"`` spec, or any
    dtype the wire header can name (``"bfloat16"``, ``np.float16``, ...).
    Every rank parses the same launcher-level spec, so the wire decision
    stays rank-consistent."""
    if spec is None:
        return None
    scheme = parse_scheme(spec)
    if scheme is not None:
        return scheme
    try:
        if isinstance(spec, str):
            from .transport import _decode_dtype
            return _decode_dtype(spec)
        return np.dtype(spec)
    except Exception as e:
        raise ValueError(
            f"comm_dtype spec {spec!r} is neither a quant scheme "
            f"(int8_block{{N}}, e.g. int8_block256) nor a wire-decodable "
            f"dtype name (e.g. bfloat16): {e!r}") from e


def wire_name(wire) -> Optional[str]:
    """Canonical spec string for a resolved wire (None / dtype / scheme) —
    what the sanitizer signs and obs spans carry."""
    if wire is None:
        return None
    if isinstance(wire, QuantScheme):
        return wire.name
    return np.dtype(wire).name


def quantize(x, scheme: QuantScheme) -> Tuple[np.ndarray, np.ndarray]:
    """Block-quantize a flat float array; returns ``(q int8[n],
    scales float32[ceil(n/block)])``.  Deterministic (pure vectorized
    numpy), so identical inputs produce identical bytes on every rank."""
    xf = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    n = xf.size
    b = scheme.block
    nb = scheme.scales_for(n)
    if n == 0:
        return np.zeros(0, np.int8), np.zeros(0, np.float32)
    if nb * b != n:
        padded = np.zeros(nb * b, np.float32)
        padded[:n] = xf
        xb = padded.reshape(nb, b)
    else:
        xb = xf.reshape(nb, b)
    with np.errstate(divide="ignore", over="ignore", invalid="ignore"):
        amax = np.max(np.abs(xb), axis=1)
        finite = np.isfinite(amax)
        scales = np.where(finite, amax / np.float32(127.0),
                          np.float32(np.nan)).astype(np.float32)
        inv = np.where(finite & (scales > 0),
                       np.float32(1.0) / scales, np.float32(0.0))
        # 1/scale may overflow for deeply subnormal amax: such a block is
        # numerically zero at int8 resolution — emit exact zeros (scale 0)
        bad = ~np.isfinite(inv)
        if bad.any():
            inv[bad] = 0.0
            scales[bad & finite] = 0.0
        scaled = xb * inv[:, None]
        np.rint(scaled, out=scaled)
        np.clip(scaled, -127.0, 127.0, out=scaled)
    if not finite.all():
        scaled[~finite] = 0.0  # poisoned blocks: zero payload, NaN scale
    return scaled.astype(np.int8).reshape(-1)[:n], scales


def dequantize(q, scales, scheme: QuantScheme,
               dtype=np.float32) -> np.ndarray:
    """Invert :func:`quantize`: ``q * scales`` per block, returned flat in
    ``dtype``."""
    q = np.asarray(q).reshape(-1)
    n = q.size
    if n == 0:
        return np.zeros(0, dtype)
    b = scheme.block
    nb = scheme.scales_for(n)
    scales = np.asarray(scales, np.float32).reshape(-1)
    if scales.size != nb:
        raise ValueError(
            f"quant frame mismatch: {n} elements at block {b} need {nb} "
            f"scales, got {scales.size}")
    if nb * b != n:
        padded = np.zeros(nb * b, np.int8)
        padded[:n] = q
        qb = padded.reshape(nb, b)
    else:
        qb = q.reshape(nb, b)
    out = (qb.astype(np.float32) * scales[:, None]).reshape(-1)[:n]
    return out.astype(dtype, copy=False)


class QuantChunk:
    """One quantized wire frame as received: int8 payload + per-block
    scales.  The transport's reader thread hands these to the ring, which
    dequantizes at the fold (reduce-scatter) or forwards the frame
    verbatim (all-gather) — see the module docstring's byte-identity
    argument."""

    __slots__ = ("q", "scales", "scheme")

    def __init__(self, q: np.ndarray, scales: np.ndarray,
                 scheme: QuantScheme):
        self.q = q
        self.scales = scales
        self.scheme = scheme

    @property
    def size(self) -> int:
        return self.q.size

    @property
    def nbytes(self) -> int:
        """Wire payload bytes this frame occupied."""
        return self.q.nbytes + self.scales.nbytes

    def dequantize(self, dtype=np.float32) -> np.ndarray:
        return dequantize(self.q, self.scales, self.scheme, dtype=dtype)

    def __repr__(self):
        return (f"QuantChunk(n={self.q.size}, "
                f"scheme={self.scheme.name!r})")


class ErrorFeedback:
    """Error-feedback residual state for lossy wire formats.

    A plain keyed store of residual arrays (see the module docstring for
    the semantics each consumer attaches): the bucketed **all-reduce**
    keeps one full-bucket-layout residual per bucket (hop + owner errors),
    the bucketed **reduce-scatter** one owned-chunk residual per leaf.
    Pass the same object every step — the residual IS the cross-step
    state.  ``ZeroOptimizer`` builds one per step whose arrays are views
    into the checkpointed ``zstate["ef"]`` shards, so the residual rides
    the ZeRO shard layout and the elastic reshard manifest for free.
    """

    __slots__ = ("residuals",)

    def __init__(self):
        self.residuals: Dict = {}

    def residual_for(self, key, length: int, dtype) -> np.ndarray:
        """The residual array under ``key`` (created as zeros on first
        use); raises when a held residual no longer matches ``length`` —
        a world-size or tree-structure change means the residual belongs
        to a different layout and must not be folded into this one."""
        got = self.residuals.get(key)
        if got is None:
            got = self.residuals[key] = np.zeros(length, np.dtype(dtype))
        elif got.size != length:
            raise ValueError(
                f"error-feedback residual {key!r} has {got.size} "
                f"elements, this collective needs {length}: the residual "
                f"was built at a different world size / tree structure "
                f"(reset ErrorFeedback after elastic changes)")
        return got

    def norm(self) -> float:
        """Global L2 norm of the held residuals (diagnostics: how much
        gradient mass error feedback is carrying step to step)."""
        total = 0.0
        for a in self.residuals.values():
            af = np.asarray(a, np.float64)
            total += float(np.dot(af, af))
        return float(np.sqrt(total))

    def __repr__(self):
        return f"ErrorFeedback({len(self.residuals)} leaves)"
