"""Chunk-pipelined ring collectives over the p2p data plane (host-level).

This is the reference README's theory section (§1) made real for *host*
payloads: bandwidth-optimal ring all-reduce moves 2(N-1)/N of the payload
per rank — a reduce-scatter phase where each of N-1 steps passes 1/N of the
array to the right neighbor while reducing what arrives from the left, then
an all-gather phase circulating the fully-reduced chunks.  Every transfer is
point-to-point over the persistent data-plane connections
(tpu_dist/collectives/transport.py), so nothing funnels through the central
store and all N links carry traffic simultaneously.

Pipelining: each ring chunk is sent as sub-chunk frames
(``TPU_DIST_DP_CHUNK`` bytes, default 256 KiB).  The transport's receiver
thread keeps draining the socket while this thread reduces the previous
sub-chunk, so wire transfer and the local ``np.add``/``maximum``/``minimum``
overlap — the same overlap argument the paper makes for ring steps, applied
inside each step.

Double-buffered steps: within every ring step the send of sub-chunk *j+1*
and the fold of whatever already arrived interleave in one loop
(:func:`_exchange`) — the step used to serialize "send the whole chunk,
then fold the whole arriving chunk", which left the CPU idle during the
send syscalls and the wire idle during the folds.  The arriving frames land
in the transport's preallocated per-frame buffers (the recv for step *k+1*
is effectively always posted: the reader thread never stops draining), so
the only blocking recv is for frames that genuinely have not arrived yet.

Custom chunk ``bounds``: :func:`ring_all_reduce` accepts an explicit chunk
partition so the gradient bucketer can align bucket chunks with each member
leaf's own per-leaf chunks — identical chunk ownership means identical
accumulation order, which is what makes bucketed results bit-identical to
per-leaf ones (tpu_dist/collectives/bucketer.py).

``comm_dtype`` (EQuARX-style wire compression, arXiv:2506.17615): payloads
are cast to a narrower dtype on the wire and re-widened for accumulation.
After the reduce-scatter the owning rank re-quantizes its fully-reduced
chunk through the wire dtype, so the value every rank ends up holding is
bit-identical — lossy vs. full precision, but consistent across the group.
Beyond dtype casts, ``comm_dtype`` also accepts a **block-quantization
scheme** (``"int8_block256"``, tpu_dist/collectives/quant.py): frames carry
int8 payload + one f32 scale per block (~3.9× fewer wire bytes than f32).
Reduce-scatter hops quantize the partial sums fresh each step; the
all-gather phase forwards the owner's quantized frames **verbatim** hop to
hop, so cross-rank byte-identity never depends on re-quantization being a
float-rounding fixed point.  An optional error-feedback residual
(``quant_residual``; :class:`~tpu_dist.collectives.quant.ErrorFeedback`
at the bucketer/ZeRO level) folds the owner's compression loss back into
the next step's chunk — the 1-bit Adam server-error discipline.

These functions take a :class:`~tpu_dist.collectives.transport.DataPlane`
directly (rank/world come from it), so they are usable from any process
that has a store connection — no mesh or jax.distributed required.  The
eager collectives (tpu_dist/collectives/eager.py) route large array
payloads here; in-graph collectives (tpu_dist/collectives/ops.py, including
the jit-level ``ring_all_reduce`` teaching version) are unrelated code
paths.
"""

from __future__ import annotations

import os
import time
from typing import Optional, Tuple

import numpy as np

from . import quant as _Q
from .transport import CollectiveTimeoutError, coll_timeout

__all__ = ["ring_all_reduce", "ring_all_gather", "ring_reduce_scatter",
           "ring_chunk_all_gather", "tree_broadcast", "ring_chunk_span",
           "RING_OPS"]

# reduce ops the ring path implements; others (product, bitwise) stay on
# the store path in eager.py
RING_OPS = frozenset({"sum", "avg", "mean", "max", "min"})

_DEF_CHUNK = 256 * 1024  # wire frame payload bytes


def _chunk_bytes(dp=None, dst: Optional[int] = None) -> int:
    try:
        base = max(4096, int(os.environ.get("TPU_DIST_DP_CHUNK",
                                            str(_DEF_CHUNK))))
    except ValueError:
        base = _DEF_CHUNK
    if dp is not None and dst is not None:
        # per-destination grain: shared-memory lanes want far coarser
        # frames than a slow wire (the transfer is a memcpy — pipelining
        # buys nothing, per-frame overhead dominates).  Rank-local and
        # value-free: frame segmentation never changes fold arithmetic,
        # so peers need not agree on it.
        hint = getattr(dp, "send_chunk_bytes", None)
        if hint is not None:
            try:
                return max(4096, int(hint(dst, base)))
            except Exception:
                return base
    return base


def _bounds(n_elems: int, n: int):
    """Chunk boundaries [(lo, hi)] * n covering ``n_elems`` elements; the
    first ``n_elems % n`` chunks get one extra element, so payloads that do
    not divide evenly are handled without padding."""
    q, rem = divmod(n_elems, n)
    out, lo = [], 0
    for i in range(n):
        hi = lo + q + (1 if i < rem else 0)
        out.append((lo, hi))
        lo = hi
    return out


def ring_chunk_span(n_elems: int, n: int, rank: int) -> Tuple[int, int]:
    """The (lo, hi) flat span of ``rank``'s chunk in a ring reduce-scatter
    over ``n_elems`` elements."""
    return _bounds(n_elems, n)[rank]


def _obs_position() -> str:
    """This rank's last flight-recorder position (armed runs) — stamped
    into watchdog errors so the diagnosis names where the collective stood
    when it wedged, not just that it did."""
    try:
        from ..obs import hooks as _hooks
        from ..obs import recorder as _rec
        rec = _rec.get_recorder()
        if rec is not None:
            pos = rec.last_position()
            if pos is not None:
                return f"; flight recorder: {_hooks.render_tail(pos)}"
    except Exception:
        pass
    return ""


class _Watchdog:
    """End-to-end deadline for ONE collective (``TPU_DIST_COLL_TIMEOUT``).

    Every blocking recv in the ring charges against the same budget, so a
    partitioned/wedged hop raises :class:`CollectiveTimeoutError` naming
    the stalled hop within the configured bound — instead of each frame
    independently waiting out the (much longer) per-frame
    ``TPU_DIST_DP_TIMEOUT``.  Disabled (budget 0) it delegates to the
    transport's own internal deadline, exactly the old behavior."""

    __slots__ = ("op", "budget", "deadline")

    def __init__(self, op: str):
        self.op = op
        self.budget = coll_timeout()
        self.deadline = (time.monotonic() + self.budget
                         if self.budget > 0 else None)

    def recv(self, dp, src: int, tag: str, pos: int, hi: int):
        """One blocking frame recv under the collective deadline."""
        if self.deadline is None:
            # tpudlint: disable=TD004  # recv_array applies TPU_DIST_DP_TIMEOUT
            return dp.recv_array(src, tag)
        left = self.deadline - time.monotonic()
        if left <= 0:
            self._expired(dp, src, tag, pos, hi, None)
        try:
            return dp.recv_array(src, tag, timeout=left)
        except CollectiveTimeoutError:
            raise
        except TimeoutError as e:
            self._expired(dp, src, tag, pos, hi, e)

    def _expired(self, dp, src: int, tag: str, pos: int, hi: int,
                 cause) -> None:
        raise CollectiveTimeoutError(
            f"collective {self.op} wedged: rank {dp.rank} got no frame "
            f"from rank {src} (tag {tag!r}, waiting for span "
            f"[{pos}:{hi})) within TPU_DIST_COLL_TIMEOUT="
            f"{self.budget:.0f}s — stalled hop {src}->{dp.rank}"
            f"{_obs_position()}") from cause


def _combine(op: str):
    if op in ("sum", "avg", "mean"):
        return np.add
    if op == "max":
        return np.maximum
    if op == "min":
        return np.minimum
    raise ValueError(f"ring collectives support {sorted(RING_OPS)}, "
                     f"got {op!r}")


def _acc_dtype(dtype: np.dtype, op: str) -> np.dtype:
    """Accumulation dtype: widen sub-32-bit floats (bf16/f16 partial sums
    would lose whole ranks' contributions); integer avg accumulates in
    float64 to match ``np.mean`` semantics; integer sum follows
    ``np.add.reduce``'s platform promotion (int32 sums in int64 on 64-bit,
    exactly like the store path); max/min reduce in place."""
    if op in ("avg", "mean") and dtype.kind in "iub":
        return np.dtype(np.float64)
    if op == "sum" and dtype.kind in "iub":
        return np.add.reduce(np.zeros(1, dtype=dtype)).dtype
    # low-precision floats: numpy 'f2' AND the ml_dtypes family, which
    # registers as unstructured void (kind 'V', e.g. bfloat16/float8)
    low_precision_float = (dtype.itemsize < 4 and
                           (dtype.kind == "f"
                            or (dtype.kind == "V" and dtype.fields is None)))
    if low_precision_float and op not in ("max", "min"):
        return np.dtype(np.float32)
    return dtype


def _out_dtype(dtype: np.dtype, op: str) -> np.dtype:
    # store-path parity: avg mirrors np.mean's result dtype, sum mirrors
    # np.add.reduce's promotion; max/min never change dtype
    if op in ("avg", "mean"):
        try:
            return np.mean(np.zeros(1, dtype=dtype)).dtype
        except TypeError:
            return dtype
    if op == "sum" and dtype.kind in "iub":
        return np.add.reduce(np.zeros(1, dtype=dtype)).dtype
    return dtype


def _resolve_wire(comm_dtype, acc_dtype: np.dtype, float_only: bool = False):
    """Resolve a ``comm_dtype`` spec (None / dtype / quant-scheme string)
    against the accumulation dtype.  A quant scheme applies only to float
    accumulators (f32/f64) — quantizing integer payloads would silently
    change exact arithmetic.  ``float_only`` extends that gate to cast
    wires too (the gather paths: their payloads may be raw bytes — padded
    pickle frames from the object collectives — that a lossy cast would
    corrupt).  The gate depends only on dtype, so every rank answers
    identically."""
    wire = _Q.resolve_wire(comm_dtype)
    if wire is None:
        return None
    dt = np.dtype(acc_dtype)
    # float = numpy floats AND the ml_dtypes family (bfloat16/float8
    # register as unstructured void, kind 'V'), same recognition the
    # bucketer and routing gates use
    is_float = dt.kind == "f" or (dt.kind == "V" and dt.fields is None)
    if isinstance(wire, _Q.QuantScheme):
        return wire if is_float else None
    return wire if (is_float or not float_only) else None


def _send_span(dp, dst: int, tag: str, flat: np.ndarray, lo: int, hi: int,
               wire_dtype: Optional[np.dtype]) -> int:
    """Send flat[lo:hi] as sub-chunk frames; returns wire bytes sent."""
    if hi <= lo:
        return 0
    step = max(1, _chunk_bytes(dp, dst) // flat.itemsize)
    wb = 0
    for slo in range(lo, hi, step):
        seg = flat[slo:min(slo + step, hi)]
        if isinstance(wire_dtype, _Q.QuantScheme):
            q, s = _Q.quantize(seg, wire_dtype)
            wb += dp.send_quant(dst, tag, _Q.QuantChunk(q, s, wire_dtype))
            continue
        if wire_dtype is not None and seg.dtype != wire_dtype:
            seg = seg.astype(wire_dtype)
        wb += dp.send_array(dst, tag, seg)
    return wb


def _fold(flat: np.ndarray, seg, pos: int, hi: int, tag: str,
          combine) -> int:
    """Fold one arriving frame into ``flat[pos:pos+len]``; returns the new
    position.  ``combine`` is a ufunc (reduce-scatter) or None (overwrite,
    all-gather); frames in a narrower wire dtype widen here, quantized
    frames (:class:`~tpu_dist.collectives.quant.QuantChunk`) dequantize
    here."""
    m = seg.size
    if pos + m > hi:
        raise RuntimeError(
            f"ring frame overrun: got {m} elements at {pos} with only "
            f"{hi - pos} expected (tag {tag!r})")
    if isinstance(seg, _Q.QuantChunk):
        part = seg.dequantize(flat.dtype)
    else:
        part = seg if seg.dtype == flat.dtype else seg.astype(flat.dtype)
    if combine is None:
        flat[pos:pos + m] = part
    else:
        combine(flat[pos:pos + m], part, out=flat[pos:pos + m])
    return pos + m


def _recv_span(dp, src: int, tag: str, flat: np.ndarray, lo: int, hi: int,
               combine=None, wd: Optional[_Watchdog] = None) -> None:
    """Receive sub-chunk frames into flat[lo:hi]; each arriving frame is
    processed while the transport thread keeps reading the next one off
    the wire."""
    if wd is None:
        wd = _Watchdog("recv_span")
    pos = lo
    while pos < hi:
        pos = _fold(flat, wd.recv(dp, src, tag, pos, hi), pos, hi, tag,
                    combine)


def _exchange(dp, right: int, left: int, tag: str, flat: np.ndarray,
              send_lo: int, send_hi: int, recv_lo: int, recv_hi: int,
              combine, wire_dtype, residual=None,
              wd: Optional[_Watchdog] = None) -> int:
    """One double-buffered ring step: send ``flat[send_lo:send_hi]`` to
    ``right`` as sub-chunk frames while folding the frames arriving from
    ``left`` into ``flat[recv_lo:recv_hi]``.  Returns wire bytes sent.

    The send of sub-chunk *j+1* overlaps the fold of sub-chunk *i*: after
    every send the loop drains (non-blocking) whatever the transport's
    reader thread already queued, so CPU reduce time hides behind the wire
    and vice versa.  Only frames that genuinely have not arrived when the
    sends are done cost a blocking wait.

    Under a quant scheme the outgoing segments (reduce-scatter partial
    sums) are block-quantized fresh for each hop — their values change as
    contributions fold in, so there is nothing to forward verbatim; the
    verbatim-forwarding discipline belongs to the all-gather phase
    (:func:`_ag_phase_quant`).  ``residual`` (full-payload error-feedback
    buffer, indexed like ``flat``) compensates exactly this per-hop loss:
    each outgoing segment sends ``compress(seg + residual)`` and keeps the
    new loss for the next step."""
    step = max(1, _chunk_bytes(dp, right) // flat.itemsize)
    sp, rp = send_lo, recv_lo
    wb = 0
    while sp < send_hi:
        nxt = min(sp + step, send_hi)
        seg = flat[sp:nxt]
        res = residual[sp:nxt] if residual is not None else None
        sp = nxt
        if res is not None and res.size:
            seg = seg + np.asarray(res).astype(seg.dtype)
        if isinstance(wire_dtype, _Q.QuantScheme):
            q, s = _Q.quantize(seg, wire_dtype)
            if res is not None and res.size:
                _store_residual(
                    res, seg - _Q.dequantize(q, s, wire_dtype, seg.dtype))
            wb += dp.send_quant(right, tag, _Q.QuantChunk(q, s, wire_dtype))
        else:
            sent = seg
            if wire_dtype is not None and seg.dtype != wire_dtype:
                sent = seg.astype(wire_dtype)
            if res is not None and res.size and wire_dtype is not None \
                    and seg.dtype != wire_dtype:
                _store_residual(res, seg - sent.astype(seg.dtype))
            wb += dp.send_array(right, tag, sent)
        while rp < recv_hi:
            got = dp.try_recv_array(left, tag)
            if got is None:
                break
            rp = _fold(flat, got, rp, recv_hi, tag, combine)
    if wd is None:
        wd = _Watchdog("exchange")
    while rp < recv_hi:
        rp = _fold(flat, wd.recv(dp, left, tag, rp, recv_hi), rp, recv_hi,
                   tag, combine)
    return wb


def _obs_span(op: str, value):
    """Flight-recorder span for one host ring collective (tpu_dist.obs):
    ring phases are where a dead/slow peer actually manifests, so they get
    their own lockstep-sequenced span nested under the eager caller's (or
    standalone, for direct DataPlane users)."""
    from ..obs import hooks as _hooks
    return _hooks.collective_span(op, value=value, path="dataplane")


def _prepare(dp, x, op: str):
    x = np.asarray(x)
    op = str(op).lower()
    n, r = dp.num_processes, dp.rank
    acc = _acc_dtype(x.dtype, op)
    flat = np.ascontiguousarray(x).reshape(-1).astype(acc, copy=True)
    return x, op, n, r, flat


def _reduce_scatter_phase(dp, flat, bounds, n, r, op, tag,
                          wire_dtype, residual=None, wd=None) -> int:
    """N-1 double-buffered ring steps; afterwards this rank's own chunk
    ``bounds[r]`` holds the full reduction.  Schedule is the textbook one
    shifted so rank r ends up owning chunk r (send chunk (r-1-step),
    absorb (r-2-step)); within each step send and fold interleave
    (:func:`_exchange`).  Returns wire bytes sent.  ``residual`` is the
    full-payload per-hop error-feedback buffer (each chunk except this
    rank's own is sent exactly once, so every span is used once per
    call)."""
    comb = _combine(op)
    right, left = (r + 1) % n, (r - 1) % n
    rp = (r - 1) % n
    wb = 0
    for step in range(n - 1):
        si = (rp - step) % n
        ri = (rp - step - 1) % n
        wb += _exchange(dp, right, left, tag, flat, *bounds[si],
                        *bounds[ri], combine=comb, wire_dtype=wire_dtype,
                        residual=residual, wd=wd)
    return wb


def _all_gather_phase(dp, flat, bounds, n, r, tag, wire_dtype,
                      wd=None) -> int:
    """N-1 double-buffered ring steps circulating the fully-reduced chunks
    (rank r starts owning chunk r).  Returns wire bytes sent.  Quant
    schemes take :func:`_ag_phase_quant` instead (verbatim frame
    forwarding)."""
    right, left = (r + 1) % n, (r - 1) % n
    wb = 0
    for step in range(n - 1):
        si = (r - step) % n
        ri = (r - step - 1) % n
        wb += _exchange(dp, right, left, tag, flat, *bounds[si],
                        *bounds[ri], combine=None, wire_dtype=wire_dtype,
                        wd=wd)
    return wb


def _store_residual(residual, diff) -> None:
    """Update an error-feedback residual with this step's compression
    loss, dropping non-finite entries: a transient inf/nan gradient
    poisons THIS step's output loudly (the quant NaN-block policy), but
    must not lodge NaN in the residual and re-inject it forever — the
    poison stays one step, the residual restarts from zero there."""
    diff = np.asarray(diff)
    finite = np.isfinite(diff.astype(np.float32, copy=False))
    if not finite.all():
        diff = np.where(finite, diff, 0)
    residual[...] = diff.astype(residual.dtype)


def _compress_owned(chunk: np.ndarray, wire, residual):
    """Round this rank's fully-reduced owned chunk through the wire format
    — the value every peer will receive — optionally folding in and
    updating an error-feedback residual (the owner adds last step's
    compression loss before compressing, then keeps the new loss).

    Returns ``(values, qframes)``: the wire-faithful replacement values in
    the chunk's dtype, plus the exact ``(q, scales)`` pair to forward
    (quant schemes only, else None)."""
    if chunk.size == 0:
        return chunk, None
    if residual is not None and residual.size:
        chunk = chunk + np.asarray(residual).astype(chunk.dtype)
    if isinstance(wire, _Q.QuantScheme):
        q, s = _Q.quantize(chunk, wire)
        deq = _Q.dequantize(q, s, wire, dtype=chunk.dtype)
        frames = (q, s)
    else:
        deq = chunk.astype(wire).astype(chunk.dtype)
        frames = None
    if residual is not None and residual.size:
        _store_residual(residual, chunk - deq)
    return deq, frames


def _split_quant(q: np.ndarray, scales: np.ndarray, scheme, dp=None,
                 dst=None):
    """Split one whole-chunk quantization into wire frames at
    block-aligned boundaries, so each frame carries exactly its own
    scales.  Frame size tracks ``TPU_DIST_DP_CHUNK`` (the wire payload is
    ~1 byte per element)."""
    n = q.size
    cb = _chunk_bytes(dp, dst)
    step = max(scheme.block, cb - cb % scheme.block)
    frames = []
    for flo in range(0, n, step):
        fhi = min(flo + step, n)
        frames.append(_Q.QuantChunk(
            q[flo:fhi],
            scales[flo // scheme.block:scheme.scales_for(fhi)], scheme))
    return frames


def _land_quant(flat, got, pos: int, hi: int, tag: str, incoming) -> int:
    """All-gather-phase landing of one quantized frame: dequantize into
    ``flat`` AND keep the frame for verbatim forwarding next step."""
    if not isinstance(got, _Q.QuantChunk):
        raise RuntimeError(
            f"quantized ring expected a q8 frame on tag {tag!r}, got a "
            f"plain {getattr(got, 'dtype', type(got).__name__)} frame — "
            f"ranks disagree on the comm scheme")
    m = got.size
    if pos + m > hi:
        raise RuntimeError(
            f"ring frame overrun: got {m} elements at {pos} with only "
            f"{hi - pos} expected (tag {tag!r})")
    flat[pos:pos + m] = got.dequantize(flat.dtype)
    incoming.append(got)
    return pos + m


def _ag_phase_quant(dp, flat, bounds, n, r, tag, scheme,
                    residual=None, wd=None) -> int:
    """All-gather phase under a quant scheme: the owner compresses its
    chunk ONCE (folding in the error-feedback residual, replacing its own
    span with the dequantized values every peer will hold), then the
    quantized frames circulate **verbatim** — each rank forwards exactly
    the bytes it received, so all N ranks reconstruct every chunk from
    identical frames.  Returns wire bytes sent."""
    if wd is None:
        wd = _Watchdog("ag_phase_quant")
    right, left = (r + 1) % n, (r - 1) % n
    lo, hi = bounds[r]
    chunk = np.array(flat[lo:hi])  # standalone: _compress_owned re-binds
    deq, qs = _compress_owned(chunk, scheme, residual)
    flat[lo:hi] = deq
    frames = _split_quant(*qs, scheme, dp, right) if qs is not None else []
    wb = 0
    for step in range(n - 1):
        ri = (r - step - 1) % n
        rlo, rhi = bounds[ri]
        incoming: list = []
        pos = rlo
        for fr in frames:
            wb += dp.send_quant(right, tag, fr)
            while pos < rhi:
                got = dp.try_recv_array(left, tag)
                if got is None:
                    break
                pos = _land_quant(flat, got, pos, rhi, tag, incoming)
        while pos < rhi:
            pos = _land_quant(flat, wd.recv(dp, left, tag, pos, rhi), pos,
                              rhi, tag, incoming)
        frames = incoming
    return wb


def _note_stats(stats, wire, wire_bytes: int, raw_bytes: int) -> None:
    """Fill the caller's ``stats`` dict and stamp the enclosing obs span
    with the wire quantities: ``wire_bytes`` = what actually crossed the
    wire (compressed), ``raw_wire_bytes`` = what the SAME traffic would
    have cost uncompressed — their ratio is the wire-format compression
    factor, independent of the ring's 2(N-1)/N amplification over the
    logical payload (which the span's ``bytes`` field still shows)."""
    name = _Q.wire_name(wire)
    if stats is not None:
        stats["wire_bytes"] = int(wire_bytes)
        stats["raw_wire_bytes"] = int(raw_bytes)
        stats["comm"] = name
    from ..obs import hooks as _hooks
    _hooks.note_wire(int(wire_bytes), name, raw_bytes=int(raw_bytes))


def ring_all_reduce(dp, x, op: str = "sum", tag: str = "ar",
                    comm_dtype=None, bounds=None, quant_residual=None,
                    stats=None) -> np.ndarray:
    """Bandwidth-optimal ring all-reduce of ``x`` across the group.

    reduce-scatter + all-gather, 2(N-1)/N of the payload on the wire per
    rank (the reference README §1 quantity).  ``op``: sum/avg/max/min
    (avg divides once at the chunk owner, so every rank receives identical
    averaged bytes).  Deterministic accumulation order (ring order from
    each chunk's owner), so repeated runs are bit-identical — the property
    the chaos e2e's resume check depends on.

    ``bounds`` overrides the chunk partition (N contiguous ``(lo, hi)``
    spans covering the flat payload, identical on every rank): the
    bucketer aligns bucket chunks with per-leaf chunks this way so that
    bucketed and per-leaf reductions share fold order bit-for-bit.

    ``comm_dtype`` accepts a dtype (cast wire) or a quant scheme spec
    (``"int8_block256"``); ``quant_residual`` is this rank's
    error-feedback buffer, updated in place with the new compression
    losses — either **full-payload-sized** (per-hop residuals for every
    outgoing partial sum, plus the owner compression: the strong EF the
    bucketer's all-reduce uses) or **owned-chunk-sized** (length
    ``bounds[rank]``, owner compression only: the ZeRO-shard-resident
    form).  ``stats`` (a dict) receives ``wire_bytes`` and ``comm`` — the
    compressed wire quantity, vs. the logical payload."""
    x, op, n, r, flat = _prepare(dp, x, op)
    _combine(op)  # raise on an unsupported op before any traffic
    out_dtype = _out_dtype(x.dtype, op)
    if n <= 1:
        return flat.astype(out_dtype).reshape(x.shape)
    wire = _resolve_wire(comm_dtype, flat.dtype)
    if flat.size == 0:
        return flat.astype(out_dtype).reshape(x.shape)
    if bounds is None:
        bounds = _bounds(flat.size, n)
    else:
        bounds = _check_bounds(bounds, n, flat.size)
    res_full, res_own = _split_residual(quant_residual, wire, flat.size,
                                        bounds[r])
    utag = f"{tag}/rar"
    wd = _Watchdog(f"ring_all_reduce[{op}]")
    with _obs_span("ring_all_reduce", x):
        wb = _reduce_scatter_phase(dp, flat, bounds, n, r, op, utag, wire,
                                   residual=res_full, wd=wd)
        lo, hi = bounds[r]
        if op in ("avg", "mean"):
            flat[lo:hi] = flat[lo:hi] / n
        if isinstance(wire, _Q.QuantScheme):
            # owner compression + verbatim frame circulation (quant.py's
            # byte-identity discipline)
            wb += _ag_phase_quant(dp, flat, bounds, n, r, utag, wire,
                                  residual=res_own, wd=wd)
        else:
            if wire is not None:
                # re-quantize the owned chunk through the wire dtype so
                # the values this rank keeps match the compressed copies
                # every peer receives
                deq, _ = _compress_owned(np.array(flat[lo:hi]), wire,
                                         res_own)
                flat[lo:hi] = deq
            wb += _all_gather_phase(dp, flat, bounds, n, r, utag, wire,
                                    wd=wd)
        # uncompressed-equivalent of the same traffic: this rank sends
        # every chunk but its own in the RS phase and every chunk but its
        # right neighbor's in the AG phase
        raw = ((2 * flat.size - (hi - lo)
                - _span_len(bounds, (r + 1) % n)) * flat.itemsize)
        _note_stats(stats, wire, wb, raw)
    return flat.astype(out_dtype, copy=False).reshape(x.shape)


def _span_len(bounds, i: int) -> int:
    lo, hi = bounds[i]
    return hi - lo


def _split_residual(quant_residual, wire, size: int, own_span):
    """Dispatch an error-feedback buffer by its length: full-payload
    (per-hop + owner residuals; the owner part is a view into it) or
    owned-chunk (owner compression only).  None when no lossy wire is in
    play — the residual must not drift while compression is off."""
    if quant_residual is None or wire is None:
        return None, None
    lo, hi = own_span
    res = quant_residual
    if res.size == size:
        return res, res[lo:hi]
    if res.size == hi - lo:
        return None, res
    raise ValueError(
        f"quant_residual must be full-payload ({size}) or owned-chunk "
        f"({hi - lo}) sized, got {res.size}")


def _check_bounds(bounds, n: int, size: int):
    bounds = [(int(lo), int(hi)) for lo, hi in bounds]
    if (len(bounds) != n or bounds[0][0] != 0 or bounds[-1][1] != size
            or any(bounds[i][1] != bounds[i + 1][0] for i in range(n - 1))):
        raise ValueError(
            f"bounds must be {n} contiguous spans covering [0, {size}), "
            f"got {bounds}")
    return bounds


def ring_reduce_scatter(dp, x, op: str = "sum", tag: str = "rs",
                        comm_dtype=None, bounds=None, quant_residual=None,
                        stats=None) -> np.ndarray:
    """Reduce-scatter phase alone: returns this rank's fully-reduced chunk
    (flat 1-D; its span is :func:`ring_chunk_span`, or ``bounds[rank]`` when
    a custom chunk partition is passed).  Uneven payloads give the first
    ``size % world`` ranks one extra element.

    The returned chunk is **bitwise-identical to the span a full
    :func:`ring_all_reduce` would have folded there** — same chunk owner,
    same accumulation order, same owner-side avg division and (under
    ``comm_dtype``) the same owner re-quantization through the wire dtype
    that the all-gather phase would have distributed.  That identity is
    what lets ZeRO-style sharded optimizers (tpu_dist/parallel/zero.py)
    stop here, update the owned shard, and still match the replicated
    update bit-for-bit."""
    x, op, n, r, flat = _prepare(dp, x, op)
    out_dtype = _out_dtype(x.dtype, op)
    if n <= 1:
        return flat.astype(out_dtype)
    wire = _resolve_wire(comm_dtype, flat.dtype)
    if bounds is None:
        bounds = _bounds(flat.size, n)
    else:
        bounds = _check_bounds(bounds, n, flat.size)
    res_full, res_own = _split_residual(quant_residual, wire, flat.size,
                                        bounds[r])
    wb = 0
    if flat.size:
        with _obs_span("ring_reduce_scatter", x):
            wb = _reduce_scatter_phase(dp, flat, bounds, n, r,
                                       op, f"{tag}/rrs", wire,
                                       residual=res_full,
                                       wd=_Watchdog(
                                           f"ring_reduce_scatter[{op}]"))
            _note_stats(stats, wire, wb,
                        (flat.size - _span_len(bounds, r)) * flat.itemsize)
    lo, hi = bounds[r]
    chunk = flat[lo:hi]
    if op in ("avg", "mean"):
        chunk = chunk / n
    if wire is not None:
        # owner compression, exactly as ring_all_reduce performs before
        # its all-gather phase: the shard this rank keeps must equal the
        # compressed bytes every peer would have received (error-feedback
        # residual folded in / updated at the same point)
        chunk, _ = _compress_owned(np.array(chunk), wire, res_own)
    # copy: the slice would otherwise pin the whole widened accumulation
    # buffer alive for the lifetime of the (small) shard
    return np.array(chunk.astype(out_dtype, copy=False))


def ring_chunk_all_gather(dp, flat, bounds, tag: str = "cag",
                          comm_dtype=None, stats=None) -> np.ndarray:
    """All-gather of pre-owned chunks — the all-gather phase of the ring
    alone, the inverse of :func:`ring_reduce_scatter`'s stop.

    Every rank passes the same full-size 1-D ``flat`` buffer with its own
    chunk ``bounds[rank]`` filled (the other spans are scratch); after
    N-1 double-buffered ring steps every span holds its owner's bytes —
    identical on every rank.  Fills ``flat`` in place and returns it.
    This is how a ZeRO optimizer redistributes updated parameter shards
    (tpu_dist/parallel/zero.py).

    ``comm_dtype`` (dtype or quant scheme) compresses the gathered chunks
    on the wire — **lossy**: every rank, including the owner, ends up with
    the chunk rounded through the wire format (the owner replaces its own
    span first, so the result stays byte-identical across ranks).  Leave
    it None when the gathered values are parameters that must stay
    exact."""
    flat = np.asarray(flat)
    if flat.ndim != 1:
        raise ValueError(f"ring_chunk_all_gather wants a flat 1-D buffer, "
                         f"got shape {flat.shape}")
    n, r = dp.num_processes, dp.rank
    if n <= 1 or flat.size == 0:
        return flat
    bounds = _check_bounds(bounds, n, flat.size)
    wire = _resolve_wire(comm_dtype, flat.dtype, float_only=True)
    wd = _Watchdog("ring_chunk_all_gather")
    with _obs_span("ring_chunk_all_gather", flat):
        if isinstance(wire, _Q.QuantScheme):
            wb = _ag_phase_quant(dp, flat, bounds, n, r, f"{tag}/rcag",
                                 wire, wd=wd)
        else:
            if wire is not None:
                lo, hi = bounds[r]
                deq, _ = _compress_owned(np.array(flat[lo:hi]), wire, None)
                flat[lo:hi] = deq
            wb = _all_gather_phase(dp, flat, bounds, n, r, f"{tag}/rcag",
                                   wire_dtype=wire, wd=wd)
        _note_stats(stats, wire, wb,
                    (flat.size - _span_len(bounds, (r + 1) % n))
                    * flat.itemsize)
    return flat


def ring_all_gather(dp, x, tag: str = "ag", comm_dtype=None,
                    stats=None) -> np.ndarray:
    """Ring all-gather: every rank contributes ``x`` (same shape/dtype on
    all ranks); returns an array with a leading process axis, blocks in
    rank order — (N-1)/N of the output on the wire per rank.

    ``comm_dtype`` (dtype or quant scheme) compresses the circulated
    blocks — **lossy**: every rank's block, including its own copy in the
    result, is rounded through the wire format at the source, so the
    gathered array stays byte-identical across ranks."""
    x = np.asarray(x)
    n, r = dp.num_processes, dp.rank
    if n <= 1:
        return x[None].copy()
    flat = np.ascontiguousarray(x).reshape(-1)
    out = np.empty((n, flat.size), dtype=x.dtype)
    out[r] = flat
    utag = f"{tag}/rag"
    # the (n, size) block matrix viewed flat so each step's send/recv rows
    # become spans of ONE buffer the double-buffered exchange can walk
    out_flat = out.reshape(-1)
    sz = flat.size
    bounds = [(i * sz, (i + 1) * sz) for i in range(n)]
    wire = _resolve_wire(comm_dtype, out.dtype, float_only=True)
    wd = _Watchdog("ring_all_gather")
    with _obs_span("ring_all_gather", x):
        wb = 0
        if sz:
            if isinstance(wire, _Q.QuantScheme):
                wb = _ag_phase_quant(dp, out_flat, bounds, n, r, utag,
                                     wire, wd=wd)
            else:
                if wire is not None:
                    deq, _ = _compress_owned(np.array(out[r]), wire, None)
                    out[r] = deq
                wb = _all_gather_phase(dp, out_flat, bounds, n, r, utag,
                                       wire_dtype=wire, wd=wd)
        _note_stats(stats, wire, wb, sz * (n - 1) * out.itemsize)
    return out.reshape((n,) + x.shape)


def tree_broadcast(dp, x, src: int = 0, tag: str = "bc") -> np.ndarray:
    """Binomial-tree broadcast of ``src``'s array: log2(N) rounds, each
    holder forwarding to a rank 2^k away, sub-chunked on the wire.  Every
    rank passes an ``x`` of the broadcast shape/dtype (non-src values are
    templates, as in ``broadcast_host``)."""
    x = np.asarray(x)
    n, r = dp.num_processes, dp.rank
    if n <= 1:
        return np.asarray(x)
    rel = (r - src) % n
    if rel == 0:
        # copy, not a view: receivers get fresh arrays off the wire, and the
        # source's return value must have the same no-aliasing property
        flat = np.array(x, copy=True).reshape(-1)
    else:
        flat = np.empty(x.size, dtype=x.dtype)
    utag = f"{tag}/tbc"
    k = 1
    wd = _Watchdog("tree_broadcast")
    with _obs_span("tree_broadcast", x):
        while k < n:
            if rel < k:
                peer_rel = rel + k
                if peer_rel < n:
                    _send_span(dp, (src + peer_rel) % n, utag, flat, 0,
                               flat.size, wire_dtype=None)
            elif rel < 2 * k:
                _recv_span(dp, (src + rel - k) % n, utag, flat, 0,
                           flat.size, combine=None, wd=wd)
            k *= 2
    return flat.reshape(x.shape)
