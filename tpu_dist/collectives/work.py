"""Async collective engine: ``Work`` futures over a per-ring ordered executor.

The reference's whole performance story is DDP's Reducer firing bucketed
all-reduces *asynchronously* so communication overlaps the backward pass
(torch ``DistributedDataParallel``; README §1).  Our XLA path gets that
overlap for free inside the jitted graph, but the host data plane — the
path every CPU-backend job, chaos/elastic run, and store-transport job
takes — was fully synchronous: each ``*_host`` collective blocked its
caller until the last byte landed.

``async_op=True`` on the eager collectives (and the
:class:`~tpu_dist.collectives.bucketer.Bucketer`) now returns a
:class:`Work` future instead, executed on an **ordered executor**:

- **One FIFO worker thread per process** (the ``engine_for(None)``
  engine — every production async path submits there, since a process has
  one ring; per-:class:`DataPlane` engines exist for in-process
  multi-rank test rigs, where each fake rank needs its own independent
  stream).  Collectives on a ring are not independent jobs — every rank
  must walk the same sequence of ring steps in the same order, so a
  thread *pool* would let two in-flight collectives interleave their wire
  traffic differently on different ranks.  A single ordered worker keeps
  issue order == wire order == the order every peer sees, which is
  exactly the NCCL stream-semantics contract torch's async ops rely on.
- **Errors are captured at issue time, raised at ``wait()``.**  A
  :class:`~tpu_dist.collectives.transport.PeerGoneError` or
  :class:`~tpu_dist.analysis.sanitizer.CollectiveMismatchError` thrown
  while the work executes is stored on the handle; ``wait()`` re-raises
  it on the caller's thread, ``exception()`` exposes it without raising.
  A dropped handle therefore silently swallows the diagnosis — which is
  what tpudlint rule TD007 exists to catch.
- **Sync collectives drain the queue first.**  A synchronous collective
  issued after async work must not overtake it (ranks would disagree on
  collective order — the sanitizer would flag it, and interleaved ring
  tags would stall); every sync eager entry point calls
  :func:`drain_pending` (a no-op lock check when nothing is queued).
- **Queue-wait vs wire time are split on the flight recorder**: the span
  a collective opens when it *executes* carries ``queue_ns`` — how long
  the work sat behind earlier collectives — so an overlap regression
  (bucket N stuck behind bucket N-1) is visible in the trace, not folded
  into "the collective was slow".
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Callable, List, Optional, Sequence

__all__ = ["Work", "wait_all", "drain_pending"]


class Work:
    """Handle for one asynchronously-issued collective (torch
    ``dist.Work`` parity, future-flavored).

    ``wait(timeout)`` blocks until the collective completes and returns
    its result (the reduced/gathered value), re-raising any error the
    collective hit while executing.  ``is_completed()`` polls without
    blocking; ``exception()`` returns the captured error (None while
    pending or on success).
    """

    __slots__ = ("_done", "_result", "_exc", "_label", "issued_ns",
                 "started_ns", "site")

    def __init__(self, label: str = "work"):
        self._done = threading.Event()
        self._result = None
        self._exc: Optional[BaseException] = None
        self._label = label
        self.issued_ns = time.monotonic_ns()
        self.started_ns: Optional[int] = None
        self.site: Optional[str] = None   # caller's call-site at issue

    # -- executor side -------------------------------------------------------

    def _finish(self, result=None, exc: Optional[BaseException] = None):
        self._result = result
        self._exc = exc
        self._done.set()

    # -- caller side ---------------------------------------------------------

    def wait(self, timeout: Optional[float] = None):
        """Block until the work completes; returns its result.  Re-raises
        the error captured at issue/execution time (``PeerGoneError``,
        ``CollectiveMismatchError``, ...).  Raises ``TimeoutError`` if the
        work is still in flight after ``timeout`` seconds — the work keeps
        running and may be waited on again."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"async collective {self._label!r} still in flight after "
                f"{timeout}s (wait again, or check exception())")
        if self._exc is not None:
            raise self._exc
        return self._result

    def is_completed(self) -> bool:
        """True once the collective finished (successfully or not)."""
        return self._done.is_set()

    def exception(self) -> Optional[BaseException]:
        """The error captured while the work executed, or None (also None
        while the work is still pending)."""
        return self._exc if self._done.is_set() else None

    def result(self, timeout: Optional[float] = None):
        """Alias for :meth:`wait` (concurrent.futures flavor)."""
        return self.wait(timeout)

    def __repr__(self):
        state = ("pending" if not self._done.is_set()
                 else "error" if self._exc is not None else "done")
        return f"Work({self._label!r}, {state})"


def completed_work(result, label: str = "work") -> Work:
    """An already-finished :class:`Work` (single-process fast paths)."""
    w = Work(label)
    w._finish(result=result)
    return w


# thread-local marker + handoff slot: set while an executor worker runs a
# body, so (a) drain_pending from inside a work cannot deadlock on its own
# queue, and (b) the obs span the body opens can pick up its queue wait
_tls = threading.local()


def take_pending_queue_ns() -> Optional[int]:
    """Pop the queue-wait (ns) of the work body currently executing on this
    thread — consumed by the first flight-recorder span the body opens, so
    the span splits time-behind-earlier-collectives from wire time."""
    ns = getattr(_tls, "queue_ns", None)
    _tls.queue_ns = None
    return ns


def pending_site() -> Optional[str]:
    """The ISSUE call-site of the work body executing on this thread (not
    consumed: every span the body opens attributes to it).  An engine
    thread's own stack holds no user frames, so spans opened there would
    otherwise attribute to framework internals."""
    return getattr(_tls, "site", None)


def _issue_site() -> Optional[str]:
    """The submitting caller's call-site, captured only when the flight
    recorder is armed (stack walks are not free)."""
    try:
        from ..obs import recorder as _rec
        if _rec.enabled():
            return _rec.call_site()
    except Exception:
        pass
    return None


class _OrderedExecutor:
    """Single-worker FIFO executor: submitted bodies run in issue order,
    one at a time — the per-ring stream.  The worker thread is lazy
    (created on first submit) and daemon (dies with the process; a gang
    teardown must not wait on queued diagnostics)."""

    def __init__(self, name: str = "tpu_dist-async-coll"):
        self._name = name
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._q: deque = deque()
        self._pending = 0          # queued + currently executing
        self._thread: Optional[threading.Thread] = None

    def submit(self, fn: Callable[[], object], label: str = "work") -> Work:
        w = Work(label)
        w.site = _issue_site()
        with self._mu:
            self._q.append((fn, w))
            self._pending += 1
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name=self._name)
                self._thread.start()
            self._cv.notify_all()
        return w

    def _run(self):
        while True:
            with self._mu:
                while not self._q:
                    # park with a deadline so an idle engine's worker can
                    # retire; a later submit starts a fresh one
                    if not self._cv.wait(30.0) and not self._q:
                        self._thread = None
                        return
                # BATCHED handoff: drain everything already queued under
                # ONE lock acquisition and run it back-to-back.  A caller
                # issuing N handles and immediately wait_all()-ing them
                # (the per-leaf async pattern) used to pay a lock/CV
                # round-trip per item; the batch pop amortizes that to one
                # per burst, which is what keeps per-leaf async from
                # regressing below per-leaf sync on small worlds.
                batch = list(self._q)
                self._q.clear()
            for fn, w in batch:
                w.started_ns = time.monotonic_ns()
                _tls.queue_ns = w.started_ns - w.issued_ns
                _tls.site = w.site
                _tls.on_engine = True
                try:
                    w._finish(result=fn())
                except BaseException as e:
                    w._finish(exc=e)
                finally:
                    _tls.queue_ns = None
                    _tls.site = None
                    _tls.on_engine = False
                    with self._mu:
                        self._pending -= 1
                        self._cv.notify_all()

    def pending(self) -> int:
        with self._mu:
            return self._pending

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every previously-submitted work has finished
        (results/errors stay on their handles).  Returns False on
        timeout."""
        deadline = (time.monotonic() + timeout) if timeout is not None \
            else None
        with self._mu:
            while self._pending > 0:
                left = (None if deadline is None
                        else deadline - time.monotonic())
                if left is not None and left <= 0:
                    return False
                self._cv.wait(left if left is not None else 1.0)
        return True


# -- engine registry ----------------------------------------------------------
#
# One ordered executor per ring (keyed by DataPlane instance, weakly — a
# closed plane's engine dies with it), plus one process-wide executor for
# collectives that never touch a ring (store-only payloads).  drain_pending
# sweeps them all: sync collectives must order after EVERY queued async op.

_engines: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_default_engine: Optional[_OrderedExecutor] = None
_engines_mu = threading.Lock()


def engine_for(dp=None) -> _OrderedExecutor:
    """The ordered executor for ``dp``'s ring (or the process-wide one for
    ``dp=None``)."""
    global _default_engine
    with _engines_mu:
        if dp is None:
            if _default_engine is None:
                _default_engine = _OrderedExecutor()
            return _default_engine
        eng = _engines.get(dp)
        if eng is None:
            eng = _engines[dp] = _OrderedExecutor(
                f"tpu_dist-async-coll-r{getattr(dp, 'rank', '?')}")
        return eng


def drain_pending(timeout: Optional[float] = None) -> None:
    """Wait for every queued async collective (all engines) to finish.

    Called at the top of every *sync* eager collective so sync ops cannot
    overtake queued async ones (stream semantics).  No-op (one lock check
    per engine) when nothing is queued, and a no-op from inside an
    executor worker — a work body calling a sync collective must not wait
    on its own queue."""
    if getattr(_tls, "on_engine", False):
        return  # executing ON an engine thread
    with _engines_mu:
        engines = list(_engines.values())
        if _default_engine is not None:
            engines.append(_default_engine)
    for eng in engines:
        eng.drain(timeout)


def wait_all(works: Sequence[Work], timeout: Optional[float] = None) -> List:
    """Wait on several :class:`Work` handles; returns their results in
    order.  The first captured error re-raises (after all handles were
    given their share of the deadline)."""
    deadline = (time.monotonic() + timeout) if timeout is not None else None
    out = []
    for w in works:
        left = None if deadline is None else max(0.0,
                                                 deadline - time.monotonic())
        out.append(w.wait(left))
    return out


def reset() -> None:
    """Drop all engines (tests): queued work keeps running on orphaned
    threads, but new submissions get fresh queues."""
    global _default_engine
    with _engines_mu:
        _default_engine = None
        _engines.clear()
