"""Topology-aware host collectives: scoped sub-groups, host detection,
the two-level (hierarchical) ring, and per-collective algorithm selection.

Three pieces, layered on the existing data plane:

- **Host topology** (:func:`detect_topology`): every rank publishes a host
  fingerprint through the control-plane store (at rendezvous pre-flight
  and again when its :class:`~tpu_dist.collectives.transport.DataPlane`
  comes up); reading all of them yields a :class:`Topology` — which ranks
  share a physical host.  Co-located pairs get shared-memory payload lanes
  (tpu_dist/collectives/shm.py) automatically; the fingerprint is also
  what the hierarchical ring and the algorithm autoselector consume.
  ``TPU_DIST_HOST_ID`` / ``TPU_DIST_HOST_ID_R{rank}`` override the
  fingerprint (simulated layouts for benchmarks and tests).

- **Scoped sub-groups** (:func:`new_group`, the ``torch.distributed
  .new_group`` analogue): a :class:`SubGroup` carves the flat rank space
  into a group with its own ring order (the member list's order), its own
  store-key namespace (``tpu_dist/g{gen}/grp{id}/…``), its own data-plane
  tag prefix, group-scoped sanitizer signatures, and obs span attribution.
  Every existing ring collective — ``ring_all_reduce`` /
  ``ring_reduce_scatter`` / ``ring_all_gather``, including ``comm_dtype``
  quantization and custom ``bounds=`` — runs unchanged inside a group
  through the :class:`GroupDataPlane` view, which translates group-local
  ranks to global ones and namespaces wire tags.  Like torch, every rank
  of the *parent* group must call :func:`new_group` with the identical
  member list (tpudlint TD008 flags rank-divergent lists); issuing a
  collective on a group the caller is not a member of raises a named
  :class:`GroupMembershipError` instead of wedging the members.

- **Hierarchical (two-level) ring** (:func:`hier_all_reduce`): the ring
  all-reduce run over the **host-major** rank order — every host's ranks
  form a contiguous ring segment, so a reducing chunk snakes through each
  host over shared memory (the intra-host reduce), crosses to the next
  host exactly once per revolution carried by the host's edge rank (the
  inter-host ring over per-host "leaders"), and the all-gather phase
  distributes results the same way (the intra-host broadcast).  Cross-host
  traffic drops by ranks_per_host× versus a host-oblivious layout where
  every hop crosses the wire.  **Bitwise contract**: the fold order per
  chunk is strictly sequential — the one property that makes results
  bit-identical to the flat ring.  A leader that pre-reduced its host's
  values into one partial would re-associate the sum (``(T+(a+b))`` ≠
  ``((T+a)+b)`` in floats), so this implementation deliberately keeps the
  flat ring's per-rank fold sequence; when the global rank order is
  already host-contiguous (the launcher default, and every layout the
  tests/bench run) the host-major order is the identity and hierarchical
  results are **bitwise-equal to the flat ring by construction**.  Under
  an interleaved layout the ring is re-ordered host-major: results are
  still deterministic and identical on every rank, but the fold order is
  the permuted ring's (same status as a custom ``bounds=``).

- **Algorithm autoselection** (:func:`select_algo`): per-collective choice
  among store / flat ring / hierarchical by payload size and detected
  topology, overridable with ``TPU_DIST_ALGO`` (``auto`` | ``flat`` |
  ``hier`` | ``store``).  The compute-bound guard closes PR 8's world-4
  inversion: when ranks-per-host *exceeds* the core count
  (``TPU_DIST_ALGO_CORES``, default ``os.cpu_count()``), per-hop quant
  arithmetic lands on the critical path, so auto mode falls back to the
  flat **f32** ring (wire compression suppressed) instead of losing
  throughput to compression math.  The chosen algorithm is recorded on
  obs spans and in :func:`algo_counters`.
"""

from __future__ import annotations

import hashlib
import os
import threading
import weakref
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["Topology", "SubGroup", "GroupDataPlane", "GroupMembershipError",
           "new_group", "detect_topology", "host_fingerprint", "host_key",
           "publish_host_fingerprint", "parse_host_record",
           "hier_all_reduce", "hier_group",
           "select_algo", "algo_counters", "reset_algo_counters"]

_DEF_HIER_THRESHOLD = 1 << 20  # hierarchical pays off once wire-bound


class GroupMembershipError(RuntimeError):
    """A collective was issued on a :class:`SubGroup` the calling rank is
    not a member of (the runtime complement of tpudlint TD008)."""


# -- host fingerprints --------------------------------------------------------


def host_fingerprint(rank: Optional[int] = None) -> str:
    """This process's host identity.  Two processes report the same
    fingerprint iff they share a physical host (kernel boot id +
    hostname).  Overrides, for simulated topologies:
    ``TPU_DIST_HOST_ID_R{rank}`` (per-rank — in-process multi-rank test
    rigs), then ``TPU_DIST_HOST_ID`` (per-process — spawned benchmark
    workers)."""
    if rank is not None:
        per_rank = os.environ.get(f"TPU_DIST_HOST_ID_R{int(rank)}")
        if per_rank:
            return per_rank
    forced = os.environ.get("TPU_DIST_HOST_ID")
    if forced:
        return forced
    boot = ""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            boot = f.read().strip()
    except OSError:
        pass
    import socket as _socket
    return f"{_socket.gethostname()}|{boot}"


def host_key(generation: int, rank: int) -> str:
    """THE store key a rank's host fingerprint lives under — one
    definition, shared by the DataPlane, rendezvous pre-flight, and
    :func:`detect_topology`, so publishers and readers cannot drift."""
    return f"tpu_dist/g{generation}/dp/host/{rank}"


def publish_host_fingerprint(store, rank: int, generation: int) -> None:
    """Publish this rank's fingerprint + core count (idempotent —
    rendezvous pre-flight and DataPlane construction both call this; same
    key, same value).  The core count rides along so the compute-bound
    autoselection guard works from STORE-AGREED numbers: with a local
    ``os.cpu_count()`` heterogeneous hosts would pick different
    algorithms and mute-deadlock."""
    import json
    store.set(host_key(generation, rank),
              json.dumps({"host": host_fingerprint(rank),
                          "cores": os.cpu_count() or 1}).encode())


def parse_host_record(raw: bytes):
    """``(fingerprint, cores)`` from a published host key (cores None for
    a legacy bare-fingerprint value)."""
    import json
    text = raw.decode()
    try:
        rec = json.loads(text)
        return str(rec["host"]), int(rec.get("cores") or 0) or None
    except (ValueError, KeyError, TypeError):
        return text, None


# -- topology -----------------------------------------------------------------


class Topology:
    """Which ranks share a host.  ``hosts`` maps fingerprint → sorted
    member ranks, hosts ordered by their smallest member."""

    def __init__(self, hosts_by_rank: Sequence[str],
                 cores_by_rank: Optional[Sequence[Optional[int]]] = None):
        self.hosts_by_rank = list(hosts_by_rank)
        self.world = len(self.hosts_by_rank)
        self.cores_by_rank = (list(cores_by_rank) if cores_by_rank
                              else [None] * self.world)
        by_host: Dict[str, List[int]] = {}
        for r, h in enumerate(self.hosts_by_rank):
            by_host.setdefault(h, []).append(r)
        self.hosts: Dict[str, List[int]] = dict(
            sorted(by_host.items(), key=lambda kv: min(kv[1])))

    @property
    def min_cores(self) -> Optional[int]:
        """Smallest published core count across ranks — the store-agreed
        core budget the compute-bound guard uses, so every rank (on
        heterogeneous hosts too) reaches the identical decision.  None
        when no rank published one (legacy / hand-built topologies)."""
        known = [c for c in self.cores_by_rank if c]
        return min(known) if known else None

    @property
    def nhosts(self) -> int:
        return len(self.hosts)

    @property
    def max_ranks_per_host(self) -> int:
        return max((len(rs) for rs in self.hosts.values()), default=1)

    @property
    def colocated(self) -> bool:
        """Any host holding more than one rank?"""
        return self.max_ranks_per_host > 1

    def host_of(self, rank: int) -> str:
        return self.hosts_by_rank[rank]

    def host_major_order(self) -> List[int]:
        """Global ranks grouped by host (hosts by smallest member, members
        ascending) — the two-level ring order.  Identity whenever the
        launcher laid ranks out host-contiguously."""
        out: List[int] = []
        for members in self.hosts.values():
            out.extend(members)
        return out

    def is_host_major(self) -> bool:
        return self.host_major_order() == list(range(self.world))

    def __repr__(self):
        return (f"Topology(world={self.world}, hosts="
                f"{ {h: rs for h, rs in self.hosts.items()} })")


def detect_topology(dp, timeout: Optional[float] = None) -> Topology:
    """The gang's host topology, read from the fingerprints every rank
    published to the control-plane store (bounded wait — a peer that died
    before publishing surfaces as a named ``TimeoutError``, not a hang).
    Cached on the DataPlane: one store round per incarnation."""
    cached = getattr(dp, "_topo_cache", None)
    if cached is not None:
        return cached
    from . import transport as _t
    store, gen, n = dp._store, dp.generation, dp.num_processes
    keys = [host_key(gen, r) for r in range(n)]
    if timeout is None:
        timeout = _t._default_timeout()
    try:
        store.wait(keys, timeout=timeout if timeout > 0 else None)
    except TimeoutError as e:
        raise TimeoutError(
            f"topology detection: not every rank published a host "
            f"fingerprint within {timeout:.0f}s (a peer likely died before "
            f"its data plane came up): {e}") from e
    records = [parse_host_record(store.get(k)) for k in keys]
    topo = Topology([h for h, _ in records], [c for _, c in records])
    dp._topo_cache = topo
    return topo


# -- scoped sub-groups --------------------------------------------------------


def _digest8(items) -> str:
    return hashlib.sha256(repr(list(items)).encode()).hexdigest()[:8]


# membership -> how many groups with that exact member list this process
# has created; SPMD-consistent for the same reason the collective sequence
# counters are (every rank creates groups in the same program order)
_group_instances: Dict[Tuple[int, ...], int] = {}
_group_mu = threading.Lock()


class SubGroup:
    """A scoped sub-group of the flat rank space (``torch.distributed
    .new_group`` analogue) — create via :func:`new_group`.

    - ``members``: global ranks in **ring order** (the order given).
    - ``rank`` / ``num_processes``: this process's group-local rank (None
      for non-members) and the group size — the same duck-type every eager
      collective and ring function already consumes, so a SubGroup drops
      in wherever a ProcessGroup shim does.
    - ``group_id``: deterministic id (ordered-membership digest + a
      per-membership creation counter) — namespaces store keys
      (``tpu_dist/g{gen}/grp{id}/…``) and data-plane wire tags, so two
      groups' collectives can never cross.
    - ``set_scope``: digest of the *sorted* member set — the sanitizer
      signature namespace.  Ranks whose group objects diverge only in
      order/identity still land in the same signature keyspace, so the
      mismatch is *named* (both memberships) rather than a timeout.
    """

    def __init__(self, members: Sequence[int], parent_rank: int,
                 parent_world: int, instance: int):
        members = [int(r) for r in members]
        if len(set(members)) != len(members):
            raise ValueError(f"duplicate ranks in group members: {members}")
        if not members:
            raise ValueError("a group needs at least one member")
        for r in members:
            if not 0 <= r < parent_world:
                raise ValueError(
                    f"group member {r} out of range (world {parent_world})")
        self.members: Tuple[int, ...] = tuple(members)
        self.parent_rank = int(parent_rank)
        self.parent_world = int(parent_world)
        self.group_id = f"{_digest8(self.members)}.{instance}"
        self.member_hash = _digest8(self.members)
        self.set_scope = _digest8(sorted(self.members))
        self.num_processes = len(self.members)
        self.rank: Optional[int] = (
            self.members.index(self.parent_rank)
            if self.parent_rank in self.members else None)
        self._views: "weakref.WeakValueDictionary" = \
            weakref.WeakValueDictionary()

    def describe(self) -> str:
        return f"grp{self.group_id}{list(self.members)}"

    def require_member(self, what: str = "collective") -> int:
        """This process's group-local rank; raises
        :class:`GroupMembershipError` for non-members — a non-member
        joining a group collective would desynchronize every member's ring
        tags and sanitizer sequence, so it fails loudly *before* payload
        moves."""
        if self.rank is None:
            raise GroupMembershipError(
                f"rank {self.parent_rank} issued a {what} on "
                f"{self.describe()} but is not a member — every "
                f"participant of a sub-group collective must be in its "
                f"member list")
        return self.rank

    def view(self, dp) -> "GroupDataPlane":
        """The group-scoped DataPlane view over ``dp`` (cached per dp)."""
        got = self._views.get(id(dp))
        if got is None or got._dp is not dp:
            got = GroupDataPlane(dp, self)
            self._views[id(dp)] = got
        return got

    def __repr__(self):
        return (f"SubGroup({self.describe()}, rank={self.rank}, "
                f"world={self.parent_world})")


def new_group(ranks: Sequence[int], group=None) -> SubGroup:
    """Create a scoped sub-group from global ``ranks`` (ring order = list
    order).  Like torch's ``new_group``: **every rank of the parent group
    must call this with the identical list, in the same program order**,
    whether or not it is a member — the group id that namespaces keys and
    tags is derived from the list and a creation counter, so divergent
    lists produce divergent groups (the sanitizer then names both
    memberships, and tpudlint TD008 flags the pattern statically)."""
    if group is None:
        from ..dist import get_default_group
        group = get_default_group()
    members = tuple(int(r) for r in ranks)
    with _group_mu:
        instance = _group_instances.get(members, 0)
        _group_instances[members] = instance + 1
    return SubGroup(members, group.rank, group.num_processes, instance)


class GroupDataPlane:
    """Group-scoped view of a :class:`~tpu_dist.collectives.transport
    .DataPlane`: group-local ranks in, global ranks out, every wire tag
    prefixed with the group id.  Exposes the exact method surface the ring
    collectives and eager routing consume, so they run unchanged inside a
    group."""

    def __init__(self, dp, group: SubGroup):
        group.require_member("data-plane collective")
        self._dp = dp
        self.group = group
        self.rank = group.rank
        self.num_processes = group.num_processes
        self.generation = dp.generation

    def _g(self, r: int) -> int:
        if not 0 <= r < self.num_processes:
            raise ValueError(
                f"group-local rank {r} out of range for "
                f"{self.group.describe()}")
        return self.group.members[r]

    def _t(self, tag: str) -> str:
        return f"grp{self.group.group_id}/{tag}"

    def send_array(self, dst: int, tag: str, arr) -> int:
        return self._dp.send_array(self._g(dst), self._t(tag), arr)

    def send_quant(self, dst: int, tag: str, chunk) -> int:
        return self._dp.send_quant(self._g(dst), self._t(tag), chunk)

    def recv_array(self, src: int, tag: str, timeout=None):
        return self._dp.recv_array(self._g(src), self._t(tag),
                                   timeout=timeout)

    def recv_array_dual(self, src: int, tag: str, alt_check=None,
                        timeout=None):
        return self._dp.recv_array_dual(self._g(src), self._t(tag),
                                        alt_check=alt_check,
                                        timeout=timeout)

    def try_recv_array(self, src: int, tag: str):
        return self._dp.try_recv_array(self._g(src), self._t(tag))

    def peer_gone(self, src: int):
        return self._dp.peer_gone(self._g(src))

    def gone_error(self, peer: int, detail: str = ""):
        note = f"group-local rank {peer} of {self.group.describe()}"
        return self._dp.gone_error(
            self._g(peer), f"{detail}; {note}" if detail else note)

    def shm_active(self, dst: int) -> bool:
        return self._dp.shm_active(self._g(dst))

    def send_chunk_bytes(self, dst: int, base: int) -> int:
        return self._dp.send_chunk_bytes(self._g(dst), base)

    def __repr__(self):
        return f"GroupDataPlane({self.group.describe()}, over {self._dp!r})"


# -- hierarchical (two-level) ring --------------------------------------------

# dp -> (host-major order, spanning SubGroup); weak so in-process test rigs
# with many DataPlanes do not pin them
_hier_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_hier_mu = threading.Lock()


def hier_group(dp, topo: Optional[Topology] = None) -> SubGroup:
    """The all-ranks SubGroup in host-major ring order — the two-level
    ring's backbone (cached per DataPlane; the group id therefore stays
    stable across calls, keeping wire tags and engine keys steady)."""
    if topo is None:
        topo = detect_topology(dp)
    order = tuple(topo.host_major_order())
    with _hier_mu:
        hit = _hier_cache.get(dp)
        if hit is not None and hit[0] == order:
            return hit[1]
        grp = SubGroup(order, dp.rank, dp.num_processes, instance=0)
        _hier_cache[dp] = (order, grp)
        return grp


def hier_all_reduce(dp, x, op: str = "sum", tag: str = "har",
                    comm_dtype=None, bounds=None, quant_residual=None,
                    stats=None, topo: Optional[Topology] = None):
    """Two-level (hierarchical) ring all-reduce: the ring run in host-major
    order, intra-host hops over shared memory, one inter-host hop per host
    per revolution (see the module docstring for the phase structure and
    the bitwise contract).  Signature-compatible with
    :func:`~tpu_dist.collectives.ring.ring_all_reduce` — ``comm_dtype``
    (cast or quant schemes), custom ``bounds``, error-feedback residuals
    and ``stats`` all pass straight through, because this *is* that ring,
    over a re-ordered group view."""
    if topo is None:
        topo = detect_topology(dp)
    from . import ring as _ring
    gdp = hier_group(dp, topo).view(dp)
    return _ring.ring_all_reduce(gdp, x, op=op, tag=tag,
                                 comm_dtype=comm_dtype, bounds=bounds,
                                 quant_residual=quant_residual, stats=stats)


# -- algorithm autoselection --------------------------------------------------

_algo_mu = threading.Lock()
_algo_counts: Dict[str, int] = {}

_ALGO_MODES = ("auto", "flat", "hier", "store")


def algo_mode() -> str:
    """``TPU_DIST_ALGO``: ``auto`` (default — select by size + topology),
    ``flat`` / ``hier`` (force the ring shape; explicit modes also keep
    the configured ``comm_dtype``, compute-bound or not), ``store``
    (bypass the data plane entirely)."""
    mode = os.environ.get("TPU_DIST_ALGO", "auto").strip().lower()
    if not mode:
        return "auto"
    if mode not in _ALGO_MODES:
        raise ValueError(
            f"TPU_DIST_ALGO={mode!r}: expected one of {_ALGO_MODES}")
    return mode


def _cores(topo: Optional[Topology] = None) -> int:
    """Core budget for the compute-bound guard: ``TPU_DIST_ALGO_CORES``
    (launcher-uniform override), else the STORE-AGREED minimum core count
    the ranks published with their fingerprints, else local
    ``os.cpu_count()``.  Preferring the published minimum keeps the guard
    rank-consistent on heterogeneous hosts — a local count would make
    big-host ranks pick ``hier`` while small-host ranks pick ``flat``,
    and the mismatched ring tags would mute-deadlock."""
    try:
        forced = int(os.environ.get("TPU_DIST_ALGO_CORES", "0"))
    except ValueError:
        forced = 0
    if forced > 0:
        return forced
    agreed = topo.min_cores if topo is not None else None
    return agreed if agreed else (os.cpu_count() or 1)


def _hier_threshold() -> int:
    try:
        return int(os.environ.get("TPU_DIST_HIER_THRESHOLD",
                                  str(_DEF_HIER_THRESHOLD)))
    except ValueError:
        return _DEF_HIER_THRESHOLD


def select_algo(nbytes: int, dp=None,
                topo: Optional[Topology] = None) -> Tuple[str, bool]:
    """Choose the ring shape for one data-plane reduction leaf: returns
    ``(algo, comm_ok)`` with ``algo`` ∈ {``"flat"``, ``"hier"``,
    ``"store"``} and ``comm_ok=False`` meaning *suppress wire
    compression* (run plain f32).  ``"store"`` only under the explicit
    ``TPU_DIST_ALGO=store`` override — the eager router keeps such leaves
    off the data plane before selection is ever consulted.

    ``auto`` policy, in order:

    1. no topology available (store-less rig) → flat, compression kept;
    2. no co-located ranks → flat (there is nothing hierarchical to do);
    3. **compute-bound guard**: ranks-per-host > cores → flat **f32** —
       with more ranks than cores the ring serializes on CPU and any
       per-hop arithmetic (quant encode/decode, dtype casts) lands on the
       critical path; PR 8 measured the int8 wire *inverting* (21.5 vs
       30.5 MB/s) at exactly this point (world 4, 2 cores);
    4. payload below ``TPU_DIST_HIER_THRESHOLD`` (1 MiB) → flat (the
       re-ordered ring buys nothing until the wire dominates);
    5. otherwise → hierarchical.

    The decision depends only on launcher-uniform env, payload size, and
    the store-agreed topology — every rank answers identically."""
    mode = algo_mode()
    if mode == "flat":
        return "flat", True
    if mode == "hier":
        return "hier", True
    if mode == "store":
        # the eager router already short-circuits store mode before any
        # leaf reaches here (_dp_leaf_ok); direct callers get the honest
        # answer rather than a fall-through to the auto policy
        return "store", True
    if topo is None and dp is not None:
        topo = detect_topology(dp)
    if topo is None or not topo.colocated:
        return "flat", True
    if topo.max_ranks_per_host > _cores(topo):
        return "flat", False
    if int(nbytes) < _hier_threshold():
        return "flat", True
    return "hier", True


def record_algo(op: str, algo: str) -> None:
    """Count one algorithm choice and stamp it on the enclosing obs span."""
    with _algo_mu:
        key = f"{op}/{algo}"
        _algo_counts[key] = _algo_counts.get(key, 0) + 1
    try:
        from ..obs import hooks as _hooks
        _hooks.note_algo(algo)
    except Exception:
        pass


def algo_counters(reset: bool = False) -> Dict[str, int]:
    """Per-``op/algo`` selection counts (tests/benchmarks introspection)."""
    with _algo_mu:
        out = dict(_algo_counts)
        if reset:
            _algo_counts.clear()
    return out


def reset_algo_counters() -> None:
    with _algo_mu:
        _algo_counts.clear()
