"""Eager host-level collectives on a ProcessGroup.

torch call-style parity (``dist.all_reduce(tensor)``,
/root/reference/README.md:38-43 usage flow) for out-of-graph syncs: metric
averaging, init-time parameter broadcast, debugging.  NOT for the training
hot path — there the all-reduce is fused into the jitted step
(tpu_dist.parallel); each eager call is a separate compiled program.

Semantics: the input is this *process*'s local value; the collective runs
across all processes of the group (one leader device per process carries the
payload).  Single-process groups are a fast no-op/copy, so the same training
script runs unchanged from 1 host to a pod (the property the reference gets
from torch.distributed working at world_size=1).
"""

from __future__ import annotations

import jax
import numpy as np

__all__ = ["all_reduce_host", "all_gather_host", "broadcast_host"]


def _default_group(group):
    if group is None:
        from ..dist import get_default_group
        group = get_default_group()
    return group


def all_reduce_host(x, group=None, op: str = "sum"):
    """Reduce a per-process host value across processes; returns the reduced
    value on host (as numpy / python scalar tree)."""
    group = _default_group(group)
    np_op = {"sum": None, "avg": None, "mean": None, "max": np.maximum,
             "min": np.minimum}
    if op.lower() not in np_op:
        raise ValueError(f"Unknown reduce op {op!r}")
    if group.num_processes <= 1:
        return jax.tree.map(np.asarray, x)
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(x)  # leading axis = process
    if op.lower() == "sum":
        return jax.tree.map(lambda v: np.sum(v, axis=0), gathered)
    if op.lower() in ("avg", "mean"):
        return jax.tree.map(lambda v: np.mean(v, axis=0), gathered)
    fn = np_op[op.lower()]
    return jax.tree.map(lambda v: fn.reduce(v, axis=0), gathered)


def all_gather_host(x, group=None):
    """Gather per-process values; returns tree with leading process axis."""
    group = _default_group(group)
    if group.num_processes <= 1:
        return jax.tree.map(lambda v: np.asarray(v)[None], x)
    from jax.experimental import multihost_utils
    return multihost_utils.process_allgather(x)


def broadcast_host(x, group=None, src: int = 0):
    """Broadcast process ``src``'s value to all processes (DDP's wrap-time
    rank-0 parameter broadcast, /root/reference/example_mp.py:53)."""
    group = _default_group(group)
    if group.num_processes <= 1:
        return jax.tree.map(np.asarray, x)
    from jax.experimental import multihost_utils
    return multihost_utils.broadcast_one_to_all(
        x, is_source=group.rank == src)
