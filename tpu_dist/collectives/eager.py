"""Eager host-level collectives on a ProcessGroup.

torch call-style parity (``dist.all_reduce(tensor)``, ``dist.reduce``,
``dist.gather``/``scatter``, ``dist.send``/``recv`` —
/root/reference/README.md:38-43 usage flow) for out-of-graph syncs: metric
averaging, init-time parameter broadcast, debugging.  NOT for the training
hot path — there the all-reduce is fused into the jitted step
(tpu_dist.parallel); each eager call is a separate compiled program.

Semantics: the input is this *process*'s local value; the collective runs
across all processes of the group (one leader device per process carries the
payload).  Single-process groups are a fast no-op/copy, so the same training
script runs unchanged from 1 host to a pod (the property the reference gets
from torch.distributed working at world_size=1).

Point-to-point ``send``/``recv`` ride the control-plane TCPStore (the c10d
TCPStore analogue, tpu_dist/dist/store.py) — available whenever the job was
brought up through ``tpu_dist.launch`` (default) or with
``TPU_DIST_STORE_ADDR``/``TPU_DIST_STORE_PREFLIGHT`` set.
"""

from __future__ import annotations

import io
import pickle
import weakref
from typing import Any, List, Optional

import jax
import numpy as np

__all__ = ["ReduceOp", "all_reduce_host", "all_gather_host",
           "broadcast_host", "reduce_host", "gather_host", "scatter_host",
           "send", "recv", "send_recv_device", "all_gather_object",
           "gather_object", "broadcast_object_list", "scatter_object_list",
           "all_to_all_host"]


class ReduceOp:
    """torch.distributed.ReduceOp parity (string-valued; the *_host
    collectives accept either these constants or the lowercase strings)."""
    SUM = "sum"
    AVG = "avg"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"
    BAND = "band"
    BOR = "bor"
    BXOR = "bxor"


# op name -> numpy ufunc reduced over the process axis; avg handled apart
_REDUCE_UFUNCS = {
    "sum": np.add,
    "prod": np.multiply,
    "product": np.multiply,
    "min": np.minimum,
    "max": np.maximum,
    "band": np.bitwise_and,
    "bor": np.bitwise_or,
    "bxor": np.bitwise_xor,
}


def _reduce_fn(op: str):
    op = op.lower()
    if op in ("avg", "mean"):
        return lambda v: np.mean(v, axis=0)
    if op in _REDUCE_UFUNCS:
        ufunc = _REDUCE_UFUNCS[op]
        return lambda v: ufunc.reduce(v, axis=0)
    raise ValueError(f"Unknown reduce op {op!r}; one of "
                     f"{sorted(_REDUCE_UFUNCS) + ['avg']}")


def _default_group(group):
    if group is None:
        from ..dist import get_default_group
        group = get_default_group()
    return group


def all_reduce_host(x, group=None, op: str = ReduceOp.SUM):
    """Reduce a per-process host value across processes; returns the reduced
    value on host (as numpy / python scalar tree)."""
    group = _default_group(group)
    fn = _reduce_fn(op)  # validate op before the fast path returns
    if group.num_processes <= 1:
        return jax.tree.map(np.asarray, x)
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(x)  # leading axis = process
    return jax.tree.map(fn, gathered)


def all_gather_host(x, group=None):
    """Gather per-process values; returns tree with leading process axis."""
    group = _default_group(group)
    if group.num_processes <= 1:
        return jax.tree.map(lambda v: np.asarray(v)[None], x)
    from jax.experimental import multihost_utils
    return multihost_utils.process_allgather(x)


def broadcast_host(x, group=None, src: int = 0):
    """Broadcast process ``src``'s value to all processes (DDP's wrap-time
    rank-0 parameter broadcast, /root/reference/example_mp.py:53)."""
    group = _default_group(group)
    if group.num_processes <= 1:
        return jax.tree.map(np.asarray, x)
    from jax.experimental import multihost_utils
    return multihost_utils.broadcast_one_to_all(
        x, is_source=group.rank == src)


def _check_peer(rank: int, group, what: str) -> None:
    if not 0 <= rank < group.num_processes:
        raise ValueError(f"{what} {rank} out of range "
                         f"(num_processes={group.num_processes})")


def reduce_host(x, dst: int = 0, group=None, op: str = ReduceOp.SUM):
    """torch ``dist.reduce`` parity: the reduced value lands on process
    ``dst`` (returned there); every other process gets ``None``."""
    group = _default_group(group)
    fn = _reduce_fn(op)
    _check_peer(dst, group, "dst")
    if group.num_processes <= 1:
        return jax.tree.map(np.asarray, x)
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(x)
    if group.rank != dst:
        return None
    return jax.tree.map(fn, gathered)


# -- O(1)-per-rank store transport for rooted collectives ---------------------
#
# gather/scatter/all_to_all have a natural point-to-point structure; the
# mesh collectives (process_allgather / broadcast_one_to_all) give every
# rank the FULL list — O(world) traffic per rank.  When the control-plane
# store is up (launcher default), these ride per-(src,dst) store keys
# instead, so each rank moves only the entries it owns.  Same
# matched-by-program-order discipline as send/recv; same trust model as
# the object collectives (one job, pickled trees on the wire).

_coll_seq: dict = {}    # (op, root) -> next sequence number


def _coll_store():
    import importlib
    rdzv = importlib.import_module("tpu_dist.dist.rendezvous")
    return rdzv._store


def _coll_key(op: str, root: int, seq: int, peer: int) -> str:
    return f"tpu_dist/coll/{op}/{root}/{seq}/{peer}"


def _tree_to_bytes(tree) -> bytes:
    return pickle.dumps(jax.tree.map(np.asarray, tree))


def _tree_from_bytes(raw: bytes):
    return pickle.loads(raw)


def gather_host(x, dst: int = 0, group=None) -> Optional[List]:
    """torch ``dist.gather`` parity: process ``dst`` returns the list of all
    processes' values (index = rank); everyone else gets ``None``.

    With the control-plane store up, each rank posts only its own entry
    and ``dst`` collects them — non-destination ranks transfer O(1), not
    the O(world) of the all-gather fallback."""
    group = _default_group(group)
    _check_peer(dst, group, "dst")
    n = group.num_processes
    if n <= 1:
        return [jax.tree.map(np.asarray, x)]
    store = _coll_store()
    if store is not None:
        seq = _coll_seq.get(("gather", dst), 0)
        _coll_seq[("gather", dst)] = seq + 1
        if group.rank != dst:
            store.set(_coll_key("gather", dst, seq, group.rank),
                      _tree_to_bytes(x))
            return None
        out = []
        for r in range(n):
            if r == dst:
                out.append(jax.tree.map(np.asarray, x))
            else:
                key = _coll_key("gather", dst, seq, r)
                out.append(_tree_from_bytes(store.get(key)))
                store.delete_key(key)
        return out
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(x)
    if group.rank != dst:
        return None
    return [jax.tree.map(lambda v: v[r], gathered) for r in range(n)]


def scatter_host(output_template, scatter_list: Optional[List] = None,
                 src: int = 0, group=None):
    """torch ``dist.scatter`` parity: process ``src`` supplies
    ``scatter_list`` with one entry per process; every process returns its
    entry.  ``output_template`` plays the role of torch's preallocated
    output tensor: a value (tree) of the shape/dtype being received.  As in
    torch's NCCL scatter, every entry must share that shape/dtype (the wire
    format is uniform).  Non-source processes pass ``scatter_list=None``."""
    group = _default_group(group)
    n = group.num_processes
    _check_peer(src, group, "src")
    if group.rank == src:
        if scatter_list is None or len(scatter_list) != n:
            raise ValueError(
                f"scatter src must pass scatter_list with num_processes="
                f"{n} entries, got "
                f"{None if scatter_list is None else len(scatter_list)}")
        payload = [jax.tree.map(np.asarray, e) for e in scatter_list]
        tshape = jax.tree.map(lambda v: np.asarray(v).shape, output_template)
        for i, e in enumerate(payload):
            eshape = jax.tree.map(lambda v: v.shape, e)
            if eshape != tshape:
                raise ValueError(
                    f"scatter_list[{i}] shape {eshape} != output_template "
                    f"shape {tshape}: entries must be uniform (NCCL scatter "
                    f"semantics)")
        if n <= 1:
            return payload[0]
    # O(1)-per-rank path: src posts one store key per destination, each
    # rank fetches only its own entry (send/recv's matched-by-program-order
    # discipline; entries never fan out to bystanders).  Falls back to one
    # broadcast of the full list + local pick when no store is up.
    store = _coll_store()
    if store is not None:
        seq = _coll_seq.get(("scatter", src), 0)
        _coll_seq[("scatter", src)] = seq + 1
        if group.rank == src:
            for dst in range(n):
                if dst != src:
                    store.set(_coll_key("scatter", src, seq, dst),
                              _tree_to_bytes(payload[dst]))
            return payload[src]
        key = _coll_key("scatter", src, seq, group.rank)
        raw = store.get(key)       # blocks until src posts it
        store.delete_key(key)
        return _tree_from_bytes(raw)
    if group.rank != src:
        payload = [jax.tree.map(lambda v: np.zeros_like(np.asarray(v)),
                                output_template) for _ in range(n)]
    from jax.experimental import multihost_utils
    full = multihost_utils.broadcast_one_to_all(
        payload, is_source=group.rank == src)
    return jax.tree.map(np.asarray, full[group.rank])


# -- object collectives (pickle wire format, torch parity) --------------------
#
# torch's *_object collectives pickle arbitrary Python objects onto the
# tensor transport; same here, onto the uint8 array transport.  Same trust
# model as torch: never unpickle across a trust boundary — the group is
# assumed to be one job.  Payload sizes may differ per process, so each
# collective first agrees on the max length, pads, then truncates per rank.


def _obj_to_u8(obj: Any) -> np.ndarray:
    return np.frombuffer(pickle.dumps(obj), np.uint8)


def _all_gather_u8(obj: Any, group) -> tuple:
    """Pickle + pad + all-gather; returns ``(rows, lens)`` with ``rows[r]``
    the padded uint8 payload of rank ``r`` and ``lens[r]`` its true size."""
    payload = _obj_to_u8(obj)
    lens = all_gather_host(np.int64(payload.size), group)
    padded = np.zeros(int(lens.max()), np.uint8)
    padded[:payload.size] = payload
    return all_gather_host(padded, group), lens


def all_gather_object(obj: Any, group=None) -> List[Any]:
    """torch ``dist.all_gather_object`` parity: every process returns the
    list of all processes' objects (index = rank)."""
    group = _default_group(group)
    if group.num_processes <= 1:
        return [obj]
    rows, lens = _all_gather_u8(obj, group)
    return [pickle.loads(rows[r, :int(lens[r])].tobytes())
            for r in range(group.num_processes)]


def gather_object(obj: Any, dst: int = 0, group=None) -> Optional[List[Any]]:
    """torch ``dist.gather_object`` parity: process ``dst`` returns the
    rank-indexed object list; every other process returns ``None``."""
    group = _default_group(group)
    _check_peer(dst, group, "dst")
    if group.num_processes <= 1:
        return [obj] if group.rank == dst else None
    # the gather itself is collective (every rank participates in the
    # underlying all-gather), but only dst pays the unpickling
    rows, lens = _all_gather_u8(obj, group)
    if group.rank != dst:
        return None
    return [pickle.loads(rows[r, :int(lens[r])].tobytes())
            for r in range(group.num_processes)]


def broadcast_object_list(object_list: List[Any], src: int = 0,
                          group=None) -> List[Any]:
    """torch ``dist.broadcast_object_list`` parity, functional form: returns
    process ``src``'s list on every process (same length; torch mutates the
    preallocated list in place instead of returning)."""
    group = _default_group(group)
    _check_peer(src, group, "src")
    if group.num_processes <= 1:
        return list(object_list)
    is_src = group.rank == src
    payload = _obj_to_u8(list(object_list)) if is_src else np.zeros(0, np.uint8)
    # non-src processes don't know the size: agree on it first
    size = int(broadcast_host(np.int64(payload.size), group, src=src))
    buf = np.zeros(size, np.uint8)
    buf[:payload.size] = payload
    out = broadcast_host(buf, group, src=src)
    return pickle.loads(np.asarray(out).tobytes())


def scatter_object_list(scatter_object_input_list: Optional[List[Any]] = None,
                        src: int = 0, group=None) -> Any:
    """torch ``dist.scatter_object_list`` parity, functional form: process
    ``src`` supplies one object per process; every process returns its own
    (torch writes it into a 1-element output list instead)."""
    group = _default_group(group)
    n = group.num_processes
    _check_peer(src, group, "src")
    if group.rank == src:
        if (scatter_object_input_list is None
                or len(scatter_object_input_list) != n):
            got = (None if scatter_object_input_list is None
                   else len(scatter_object_input_list))
            raise ValueError(
                f"scatter src must pass scatter_object_input_list with "
                f"num_processes={n} entries, got {got}")
        if n <= 1:
            return scatter_object_input_list[0]
    store = _coll_store()
    if store is not None:
        # O(1)-per-rank: one store key per destination (see gather_host)
        seq = _coll_seq.get(("scatter_obj", src), 0)
        _coll_seq[("scatter_obj", src)] = seq + 1
        if group.rank == src:
            for dst in range(n):
                if dst != src:
                    store.set(_coll_key("scatter_obj", src, seq, dst),
                              pickle.dumps(scatter_object_input_list[dst]))
            return scatter_object_input_list[src]
        key = _coll_key("scatter_obj", src, seq, group.rank)
        obj = pickle.loads(store.get(key))
        store.delete_key(key)
        return obj
    # one broadcast of the full list, then local pick (the no-store
    # fallback: O(world) per rank)
    full = broadcast_object_list(
        scatter_object_input_list if group.rank == src else [None] * n,
        src=src, group=group)
    return full[group.rank]


def all_to_all_host(input_list: List[Any], group=None) -> List[Any]:
    """torch ``dist.all_to_all`` parity: process *p* sends
    ``input_list[q]`` to process *q*; returns the received list, entry *r*
    = what rank *r* addressed to this process.  Rides the object transport,
    so entries may be arrays of any (per-pair) shape or arbitrary objects.
    With the control-plane store up, pairwise store keys move only each
    rank's own row and column; without it, the fallback is one full
    all-gather.  Control-plane traffic either way — hot-path tensor
    redistribution is the in-jit :func:`tpu_dist.collectives.all_to_all`."""
    group = _default_group(group)
    n = group.num_processes
    if len(input_list) != n:
        raise ValueError(f"all_to_all needs one entry per process "
                         f"(num_processes={n}), got {len(input_list)}")
    if n <= 1:
        return list(input_list)
    store = _coll_store()
    if store is not None:
        # pairwise store keys: rank p moves only its row (sends) and its
        # column (receives) — not every rank x rank entry like the
        # all-gather fallback
        me = group.rank
        seq = _coll_seq.get(("a2a", 0), 0)
        _coll_seq[("a2a", 0)] = seq + 1
        for q in range(n):
            if q != me:
                # plain pickle (object transport): entries may be arrays
                # OR arbitrary objects — no np coercion on the wire
                store.set(_coll_key("a2a", q, seq, me),
                          pickle.dumps(input_list[q]))
        out = []
        for r in range(n):
            if r == me:
                out.append(input_list[me])
            else:
                key = _coll_key("a2a", me, seq, r)
                out.append(pickle.loads(store.get(key)))
                store.delete_key(key)
        return out
    rows = all_gather_object(list(input_list), group)
    return [rows[r][group.rank] for r in range(n)]


# -- point-to-point over the control-plane store ------------------------------

_p2p_send_seq: dict = {}   # (me, dst, tag) -> next sequence number
_p2p_recv_seq: dict = {}   # (src, me, tag) -> next sequence number


def _p2p_store():
    # importlib: `from ..dist import rendezvous` would fetch the FUNCTION
    # re-exported by dist/__init__, not the module
    import importlib
    rdzv = importlib.import_module("tpu_dist.dist.rendezvous")
    if rdzv._store is None:
        raise RuntimeError(
            "send/recv need the control-plane store: bring the job up via "
            "tpu_dist.launch (default), or set TPU_DIST_STORE_ADDR, or use "
            "TPU_DIST_STORE_PREFLIGHT=1 with tcp:// rendezvous")
    return rdzv._store


def _p2p_key(src: int, dst: int, tag: int, seq: int) -> str:
    return f"tpu_dist/p2p/{src}->{dst}/t{tag}/{seq}"


def send(x, dst: int, group=None, tag: int = 0) -> None:
    """torch ``dist.send`` parity: deliver this process's array to process
    ``dst``.  Matched by program order per (src, dst, tag), like torch.
    Buffered through the store server, so send does not block on the
    receiver.  Control-plane transport: host serialization over the TCP
    store — for tensor p2p between devices of the SAME mesh use
    :func:`send_recv_device` (one ppermute hop over ICI, never touches
    the host)."""
    group = _default_group(group)
    me = group.rank
    if dst == me:
        raise ValueError("send to self deadlocks (torch semantics)")
    if not 0 <= dst < group.num_processes:
        raise ValueError(f"dst {dst} out of range "
                         f"(num_processes={group.num_processes})")
    store = _p2p_store()
    seq = _p2p_send_seq.get((me, dst, tag), 0)
    _p2p_send_seq[(me, dst, tag)] = seq + 1
    buf = io.BytesIO()
    np.save(buf, np.asarray(x), allow_pickle=False)
    store.set(_p2p_key(me, dst, tag, seq), buf.getvalue())


# mesh (weak) -> {(axis, src, dst): jitted mover}; weak so compiled movers
# die with their mesh across init/destroy process-group cycles
_device_p2p_cache = weakref.WeakKeyDictionary()


def send_recv_device(x, src: int, dst: int, group=None):
    """Tensor p2p between two *devices of the same mesh*, on the data
    plane: one jitted ``lax.ppermute`` hop over ICI — no host readback,
    no store round-trip, no pickle (c10d ``send``/``recv`` semantics for
    the in-mesh case; the store-backed :func:`send`/:func:`recv` remain
    the cross-process/control path, see their docstrings).

    ``x`` is sharded ``P(axis)`` over the group's mesh (row blocks, like
    every data batch); returns the same array with device ``dst``'s block
    REPLACED by device ``src``'s block, all other blocks untouched.  The
    single-controller analogue of rank ``src`` sending its shard and rank
    ``dst`` receiving it.  Jit-cached per (mesh, src, dst); reuses the
    compiled program across calls and shapes via jax's own cache.
    """
    group = _default_group(group)
    src, dst = int(src), int(dst)
    n = group.size()
    for name, r in (("src", src), ("dst", dst)):
        if not 0 <= r < n:
            raise ValueError(f"{name} {r} out of range (mesh size {n})")
    if src == dst:
        raise ValueError("send to self deadlocks (torch semantics)")
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    mesh, axis = group.mesh, group.axis_name
    per_mesh = _device_p2p_cache.setdefault(mesh, {})
    fn = per_mesh.get((axis, src, dst))
    if fn is None:
        def local(xs):
            moved = lax.ppermute(xs, axis, perm=[(src, dst)])
            return jnp.where(lax.axis_index(axis) == dst, moved, xs)

        fn = jax.jit(jax.shard_map(local, mesh=mesh, in_specs=P(axis),
                                   out_specs=P(axis)))
        per_mesh[(axis, src, dst)] = fn
    return fn(x)


def recv(src: int, group=None, tag: int = 0) -> np.ndarray:
    """torch ``dist.recv`` parity: block until the matching :func:`send`
    from ``src`` arrives; returns the array (no preallocated output buffer
    needed — shape/dtype travel on the wire)."""
    group = _default_group(group)
    me = group.rank
    if src == me:
        raise ValueError("recv from self deadlocks (torch semantics)")
    if not 0 <= src < group.num_processes:
        raise ValueError(f"src {src} out of range "
                         f"(num_processes={group.num_processes})")
    store = _p2p_store()
    seq = _p2p_recv_seq.get((src, me, tag), 0)
    _p2p_recv_seq[(src, me, tag)] = seq + 1
    key = _p2p_key(src, me, tag, seq)
    raw = store.get(key)  # blocks until the key exists
    store.delete_key(key)
    return np.load(io.BytesIO(raw), allow_pickle=False)
